"""End-to-end tests: real client -> coordinator -> agent subprocesses on
localhost, payload scripts as assertions.

Reference: TestTonyE2E.java (679 LoC, 27 cases) over MiniCluster. Each test
submits a real job; the job's final status IS the assertion.
"""

import os

import pytest

from tony_tpu import constants as C
from tony_tpu.mini import MiniTonyCluster, script_conf

SCRIPTS = os.path.join(os.path.dirname(__file__), "scripts")


def script(name: str) -> str:
    return os.path.join(SCRIPTS, name)


@pytest.fixture
def cluster():
    with MiniTonyCluster() as c:
        yield c


def run_job(cluster, conf):
    client = cluster.make_client(conf)
    ok = client.run()
    return ok, client


# -- happy paths -------------------------------------------------------------


def test_single_worker_pass(cluster):
    """Ref: testSingleNodeTrainingShouldPass."""
    ok, client = run_job(cluster, script_conf(cluster, script("exit_0.py"),
                                              {"worker": 1}))
    assert ok, client.final_status
    assert client.final_status["status"] == "SUCCEEDED"


def test_single_worker_fail(cluster):
    """Ref: testSingleNodeTrainingShouldFail."""
    ok, client = run_job(cluster, script_conf(cluster, script("exit_1.py"),
                                              {"worker": 1}))
    assert not ok
    assert client.final_status["status"] == "FAILED"


def test_gang_env_contract(cluster):
    """2 workers check the full injected env (ref: testPSWorkerTraining +
    exit_0_check_env payloads)."""
    ok, client = run_job(cluster, script_conf(cluster, script("check_env.py"),
                                              {"worker": 2}))
    assert ok, client.final_status


def test_jax_rendezvous_env(cluster):
    """The TPU-native TF_CONFIG analog reaches tasks correctly."""
    ok, client = run_job(cluster, script_conf(cluster, script("check_jax_env.py"),
                                              {"worker": 2}))
    assert ok, client.final_status


def test_pytorch_runtime_env(cluster):
    """Ref: testPyTorchEnv (:195)."""
    ok, client = run_job(
        cluster,
        script_conf(cluster, script("check_pytorch_env.py"), {"worker": 2},
                    framework="pytorch"),
    )
    assert ok, client.final_status


def test_tb_port_only_on_chief(cluster):
    """Ref: testTBPortSetOnlyOnChief (:359)."""
    ok, client = run_job(
        cluster,
        script_conf(cluster, script("check_tb_port_set_in_chief_only.py"),
                    {"worker": 2}),
    )
    assert ok, client.final_status


def test_standalone_runtime(cluster):
    """Ref: testStandaloneRuntimePass (:375)."""
    ok, client = run_job(
        cluster,
        script_conf(cluster, script("exit_0.py"), {"worker": 1},
                    framework="standalone"),
    )
    assert ok, client.final_status


# -- failure policy ----------------------------------------------------------


def test_chief_failure_fails_job(cluster):
    """worker:0 (chief) fails -> job fails even though worker:1 passes.

    Payload: chief exits 1, other exits 0, via a role command split."""
    conf = cluster.base_conf()
    conf.set("tony.chief.instances", 1)
    conf.set("tony.worker.instances", 1)
    conf.set("tony.chief.command", f"python {script('exit_1.py')}")
    conf.set("tony.worker.command", f"python {script('exit_0.py')}")
    ok, client = run_job(cluster, conf)
    assert not ok
    assert "chief" in (client.final_status.get("reason") or "")


def test_non_chief_failure_tolerated(cluster):
    """Ref: testNonChiefWorkerFailureTolerated (:323)."""
    conf = cluster.base_conf()
    conf.set("tony.chief.instances", 1)
    conf.set("tony.failing.instances", 1)
    conf.set("tony.chief.command", f"python {script('exit_0.py')}")
    conf.set("tony.failing.command", f"python {script('exit_1.py')}")
    ok, client = run_job(cluster, conf)
    assert ok, client.final_status


def test_untracked_failure_fails_fast(cluster):
    """Ref: testPSCrashShouldFailFast (:467) — untracked 'ps' crash."""
    conf = cluster.base_conf()
    conf.set("tony.worker.instances", 1)
    conf.set("tony.ps.instances", 1)
    conf.set("tony.worker.command", f"python {script('sleep_5.py')}")
    conf.set("tony.ps.command", f"python {script('exit_1.py')}")
    ok, client = run_job(cluster, conf)
    assert not ok


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_sidecar_tb_builtin_launcher(cluster):
    """A tensorboard role with no command gets the built-in sidecar
    launcher shipped into the job dir, and its URL reaches the client
    (ref: setSidecarTBResources TonyClient.java:571-600)."""
    conf = cluster.base_conf()
    conf.set("tony.worker.instances", 1)
    conf.set("tony.tensorboard.instances", 1)
    conf.set("tony.worker.command", f"python {script('sleep_5.py')}")
    conf.set("tony.application.tensorboard-log-dir",
             os.path.join(cluster.root, "tblogs"))
    conf.set("tony.application.shell-env", "TONY_TEST_TB_SLEEP=30")
    ok, client = run_job(cluster, conf)
    assert ok, client.final_status
    cmd = str(client.conf.role_get("tensorboard", "command"))
    assert "sidecar_tensorboard.py" in cmd and client.job_dir in cmd
    assert client.tensorboard_url.startswith("http://")


def test_sidecar_tb_executes_fallback_preserved(cluster):
    """A command-less tensorboard role with tony.application.executes set
    keeps the entrypoint-switches-on-JOB_NAME fallback — the built-in
    launcher must not hijack it."""
    conf = cluster.base_conf()
    conf.set("tony.worker.instances", 1)
    conf.set("tony.tensorboard.instances", 1)
    conf.set("tony.application.executes", script("exit_0.py"))
    ok, client = run_job(cluster, conf)
    assert ok, client.final_status
    assert str(client.conf.role_get("tensorboard", "command")) == ""


def test_sidecar_tb_requires_log_dir(cluster):
    """Command-less tensorboard role without a log dir fails at submit
    time instead of as a silently tolerated sidecar crash."""
    from tony_tpu.config import ConfError

    conf = cluster.base_conf()
    conf.set("tony.worker.instances", 1)
    conf.set("tony.tensorboard.instances", 1)
    conf.set("tony.worker.command", f"python {script('exit_0.py')}")
    with pytest.raises(ConfError):
        cluster.make_client(conf).run()


def test_sidecar_failure_tolerated(cluster):
    """Ref: testSidecarCrashTolerated (:499)."""
    conf = cluster.base_conf()
    conf.set("tony.worker.instances", 1)
    conf.set("tony.tensorboard.instances", 1)
    conf.set("tony.worker.command", f"python {script('exit_0.py')}")
    conf.set("tony.tensorboard.command", f"python {script('exit_1.py')}")
    ok, client = run_job(cluster, conf)
    assert ok, client.final_status


# -- DAG scheduling ----------------------------------------------------------


def test_role_dag_scheduling(cluster):
    """Ref: testJobTypeDAGScheduling (:271): prep must complete before
    worker starts; worker checks a file prep wrote."""
    marker = os.path.join(cluster.root, "prep_done")
    conf = cluster.base_conf()
    conf.set("tony.prep.instances", 1)
    conf.set("tony.worker.instances", 1)
    conf.set("tony.worker.depends-on", "prep")
    conf.set("tony.prep.command", f"touch {marker}")
    conf.set("tony.worker.command", f"test -f {marker}")
    ok, client = run_job(cluster, conf)
    assert ok, client.final_status


# -- fault injection ---------------------------------------------------------


def test_missed_heartbeats_fail_job(cluster, monkeypatch):
    """Ref: testMissedHeartbeats (:143)."""
    monkeypatch.setenv(C.TEST_TASK_NUM_HB_MISS, "1000")
    conf = script_conf(cluster, script("sleep_5.py"), {"worker": 1})
    conf.set("tony.task.max-missed-heartbeats", 3)
    ok, client = run_job(cluster, conf)
    assert not ok
    assert "heartbeat" in (client.final_status.get("reason") or "")


def test_worker_skew(cluster, monkeypatch):
    """Ref: testTaskExecutorSkew (:162) — one straggler still succeeds."""
    monkeypatch.setenv(C.TEST_TASK_SKEW, "worker#1#1500")
    ok, client = run_job(cluster, script_conf(cluster, script("check_env.py"),
                                              {"worker": 2}))
    assert ok, client.final_status


def test_chief_kill_mid_run(cluster, monkeypatch):
    """Ref: testChiefWorkerKilled (:298) via TEST_WORKER_TERMINATION."""
    monkeypatch.setenv(C.TEST_WORKER_TERMINATION, "1")
    ok, client = run_job(cluster, script_conf(cluster, script("sleep_5.py"),
                                              {"worker": 2}))
    assert not ok


def test_coordinator_exception_retry(cluster, monkeypatch):
    """Ref: testAMCrashShouldRetry-style (:241-256): first attempt throws,
    retry succeeds."""
    monkeypatch.setenv(C.TEST_COORD_THROW, "1")
    conf = script_conf(cluster, script("exit_0.py"), {"worker": 1})
    conf.set("tony.coordinator.retry-count", 1)
    ok, client = run_job(cluster, conf)
    assert ok, client.final_status


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_resume_from_checkpoint_on_retry(cluster):
    """Restart-with-resume (no reference analog, SURVEY 5.4): attempt 0
    checkpoints then fails; the retry attempt must see TONY_RESUME_STEP and
    restore the saved state before succeeding."""
    conf = script_conf(cluster, script("resume_from_checkpoint.py"),
                       {"worker": 1})
    conf.set("tony.coordinator.retry-count", 1)
    conf.set("tony.application.checkpoint-dir", "ckpts")  # job-dir relative
    ok, client = run_job(cluster, conf)
    assert ok, client.final_status


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_preemption_grace_checkpoint_and_resume(cluster):
    """TPU-preemption path (SURVEY 7.9b: the heartbeat-expiry analog):
    SIGTERM to the agent forwards to the user process with a grace window;
    the exit is reported preempted; the retry resumes from the checkpoint
    saved inside the window."""
    conf = script_conf(cluster, script("preempt_and_resume.py"),
                       {"worker": 1})
    conf.set("tony.coordinator.retry-count", 1)
    conf.set("tony.application.checkpoint-dir", "ckpts")
    ok, client = run_job(cluster, conf)
    assert ok, client.final_status


def test_coordinator_exception_no_retry_fails(cluster, monkeypatch):
    monkeypatch.setenv(C.TEST_COORD_THROW, "1")
    conf = script_conf(cluster, script("exit_0.py"), {"worker": 1})
    ok, client = run_job(cluster, conf)
    assert not ok


def test_ps_worker_training_pass(cluster):
    """Ref: testPSWorkerTrainingShouldPass (:128): untracked ps + 2 tracked
    workers; job succeeds when the tracked gang completes."""
    conf = cluster.base_conf()
    conf.set("tony.ps.instances", 1)
    conf.set("tony.worker.instances", 2)
    conf.set("tony.ps.command", f"python {script('sleep_5.py')}")
    conf.set("tony.worker.command", f"python {script('check_env.py')}")
    ok, client = run_job(cluster, conf)
    assert ok, client.final_status


def test_delayed_completion_notification(cluster, monkeypatch):
    """Ref: testTaskCompletionNotificationDelayed (:412): a late launcher
    exit callback must not override the RPC-registered result."""
    monkeypatch.setenv(C.TEST_COMPLETION_DELAY, "500")
    ok, client = run_job(cluster, script_conf(cluster, script("exit_0.py"),
                                              {"worker": 1}))
    assert ok, client.final_status


def test_resources_localization(cluster):
    """Ref: testResourcesLocalization (:339) + archive payload: per-role
    resources (plain file, renamed file, archive) appear in the task cwd."""
    res_dir = os.path.join(cluster.root, "res")
    os.makedirs(res_dir)
    plain = os.path.join(res_dir, "data.txt")
    with open(plain, "w") as f:
        f.write("x")
    import zipfile

    archive = os.path.join(res_dir, "bundle.zip")
    with zipfile.ZipFile(archive, "w") as z:
        z.writestr("inner.txt", "y")
    conf = cluster.base_conf()
    conf.set("tony.worker.instances", 1)
    conf.set("tony.worker.resources",
             f"{plain},{plain}::renamed.txt,{archive}::bundle#archive")
    conf.set("tony.worker.command",
             "test -f data.txt -a -f renamed.txt -a -f bundle/inner.txt")
    ok, client = run_job(cluster, conf)
    assert ok, client.final_status


def test_venv_interpreter_used(cluster):
    """Ref: check_env_and_venv payload: tasks run under the shipped venv's
    interpreter, not the system python."""
    import stat
    import sys

    venv_bin = os.path.join(cluster.root, "venv", "bin")
    os.makedirs(venv_bin)
    shim = os.path.join(venv_bin, "python")
    with open(shim, "w") as f:
        f.write(f"#!/bin/bash\nexport TONY_VENV_MARK=1\n"
                f"exec {sys.executable} \"$@\"\n")
    os.chmod(shim, os.stat(shim).st_mode | stat.S_IEXEC)
    conf = cluster.base_conf()
    conf.set("tony.application.python-venv", os.path.dirname(venv_bin))
    conf.set("tony.worker.instances", 1)
    conf.set("tony.application.executes", script("check_venv_mark.py"))
    ok, client = run_job(cluster, conf)
    assert ok, client.final_status


def test_application_timeout_fails_job(cluster):
    """Ref: tony.application.timeout semantics — whole-job deadline."""
    conf = script_conf(cluster, script("sleep_5.py"), {"worker": 1})
    conf.set("tony.application.timeout-ms", 800)
    ok, client = run_job(cluster, conf)
    assert not ok
    assert "timed out" in (client.final_status.get("reason") or "").lower()


def test_client_task_update_listener(cluster):
    """Ref: testTaskUpdateListener (:430): the client fans task-info
    updates out to registered listeners (NotebookSubmitter's discovery
    mechanism)."""
    conf = script_conf(cluster, script("exit_0.py"), {"worker": 1})
    client = cluster.make_client(conf)
    seen: list[list] = []
    client.add_listener(lambda infos: seen.append(infos))
    ok = client.run()
    assert ok
    assert seen, "listener never called"
    final = {f"{t.name}:{t.index}": t.status for t in seen[-1]}
    assert final.get("worker:0") in ("FINISHED", "SUCCEEDED")


def test_final_conf_written(cluster):
    """Ref: testTonyFinalConf (:621-654): the merged conf is serialized
    into the job dir and reloadable."""
    import json as _json

    conf = script_conf(cluster, script("exit_0.py"), {"worker": 1})
    ok, client = run_job(cluster, conf)
    assert ok
    final_path = os.path.join(client.job_dir, "tony-final.json")
    assert os.path.exists(final_path)
    with open(final_path) as f:
        merged = _json.load(f)
    assert merged.get("tony.worker.instances") in (1, "1")
    assert merged.get("tony.application.framework") == "jax"


# -- history -----------------------------------------------------------------


def test_history_written(cluster):
    from tony_tpu.events import history

    conf = script_conf(cluster, script("exit_0.py"), {"worker": 1})
    ok, client = run_job(cluster, conf)
    assert ok
    jobs = history.list_jobs(os.path.join(cluster.root, "history"))
    assert len(jobs) == 1
    assert jobs[0]["status"] == "SUCCEEDED"
    events = history.parse_events(jobs[0]["jhist"])
    types = [e.type.value for e in events]
    assert types[0] == "APPLICATION_INITED"
    assert "TASK_STARTED" in types
    assert "TASK_FINISHED" in types
    assert types[-1] == "APPLICATION_FINISHED"


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_coordinator_hard_crash_respawned(cluster, monkeypatch):
    """Ref: TEST_AM_CRASH + YARN AM restart (testAMCrash :241): the
    coordinator process hard-exits; the client respawns it (the AM-attempt
    analog) and the job completes."""
    monkeypatch.setenv(C.TEST_COORD_CRASH, "1")
    conf = script_conf(cluster, script("exit_0.py"), {"worker": 1})
    conf.set("tony.client.coordinator-max-attempts", 2)
    # shrink the respawn fence (liveness horizon + grace) for test speed
    conf.set("tony.task.heartbeat-interval-ms", 100)
    conf.set("tony.task.max-missed-heartbeats", 3)
    conf.set("tony.task.preemption-grace-ms", 300)
    ok, client = run_job(cluster, conf)
    assert ok, client.final_status


def test_coordinator_hard_crash_without_respawn_fails(cluster, monkeypatch):
    monkeypatch.setenv(C.TEST_COORD_CRASH, "1")
    conf = script_conf(cluster, script("exit_0.py"), {"worker": 1})
    ok, client = run_job(cluster, conf)
    assert not ok
    assert "coordinator" in str(client.final_status.get("reason", ""))


def test_registration_timeout_fails_job(cluster, monkeypatch):
    """Ref: registrationTimeout (:1309-1329): a launched task that never
    registers within tony.coordinator.registration-timeout-ms fails the
    app with a clear reason."""
    monkeypatch.setenv(C.TEST_TASK_SKEW, "worker#0#15000")  # stalls pre-reg
    conf = script_conf(cluster, script("exit_0.py"), {"worker": 1})
    conf.set("tony.coordinator.registration-timeout-ms", 1500)
    ok, client = run_job(cluster, conf)
    assert not ok
    assert "register" in str(client.final_status.get("reason", ""))


def test_jax_distributed_psum_e2e(cluster):
    """The rendezvous contract itself: 2 processes initialize
    jax.distributed from the injected env and allgather across the gang
    (beyond check_jax_env's env-spelling assertions)."""
    ok, _ = run_job(cluster, script_conf(cluster, script("check_jax_psum.py"),
                                         {"worker": 2}))
    assert ok


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_multislice_gang_e2e(cluster):
    """Multislice driven through the REAL submit->agents path (VERDICT
    r4 stretch #10): 4 workers as 2 virtual slices — every worker
    asserts its injected MEGASCALE_*/per-slice libtpu env, then the
    whole gang rendezvouses globally and allgathers across both slices
    (the contract was previously unit-tested + dryrun-validated only)."""
    conf = script_conf(cluster, script("check_multislice_env.py"),
                       {"worker": 4})
    conf.set("tony.tpu.num-slices", 2)
    ok, _ = run_job(cluster, conf)
    assert ok


def test_fcfs_mode_e2e(cluster):
    """FCFS scheduling through the full cluster (ref: TestTonyE2E FCFS
    cases over MLGenericRuntime.java:79-99): tasks start without waiting
    for the whole gang and the job still completes. The tf runtime hosts
    it (the reference's FCFS jobs are TF) — the jax runtime correctly
    refuses FCFS, since its rendezvous needs the entire gang."""
    conf = script_conf(cluster, script("exit_0.py"), {"worker": 2},
                       framework="tensorflow")
    conf.set("tony.application.distributed-mode", "FCFS")
    ok, client = run_job(cluster, conf)
    assert ok, client.final_status


def test_preprocess_stdout_feeds_training_env(cluster, tmp_path):
    """VERDICT r2 #7: preprocess-then-train — the coordinator runs the
    preprocess command first and its scraped 'Model parameters: ' stdout
    changes worker behavior via the MODEL_PARAMS env (ref:
    doPreprocessingJob, ApplicationMaster.java:780-832)."""
    prep = tmp_path / "prep.py"
    prep.write_text("print('preprocess warming up')\n"
                    "print('Model parameters: ' + str(6 * 7))\n")
    worker = tmp_path / "worker.py"
    worker.write_text("import os, sys\n"
                      "sys.exit(0 if os.environ.get('MODEL_PARAMS') == '42' "
                      "else 9)\n")
    conf = script_conf(cluster, str(worker), {"worker": 2})
    conf.set("tony.application.enable-preprocess", True)
    conf.set("tony.coordinator.command", f"python3 {prep}")
    ok, client = run_job(cluster, conf)
    assert ok, client.final_status
    assert client.final_status["status"] == "SUCCEEDED"


def test_preprocess_failure_skips_training(cluster, tmp_path):
    """A failed preprocess short-circuits: no training task ever launches
    (ref: 'Short circuit if preprocessing job fails', :813-817)."""
    marker = tmp_path / "worker_ran"
    worker = tmp_path / "worker.py"
    worker.write_text(f"open({str(marker)!r}, 'w').write('x')\n")
    conf = script_conf(cluster, str(worker), {"worker": 1})
    conf.set("tony.application.enable-preprocess", True)
    conf.set("tony.coordinator.command", "exit 3")
    ok, client = run_job(cluster, conf)
    assert not ok
    assert client.final_status["status"] == "FAILED"
    assert not marker.exists(), "worker launched despite preprocess failure"


def test_preprocess_failure_then_retry_succeeds(cluster, tmp_path):
    """A failed preprocess must not poison the retry attempt: the retried
    epoch re-runs preprocess, scrapes fresh params, and trains (regression:
    a sticky _preprocess_ran flag made _monitor bail before the retried
    gang ran)."""
    marker = tmp_path / "prep_attempts"
    prep = tmp_path / "prep.py"
    prep.write_text(
        "import os\n"
        f"p = {str(marker)!r}\n"
        "n = int(open(p).read()) if os.path.exists(p) else 0\n"
        "open(p, 'w').write(str(n + 1))\n"
        "if n == 0:\n"
        "    raise SystemExit(3)\n"
        "print('Model parameters: ok42')\n")
    worker = tmp_path / "worker.py"
    worker.write_text("import os, sys\n"
                      "sys.exit(0 if os.environ.get('MODEL_PARAMS') == "
                      "'ok42' else 9)\n")
    conf = script_conf(cluster, str(worker), {"worker": 1})
    conf.set("tony.application.enable-preprocess", True)
    conf.set("tony.coordinator.command", f"python3 {prep}")
    conf.set("tony.coordinator.retry-count", 1)
    ok, client = run_job(cluster, conf)
    assert ok, client.final_status
    assert marker.read_text() == "2"  # preprocess genuinely re-ran
