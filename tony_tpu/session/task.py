"""Task state: status enum, per-task record, client-visible TaskInfo.

Reference: rpc/impl/TaskStatus.java (attention-sorted order preserved below),
rpc/TaskInfo.java, TonySession.TonyTask (tensorflow/TonySession.java:436).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field


class TaskStatus(enum.IntEnum):
    """Ordered by display attention (ref: TaskStatus attention sort)."""

    FAILED = 0
    FINISHED = 1
    RUNNING = 2
    READY = 3
    NEW = 4

    @property
    def terminal(self) -> bool:
        return self in (TaskStatus.FAILED, TaskStatus.FINISHED)


@dataclass
class Task:
    """One task instance of a role (ref: TonySession.TonyTask)."""

    role: str
    index: int
    session_id: int = 0
    host: str = ""
    port: int = -1
    status: TaskStatus = TaskStatus.NEW
    exit_code: int | None = None
    registered: bool = False
    completed: bool = False
    log_url: str = ""
    metrics: dict[str, float] = field(default_factory=dict)

    @property
    def id(self) -> str:
        """Canonical "role:index" id (ref: task id format "job:idx")."""
        return f"{self.role}:{self.index}"

    @property
    def host_port(self) -> str:
        return f"{self.host}:{self.port}"

    def set_host_port(self, host_port: str) -> None:
        host, sep, port = host_port.rpartition(":")
        if not sep or not host or not port.isdigit():
            raise ValueError(f"malformed host:port: {host_port!r}")
        self.host = host
        self.port = int(port)

    def set_exit_status(self, exit_code: int) -> None:
        """Exit code -> status mapping (ref: TonySession.java:506-523)."""
        if self.completed:
            return
        self.completed = True
        self.exit_code = exit_code
        self.status = TaskStatus.FINISHED if exit_code == 0 else TaskStatus.FAILED

    def to_info(self) -> "TaskInfo":
        return TaskInfo(
            name=self.role,
            index=self.index,
            status=self.status.name,
            url=self.log_url,
            host=self.host,
            metrics=dict(self.metrics),
        )


@dataclass
class TaskInfo:
    """Client-facing task view (ref: rpc/TaskInfo.java)."""

    name: str
    index: int
    status: str
    url: str = ""
    host: str = ""
    metrics: dict[str, float] = field(default_factory=dict)

    @property
    def attention(self) -> int:
        return TaskStatus[self.status].value

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "index": self.index,
            "status": self.status,
            "url": self.url,
            "host": self.host,
            "metrics": self.metrics,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "TaskInfo":
        return cls(**d)
