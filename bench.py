"""Headline benchmark: ResNet-50 images/sec/chip through the tony-tpu
trainer vs a hand-rolled native-JAX train step (BASELINE.json north star:
framework >= 90% of native JAX).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = framework_throughput / native_jax_throughput (1.0 = parity;
>= 0.9 meets the north star; > 1.0 beats it).

On TPU runs ResNet-50 at a production batch; off-TPU (CI boxes) it shrinks
to ResNet-18 / tiny batch so the line still prints quickly.
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import optax

# honor an env request for the CPU platform even under this image's TPU
# sitecustomize, which overrides jax_platforms at interpreter startup
_env_platforms = os.environ.get("JAX_PLATFORMS", "")
if _env_platforms and "axon" not in _env_platforms:
    jax.config.update("jax_platforms", _env_platforms)


def _platform() -> str:
    try:
        return jax.devices()[0].platform
    except Exception:
        return "cpu"


def make_model(on_tpu: bool):
    from tony_tpu.models import ResNet18, ResNet50

    if on_tpu:
        return ResNet50(num_classes=1000), 128, 224
    return ResNet18(num_classes=100, num_filters=16), 8, 32


def _timed(fn, steps: int) -> float:
    start = time.perf_counter()
    for _ in range(steps):
        out = fn()
    jax.block_until_ready(out)
    return time.perf_counter() - start


def bench_pair(native_fn, fw_fn, steps: int, warmup: int = 2,
               repeats: int = 5) -> tuple[float, float, float]:
    """Interleaved A/B timing: (t_native, t_fw, vs_baseline).

    The device (possibly a shared/tunneled chip) drifts in speed over the
    seconds a run takes, so timing all-native-then-all-framework folds that
    drift into the ratio. Instead each repeat times native then framework
    back-to-back and the reported ratio is the median of PER-ROUND ratios —
    drift slower than a round cancels; times are medians for the absolute
    throughput line.
    """
    for _ in range(max(warmup, 1)):  # >=1: the block below needs outputs
        out = native_fn()
        out2 = fw_fn()
    jax.block_until_ready((out, out2))
    rounds = []
    for _ in range(repeats):
        rounds.append((_timed(native_fn, steps), _timed(fw_fn, steps)))
    t_nat = sorted(t for t, _ in rounds)[len(rounds) // 2]
    t_fw = sorted(t for _, t in rounds)[len(rounds) // 2]
    ratios = sorted(tn / tf for tn, tf in rounds)
    return t_nat, t_fw, ratios[len(ratios) // 2]


def main() -> None:
    on_tpu = _platform() in ("tpu", "axon")
    steps = 20 if on_tpu else 3
    model, batch, size = make_model(on_tpu)
    rng = jax.random.PRNGKey(0)
    images = jnp.ones((batch, size, size, 3), jnp.float32)
    labels = jnp.zeros((batch,), jnp.int32)
    variables = model.init(rng, images, train=False)
    params, batch_stats = variables["params"], variables.get("batch_stats", {})
    tx = optax.sgd(0.1, momentum=0.9)

    # ---- native JAX step (the baseline): plain jit, hand-rolled update ----
    opt_state = tx.init(params)

    def native_loss(p, bs, x, y):
        logits, new_model_state = model.apply(
            {"params": p, "batch_stats": bs}, x, train=True,
            mutable=["batch_stats"])
        onehot = jax.nn.one_hot(y, logits.shape[-1])
        loss = -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), axis=-1))
        return loss, new_model_state["batch_stats"]

    @jax.jit
    def native_step(p, bs, o, x, y):
        (loss, new_bs), grads = jax.value_and_grad(native_loss, has_aux=True)(
            p, bs, x, y)
        updates, o = tx.update(grads, o, p)
        p = optax.apply_updates(p, updates)
        return p, new_bs, o, loss

    def native_once():
        # return + block on the loss only, symmetric with fw_once below
        return native_step(params, batch_stats, opt_state, images, labels)[3]

    # ---- framework step: tony_tpu Trainer over a mesh ---------------------
    from tony_tpu.parallel import data_parallel_mesh
    from tony_tpu.train import Trainer

    mesh = data_parallel_mesh()

    def apply_fn(state_params, train_batch):
        x, y, bs = train_batch["x"], train_batch["y"], train_batch["bs"]
        logits, _ = model.apply({"params": state_params, "batch_stats": bs},
                                x, train=True, mutable=["batch_stats"])
        onehot = jax.nn.one_hot(y, logits.shape[-1])
        return -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), axis=-1))

    trainer = Trainer(mesh=mesh, apply_fn=apply_fn, optimizer=tx, donate=False)
    state = trainer.init_state(params)
    from jax.sharding import NamedSharding, PartitionSpec as P
    from tony_tpu.parallel.sharding import batch_sharding

    b_sh = batch_sharding(mesh)
    train_batch = {
        "x": jax.device_put(images, b_sh),
        "y": jax.device_put(labels, b_sh),
        "bs": jax.device_put(batch_stats, NamedSharding(mesh, P())),
    }
    step_fn, placed = trainer.build_step(state)

    def fw_once():
        new_state, metrics = step_fn(placed, train_batch)
        return metrics["loss"]

    _, t_fw, ratio = bench_pair(native_once, fw_once, steps)
    fw_ips = batch * steps / t_fw

    n_chips = max(1, jax.device_count())
    print(json.dumps({
        "metric": "resnet_images_per_sec_per_chip"
                  + ("" if on_tpu else "_cpu_proxy"),
        "value": round(fw_ips / n_chips, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(ratio, 4),
    }))


if __name__ == "__main__":
    main()
