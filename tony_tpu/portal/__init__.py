from tony_tpu.portal.app import Portal

__all__ = ["Portal"]
