from tony_tpu.mini.cluster import MiniTonyCluster, script_conf

__all__ = ["MiniTonyCluster", "script_conf"]
