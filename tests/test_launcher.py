"""Docker/container launch mode.

Reference: tony.docker.* keys + docker container env
(HadoopCompatibleAdapter.getContainerEnvForDocker). The e2e test runs a
real job through a fake-docker shim that interprets ``docker run`` locally,
so the full coordinator->container->agent->payload path is exercised
without a docker daemon.
"""

import os
import stat
import textwrap

import pytest

from tony_tpu.mini import MiniTonyCluster, script_conf
from tony_tpu.session import Task

SCRIPTS = os.path.join(os.path.dirname(__file__), "scripts")


def test_build_docker_command():
    from tony_tpu.coordinator.launcher import build_docker_command

    task = Task(role="worker", index=0)
    argv = build_docker_command(
        task, {"JOB_NAME": "worker", "TASK_INDEX": "0"},
        image="gcr.io/proj/train:1", mounts=["/data:/data:ro"],
        extra_args=["--shm-size=4g"], workdir="/jobs/app1")
    assert argv[:2] == ["docker", "run"]
    assert "--net=host" in argv and "--privileged" in argv
    assert "tony-s0-worker-0" in argv  # epoch-qualified container name
    assert "/data:/data:ro" in argv
    # job dir is mounted at the same path and set as the workdir
    assert "/jobs/app1:/jobs/app1" in argv
    assert argv[argv.index("-w") + 1] == "/jobs/app1"
    assert "JOB_NAME=worker" in argv and "TASK_INDEX=0" in argv
    assert "--shm-size=4g" in argv
    assert argv[-4:] == ["gcr.io/proj/train:1", "python3", "-m",
                         "tony_tpu.agent"]


def test_build_docker_command_user_mount_covers_workdir():
    """A user mount of the workdir target must suppress the implicit one —
    docker rejects duplicate mount points."""
    from tony_tpu.coordinator.launcher import build_docker_command

    task = Task(role="worker", index=0)
    argv = build_docker_command(
        task, {}, image="img", mounts=["/jobs/app1:/jobs/app1"],
        workdir="/jobs/app1")
    assert argv.count("/jobs/app1:/jobs/app1") == 1
    assert argv[argv.index("-w") + 1] == "/jobs/app1"


def test_docker_launcher_rejects_missing_image():
    from tony_tpu.coordinator.launcher import DockerLauncher

    with pytest.raises(ValueError):
        DockerLauncher("", on_exit=lambda t, c: None)


FAKE_DOCKER = textwrap.dedent("""\
    #!/bin/bash
    # fake docker CLI: "run" interprets the agent container locally;
    # "kill" is a no-op (the local process group dies via the launcher).
    cmd="$1"; shift
    [ "$cmd" = kill ] && exit 0
    [ "$cmd" = run ] || exit 64
    envs=()
    while [ $# -gt 0 ]; do
      case "$1" in
        --rm|--net=host|--privileged) shift;;
        --name|-v|-w) shift 2;;
        -e) envs+=("$2"); shift 2;;
        *) break;;
      esac
    done
    image="$1"; shift  # drop the image; exec the container command locally
    exec env "${envs[@]}" "$@"
    """)


def fake_docker_bin(tmp_path) -> str:
    path = os.path.join(str(tmp_path), "docker")
    with open(path, "w") as f:
        f.write(FAKE_DOCKER)
    os.chmod(path, os.stat(path).st_mode | stat.S_IEXEC)
    return path


def test_docker_mode_e2e(tmp_path):
    """Gang job where every agent is 'containerized' through the shim."""
    with MiniTonyCluster() as cluster:
        conf = script_conf(cluster, os.path.join(SCRIPTS, "check_env.py"),
                           {"worker": 2})
        conf.set("tony.application.launch-mode", "docker")
        conf.set("tony.docker.image", "tony-test-image")
        conf.set("tony.docker.bin", fake_docker_bin(tmp_path))
        client = cluster.submit(conf)
        assert client.final_status["status"] == "SUCCEEDED", \
            client.final_status


def test_docker_enabled_key_requires_image(tmp_path):
    """Missing image fails fast at coordinator startup (ref: config
    validation in validateAndUpdateConfig)."""
    with MiniTonyCluster() as cluster:
        conf = script_conf(cluster, os.path.join(SCRIPTS, "exit_0.py"),
                           {"worker": 1})
        conf.set("tony.docker.enabled", True)
        client = cluster.make_client(conf)
        with pytest.raises(RuntimeError, match="coordinator exited"):
            client.run()


# -- ssh launch mode ---------------------------------------------------------

FAKE_SSH = os.path.join(SCRIPTS, "fake_ssh.sh")
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _wait_for(pred, timeout=15.0):
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return pred()


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False


def test_ssh_launcher_remote_kill(tmp_path, monkeypatch):
    """kill_task must kill the REMOTE process tree (via the recorded pgid),
    not just the local ssh client — otherwise a resized/retried gang
    overlaps the old one until the agent's coordinator-lost horizon
    (ref analog: NM container kill, ApplicationMaster.java:735-777)."""
    from tony_tpu.coordinator import launcher as L

    monkeypatch.setattr(L, "REMOTE_AGENT_CMD", "sleep 300")
    exits = []
    lch = L.SshLauncher(["fakehost"], on_exit=lambda t, c: exits.append((t, c)),
                        ssh_bin=FAKE_SSH)
    task = Task(role="worker", index=0)
    pgid_file = L.remote_pgid_file(task)
    if os.path.exists(pgid_file):
        os.remove(pgid_file)
    lch.launch(task, {"TONY_TEST": "1"}, os.path.join(str(tmp_path), "w.log"))
    assert _wait_for(lambda: os.path.exists(pgid_file)), "pgid never recorded"
    pid = int(open(pgid_file).read().strip())
    assert _alive(pid)
    assert lch.kill_task(task.id)
    assert _wait_for(lambda: not _alive(pid)), \
        "remote tree survived kill_task"
    assert not os.path.exists(pgid_file)  # kill cleans the pgid file


def test_ssh_launcher_stop_all_kills_remote_trees(tmp_path, monkeypatch):
    from tony_tpu.coordinator import launcher as L

    monkeypatch.setattr(L, "REMOTE_AGENT_CMD", "sleep 300")
    exits = []
    lch = L.SshLauncher(["h1", "h2"], on_exit=lambda t, c: exits.append(t),
                        ssh_bin=FAKE_SSH)
    tasks = [Task(role="worker", index=i) for i in range(2)]
    pids = []
    for t in tasks:
        pgid_file = L.remote_pgid_file(t)
        if os.path.exists(pgid_file):
            os.remove(pgid_file)
        lch.launch(t, {}, os.path.join(str(tmp_path), f"{t.id}.log"))
    for t in tasks:
        pgid_file = L.remote_pgid_file(t)
        assert _wait_for(lambda: os.path.exists(pgid_file))
        pids.append(int(open(pgid_file).read().strip()))
    lch.stop_all()
    for pid in pids:
        assert _wait_for(lambda: not _alive(pid)), \
            f"remote pid {pid} survived stop_all"
    assert exits == []  # teardown exits never reach on_exit


def test_ssh_mode_e2e(tmp_path):
    """Full gang over fake ssh: launch, env contract, clean finish."""
    with MiniTonyCluster() as cluster:
        conf = script_conf(cluster, os.path.join(SCRIPTS, "check_env.py"),
                           {"worker": 2})
        conf.set("tony.application.launch-mode", "ssh")
        conf.set("tony.application.hosts", "hostA,hostB")
        conf.set("tony.application.ssh-bin", FAKE_SSH)
        conf.set("tony.application.remote-pythonpath", REPO_ROOT)
        client = cluster.submit(conf)
        assert client.final_status["status"] == "SUCCEEDED", \
            client.final_status


def test_ssh_launcher_packs_hosts_by_free_chips(tmp_path, monkeypatch):
    """Capacity-aware placement: tasks carrying a chip demand land on the
    host with the most free chips and get disjoint TPU_VISIBLE_DEVICES
    subsets; capacity returns only once the ssh client confirms the
    remote tree is gone (the pod-wide analog of the coordinator-host
    ChipAllocator)."""
    from tony_tpu import constants as C
    from tony_tpu.coordinator import launcher as L

    placements = []

    monkeypatch.setattr(
        L, "REMOTE_AGENT_CMD",
        "sh -c 'echo HOSTENV=$TPU_VISIBLE_DEVICES; sleep 60'")
    lch = L.SshLauncher(["h1", "h2"], on_exit=lambda t, c: None,
                        ssh_bin=FAKE_SSH, chips_per_host=4)
    orig_place = lch._place

    def spy(task, env):
        host, env2 = orig_place(task, env)
        placements.append((task.id, host, env2.get(C.TPU_VISIBLE_DEVICES)))
        return host, env2

    monkeypatch.setattr(lch, "_place", spy)
    tasks = [Task(role="worker", index=i) for i in range(4)]
    for t in tasks:
        lch.launch(t, {C.TASK_CHIPS: "2"},
                   os.path.join(str(tmp_path), f"{t.id}.log"))
    by_host = {}
    for tid, host, vis in placements:
        assert vis is not None
        by_host.setdefault(host, []).append(vis)
    # 4 tasks x 2 chips over 2x4-chip hosts: 2 per host, disjoint pairs
    assert sorted(len(v) for v in by_host.values()) == [2, 2]
    for host, subsets in by_host.items():
        assert sorted(subsets) == ["0,1", "2,3"]
    # a 5th task cannot fit anywhere
    with pytest.raises(RuntimeError, match="chips"):
        lch.launch(Task(role="worker", index=4), {C.TASK_CHIPS: "2"},
                   os.path.join(str(tmp_path), "w4.log"))
    # kill returns capacity only after the local ssh client confirms the
    # exit (deferred release: a timed-out remote kill must not let a
    # relaunch share devices with a live agent)
    assert lch.kill_task("worker:0")
    assert _wait_for(lambda: sum(
        p.free_count for p in lch._pools.values()) == 2), \
        "capacity not returned after confirmed kill"
    host, env2 = orig_place(Task(role="worker", index=5),
                            {C.TASK_CHIPS: "2"})
    assert env2[C.TPU_VISIBLE_DEVICES] in ("0,1", "2,3")
    lch.stop_all()


def test_ssh_packing_e2e(tmp_path):
    """Full job: two 2-chip workers packed onto ONE 4-chip ssh host must
    see disjoint TPU_VISIBLE_DEVICES subsets end-to-end."""
    import glob

    from tony_tpu import constants as C

    payload = os.path.join(str(tmp_path), "check_chips.py")
    with open(payload, "w") as f:
        f.write("import os, sys\n"
                "vis = os.environ.get('TPU_VISIBLE_DEVICES', '')\n"
                "ids = [int(x) for x in vis.split(',') if x]\n"
                "print('TPU_VISIBLE_DEVICES =', vis)\n"
                "sys.exit(0 if len(ids) == 2 else 9)\n")
    with MiniTonyCluster() as cluster:
        conf = script_conf(cluster, payload, {"worker": 2})
        conf.set("tony.application.launch-mode", "ssh")
        conf.set("tony.application.hosts", "hX")
        conf.set("tony.application.ssh-bin", FAKE_SSH)
        conf.set("tony.application.remote-pythonpath", REPO_ROOT)
        conf.set("tony.worker.chips", 2)
        conf.set("tony.tpu.chips-per-host", 4)
        client = cluster.submit(conf)
        assert client.final_status["status"] == "SUCCEEDED", \
            client.final_status
        subsets = []
        for lf in glob.glob(os.path.join(client.job_dir, "logs",
                                         "worker-*.log")):
            for line in open(lf):
                if "TPU_VISIBLE_DEVICES =" in line:
                    subsets.append(line.strip().split("= ")[1])
        assert sorted(subsets) == ["0,1", "2,3"], subsets
