"""Profiler subsystem tests (greenfield vs reference; SURVEY.md §5.1)."""

import glob
import json
import os

import pytest

import jax.numpy as jnp

from tony_tpu.profiler import StepProfiler, trigger_path, write_trigger


def test_trigger_roundtrip(tmp_path):
    path = write_trigger(str(tmp_path), num_steps=3, task_id="worker:1")
    assert path == trigger_path(str(tmp_path), "worker:1")
    with open(path) as f:
        assert json.load(f)["num_steps"] == 3
    # per-task isolation: a different task's poller must not see it
    assert not os.path.exists(trigger_path(str(tmp_path), "worker:0"))


def test_step_profiler_captures_trace(tmp_path):
    prof = StepProfiler(workdir=str(tmp_path), task_id="worker:0")
    assert prof.poll() is False  # idle poll is cheap + false
    write_trigger(str(tmp_path), num_steps=2, task_id="worker:0",
                  logdir=str(tmp_path / "prof"))
    for _ in range(4):
        (jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready()
        prof.poll()
    assert prof.captures == 1
    assert prof.active_steps_left == 0
    # trigger consumed; xplane artifacts written
    assert not os.path.exists(trigger_path(str(tmp_path), "worker:0"))
    artifacts = glob.glob(str(tmp_path / "prof" / "**" / "*"), recursive=True)
    assert any(os.path.isfile(a) for a in artifacts), artifacts


def test_step_profiler_ignores_foreign_trigger(tmp_path):
    prof = StepProfiler(workdir=str(tmp_path), task_id="worker:0")
    write_trigger(str(tmp_path), num_steps=1, task_id="worker:1")
    assert prof.poll() is False
    assert prof.captures == 0


def test_coordinator_command_queue():
    """request_profile -> queued -> drained exactly once on heartbeat."""
    import tempfile

    from tony_tpu.config import TonyConf
    from tony_tpu.coordinator.coordinator import ClientRpcHandler, Coordinator

    conf = TonyConf()
    conf.set("tony.worker.instances", 1)
    conf.set("tony.application.security.enabled", False)
    with tempfile.TemporaryDirectory() as tmp:
        conf.set("tony.staging-dir", tmp)
        conf.set("tony.history.location", os.path.join(tmp, "hist"))
        coord = Coordinator(conf, "application_cmdq", os.path.join(tmp, "job"))
        try:
            handler = ClientRpcHandler(coord)
            assert handler.request_profile("worker:0", 7) is True
            assert handler.request_profile("ghost:9", 1) is False
            resp = handler.task_executor_heartbeat("worker:0")
            assert resp["commands"] == [{"type": "profile", "num_steps": 7}]
            # drained: second heartbeat is empty
            assert handler.task_executor_heartbeat("worker:0")["commands"] == []
        finally:
            coord.rpc.stop()
            coord.metrics_rpc.stop()


# ------------------------------------------------------- xplane parsing


def test_xplane_parse_cpu_trace(tmp_path):
    """On the CPU backend the trace has host planes but no /device: plane
    — the parser must say 'no device data' (None), not crash, so bench
    callers can fall back to wall-clock."""
    import jax

    from tony_tpu.profiler import device_busy_ms, op_totals_ms, xplane

    logdir = str(tmp_path / "trace")
    f = jax.jit(lambda a: a @ a)
    x = jnp.ones((16, 16))
    f(x).block_until_ready()
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    f(x).block_until_ready()
    jax.profiler.stop_trace()

    files = xplane.xplane_files(logdir)
    assert files, "trace wrote no xplane dump"
    space = xplane.load_xspace(files[-1])
    if space is None:  # proto stubs unavailable in this env: degraded mode
        assert op_totals_ms(logdir) is None
        assert device_busy_ms(logdir) is None
        return
    assert [p.name for p in space.planes]  # parsed something real
    # CPU backend -> no TPU device plane -> None (graceful degradation)
    if not xplane.device_planes(space):
        assert device_busy_ms(logdir) is None


def test_trace_device_ms_cpu_returns_none_or_positive():
    import jax

    from tony_tpu.profiler import trace_device_ms

    f = jax.jit(lambda a: (a @ a).sum())
    x = jnp.ones((16, 16))
    f(x).block_until_ready()
    out = trace_device_ms(f, (x,), steps=2)
    assert out is None or out > 0


def test_hbm_estimate_bytes():
    import jax

    from tony_tpu.profiler import hbm_estimate_bytes

    f = jax.jit(lambda a: a @ a)
    x = jnp.ones((64, 64), jnp.float32)
    est = hbm_estimate_bytes(f, x)
    # args (16 KB) + out (16 KB); CPU backends may report nothing (0)
    assert est == 0 or est >= 2 * 64 * 64 * 4


def test_hbm_estimate_bytes_bad_input_is_zero():
    from tony_tpu.profiler import hbm_estimate_bytes

    assert hbm_estimate_bytes(object()) == 0


def _synthetic_two_plane_xspace(tmp_path):
    """Build an XSpace with TWO device planes (a 2-chip trace): plane 0
    runs ops totalling 5 ms, plane 1 totalling 4 ms. Skips when the
    tensorflow proto stubs are unavailable (the parser degrades to None
    there anyway)."""
    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION",
                          "python")
    xplane_pb2 = pytest.importorskip(
        "tensorflow.tsl.profiler.protobuf.xplane_pb2")
    space = xplane_pb2.XSpace()
    per_plane_ms = [(3.0, 2.0), (4.0,)]
    for i, durs in enumerate(per_plane_ms):
        plane = space.planes.add()
        plane.name = f"/device:TPU:{i}"
        meta = plane.event_metadata[1]
        meta.id = 1
        meta.name = f"%fusion.{i} = f32[8]{{0}} fusion(%p0)"
        line = plane.lines.add()
        line.name = "XLA Ops"
        for ms in durs:
            ev = line.events.add()
            ev.metadata_id = 1
            ev.duration_ps = int(ms * 1e9)
    # a host plane rides along and must be ignored
    host = space.planes.add()
    host.name = "/host:CPU"
    logdir = tmp_path / "twoplane"
    os.makedirs(logdir)
    with open(logdir / "x.xplane.pb", "wb") as f:
        f.write(space.SerializeToString())
    return str(logdir)


def test_device_busy_ms_multi_plane_reports_busiest_not_sum(tmp_path):
    """The ADVICE-r5 satellite pin: device_busy_ms on a 2-plane trace
    reports the BUSIEST plane (critical-path chip, comparable to wall
    clock) — the old cross-plane sum over-reported by n_devices."""
    from tony_tpu.profiler import (device_busy_ms, op_totals_ms,
                                   per_plane_op_totals_ms)

    logdir = _synthetic_two_plane_xspace(tmp_path)
    per_plane = per_plane_op_totals_ms(logdir)
    assert set(per_plane) == {"/device:TPU:0", "/device:TPU:1"}
    assert sum(per_plane["/device:TPU:0"].values()) == 5.0
    assert sum(per_plane["/device:TPU:1"].values()) == 4.0
    # busiest plane, NOT the 9 ms cross-chip sum
    assert device_busy_ms(logdir) == 5.0
    # the per-op breakdown view still sums across chips (documented)
    assert sum(op_totals_ms(logdir).values()) == 9.0


# ------------------------------------------------------- ServeProfiler


class _FakeJaxProfiler:
    def __init__(self):
        self.started = []
        self.stopped = 0

    def start_trace(self, logdir):
        self.started.append(logdir)

    def stop_trace(self):
        self.stopped += 1


def test_serve_profiler_request_poll_protocol(tmp_path, monkeypatch):
    """The on-demand serving capture state machine: request(N) arms,
    the first working poll starts the trace, each later poll burns a
    step, the Nth stops it; double-arm is refused while busy."""
    import jax

    from tony_tpu.profiler import ServeProfiler

    fake = _FakeJaxProfiler()
    monkeypatch.setattr(jax, "profiler", fake)
    prof = ServeProfiler(default_logdir=str(tmp_path))
    assert not prof.busy
    prof.poll()  # idle poll: near-free no-op
    assert fake.started == []

    logdir = prof.request(2)
    assert prof.busy and logdir.startswith(str(tmp_path))
    with pytest.raises(RuntimeError, match="already"):
        prof.request(1)  # one global jax profiler session
    prof.poll()  # starts the trace
    assert fake.started == [logdir] and fake.stopped == 0
    prof.poll()  # burns step 1 of 2
    assert fake.stopped == 0 and prof.status()["steps_left"] == 1
    prof.poll()  # burns step 2: capture finishes
    assert fake.stopped == 1
    assert prof.captures == 1 and prof.last_logdir == logdir
    assert not prof.busy
    prof.poll()  # back to the near-free idle path
    assert fake.stopped == 1

    # re-armable after a finished capture; close() finalizes a capture
    # left mid-flight (gateway shutdown)
    prof.request(5)
    prof.poll()   # started
    prof.close()
    assert fake.stopped == 2 and prof.captures == 2
    assert not prof.busy
    with pytest.raises(RuntimeError, match="closed"):
        prof.request(1)  # close() is terminal: the gateway drained


def test_serve_profiler_start_failure_degrades(tmp_path, monkeypatch):
    """A broken profiler must not take the serving loop with it: the
    capture is abandoned with last_error set, polls return to idle."""
    import jax

    from tony_tpu.profiler import ServeProfiler

    class _Broken:
        def start_trace(self, logdir):
            raise RuntimeError("no backend")

    monkeypatch.setattr(jax, "profiler", _Broken())
    prof = ServeProfiler(default_logdir=str(tmp_path))
    prof.request(3)
    prof.poll()
    assert not prof.busy
    assert "no backend" in prof.last_error
    assert prof.captures == 0
    with pytest.raises(ValueError):
        prof.request(0)
