"""Live session migration (ISSUE-18): the zero-copy KV fabric that
moves in-flight streams between replicas token-exact.

The exactness discipline is the house rule: every migrated stream is
pinned BYTE-IDENTICAL to a no-migration control on a fresh engine —
greedy and seeded sampling, speculation live — because a freeze
captures the rng chain mid-flight and the adopting engine resumes it
at the exact position. The structural claims ride deterministic
counters: a shared-pool owner swap moves ZERO pages (bytes_avoided
grows instead), a cross-host migration ships real pages over the
wire (pages_moved grows), a retiring replica's out-side ledger
survives its own departure via the gateway carry, and the page pool
conserves refcounts (n_used == 0 after drain, always).

The failure half of the contract: a migrated payload is ONE-SHOT —
consumed at admit — so a SIGKILL on the adopting host afterwards
degrades to the ordinary crash path (re-run from the prompt), which
determinism makes token-exact too. Zero 5xx throughout.

Tiny reference-attention model, CPU-only; engines are throttled with
a wedge fault (30 ms per dispatch, token-exact preserved) so the
mid-stream windows the tests need actually exist on a model this
small.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.gateway.core import Gateway, GenRequest
from tony_tpu.models import Transformer, TransformerConfig
from tony_tpu.serve import Request, Server
from tony_tpu.serve.faults import FaultPlan
from tony_tpu.serve.slots import PagePool

pytestmark = pytest.mark.filterwarnings("ignore::ResourceWarning")


@pytest.fixture(scope="module")
def tiny():
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=64,
                            dtype=jnp.float32,
                            attention_backend="reference")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _prompt(seed=3, n=13):
    return np.random.default_rng(seed).integers(1, 64, size=n).tolist()


def _slow():
    # 30 ms per dispatch: a 40-token stream stays in flight ~1.2 s,
    # wide enough to freeze mid-stream deterministically
    return FaultPlan.wedge_at(1, 0.03, times=-1)


def _mk(tiny, **kw):
    model, params = tiny
    kw.setdefault("prefix_cache_mb", 0)
    kw.setdefault("batch_size", 2)
    return Server(model, params, eos_id=-1, paged=True,
                  kv_page_size=8, **kw)


def _control(tiny, prompt, budget, *, temperature=0.0, top_k=0,
             seed=0, **server_kw):
    """No-migration control on a fresh single engine."""
    srv = _mk(tiny, **server_kw)
    srv.submit(Request(list(prompt), budget, id="c",
                       temperature=temperature, top_k=top_k, seed=seed))
    return list(srv.run())[0].tokens


def _wait(cond, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def _wait_emitted(t, n, timeout=30.0):
    _wait(lambda: t._n_emitted >= n, timeout,
          f"{n} tokens emitted (got {t._n_emitted})")


# ------------------------------------------------- local owner swap


@pytest.mark.parametrize("temperature,top_k,seed",
                         [(0.0, 0, 0), (0.8, 8, 7)])
def test_remove_replica_migrates_mid_stream_token_exact(
        tiny, temperature, top_k, seed):
    """THE local anchor: two replicas lease one shared PagePool;
    remove_replica mid-stream freezes the live session and the
    survivor adopts it by OWNER SWAP — zero pages copied, tokens
    byte-identical to the no-migration control, both greedy and
    seeded (the rng chain migrates at its exact position). The trace
    carries the migrate fence between the two attempt spans, and the
    pool refcounts conserve to zero after drain."""
    model, params = tiny
    prompt, budget = _prompt(), 40
    expect = _control(tiny, prompt, budget, temperature=temperature,
                      top_k=top_k, seed=seed)
    pool = PagePool(model, params, 128, 8, shared=True)
    gw = Gateway([_mk(tiny, page_pool=pool, fault_plan=_slow()),
                  _mk(tiny, page_pool=pool, fault_plan=_slow())]).start()
    try:
        t = gw.submit(GenRequest(list(prompt), max_new_tokens=budget,
                                 temperature=temperature, top_k=top_k,
                                 seed=seed, id="mig"))
        _wait_emitted(t, 3)
        src = t.replica
        assert src is not None
        assert gw.remove_replica(src, timeout=60)
        res = t.result(timeout=120)
        assert list(res.tokens) == list(expect)
        snap = gw.snapshot()
        assert snap["shed"] == {}  # zero 5xx
        assert snap["routing"]["migrations"] >= 1
        mig = snap["engine"]["migrations"]
        # out-side counters survived the source's retirement (carry)
        assert mig["out"] >= 1 and mig["in"] >= 1
        # owner swap: both sides count local, nothing crosses a wire
        assert mig["local"] >= 2 and mig["remote"] == 0
        assert mig["pages_moved"] == 0
        assert mig["bytes_avoided"] > 0
        assert mig["freeze_resume_ms"] >= 0
        # ONE trace spans the handover: attempt on the source ends
        # with the migrate fence, attempt on the survivor follows
        tr = gw.traces.get("mig")
        assert tr is not None and tr.n_attempts >= 2
        names = {e.get("name")
                 for e in tr.to_chrome().get("traceEvents", [])}
        assert "migrate" in names, names
    finally:
        assert gw.drain(timeout=60)
    assert pool.n_used == 0
    assert (np.asarray(pool.refcount) >= 0).all()


def test_migration_with_speculation_live_token_exact(tiny):
    """Speculation survives the freeze: the snapshot carries the
    draft-acceptance EMA and the adopting engine keeps speculating —
    output still byte-identical to a speculating control. Greedy with
    a repetitive prompt: prompt-lookup drafting only arms on greedy
    requests, and the repeated n-gram guarantees proposals fire."""
    prompt, budget = [1, 2, 3] * 4 + [1, 2], 40
    model, params = tiny
    expect = _control(tiny, prompt, budget, speculate_k=2)
    pool = PagePool(model, params, 128, 8, shared=True)
    gw = Gateway([_mk(tiny, page_pool=pool, fault_plan=_slow(),
                      speculate_k=2),
                  _mk(tiny, page_pool=pool, fault_plan=_slow(),
                      speculate_k=2)]).start()
    try:
        t = gw.submit(GenRequest(list(prompt), max_new_tokens=budget,
                                 id="spec"))
        _wait_emitted(t, 3)
        assert gw.remove_replica(t.replica, timeout=60)
        res = t.result(timeout=120)
        assert list(res.tokens) == list(expect)
        snap = gw.snapshot()
        assert snap["shed"] == {}
        assert snap["engine"]["migrations"]["out"] >= 1
        # the adopter actually speculated after the handover
        assert snap["engine"]["spec"]["rounds"] >= 1
    finally:
        assert gw.drain(timeout=60)
    assert pool.n_used == 0


# ------------------------------------------------- cross-host wire


def _start_agent(tiny, **server_kw):
    from tony_tpu.serve.agent import AgentHTTP, ReplicaAgent

    return AgentHTTP(ReplicaAgent(_mk(tiny, **server_kw))).start()


def _stub(address, **kw):
    from tony_tpu.gateway.remote import RemoteServer

    kw.setdefault("heartbeat_interval_s", 0.1)
    kw.setdefault("lease_misses", 3)
    kw.setdefault("boot_timeout_s", 20.0)
    return RemoteServer(address, **kw)


def test_cross_host_migration_token_exact(tiny):
    """The wire anchor: one local replica, one remote agent. Removing
    whichever replica holds the stream ships the session to the other
    side of the wire — gathered pages travel as the codec's bitwise
    wire form (pages_moved > 0; this direction has no shared pool to
    swap within) and the stream stays byte-identical to the
    control."""
    prompt, budget = _prompt(), 40
    expect = _control(tiny, prompt, budget)
    http = _start_agent(tiny, fault_plan=_slow(), prefix_cache_mb=4)
    gw = Gateway([_mk(tiny, fault_plan=_slow()),
                  _stub(http.address)]).start()
    try:
        t = gw.submit(GenRequest(list(prompt), max_new_tokens=budget,
                                 id="wire"))
        _wait_emitted(t, 3)
        assert gw.remove_replica(t.replica, timeout=60)
        res = t.result(timeout=120)
        assert list(res.tokens) == list(expect)
        assert gw.snapshot()["shed"] == {}

        def _settled():
            m = gw.snapshot()["engine"]["migrations"]
            return m["out"] >= 1 and m["in"] >= 1 \
                and m["pages_moved"] >= 1
        # remote counters ride the next heartbeat; don't race it
        _wait(_settled, msg="migration counters settled")
        mig = gw.snapshot()["engine"]["migrations"]
        assert mig["remote"] >= 1
    finally:
        assert gw.drain(timeout=60)
        http.stop()


def test_sigkill_after_migration_falls_back_to_rerun(tiny):
    """The failure half of the one-shot payload contract: migrate a
    stream between two REMOTE replicas, then SIGKILL the adopter (as
    the network sees it). The payload was consumed at admit, so
    failover re-runs the request from its prompt on the survivor —
    greedy determinism makes even the re-run token-exact, and no
    client ever sees a 5xx."""
    prompt, budget = _prompt(9), 40
    expect = _control(tiny, prompt, budget)
    # 100 ms wedge (vs _slow's 30): the remote-to-remote migration
    # dance (probe, extract through a first-time XLA gather compile,
    # ship, adopt) costs 1-2 s on a starved 1-core host, and the
    # stream must still have tokens LEFT afterwards for the kill to
    # land on a live migrated session.
    agents = [_start_agent(tiny,
                           fault_plan=FaultPlan.wedge_at(1, 0.1,
                                                         times=-1))
              for _ in range(2)]
    # lease_misses=30 (3 s lease): the wire extract holds the agent's
    # dispatch lock through that same compile stall, which can outlive
    # the default 0.3 s lease — expiring the SOURCE mid-migration and
    # turning the test into a different (crash-path) scenario than the
    # one under test. The kill half only needs expiry to happen at
    # all, not fast.
    gw = Gateway([_stub(a.address, lease_misses=30) for a in agents],
                 stall_timeout_s=10.0, breaker_base_s=0.05,
                 breaker_max_s=0.25).start()
    try:
        t = gw.submit(GenRequest(list(prompt), max_new_tokens=budget,
                                 id="chaos"))
        _wait_emitted(t, 3)
        src = t.replica
        assert gw.migrate_session("chaos") is True
        _wait(lambda: t.replica is not None and t.replica != src,
              msg="stream adopted by the other replica")
        target = t.replica
        # let the adopter stream a few tokens so the kill lands on a
        # LIVE migrated session, then drop it off the network
        n_now = t._n_emitted
        _wait_emitted(t, n_now + 2)
        agents[target].kill()
        res = t.result(timeout=180)
        assert list(res.tokens) == list(expect)
        snap = gw.snapshot()
        assert snap["shed"] == {}  # zero 5xx
        assert snap["supervision"]["failovers"] >= 1
    finally:
        gw.drain(timeout=60)
        for a in agents:
            a.stop()


# ---------------------------------------------- rebalance + affinity


def test_migrate_session_rebalances_token_exact(tiny):
    """The operator-driven flavor: migrate_session moves a live
    stream with NO retirement — the source keeps serving — and an
    unknown request id reports False instead of raising."""
    model, params = tiny
    prompt, budget = _prompt(), 40
    expect = _control(tiny, prompt, budget, temperature=0.6, top_k=4,
                      seed=5)
    pool = PagePool(model, params, 128, 8, shared=True)
    gw = Gateway([_mk(tiny, page_pool=pool, fault_plan=_slow()),
                  _mk(tiny, page_pool=pool, fault_plan=_slow())]).start()
    try:
        t = gw.submit(GenRequest(list(prompt), max_new_tokens=budget,
                                 temperature=0.6, top_k=4, seed=5,
                                 id="reb"))
        _wait_emitted(t, 3)
        src = t.replica
        assert gw.migrate_session("reb") is True
        res = t.result(timeout=120)
        assert list(res.tokens) == list(expect)
        assert t.replica != src
        assert gw.migrate_session("nope") is False
        assert gw.snapshot()["shed"] == {}
    finally:
        assert gw.drain(timeout=60)
    assert pool.n_used == 0


def test_kill_between_freeze_and_ship_adopts_leased_snapshot(tiny):
    """The extract-vs-steal lease (this PR): the source replica dies
    WHILE the migrate extract is in flight — the old behavior
    abandoned the frozen snapshot and re-ran the victim from its
    prompt even when the freeze completed a moment later. With the
    lease, failover waits for the in-flight extract and ADOPTS the
    completed snapshot: the stream resumes token-exact with no
    recompute, and migrate_lease_adoptions proves the path taken."""
    import threading

    prompt, budget = _prompt(11), 40
    expect = _control(tiny, prompt, budget)
    srv0 = _mk(tiny, fault_plan=_slow())
    gw = Gateway([srv0, _mk(tiny, fault_plan=_slow())]).start()
    froze = threading.Event()   # the real extract finished
    release = threading.Event()  # let the wrapper return the snap
    real_extract = srv0.extract_session

    def held_extract(engine_id, wire=True):
        snap = real_extract(engine_id, wire=wire)
        froze.set()
        # the kill window: the snapshot exists but has not shipped —
        # the test fails the source here, then lets us return
        assert release.wait(20.0), "test release never arrived"
        return snap

    srv0.extract_session = held_extract
    try:
        t = gw.submit(GenRequest(list(prompt), max_new_tokens=budget,
                                 id="lease"))
        _wait_emitted(t, 3)
        r0 = gw.replicas[t.replica]
        epoch = r0.epoch
        mover = threading.Thread(
            target=lambda: gw.migrate_session("lease"), daemon=True)
        mover.start()
        assert froze.wait(30.0), "extract never froze the session"
        # SIGKILL-as-the-gateway-sees-it, mid-extract: the steal runs
        # on its own thread (like the watchdog) and its _failover
        # blocks inside the lease claim until the extract completes
        killer = threading.Thread(
            target=lambda: gw._fail_replica(
                r0, epoch, "test: source died mid-extract"),
            daemon=True)
        killer.start()
        _wait(lambda: not gw._snap_leases, msg="failover claimed the "
                                               "in-flight lease")
        release.set()
        mover.join(30.0)
        killer.join(30.0)
        res = t.result(timeout=120)
        assert list(res.tokens) == list(expect)
        snap = gw.snapshot()
        assert snap["shed"] == {}  # zero 5xx
        assert snap["routing"]["migrate_lease_adoptions"] == 1
        # adopted, not recomputed: the survivor resumed mid-stream
        # (its engine counted a migrate-in), and the whole fleet never
        # re-prefilled the prompt a second time
        assert snap["engine"]["migrations"]["in"] >= 1
    finally:
        srv0.extract_session = real_extract
        gw.drain(timeout=60)


def test_lease_expiry_falls_back_to_rerun(tiny):
    """The lease's other half: an extract that NEVER completes (agent
    truly dead) must not wedge failover — the claim times out after
    migrate_lease_s, the ticket re-runs from its prompt (token-exact
    by determinism), and the late snapshot is released by the
    abandoned flag, not leaked."""
    import threading

    prompt, budget = _prompt(12), 40
    expect = _control(tiny, prompt, budget)
    srv0 = _mk(tiny, fault_plan=_slow())
    gw = Gateway([srv0, _mk(tiny, fault_plan=_slow())]).start()
    gw.migrate_lease_s = 0.2  # keep the test fast
    froze = threading.Event()
    release = threading.Event()
    real_extract = srv0.extract_session

    def wedged_extract(engine_id, wire=True):
        snap = real_extract(engine_id, wire=wire)
        froze.set()
        release.wait(20.0)  # holds well past the 0.2 s lease
        return snap

    srv0.extract_session = wedged_extract
    try:
        t = gw.submit(GenRequest(list(prompt), max_new_tokens=budget,
                                 id="wedge"))
        _wait_emitted(t, 3)
        r0 = gw.replicas[t.replica]
        epoch = r0.epoch
        mover = threading.Thread(
            target=lambda: gw.migrate_session("wedge"), daemon=True)
        mover.start()
        assert froze.wait(30.0), "extract never froze the session"
        gw._fail_replica(r0, epoch, "test: extract wedged")  # blocks
        # ~migrate_lease_s, then gives up and requeues crash-path
        release.set()  # the late snapshot arrives AFTER abandonment
        mover.join(30.0)
        res = t.result(timeout=120)
        assert list(res.tokens) == list(expect)
        snap = gw.snapshot()
        assert snap["shed"] == {}
        assert snap["routing"]["migrate_lease_adoptions"] == 0
        assert snap["supervision"]["failovers"] >= 1
        assert not gw._snap_leases  # nothing leaked on either path
    finally:
        srv0.extract_session = real_extract
        gw.drain(timeout=60)


# ------------------------------------------- prefix-delta migration


@pytest.fixture(scope="module", params=[False, True],
                ids=["f32kv", "int8kv"])
def kvmodel(request):
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=64,
                            dtype=jnp.float32,
                            attention_backend="reference",
                            kv_cache_quant=request.param)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _freeze_wire(srv, prompt, budget, rid="src", min_gen=4):
    """Run ``prompt`` on ``srv`` until at least ``min_gen`` tokens are
    live, then freeze + evict it as a WIRE snapshot (page content, not
    ids) — the in-process stand-in for a source replica mid-stream."""
    srv.submit(Request(list(prompt), budget, id=rid))
    for _ in range(600):
        srv.step()
        lv = next((l for l in srv._live
                   if l is not None and l.request.id == rid), None)
        if lv is not None and len(lv.generated) >= min_gen:
            break
    else:
        raise AssertionError("source stream never reached min_gen")
    snap = srv.extract_session(rid, wire=True)
    assert snap is not None
    return snap


def _warm(srv, tokens):
    """Put ``tokens``' KV into ``srv``'s prefix store (run + donate)."""
    srv.submit(Request(list(tokens), 1, id=f"warm{len(tokens)}"))
    list(srv.run())


def _wire_pages(payload):
    for d in payload["leaves"]:
        ax = d.get("page_axis")
        if ax is not None:
            return int(d["shape"][int(ax)])
    return 0


@pytest.mark.parametrize("scenario",
                         ["exact", "partial", "nomatch", "stale"])
def test_delta_migration_matrix(kvmodel, scenario):
    """The delta-trim contract cell by cell, f32 and int8-KV pages:

    - exact:   target store covers the whole context -> only the
               final (always-shipped) page crosses; the adopter
               refcount-shares its own store pages for the prefix.
    - partial: target covers a shorter prefix -> exactly the
               uncovered suffix ships.
    - nomatch: cold target summary -> the trim declines (None) and
               the full payload ships, delta counters untouched.
    - stale:   the summary CLAIMS coverage the target no longer has
               -> submit refuses with StaleDelta (no pin leaked) and
               the full-payload re-ship lands token-exact.

    Every cell's resumed stream is byte-identical to the no-migration
    control, and ``migrate_bytes_wire`` counts exactly the shipped
    pages."""
    from tony_tpu.serve.migrate import StaleDelta, delta_trim_doc, \
        snapshot_to_doc
    from tony_tpu.serve.prefix import summary_match_len
    from tony_tpu.serve.tier import payload_nbytes

    prompt, budget = _prompt(11, 21), 12
    expect = _control(kvmodel, prompt, budget)
    src = _mk(kvmodel)
    snap = _freeze_wire(src, prompt, budget)
    doc = snapshot_to_doc(snap)
    ctx = [int(t) for t in snap.prompt] \
        + [int(t) for t in snap.generated][:-1]
    ps = src.slots.pool.page_size
    n = -(-int(doc["n_tokens"]) // ps)
    assert n >= 3  # the matrix needs room between exact and partial

    tgt = _mk(kvmodel, prefix_cache_mb=2.0)
    if scenario == "exact":
        _warm(tgt, ctx)
    elif scenario == "partial":
        _warm(tgt, ctx[:2 * ps])
    summary = tgt.prefix_summary()
    if scenario == "stale":
        # an honest summary from a DIFFERENT warm engine: it claims
        # coverage the actual target does not hold
        helper = _mk(kvmodel, prefix_cache_mb=2.0)
        _warm(helper, ctx)
        summary = helper.prefix_summary()
    trimmed = delta_trim_doc(doc, summary)

    if scenario == "nomatch":
        assert trimmed is None
        send = doc
    else:
        assert trimmed is not None
        covered = summary_match_len(summary, ctx)
        k = min(covered // ps, n - 1)
        assert trimmed["delta"]["prefix_tokens"] == k * ps
        assert _wire_pages(trimmed["pages"]) == n - k
        if scenario == "exact":
            assert k == n - 1          # only the tail page ships
        elif scenario == "partial":
            assert k == 2 and k < n - 1
        assert payload_nbytes(trimmed["pages"]) \
            < payload_nbytes(doc["pages"])
        send = trimmed

    if scenario == "stale":
        with pytest.raises(StaleDelta):
            tgt.submit(Request(list(prompt), budget, id="adopt",
                               migrate=send))
        assert not tgt._migrate_pins  # the refusal released its pin
        send = doc                    # the sender's contracted retry

    tgt.submit(Request(list(prompt), budget, id="adopt", migrate=send))
    res = {r.id: r for r in tgt.run()}["adopt"]
    assert list(res.tokens) == list(expect)
    nb = tgt.slots.pool.page_nbytes
    if scenario in ("exact", "partial"):
        assert tgt.migrate_delta_in == 1
        assert tgt.migrate_bytes_wire == (n - k) * nb
        assert tgt.migrate_bytes_avoided >= k * nb
    else:
        assert tgt.migrate_delta_in == 0
        assert tgt.migrate_bytes_wire == n * nb
    assert tgt.migrations_in == 1 and tgt.migrations_remote == 1
    assert not tgt._migrate_pins


def test_remote_delta_migration_ships_suffix_only(tiny):
    """The wire half of the tentpole: the gateway's RemoteServer stub
    trims the migrate doc against the target agent's heartbeat radix
    summary, so a migration into a warm remote ships only the
    uncovered suffix pages — token-exact, with the trim visible in the
    stub's ``migrate_delta_trims`` and the agent engine's
    ``delta_in``/``bytes_avoided`` counters riding the next
    heartbeat."""
    prompt, budget = _prompt(), 24
    expect = _control(tiny, prompt, budget)
    http = _start_agent(tiny, prefix_cache_mb=2.0, fault_plan=_slow())
    stub = _stub(http.address)
    # affinity off: it would route the live stream straight onto the
    # warm remote, and the point is to MIGRATE into it over the wire
    gw = Gateway([_mk(tiny, fault_plan=_slow()), stub],
                 prefix_affinity=False).start()
    try:
        # warm the REMOTE with the stream's eventual full context
        # (greedy determinism makes it knowable in advance), then let
        # a heartbeat ship the summary that proves it
        gw.replicas[0].outstanding = 500
        gw.submit(GenRequest(list(prompt) + list(expect), 1,
                             id="warm")).result(timeout=300)
        gw.replicas[0].outstanding = 0
        _wait(lambda: stub.prefix_match_len(list(prompt)) >= 8,
              msg="heartbeat shipped the radix summary")
        # pin the live stream on the LOCAL replica
        gw.replicas[1].outstanding = 500
        t = gw.submit(GenRequest(list(prompt), max_new_tokens=budget,
                                 id="d"))
        _wait_emitted(t, 3)
        gw.replicas[1].outstanding = 0
        assert gw.migrate_session("d") is True
        res = t.result(timeout=120)
        assert list(res.tokens) == list(expect)
        assert gw.snapshot()["shed"] == {}
        assert stub.migrate_delta_trims >= 1
        assert stub.migrate_delta_fallbacks == 0

        def _settled():
            m = gw.snapshot()["engine"]["migrations"]
            return m["delta_in"] >= 1 and m["bytes_wire"] > 0
        _wait(_settled, msg="delta counters settled")
        m = gw.snapshot()["engine"]["migrations"]
        assert m["bytes_avoided"] > 0  # the prefix never crossed
    finally:
        gw.drain(timeout=60)
        http.stop()


def test_remote_delta_stale_summary_falls_back_full(tiny):
    """The fallback half: a stale summary makes the adopter refuse
    with kind=StaleDelta and the stub re-ships the FULL payload
    exactly once — the stream stays token-exact, and the episode is
    visible as one trim + one fallback."""
    from tony_tpu.gateway.remote import RemoteServer

    class _ForcedSummary(RemoteServer):
        # heartbeats cannot clear the forced summary: the staleness
        # window stays open for as long as the test needs it
        @property
        def _prefix_summary(self):
            return getattr(self, "_forced", [])

        @_prefix_summary.setter
        def _prefix_summary(self, value):
            pass

    prompt, budget = _prompt(9), 24
    expect = _control(tiny, prompt, budget)
    # the agent's store is ENABLED but cold; the forced summary is an
    # honest one from a different warm engine
    helper = _mk(tiny, prefix_cache_mb=2.0)
    _warm(helper, list(prompt) + list(expect))
    http = _start_agent(tiny, prefix_cache_mb=2.0, fault_plan=_slow())
    stub = _ForcedSummary(http.address, heartbeat_interval_s=0.1,
                          lease_misses=3, boot_timeout_s=20.0)
    gw = Gateway([_mk(tiny, fault_plan=_slow()), stub],
                 prefix_affinity=False).start()
    try:
        stub._forced = helper.prefix_summary()
        gw.replicas[1].outstanding = 500
        t = gw.submit(GenRequest(list(prompt), max_new_tokens=budget,
                                 id="d"))
        _wait_emitted(t, 3)
        gw.replicas[1].outstanding = 0
        assert gw.migrate_session("d") is True
        res = t.result(timeout=120)
        assert list(res.tokens) == list(expect)
        assert gw.snapshot()["shed"] == {}  # the fallback is silent
        assert stub.migrate_delta_trims == 1
        assert stub.migrate_delta_fallbacks == 1
    finally:
        gw.drain(timeout=60)
        http.stop()


def test_remote_prefix_affinity_via_heartbeat_summary(tiny):
    """Satellite: a REMOTE replica's warmth is visible to the
    prefix-affinity router through the bounded radix summary its
    agent ships on every heartbeat — the warm remote wins the probe
    over a cold local even when least-outstanding points the other
    way."""
    base = list(range(1, 21))
    http = _start_agent(tiny, prefix_cache_mb=2.0)
    stub = _stub(http.address)
    gw = Gateway([stub, _mk(tiny, prefix_cache_mb=2.0)],
                 prefix_affinity=True).start()
    try:
        # pin the warm-up on the remote, then let a heartbeat ship
        # the summary that proves it holds the prefix
        gw.replicas[1].outstanding = 500
        gw.submit(GenRequest(list(base), 4,
                             id="warm")).result(timeout=300)
        gw.replicas[1].outstanding = 0
        _wait(lambda: stub.prefix_match_len(base) >= len(base),
              msg="heartbeat shipped the radix summary")
        # skew load so least-outstanding prefers the cold local
        gw.replicas[0].outstanding = 500
        t = gw.submit(GenRequest(list(base) + [7, 8], 4, id="probe"))
        t.result(timeout=300)
        assert t.metrics["replica"] == 0
        assert gw.snapshot()["routing"]["prefix_routed"] >= 1
    finally:
        gw.replicas[0].outstanding = 0
        gw.drain(timeout=60)
        http.stop()
