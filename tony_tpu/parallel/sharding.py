"""Sharding presets: logical-axis rules -> PartitionSpecs for model states.

The framework's models annotate arrays with *logical* axis names
("batch", "seq", "embed", "heads", "kv_heads", "mlp", "vocab", "expert",
"layers"); "kv_heads" is the GQA-shrunk K/V head dim — always replicated,
since its size (n_kv_heads) is typically smaller than the tensor axis;
a preset maps logical names to mesh axes. This is the pjit idiom: the same
model runs DP, FSDP, TP, or combinations by swapping the rule set, and XLA
inserts the collectives (no NCCL-style explicit comms as in the reference's
delegated data plane, SURVEY.md section 2.5).
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tony_tpu.parallel.mesh import DATA, EXPERT, FSDP, PIPE, SEQ, TENSOR

# logical axis -> mesh axis (or None = replicated) per strategy
RULES: dict[str, dict[str, Any]] = {
    # pure data parallelism: params replicated, batch sharded
    "dp": {
        "batch": (DATA, FSDP),
        "seq": None, "embed": None, "heads": None, "kv": None, "kv_heads": None,
        "mlp": None, "vocab": None, "expert": None, "layers": None,
    },
    # fsdp: params sharded on the fsdp axis along their largest dim
    "fsdp": {
        "batch": (DATA, FSDP),
        "embed": FSDP,
        "seq": None, "heads": None, "kv": None, "kv_heads": None, "mlp": None,
        "vocab": None, "expert": None, "layers": None,
    },
    # tensor parallelism (megatron-style): heads + mlp sharded
    "tp": {
        "batch": (DATA, FSDP),
        "heads": TENSOR, "mlp": TENSOR, "vocab": TENSOR,
        "seq": None, "embed": None, "kv": None, "kv_heads": None, "expert": None, "layers": None,
    },
    # fsdp + tp combined (the common large-model preset)
    "fsdp_tp": {
        "batch": (DATA, FSDP),
        "embed": FSDP, "heads": TENSOR, "mlp": TENSOR, "vocab": TENSOR,
        "seq": None, "kv": None, "kv_heads": None, "expert": None, "layers": None,
    },
    # sequence/context parallelism: activations sharded along seq
    "sp": {
        "batch": (DATA, FSDP),
        "act_seq": SEQ,
        "seq": None, "embed": None, "heads": None, "kv": None, "kv_heads": None,
        "mlp": None, "vocab": None, "expert": None, "layers": None,
    },
    # expert parallelism for MoE blocks
    "ep": {
        "batch": (DATA, FSDP),
        "expert": EXPERT,
        "seq": None, "embed": None, "heads": None, "kv": None, "kv_heads": None,
        "mlp": None, "vocab": None, "layers": None,
    },
    # expert + tensor combined (large MoE: experts over the expert axis,
    # each expert's ffn dim + attention heads over tensor, batch over data)
    "ep_tp": {
        "batch": (DATA, FSDP),
        "expert": EXPERT, "heads": TENSOR, "mlp": TENSOR, "vocab": TENSOR,
        "seq": None, "embed": None, "kv": None, "kv_heads": None,
        "layers": None,
    },
    # pipeline: layers sharded across stages (used with parallel.pipeline)
    "pp": {
        "batch": (DATA, FSDP),
        "layers": PIPE,
        "seq": None, "embed": None, "heads": None, "kv": None, "kv_heads": None,
        "mlp": None, "vocab": None, "expert": None,
    },
}


def spec_for(logical_axes: tuple[str | None, ...], rules: dict[str, Any]) -> P:
    """PartitionSpec from per-dimension logical names."""
    parts = []
    for name in logical_axes:
        if name is None:
            parts.append(None)
        else:
            parts.append(rules.get(name))
    # trailing Nones can be dropped but keeping them is harmless
    return P(*parts)


def tree_shardings(mesh: Mesh, logical_tree: Any, preset: str) -> Any:
    """Map a pytree of logical-axis tuples to NamedShardings."""
    rules = RULES[preset]
    return jax.tree.map(
        lambda axes: NamedSharding(mesh, spec_for(axes, rules)),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple),
    )


def shard_params_by_size(mesh: Mesh, params: Any, axis: str = FSDP,
                         min_size: int = 2**14) -> Any:
    """Heuristic FSDP sharding for arbitrary param trees (when a model has
    no logical annotations): shard each large array along its largest
    dimension divisible by the axis size; replicate the rest."""
    n = mesh.shape.get(axis, 1)

    def spec(x):
        if n <= 1 or x.size < min_size:
            return NamedSharding(mesh, P())
        dims = sorted(range(x.ndim), key=lambda d: -x.shape[d])
        for d in dims:
            if x.shape[d] % n == 0:
                parts: list = [None] * x.ndim
                parts[d] = axis
                return NamedSharding(mesh, P(*parts))
        return NamedSharding(mesh, P())

    return jax.tree.map(spec, params)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Inputs: batch dim sharded over (data, fsdp)."""
    axes = tuple(a for a in (DATA, FSDP) if mesh.shape.get(a, 1) > 1)
    return NamedSharding(mesh, P(axes if axes else None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
