"""Layered job configuration with regex-driven per-role keys.

Reference precedence (TonyClient.java:657-691, SURVEY.md section 5.6),
low -> high:
  bundled defaults -> user conf file (tony.toml/json via --conf_file)
  -> repeated --conf k=v CLI overrides -> site file $TONY_CONF_DIR/tony-site.*

The merged conf is serialized to ``tony-final.json`` by the client and
re-read verbatim by the coordinator and agents (ref: tony-final.xml,
TonyClient.java:303-310 / ApplicationMaster.java:230 / TaskExecutor.java:257).
"""

from __future__ import annotations

import json
import os
import re

try:
    import tomllib
except ModuleNotFoundError:  # python < 3.11: tomli IS tomllib upstream
    import tomli as tomllib
from typing import Any, Iterable

from tony_tpu.config import keys as K

ROLE_KEY_RE = re.compile(
    r"^tony\.(?P<role>[A-Za-z0-9_\-]+)\.(?P<suffix>"
    + "|".join(re.escape(s) for s in K.ROLE_SUFFIXES)
    + r")$"
)

# Reserved namespaces that must not be parsed as role names by the regex
# (reference excludes tony.application.* etc. the same way).
_NON_ROLE_SEGMENTS = frozenset(
    {
        "application",
        "coordinator",
        "task",
        "history",
        "portal",
        "client",
        "staging-dir",
        "keytab",
        "tpu",
        "test",
        "horovod",
    }
)


def role_key(role: str, suffix: str) -> str:
    if suffix not in K.ROLE_SUFFIXES:
        raise KeyError(f"unknown role key suffix: {suffix}")
    return f"tony.{role}.{suffix}"


class ConfError(ValueError):
    pass


class TonyConf:
    """A flat, typed key/value job config (Hadoop-Configuration equivalent)."""

    def __init__(self, values: dict[str, Any] | None = None, load_defaults: bool = True):
        self._values: dict[str, Any] = K.defaults() if load_defaults else {}
        if values:
            for k, v in values.items():
                self.set(k, v)

    # -- core accessors -----------------------------------------------------
    def set(self, key: str, value: Any) -> None:
        self._values[key] = self._coerce(key, value)

    def append(self, key: str, value: str) -> None:
        """Append to a comma-joined multi-value key (ref: MULTI_VALUE_CONF)."""
        cur = str(self._values.get(key, "") or "")
        self._values[key] = f"{cur},{value}" if cur else value

    def get(self, key: str, default: Any = None) -> Any:
        if key in self._values:
            return self._values[key]
        m = ROLE_KEY_RE.match(key)
        if m and m.group("role") not in _NON_ROLE_SEGMENTS:
            return K.ROLE_SUFFIXES[m.group("suffix")].default
        return default

    def get_int(self, key: str, default: int = 0) -> int:
        v = self.get(key, default)
        return int(v) if v is not None and v != "" else default

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self.get(key, default)
        if isinstance(v, bool):
            return v
        return str(v).strip().lower() in ("true", "1", "yes")

    def get_list(self, key: str) -> list[str]:
        v = self.get(key, "")
        return [s.strip() for s in str(v).split(",") if s.strip()]

    def items(self) -> Iterable[tuple[str, Any]]:
        return self._values.items()

    def __contains__(self, key: str) -> bool:
        return key in self._values

    # -- typing -------------------------------------------------------------
    @staticmethod
    def _coerce(key: str, value: Any) -> Any:
        spec = K.KEYS.get(key)
        if spec is None:
            m = ROLE_KEY_RE.match(key)
            if m and m.group("role") not in _NON_ROLE_SEGMENTS:
                spec = K.ROLE_SUFFIXES[m.group("suffix")]
        if spec is None:
            return value  # unknown keys pass through untyped (Hadoop semantics)
        t = spec.type
        if t is bool and not isinstance(value, bool):
            return str(value).strip().lower() in ("true", "1", "yes")
        if t is int and not isinstance(value, int):
            try:
                return int(str(value).strip())
            except ValueError:
                # a typo'd numeric in a conf file must fail as a typed,
                # key-naming ConfError — "invalid literal for int()"
                # with no key is useless to an operator (and the
                # provisioner/autoscaler paths log exceptions verbatim)
                raise ConfError(
                    f"{key} must be an integer, got {value!r}") from None
        if t is str:
            return str(value)
        return value

    # -- roles --------------------------------------------------------------
    def roles(self) -> list[str]:
        """All role names with instances configured, in config order.

        Reference: Utils.getAllJobTypes regex scan (util/Utils.java:451) over
        ``tony.<role>.instances``.
        """
        out: list[str] = []
        for k in self._values:
            m = ROLE_KEY_RE.match(k)
            if m and m.group("suffix") == "instances" \
                    and m.group("role") not in _NON_ROLE_SEGMENTS:
                if m.group("role") not in out:
                    out.append(m.group("role"))
        return out

    def role_get(self, role: str, suffix: str) -> Any:
        return self.get(role_key(role, suffix))

    # -- layering -----------------------------------------------------------
    def load_file(self, path: str) -> None:
        """Merge a TOML or JSON conf file. Nested tables flatten with dots.
        ``gs://`` paths are fetched to a temp file first (ref: remote-scheme
        --conf_file, TonyClient.java:657-691)."""
        from tony_tpu.utils import remotefs

        if remotefs.is_remote(path):
            import tempfile

            with tempfile.TemporaryDirectory(prefix="tony_conf_") as tmp:
                return self.load_file(remotefs.fetch_to_dir(path, tmp))
        with open(path, "rb") as f:
            if path.endswith(".json"):
                data = json.load(f)
            elif path.endswith(".toml"):
                data = tomllib.load(f)
            else:
                raise ConfError(f"unsupported conf file (want .toml/.json): {path}")
        for k, v in _flatten(data):
            self.set(k, v)

    def apply_overrides(self, kvs: Iterable[str]) -> None:
        """Apply repeated ``--conf k=v`` overrides (ref: TonyClient.java:672-684)."""
        for kv in kvs:
            if "=" not in kv:
                raise ConfError(f"--conf expects k=v, got: {kv}")
            k, v = kv.split("=", 1)
            k = k.strip()
            if k in K.MULTI_VALUE_KEYS:
                self.append(k, v.strip())
            else:
                self.set(k, v.strip())

    def load_site(self, conf_dir: str | None = None) -> None:
        """Highest-precedence site overrides from $TONY_CONF_DIR/tony-site.*"""
        d = conf_dir or os.environ.get("TONY_CONF_DIR", "")
        if not d:
            return
        for name in ("tony-site.toml", "tony-site.json"):
            p = os.path.join(d, name)
            if os.path.isfile(p):
                self.load_file(p)

    # -- finalization -------------------------------------------------------
    def write_final(self, path: str) -> None:
        """Serialize the merged conf + build version info (ref: VersionInfo
        injection, TonyConfigurationKeys.java:34-41). Key order is preserved:
        roles() order — and thus the is_chief first-role fallback — must
        survive the client -> coordinator round-trip."""
        from tony_tpu.version import version_info

        for k, v in version_info().items():
            self._values.setdefault(k, v)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump(self._values, f, indent=2)

    @classmethod
    def from_final(cls, path: str) -> "TonyConf":
        with open(path) as f:
            values = json.load(f)
        conf = cls(load_defaults=True)
        for k, v in values.items():
            conf.set(k, v)
        return conf

    # -- validation (reference: TonyClient.validateTonyConf :788-857) -------
    def validate(self) -> None:
        total_instances = 0
        total_chips = 0
        for role in self.roles():
            n = int(self.role_get(role, "instances"))
            if n < 0:
                raise ConfError(f"negative instances for role {role}")
            cap = int(self.role_get(role, "max-instances"))
            if cap >= 0 and n > cap:
                raise ConfError(f"role {role}: instances {n} exceeds max-instances {cap}")
            total_instances += n
            total_chips += n * int(self.role_get(role, "chips"))
        cap = self.get_int("tony.application.max-total-instances", -1)
        if cap >= 0 and total_instances > cap:
            raise ConfError(f"total instances {total_instances} exceeds cap {cap}")
        cap = self.get_int("tony.application.max-total-chips", -1)
        if cap >= 0 and total_chips > cap:
            raise ConfError(f"total chips {total_chips} exceeds cap {cap}")
        mode = self.get("tony.application.distributed-mode")
        if mode not in ("GANG", "FCFS"):
            raise ConfError(f"bad distributed-mode: {mode}")


def _flatten(data: dict, prefix: str = "") -> Iterable[tuple[str, Any]]:
    for k, v in data.items():
        full = f"{prefix}.{k}" if prefix else k
        if isinstance(v, dict):
            yield from _flatten(v, full)
        else:
            yield full, v


def build_conf(
    conf_file: str | None = None,
    overrides: Iterable[str] = (),
    conf_dir: str | None = None,
) -> TonyConf:
    """Full layering pipeline: defaults -> file -> --conf -> site."""
    conf = TonyConf()
    if conf_file:
        conf.load_file(conf_file)
    conf.apply_overrides(overrides)
    conf.load_site(conf_dir)
    return conf
