"""JAX runtime: the flagship, TPU-native rendezvous.

This is the in-tree replacement for the reference's HorovodRuntime + NCCL
path (runtime/HorovodRuntime.java, 357 LoC + HorovodDriver + rendezvous
server): on TPU there is no rendezvous *server* at all — the chief task's
registered host:port becomes the jax.distributed coordinator address, each
task's global process id is its flat index in the cluster spec, and all
collectives are XLA over ICI/DCN. The entire HorovodDriver/slot-plan
machinery collapses into env injection (SURVEY.md section 5.8).

User scripts call ``tony_tpu.distributed.initialize()`` (reads this env) or
``jax.distributed.initialize()`` with the values below.
"""

from __future__ import annotations

import json

from tony_tpu import constants as C
from tony_tpu.config import ConfError, TonyConf
from tony_tpu.runtime.base import AMAdapter, Runtime, TaskAdapter, TaskContext


def coordinator_address(cluster_spec: dict[str, list[str]]) -> str:
    """The chief's host:port doubles as the jax coordinator address: chief
    role's task 0 if present, else the first role's task 0."""
    for role in (C.CHIEF_JOB_NAME, C.WORKER_JOB_NAME):
        slots = cluster_spec.get(role)
        if slots and slots[0]:
            return slots[0]
    for slots in cluster_spec.values():
        if slots and slots[0]:
            return slots[0]
    raise ValueError("empty cluster spec: no coordinator candidate")


class JaxAMAdapter(AMAdapter):
    def validate_and_update_config(self, conf: TonyConf) -> None:
        if conf.get("tony.application.distributed-mode") != C.GANG:
            # jax.distributed barrier-initializes: every process must attend
            raise ConfError("jax runtime requires GANG distributed mode")


class JaxTaskAdapter(TaskAdapter):
    def build_task_env(self, ctx: TaskContext) -> dict[str, str]:
        env = super().build_task_env(ctx)
        addr = coordinator_address(ctx.cluster_spec)
        pid = ctx.flat_index()
        num = ctx.total_tasks()
        env[C.COORDINATOR_ADDRESS] = addr
        env[C.PROCESS_ID] = str(pid)
        env[C.NUM_PROCESSES] = str(num)
        # ICI-topology hints for multi-host TPU slices
        topology = str(ctx.conf.get("tony.tpu.topology", ""))
        if topology:
            env["TONY_TPU_TOPOLOGY"] = topology
        return env


class JaxRuntime(Runtime):
    name = "jax"
    am_adapter_cls = JaxAMAdapter
    task_adapter_cls = JaxTaskAdapter
