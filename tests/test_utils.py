"""Utils tests (ref: TestUtils.java zip/shell/resource parsing,
TestLocalizableResource, TestPortAllocation)."""

import socket

from tony_tpu.utils import (
    LocalizableResource,
    execute_shell,
    parse_resources,
    python_interpreter,
    reserve_port,
    unzip,
    zip_dir,
)


def test_execute_shell_env_and_exit(tmp_path):
    log = tmp_path / "out.log"
    code = execute_shell('test "$FOO" = bar', env={"FOO": "bar"}, log_path=str(log))
    assert code == 0
    assert execute_shell("exit 3") == 3


def test_execute_shell_timeout_kills_tree(tmp_path):
    code = execute_shell("sleep 30", timeout_ms=200)
    assert code == 124


def test_execute_shell_logs_output(tmp_path):
    log = tmp_path / "o.log"
    execute_shell("echo hello; echo err >&2", log_path=str(log))
    text = log.read_text()
    assert "hello" in text and "err" in text


def test_zip_roundtrip(tmp_path):
    src = tmp_path / "src"
    (src / "sub").mkdir(parents=True)
    (src / "a.py").write_text("A")
    (src / "sub" / "b.py").write_text("B")
    z = zip_dir(str(src), str(tmp_path / "src.zip"))
    out = unzip(z, str(tmp_path / "out"))
    assert (tmp_path / "out" / "a.py").read_text() == "A"
    assert (tmp_path / "out" / "sub" / "b.py").read_text() == "B"


def test_localizable_resource_parsing():
    r = LocalizableResource.parse("/data/file.txt")
    assert (r.source, r.local_name, r.is_archive) == ("/data/file.txt", "file.txt", False)
    r = LocalizableResource.parse("/data/file.txt::renamed.txt")
    assert r.local_name == "renamed.txt"
    r = LocalizableResource.parse("/data/stuff.zip#archive")
    assert r.is_archive and r.local_name == "stuff.zip"
    assert len(parse_resources("/a,/b::c, /d#archive")) == 3


def test_localize_file_dir_archive(tmp_path):
    f = tmp_path / "x.txt"
    f.write_text("x")
    dest = tmp_path / "dest"
    LocalizableResource.parse(str(f)).localize(str(dest))
    assert (dest / "x.txt").read_text() == "x"
    d = tmp_path / "adir"
    d.mkdir()
    (d / "in.txt").write_text("y")
    LocalizableResource.parse(str(d)).localize(str(dest))
    assert (dest / "adir" / "in.txt").read_text() == "y"
    z = zip_dir(str(d), str(tmp_path / "z.zip"))
    LocalizableResource.parse(f"{z}#archive").localize(str(dest))
    assert (dest / "z.zip" / "in.txt").read_text() == "y"


def test_reserve_port_and_release():
    p = reserve_port()
    assert p.port > 0
    # bound while held
    s = socket.socket()
    try:
        s.bind(("", p.port))
        bound = True
    except OSError:
        bound = False
    finally:
        s.close()
    assert not bound
    p.release()
    s = socket.socket()
    s.bind(("", p.port))  # rebindable after release
    s.close()


def test_reusable_port_allows_concurrent_bind():
    """SO_REUSEPORT mode: user process can bind while the reservation is
    held (ref: TestPortAllocation SO_REUSEPORT contention)."""
    p = reserve_port(reuse=True)
    s = socket.socket()
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
    s.bind(("", p.port))
    s.close()
    p.release()


def test_python_interpreter_fallback(tmp_path):
    assert python_interpreter(None)
    venv = tmp_path / "venv" / "bin"
    venv.mkdir(parents=True)
    (venv / "python").write_text("")
    assert python_interpreter(str(tmp_path / "venv")).endswith("venv/bin/python")
