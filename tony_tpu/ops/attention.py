"""Fused flash attention as a pallas TPU kernel.

The hot op of the transformer stack (no reference analog — TonY has no
kernels; this is the TPU-first replacement for what torch users get from
SDPA/FlashAttention-CUDA). Design per the pallas TPU playbook:

- grid = (batch*heads, q_blocks, kv_blocks); kv is the innermost
  "arbitrary" (sequential) dimension so VMEM scratch carries the online-
  softmax running state (m, l) and the fp32 output accumulator across kv
  steps
- q/k/v blocks are DMA'd HBM->VMEM by BlockSpec; matmuls run in the
  input dtype (bf16 in production) with fp32 MXU accumulation; block
  sizes default to 512 (measured ~2x faster than 128 on v5-class chips:
  the kernel is grid-overhead-bound below that), clamped to a divisor of
  the sequence length
- causal masking prunes fully-masked kv blocks via @pl.when

Falls back to the interpreter off-TPU (tests run it on CPU), and exposes a
custom_vjp with a pallas FlashAttention-2 backward: the forward saves the
per-row logsumexp; dQ and dK/dV kernels recompute P = exp(S - lse)
blockwise, so no [L, L] tensor is ever materialized in either direction
and GQA K/V are never repeated in HBM (block-indexed per q-head group).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _causal_mask(qi, ki, block_q, block_k):
    pos_q = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    pos_k = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return pos_q >= pos_k


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                  *, causal: bool, block_q: int, block_k: int, scale: float):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _body():
        # inputs stay in their native dtype (bf16 in production): the MXU
        # runs bf16 x bf16 -> fp32 accumulation at full rate; casting the
        # operands to fp32 first would halve matmul throughput
        q = q_ref[0]  # [block_q, d]
        k = k_ref[0]  # [block_k, d]
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = jnp.where(_causal_mask(qi, ki, block_q, block_k), s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        m_scr[:] = m_new
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # skip kv blocks strictly above the diagonal
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _run():
            _body()
    else:
        _body()

    @pl.when(ki == nk - 1)
    def _finalize():
        l_safe = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        # logsumexp per q row ([block_q, 1], same layout as the scratch),
        # saved for the backward's softmax recompute
        lse_ref[0] = m_scr[:] + jnp.log(l_safe)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dq_scr, *, causal: bool, block_q: int,
                         block_k: int, scale: float):
    """dQ: grid (bh, nq, nk); for each q block, scan kv blocks.

    FlashAttention-2 backward math with the normalized P recomputed from
    the saved logsumexp: P = exp(S - lse); dP = dO V^T;
    dS = P * (dP - delta) * scale; dQ = sum_k dS K.
    """
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _body():
        q = q_ref[0]  # native dtype: full-rate MXU, fp32 accumulation
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = jnp.where(_causal_mask(qi, ki, block_q, block_k), s, NEG_INF)
        p = jnp.exp(s - lse_ref[0])  # lse block: [block_q, 1], broadcasts
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _run():
            _body()
    else:
        _body()

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_scr, dv_scr, *, causal: bool,
                          block_q: int, block_k: int, scale: float,
                          nq: int):
    """dK/dV: grid (b*kvh, nk, group*nq); for each KV-HEAD block, the
    innermost scan walks every q block of every q head in this kv group
    (step s: head g = s // nq, q block qi = s % nq), accumulating into one
    [block_k, d] scratch pair — so dK/dV are written at their true
    [b*kvh, lk, d] size with no group-factor HBM amplification.

    dV = sum_{g,q} P^T dO; dK = sum_{g,q} dS^T Q (dS as in the dQ kernel)."""
    ki = pl.program_id(1)
    s_idx = pl.program_id(2)
    ns = pl.num_programs(2)
    qi = s_idx % nq

    @pl.when(s_idx == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _body():
        q = q_ref[0]  # native dtype: full-rate MXU, fp32 accumulation
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = jnp.where(_causal_mask(qi, ki, block_q, block_k), s, NEG_INF)
        p = jnp.exp(s - lse_ref[0])  # [block_q, block_k]
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # q blocks whose last row is above this kv block see none of it
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _run():
            _body()
    else:
        _body()

    @pl.when(s_idx == ns - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_forward(q, k, v, *, causal: bool, block_q: int, block_k: int,
                   interpret: bool):
    b, lq, h, d = q.shape
    lk = k.shape[1]
    kvh = k.shape[2]
    if lq % block_q or lk % block_k:
        raise ValueError(
            f"seq lens ({lq},{lk}) must divide block sizes ({block_q},{block_k})")
    if h % kvh:
        raise ValueError(f"q heads {h} not divisible by kv heads {kvh}")
    group = h // kvh
    scale = d ** -0.5
    # [B, L, H, D] -> [B*H, L, D]
    qr = q.transpose(0, 2, 1, 3).reshape(b * h, lq, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * kvh, lk, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * kvh, lk, d)
    grid = (b * h, lq // block_q, lk // block_k)

    def kv_index(bh, qi, ki):
        # GQA: q head -> its kv group's row; the same kv block is DMA'd for
        # each of the `group` q heads instead of materializing a repeat
        return (bh // h) * kvh + (bh % h) // group, ki, 0

    kernel = functools.partial(_flash_kernel, causal=causal, block_q=block_q,
                               block_k=block_k, scale=scale)
    out, lse = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((b * h, lq, d), q.dtype),
            # [bh, lq, 1]: lane-dim 1 keeps the (block_q, 1) block a legal
            # TPU tile and matches the m/l scratch layout
            jax.ShapeDtypeStruct((b * h, lq, 1), jnp.float32),
        ],
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        scratch_shapes=[
            _vmem((block_q, 1)),
            _vmem((block_q, 1)),
            _vmem((block_q, d)),
        ],
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(b, h, lq, d).transpose(0, 2, 1, 3), lse


def _flash_backward(q, k, v, o, lse, g, *, causal: bool, block_q: int,
                    block_k: int, interpret: bool):
    """Pallas dQ/dK/dV (FlashAttention-2 scheme).

    GQA: the kv BlockSpec indexes each q head's group row (as in the
    forward), so K/V are never repeated in HBM, and the dK/dV kernel
    accumulates the whole q-head group in VMEM scratch so its outputs are
    the true [b*kvh] size (no group-factor HBM amplification)."""
    b, lq, h, d = q.shape
    lk, kvh = k.shape[1], k.shape[2]
    group = h // kvh
    scale = d ** -0.5
    qr = q.transpose(0, 2, 1, 3).reshape(b * h, lq, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * kvh, lk, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * kvh, lk, d)
    dor = g.transpose(0, 2, 1, 3).reshape(b * h, lq, d)
    # delta_i = rowsum(dO * O): one cheap bandwidth pass, done by XLA;
    # [bh, lq, 1] to match the lse layout
    delta = jnp.sum(dor.astype(jnp.float32)
                    * o.transpose(0, 2, 1, 3).reshape(b * h, lq, d)
                    .astype(jnp.float32), axis=-1, keepdims=True)

    def kv_index_dq(bh, qi, ki):
        return (bh // h) * kvh + (bh % h) // group, ki, 0

    q_spec_dq = pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0))
    row_spec_dq = pl.BlockSpec((1, block_q, 1),
                               lambda bh, qi, ki: (bh, qi, 0))
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, causal=causal,
                          block_q=block_q, block_k=block_k, scale=scale),
        out_shape=jax.ShapeDtypeStruct((b * h, lq, d), q.dtype),
        grid=(b * h, lq // block_q, lk // block_k),
        in_specs=[
            q_spec_dq,
            pl.BlockSpec((1, block_k, d), kv_index_dq),
            pl.BlockSpec((1, block_k, d), kv_index_dq),
            q_spec_dq,
            row_spec_dq,
            row_spec_dq,
        ],
        out_specs=q_spec_dq,
        scratch_shapes=[_vmem((block_q, d))],
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(qr, kr, vr, dor, lse, delta)

    # dK/dV grid is per KV head: the innermost axis walks group*nq steps
    # (all q blocks of all q heads in this group), so outputs are written
    # at [b*kvh, lk, d] directly — no group-factor HBM amplification
    nq = lq // block_q

    def q_row_dkv(bkv, ki, s):
        return (bkv // kvh) * h + (bkv % kvh) * group + s // nq, s % nq, 0

    q_spec_dkv = pl.BlockSpec((1, block_q, d), q_row_dkv)
    row_spec_dkv = pl.BlockSpec((1, block_q, 1), q_row_dkv)
    kv_spec_dkv = pl.BlockSpec((1, block_k, d), lambda bkv, ki, s: (bkv, ki, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, causal=causal,
                          block_q=block_q, block_k=block_k, scale=scale,
                          nq=nq),
        out_shape=[
            jax.ShapeDtypeStruct((b * kvh, lk, d), k.dtype),
            jax.ShapeDtypeStruct((b * kvh, lk, d), v.dtype),
        ],
        grid=(b * kvh, lk // block_k, group * nq),
        in_specs=[
            q_spec_dkv,
            kv_spec_dkv,
            kv_spec_dkv,
            q_spec_dkv,
            row_spec_dkv,
            row_spec_dkv,
        ],
        out_specs=[kv_spec_dkv, kv_spec_dkv],
        scratch_shapes=[_vmem((block_k, d)), _vmem((block_k, d))],
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(qr, kr, vr, dor, lse, delta)

    dq = dq.reshape(b, h, lq, d).transpose(0, 2, 1, 3)
    dk = dk.reshape(b, kvh, lk, d).transpose(0, 2, 1, 3)
    dv = dv.reshape(b, kvh, lk, d).transpose(0, 2, 1, 3)
    return dq, dk, dv


def _vmem(shape):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, jnp.float32)


def _compiler_params():
    try:
        from jax.experimental.pallas import tpu as pltpu

        return pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    except Exception:
        return None


def _on_tpu() -> bool:
    try:
        # "axon" is a tunneled TPU platform; its pallas lowering is the
        # same Mosaic path, so compiled (not interpreted) kernels apply
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


def _pick_block(limit: int, length: int) -> int:
    """Largest block <= limit that divides the sequence length and keeps a
    legal TPU tile (multiple of 8, or the whole length). Degenerate tiny
    blocks would be silently 10-100x slower than XLA attention, so a
    length with no usable divisor is an error, not a fallback."""
    b = min(limit, length)
    if length % b == 0:
        return b
    for cand in range(b - b % 8, 7, -8):  # multiples of 8, descending
        if length % cand == 0:
            return cand
    raise ValueError(
        f"no usable flash-attention block for seq len {length} (need a "
        f"divisor <= {limit} that is a multiple of 8); pad the sequence "
        f"or use the blockwise backend")


def _blocks(block_q, block_k, q, k):
    return _pick_block(block_q, q.shape[1]), _pick_block(block_k, k.shape[1])


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention_core(q, k, v, causal: bool, block_q: int, block_k: int,
                          interpret: bool | None):
    """custom_vjp core; sequence lengths must have a usable block."""
    if interpret is None:
        interpret = not _on_tpu()
    bq, bk = _blocks(block_q, block_k, q, k)
    out, _ = _flash_forward(q, k, v, causal=causal, block_q=bq, block_k=bk,
                            interpret=interpret)
    return out


def _fwd(q, k, v, causal, block_q, block_k, interpret):
    if interpret is None:
        interpret = not _on_tpu()
    bq, bk = _blocks(block_q, block_k, q, k)
    out, lse = _flash_forward(q, k, v, causal=causal, block_q=bq, block_k=bk,
                              interpret=interpret)
    return out, (q, k, v, out, lse)


def _bwd(causal, block_q, block_k, interpret, res, g):
    """Pallas FlashAttention-2 backward: recomputes P blockwise from the
    saved logsumexp — O(L) memory, no [L, L] tensor, no K/V repeat."""
    q, k, v, o, lse = res
    if interpret is None:
        interpret = not _on_tpu()
    bq, bk = _blocks(block_q, block_k, q, k)
    return _flash_backward(q, k, v, o, lse, g, causal=causal, block_q=bq,
                           block_k=bk, interpret=interpret)


_flash_attention_core.defvjp(_fwd, _bwd)


def _padded_len(length: int, limit: int) -> int:
    """Sequence length after padding so a usable block exists (unchanged
    if one already does). Only lengths > limit can need padding: a length
    <= limit is always its own legal whole-length block."""
    try:
        _pick_block(limit, length)
        return length
    except ValueError:
        return -(-length // limit) * limit


def flash_attention(q, k, v, causal: bool = True, block_q: int = 512,
                    block_k: int = 512, interpret: bool | None = None):
    """Fused attention. q: [B, L, H, D]; k/v: [B, L, KVH, D] with
    H % KVH == 0 (GQA: the kernel indexes each q head's kv group directly —
    no repeated K/V is ever materialized). Returns [B, L, H, D].

    Awkward sequence lengths (e.g. the L-1 of a shifted LM batch) are
    zero-padded up to a blockable length and sliced back — safe for causal
    attention because padded K rows sit beyond every real query's causal
    horizon and padded-row dO is zero in the backward. Non-causal calls
    with an unblockable length raise instead (padded K rows would receive
    real attention mass).

    interpret=None auto-selects: compiled on TPU, interpreter elsewhere.
    """
    lq, lk = q.shape[1], k.shape[1]
    plq, plk = _padded_len(lq, block_q), _padded_len(lk, block_k)
    if plq == lq and plk == lk:
        return _flash_attention_core(q, k, v, causal, block_q, block_k,
                                     interpret)
    if not causal:
        raise ValueError(
            f"non-causal flash attention needs blockable seq lens, got "
            f"({lq}, {lk}); pad the sequence or use the blockwise backend")
    pad_q = [(0, 0), (0, plq - lq), (0, 0), (0, 0)]
    pad_k = [(0, 0), (0, plk - lk), (0, 0), (0, 0)]
    out = _flash_attention_core(
        jnp.pad(q, pad_q), jnp.pad(k, pad_k), jnp.pad(v, pad_k),
        causal, block_q, block_k, interpret)
    return out[:, :lq]
