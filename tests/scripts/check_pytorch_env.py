"""Assert DDP env (ref: exit_0_check_pytorchenv.py)."""
import os
import sys

for k in ("RANK", "WORLD", "INIT_METHOD", "MASTER_ADDR", "MASTER_PORT"):
    if k not in os.environ:
        print("missing", k)
        sys.exit(1)
if not os.environ["INIT_METHOD"].startswith("tcp://"):
    sys.exit(2)
sys.exit(0)
