"""Fused flash attention as a pallas TPU kernel.

The hot op of the transformer stack (no reference analog — TonY has no
kernels; this is the TPU-first replacement for what torch users get from
SDPA/FlashAttention-CUDA). Design per the pallas TPU playbook:

- grid = (batch*heads, q_blocks, kv_blocks); kv is the innermost
  "arbitrary" (sequential) dimension so VMEM scratch carries the online-
  softmax running state (m, l) and the fp32 output accumulator across kv
  steps
- q/k/v blocks are DMA'd HBM->VMEM by BlockSpec; matmuls run in the
  input dtype (bf16 in production) with fp32 MXU accumulation; block
  sizes default to 512 (measured ~2x faster than 128 on v5-class chips:
  the kernel is grid-overhead-bound below that), clamped to a divisor of
  the sequence length
- causal masking prunes fully-masked kv blocks via @pl.when

Falls back to the interpreter off-TPU (tests run it on CPU), and exposes a
custom_vjp with a pallas FlashAttention-2 backward: the forward saves the
per-row logsumexp; dQ and dK/dV kernels recompute P = exp(S - lse)
blockwise, so no [L, L] tensor is ever materialized in either direction
and GQA K/V are never repeated in HBM (block-indexed per q-head group).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from tony_tpu.ops.platform import on_tpu as _on_tpu

NEG_INF = -1e30


def _causal_mask(qi, ki, block_q, block_k, window: int = 0):
    """Causal visibility for one (q block, kv block) tile; window > 0 also
    hides keys further than ``window`` behind the query (sliding window,
    key visible iff 0 <= q_pos - k_pos < window)."""
    pos_q = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    pos_k = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    mask = pos_q >= pos_k
    if window > 0:
        mask = mask & (pos_q - pos_k < window)
    return mask


def _block_interior(qi, ki, block_q, block_k, window: int):
    """Grid predicate: is this tile FULLY visible (every q sees every k)?
    Interior tiles skip the iota mask build + where entirely — at these
    head dims the kernels are VPU-bound, and for causal seq/block ratios
    around 4 most visible tiles are interior, so the saved elementwise
    passes are a real fraction of kernel time."""
    pred = qi * block_q >= ki * block_k + block_k - 1
    if window > 0:
        pred = pred & (qi * block_q + block_q - 1 - ki * block_k < window)
    return pred


def _dispatch_body(body, causal: bool, has_seg: bool, qi, ki,
                   block_q: int, block_k: int, window: int):
    """Shared tile dispatch for the three flash kernels: skip invisible
    tiles, and run fully-visible (interior) tiles without the mask build.
    ``body(masked)`` does the tile's work; segment ids are data-dependent
    so they always mask."""
    if not causal:
        body(False)
        return
    vis = _block_visible(qi, ki, block_q, block_k, window)
    if has_seg:
        pl.when(vis)(lambda: body(True))
        return
    interior = _block_interior(qi, ki, block_q, block_k, window)
    pl.when(vis & interior)(lambda: body(False))
    pl.when(vis & jnp.logical_not(interior))(lambda: body(True))


def _block_visible(qi, ki, block_q, block_k, window: int):
    """Grid predicate: does this (q block, kv block) tile contain ANY
    visible entry? Upper side: the tile's newest query must not precede
    the tile's oldest key (causal). Lower side (window only): the tile's
    oldest query must be nearer than ``window`` to the tile's newest key —
    tiles wholly behind the window are skipped, making windowed compute
    O(L*window) instead of O(L^2/2)."""
    pred = ki * block_k <= qi * block_q + block_q - 1
    if window > 0:
        pred = pred & (qi * block_q - (ki * block_k + block_k - 1) < window)
    return pred


def _kv_band(window: int, block_q: int, block_k: int, nk: int) -> int:
    """Grid width (in kv blocks) of the visible band for one q block under
    a sliding window. The band [q_first - window + 1, q_last] spans at most
    window + block_q - 1 keys, i.e. this many kv tiles (+1 for alignment
    slack). Shrinking the GRID — not just @pl.when-skipping the body —
    means invisible kv tiles are never DMA'd, so windowed attention is
    O(L*window) in HBM traffic too, which is what actually pays on a
    bandwidth-bound chip."""
    if window <= 0:
        return nk
    return min(nk, (window + block_q - 2) // block_k + 2)


def _banded_ki(qi, ki_local, nkb, block_q: int, block_k: int, nk: int):
    """Real kv block index for banded grids: the band ends at this q
    block's diagonal tile; local index 0 is ``nkb - 1`` tiles before it
    (clamped at 0 — early q blocks just re-scan the first tiles and rely
    on the visibility predicate). With a full band (nkb == nk) this is the
    identity, so the same formula serves the unwindowed causal path.

    ``nk`` is the TOTAL kv-block count: for causal cross-attention with
    lq > lk the diagonal lies past the kv grid, so it is clamped to the
    last real tile — every block is then scanned and the position mask
    alone decides visibility (the pre-band full-scan behavior)."""
    diag = jnp.minimum((qi * block_q + block_q - 1) // block_k, nk - 1)
    return jnp.maximum(diag - (nkb - 1), 0) + ki_local


def _q_band(window: int, block_q: int, block_k: int, nq: int) -> int:
    """Grid width (in q blocks) of the band of queries that can see one kv
    block under a sliding window (the dK/dV mirror of _kv_band)."""
    if window <= 0:
        return nq
    return min(nq, (window + block_k - 2) // block_q + 2)


def _banded_qi(ki, qi_local, nqb, nq, block_q: int, block_k: int):
    """Real q block index for the dK/dV banded grid: the band starts at
    the first q tile that can see this kv block (its diagonal), clamped so
    the band stays inside [0, nq)."""
    first = (ki * block_k) // block_q
    return jnp.minimum(first, nq - nqb) + qi_local


def _flash_kernel(q_ref, k_ref, v_ref, *rest,
                  causal: bool, block_q: int, block_k: int, scale: float,
                  nk_total: int, window: int = 0, has_seg: bool = False):
    if has_seg:
        qseg_ref, kseg_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    qi = pl.program_id(1)
    ki_local = pl.program_id(2)
    nk = pl.num_programs(2)  # band width (= all kv blocks when unwindowed)
    if causal:
        ki = _banded_ki(qi, ki_local, nk, block_q, block_k, nk_total)
    else:
        ki = ki_local

    @pl.when(ki_local == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _body(masked: bool):
        # inputs stay in their native dtype (bf16 in production): the MXU
        # runs bf16 x bf16 -> fp32 accumulation at full rate; casting the
        # operands to fp32 first would halve matmul throughput
        q = q_ref[0]  # [block_q, d]
        k = k_ref[0]  # [block_k, d]
        v = v_ref[0]
        # RAW scores: the softmax scale is folded into the exp (max
        # commutes with positive scaling), so no [block_q, block_k]
        # scaling pass ever runs — at d=64 the kernel is VPU-bound and
        # every elementwise pass over the scores tile is ~a third of the
        # matmul time
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if masked:
            mask = _causal_mask(qi, ki, block_q, block_k, window)
            if has_seg:
                mask = mask & (qseg_ref[0, 0][:, None]
                               == kseg_ref[0, 0][None, :])
            s = jnp.where(mask, s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp((s - m_new) * scale)  # one fused sub-mul-exp pass
        corr = jnp.exp((m_prev - m_new) * scale)
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        m_scr[:] = m_new
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    _dispatch_body(_body, causal, has_seg, qi, ki, block_q, block_k,
                   window)

    @pl.when(ki_local == nk - 1)
    def _finalize():
        l_safe = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        # logsumexp per q row ([block_q, 1], same layout as the scratch),
        # saved for the backward's softmax recompute. m_scr holds the RAW
        # running max, so it re-enters scaled space here.
        lse_ref[0] = m_scr[:] * scale + jnp.log(l_safe)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         *rest, causal: bool, block_q: int,
                         block_k: int, scale: float, nk_total: int,
                         window: int = 0, has_seg: bool = False):
    if has_seg:
        qseg_ref, kseg_ref, dq_ref, dq_scr = rest
    else:
        dq_ref, dq_scr = rest
    """dQ: grid (bh, nq, nk); for each q block, scan kv blocks.

    FlashAttention-2 backward math with the normalized P recomputed from
    the saved logsumexp: P = exp(S - lse); dP = dO V^T;
    dS = P * (dP - delta) * scale; dQ = sum_k dS K.
    """
    qi = pl.program_id(1)
    ki_local = pl.program_id(2)
    nk = pl.num_programs(2)  # band width
    if causal:
        ki = _banded_ki(qi, ki_local, nk, block_q, block_k, nk_total)
    else:
        ki = ki_local

    @pl.when(ki_local == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _body(masked: bool):
        q = q_ref[0]  # native dtype: full-rate MXU, fp32 accumulation
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        # raw scores; scale folds into the fused exp below, and the dS
        # scale is applied once to the [block_q, d] accumulator at
        # finalize instead of per-body on the [block_q, block_k] tile
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if masked:
            mask = _causal_mask(qi, ki, block_q, block_k, window)
            if has_seg:
                mask = mask & (qseg_ref[0, 0][:, None]
                               == kseg_ref[0, 0][None, :])
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s * scale - lse_ref[0])  # lse: [block_q, 1] broadcast
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0])
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    _dispatch_body(_body, causal, has_seg, qi, ki, block_q, block_k,
                   window)

    @pl.when(ki_local == nk - 1)
    def _finalize():
        dq_ref[0] = (dq_scr[:] * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          *rest, causal: bool,
                          block_q: int, block_k: int, scale: float,
                          nq: int, nqb: int, window: int = 0,
                          has_seg: bool = False):
    if has_seg:
        qseg_ref, kseg_ref, dk_ref, dv_ref, dk_scr, dv_scr = rest
    else:
        dk_ref, dv_ref, dk_scr, dv_scr = rest
    """dK/dV: grid (b*kvh, nk, group*nq); for each KV-HEAD block, the
    innermost scan walks every q block of every q head in this kv group
    (step s: head g = s // nq, q block qi = s % nq), accumulating into one
    [block_k, d] scratch pair — so dK/dV are written at their true
    [b*kvh, lk, d] size with no group-factor HBM amplification.

    dV = sum_{g,q} P^T dO; dK = sum_{g,q} dS^T Q (dS as in the dQ kernel).

    ``nq`` is the TOTAL q-block count; ``nqb`` the banded width actually
    walked per head (== nq when unwindowed)."""
    ki = pl.program_id(1)
    s_idx = pl.program_id(2)
    ns = pl.num_programs(2)
    if causal:
        qi = _banded_qi(ki, s_idx % nqb, nqb, nq, block_q, block_k)
    else:
        qi = s_idx % nqb

    @pl.when(s_idx == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _body(masked: bool):
        q = q_ref[0]  # native dtype: full-rate MXU, fp32 accumulation
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        # raw scores (see the dQ kernel): scale folds into the exp; the
        # dS scale lands on the [block_k, d] dK accumulator at finalize
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if masked:
            mask = _causal_mask(qi, ki, block_q, block_k, window)
            if has_seg:
                mask = mask & (qseg_ref[0, 0][:, None]
                               == kseg_ref[0, 0][None, :])
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s * scale - lse_ref[0])  # [block_q, block_k]
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0])
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    _dispatch_body(_body, causal, has_seg, qi, ki, block_q, block_k,
                   window)

    @pl.when(s_idx == ns - 1)
    def _finalize():
        dk_ref[0] = (dk_scr[:] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_forward(q, k, v, *, causal: bool, block_q: int, block_k: int,
                   interpret: bool, window: int = 0, segments=None):
    b, lq, h, d = q.shape
    lk = k.shape[1]
    kvh = k.shape[2]
    if lq % block_q or lk % block_k:
        raise ValueError(
            f"seq lens ({lq},{lk}) must divide block sizes ({block_q},{block_k})")
    if h % kvh:
        raise ValueError(f"q heads {h} not divisible by kv heads {kvh}")
    group = h // kvh
    scale = d ** -0.5
    # [B, L, H, D] -> [B*H, L, D]
    qr = q.transpose(0, 2, 1, 3).reshape(b * h, lq, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * kvh, lk, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * kvh, lk, d)
    nk = lk // block_k
    # windowed: the kv grid axis covers only the visible band per q block,
    # so out-of-window kv tiles are never DMA'd (O(L*window) HBM traffic)
    nkb = _kv_band(window, block_q, block_k, nk) if causal else nk
    grid = (b * h, lq // block_q, nkb)

    def kv_index(bh, qi, ki):
        # GQA: q head -> its kv group's row; the same kv block is DMA'd for
        # each of the `group` q heads instead of materializing a repeat
        row = (bh // h) * kvh + (bh % h) // group
        if causal:
            return row, _banded_ki(qi, ki, nkb, block_q, block_k, nk), 0
        return row, ki, 0

    kernel = functools.partial(_flash_kernel, causal=causal, block_q=block_q,
                               block_k=block_k, scale=scale, nk_total=nk,
                               window=window, has_seg=segments is not None)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        pl.BlockSpec((1, block_k, d), kv_index),
        pl.BlockSpec((1, block_k, d), kv_index),
    ]
    operands = [qr, kr, vr]
    if segments is not None:
        seg3 = segments[:, None, :]  # [B, 1, L]: legal TPU tile shape
        in_specs += [
            pl.BlockSpec((1, 1, block_q),
                         lambda bh, qi, ki: (bh // h, 0, qi)),
            pl.BlockSpec(
                (1, 1, block_k),
                (lambda bh, qi, ki:
                 (bh // h, 0, _banded_ki(qi, ki, nkb, block_q, block_k, nk)))
                if causal else (lambda bh, qi, ki: (bh // h, 0, ki))),
        ]
        operands += [seg3, seg3]
    out, lse = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((b * h, lq, d), q.dtype),
            # [bh, lq, 1]: lane-dim 1 keeps the (block_q, 1) block a legal
            # TPU tile and matches the m/l scratch layout
            jax.ShapeDtypeStruct((b * h, lq, 1), jnp.float32),
        ],
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        scratch_shapes=[
            _vmem((block_q, 1)),
            _vmem((block_q, 1)),
            _vmem((block_q, d)),
        ],
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(*operands)
    return out.reshape(b, h, lq, d).transpose(0, 2, 1, 3), lse


def _flash_backward(q, k, v, o, lse, g, *, causal: bool, block_q: int,
                    block_k: int, interpret: bool, window: int = 0,
                    segments=None):
    """Pallas dQ/dK/dV (FlashAttention-2 scheme).

    GQA: the kv BlockSpec indexes each q head's group row (as in the
    forward), so K/V are never repeated in HBM, and the dK/dV kernel
    accumulates the whole q-head group in VMEM scratch so its outputs are
    the true [b*kvh] size (no group-factor HBM amplification)."""
    b, lq, h, d = q.shape
    lk, kvh = k.shape[1], k.shape[2]
    group = h // kvh
    scale = d ** -0.5
    qr = q.transpose(0, 2, 1, 3).reshape(b * h, lq, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * kvh, lk, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * kvh, lk, d)
    dor = g.transpose(0, 2, 1, 3).reshape(b * h, lq, d)
    # delta_i = rowsum(dO * O): one cheap bandwidth pass, done by XLA;
    # [bh, lq, 1] to match the lse layout
    delta = jnp.sum(dor.astype(jnp.float32)
                    * o.transpose(0, 2, 1, 3).reshape(b * h, lq, d)
                    .astype(jnp.float32), axis=-1, keepdims=True)

    nk = lk // block_k
    nkb = _kv_band(window, block_q, block_k, nk) if causal else nk

    def kv_index_dq(bh, qi, ki):
        row = (bh // h) * kvh + (bh % h) // group
        if causal:
            return row, _banded_ki(qi, ki, nkb, block_q, block_k, nk), 0
        return row, ki, 0

    q_spec_dq = pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0))
    row_spec_dq = pl.BlockSpec((1, block_q, 1),
                               lambda bh, qi, ki: (bh, qi, 0))
    in_specs_dq = [
        q_spec_dq,
        pl.BlockSpec((1, block_k, d), kv_index_dq),
        pl.BlockSpec((1, block_k, d), kv_index_dq),
        q_spec_dq,
        row_spec_dq,
        row_spec_dq,
    ]
    operands_dq = [qr, kr, vr, dor, lse, delta]
    if segments is not None:
        seg3 = segments[:, None, :]
        in_specs_dq += [
            pl.BlockSpec((1, 1, block_q),
                         lambda bh, qi, ki: (bh // h, 0, qi)),
            pl.BlockSpec(
                (1, 1, block_k),
                (lambda bh, qi, ki:
                 (bh // h, 0, _banded_ki(qi, ki, nkb, block_q, block_k, nk)))
                if causal else (lambda bh, qi, ki: (bh // h, 0, ki))),
        ]
        operands_dq += [seg3, seg3]
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, causal=causal,
                          block_q=block_q, block_k=block_k, scale=scale,
                          nk_total=nk, window=window,
                          has_seg=segments is not None),
        out_shape=jax.ShapeDtypeStruct((b * h, lq, d), q.dtype),
        grid=(b * h, lq // block_q, nkb),
        in_specs=in_specs_dq,
        out_specs=q_spec_dq,
        scratch_shapes=[_vmem((block_q, d))],
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(*operands_dq)

    # dK/dV grid is per KV head: the innermost axis walks group*nqb steps
    # (the banded q blocks of all q heads in this group), so outputs are
    # written at [b*kvh, lk, d] directly — no group-factor HBM
    # amplification, and out-of-window q tiles are never DMA'd
    nq = lq // block_q
    nqb = _q_band(window, block_q, block_k, nq) if causal else nq

    def q_row_dkv(bkv, ki, s):
        row = (bkv // kvh) * h + (bkv % kvh) * group + s // nqb
        if causal:
            return row, _banded_qi(ki, s % nqb, nqb, nq, block_q, block_k), 0
        return row, s % nqb, 0

    q_spec_dkv = pl.BlockSpec((1, block_q, d), q_row_dkv)
    row_spec_dkv = pl.BlockSpec((1, block_q, 1), q_row_dkv)
    kv_spec_dkv = pl.BlockSpec((1, block_k, d), lambda bkv, ki, s: (bkv, ki, 0))
    in_specs_dkv = [
        q_spec_dkv,
        kv_spec_dkv,
        kv_spec_dkv,
        q_spec_dkv,
        row_spec_dkv,
        row_spec_dkv,
    ]
    operands_dkv = [qr, kr, vr, dor, lse, delta]
    if segments is not None:
        seg3 = segments[:, None, :]
        in_specs_dkv += [
            pl.BlockSpec(
                (1, 1, block_q),
                (lambda bkv, ki, s:
                 (bkv // kvh, 0, _banded_qi(ki, s % nqb, nqb, nq,
                                            block_q, block_k)))
                if causal else
                (lambda bkv, ki, s: (bkv // kvh, 0, s % nqb))),
            pl.BlockSpec((1, 1, block_k),
                         lambda bkv, ki, s: (bkv // kvh, 0, ki)),
        ]
        operands_dkv += [seg3, seg3]
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, causal=causal,
                          block_q=block_q, block_k=block_k, scale=scale,
                          nq=nq, nqb=nqb, window=window,
                          has_seg=segments is not None),
        out_shape=[
            jax.ShapeDtypeStruct((b * kvh, lk, d), k.dtype),
            jax.ShapeDtypeStruct((b * kvh, lk, d), v.dtype),
        ],
        grid=(b * kvh, lk // block_k, group * nqb),
        in_specs=in_specs_dkv,
        out_specs=[kv_spec_dkv, kv_spec_dkv],
        scratch_shapes=[_vmem((block_k, d)), _vmem((block_k, d))],
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(*operands_dkv)

    dq = dq.reshape(b, h, lq, d).transpose(0, 2, 1, 3)
    dk = dk.reshape(b, kvh, lk, d).transpose(0, 2, 1, 3)
    dv = dv.reshape(b, kvh, lk, d).transpose(0, 2, 1, 3)
    return dq, dk, dv


def _vmem(shape):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, jnp.float32)


def _compiler_params():
    try:
        from jax.experimental.pallas import tpu as pltpu

        return pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    except Exception:
        return None


def _pick_block(limit: int, length: int) -> int:
    """Largest block <= limit that divides the sequence length and keeps a
    legal TPU tile (multiple of 8, or the whole length). Degenerate tiny
    blocks would be silently 10-100x slower than XLA attention, so a
    length with no usable divisor is an error, not a fallback."""
    b = min(limit, length)
    if length % b == 0:
        return b
    for cand in range(b - b % 8, 7, -8):  # multiples of 8, descending
        if length % cand == 0:
            return cand
    raise ValueError(
        f"no usable flash-attention block for seq len {length} (need a "
        f"divisor <= {limit} that is a multiple of 8); pad the sequence "
        f"or use the blockwise backend")


def _blocks(block_q, block_k, q, k):
    return _pick_block(block_q, q.shape[1]), _pick_block(block_k, k.shape[1])


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_attention_core(q, k, v, segments, causal: bool, block_q: int,
                          block_k: int, interpret: bool | None,
                          window: int = 0):
    """custom_vjp core; sequence lengths must have a usable block.
    ``segments`` is an int operand (or None): zero-cotangent in the vjp."""
    if interpret is None:
        interpret = not _on_tpu()
    bq, bk = _blocks(block_q, block_k, q, k)
    out, _ = _flash_forward(q, k, v, causal=causal, block_q=bq, block_k=bk,
                            interpret=interpret, window=window,
                            segments=segments)
    return out


def _fwd(q, k, v, segments, causal, block_q, block_k, interpret, window=0):
    if interpret is None:
        interpret = not _on_tpu()
    bq, bk = _blocks(block_q, block_k, q, k)
    out, lse = _flash_forward(q, k, v, causal=causal, block_q=bq, block_k=bk,
                              interpret=interpret, window=window,
                              segments=segments)
    return out, (q, k, v, segments, out, lse)


def _bwd(causal, block_q, block_k, interpret, window, res, g):
    """Pallas FlashAttention-2 backward: recomputes P blockwise from the
    saved logsumexp — O(L) memory, no [L, L] tensor, no K/V repeat."""
    q, k, v, segments, o, lse = res
    if interpret is None:
        interpret = not _on_tpu()
    bq, bk = _blocks(block_q, block_k, q, k)
    dq, dk, dv = _flash_backward(q, k, v, o, lse, g, causal=causal,
                                 block_q=bq, block_k=bk, interpret=interpret,
                                 window=window, segments=segments)
    # int segments carry the symbolic-zero float0 cotangent
    dseg = None if segments is None else np.zeros(segments.shape,
                                                  jax.dtypes.float0)
    return dq, dk, dv, dseg


_flash_attention_core.defvjp(_fwd, _bwd)


def _padded_len(length: int, limit: int) -> int:
    """Sequence length after padding so a usable block exists (unchanged
    if one already does). Only lengths > limit can need padding: a length
    <= limit is always its own legal whole-length block."""
    try:
        _pick_block(limit, length)
        return length
    except ValueError:
        return -(-length // limit) * limit


def flash_attention(q, k, v, causal: bool = True, block_q: int = 512,
                    block_k: int = 512, interpret: bool | None = None,
                    window: int = 0, segment_ids=None):
    """Fused attention. q: [B, L, H, D]; k/v: [B, L, KVH, D] with
    H % KVH == 0 (GQA: the kernel indexes each q head's kv group directly —
    no repeated K/V is ever materialized). Returns [B, L, H, D].

    window > 0 adds sliding-window masking (key visible iff
    0 <= q_pos - k_pos < window, HF Mistral semantics; requires causal)
    with block-level pruning, so compute scales O(L*window) not O(L^2).

    Awkward sequence lengths (e.g. the L-1 of a shifted LM batch) are
    zero-padded up to a blockable length and sliced back — safe for causal
    attention because padded K rows sit beyond every real query's causal
    horizon and padded-row dO is zero in the backward. Non-causal calls
    with an unblockable length raise instead (padded K rows would receive
    real attention mass).

    interpret=None auto-selects: compiled on TPU, interpreter elsewhere.
    """
    lq, lk = q.shape[1], k.shape[1]
    if window > 0 and not causal:
        raise ValueError("window > 0 requires causal=True (the sliding "
                         "window is defined over past keys)")
    if window > 0 and lq != lk:
        raise ValueError("window > 0 needs self-attention shapes (lq == "
                         f"lk): the banded grid width is derived from lk, "
                         f"got ({lq}, {lk})")
    if segment_ids is not None:
        if not causal:
            raise ValueError("segment_ids require causal=True (packed-LM "
                             "masking)")
        if lq != lk:
            raise ValueError("segment_ids need self-attention shapes "
                             f"(lq == lk), got ({lq}, {lk})")
        segment_ids = segment_ids.astype(jnp.int32)
    plq, plk = _padded_len(lq, block_q), _padded_len(lk, block_k)
    if plq == lq and plk == lk:
        return _flash_attention_core(q, k, v, segment_ids, causal, block_q,
                                     block_k, interpret, window)
    if not causal:
        raise ValueError(
            f"non-causal flash attention needs blockable seq lens, got "
            f"({lq}, {lk}); pad the sequence or use the blockwise backend")
    if lq != lk:
        # causal cross-attention with lq > lk would let real queries past
        # lk attend zero-padded keys (score 0 > negative real scores =
        # silent mass leak); the pad path is only sound for self-attention
        raise ValueError(
            f"causal flash attention with unblockable UNEQUAL seq lens "
            f"({lq}, {lk}) cannot be zero-padded safely; pad the inputs "
            f"yourself or use the blockwise backend")
    # pad BOTH sides to one common blockable length: with block_q !=
    # block_k, plq != plk would let q-side blocks (and the banded kv
    # index) run past the shorter array
    pm = max(plq, plk)
    pad_q = [(0, 0), (0, pm - lq), (0, 0), (0, 0)]
    pad_k = [(0, 0), (0, pm - lk), (0, 0), (0, 0)]
    seg_p = None
    if segment_ids is not None:
        # padded positions get segment -1: real queries never attend them
        seg_p = jnp.pad(segment_ids, [(0, 0), (0, pm - lk)],
                        constant_values=-1)
    out = _flash_attention_core(
        jnp.pad(q, pad_q), jnp.pad(k, pad_k), jnp.pad(v, pad_k), seg_p,
        causal, block_q, block_k, interpret, window)
    return out[:, :lq]
