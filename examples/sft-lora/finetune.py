"""LoRA supervised fine-tuning on the full tony-tpu stack:
JsonlSource -> InstructionSource (prompt-masked loss) -> frozen base +
low-rank adapters -> fit() -> materialize + greedy-decode the trained
completions as the final self-check.

No reference analog (tony-examples are MNIST-era). This is the
post-training face of the framework: the optimizer state is
adapter-sized, the base stays frozen, and the job script is ~70 lines of
configuration.

Runs standalone (single process, writes its own toy dataset) or under a
tony-tpu gang:

    python -m tony_tpu.cli.local --conf_file examples/sft-lora/job.toml
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))  # repo root, for standalone runs

import jax
import jax.numpy as jnp
import optax

PAIRS = [{"prompt": "2+2=", "completion": "4"},
         {"prompt": "3+3=", "completion": "6"},
         {"prompt": "1+1=", "completion": "2"},
         {"prompt": "4+4=", "completion": "8"}]


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--global-batch", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=8)
    p.add_argument("--rank", type=int, default=8)
    p.add_argument("--alpha", type=float, default=16.0)
    p.add_argument("--data", default="",
                   help="instruction jsonl (prompt/completion per line); "
                        "default: a generated toy arithmetic set")
    args = p.parse_args()

    from tony_tpu import distributed
    from tony_tpu.data import (ByteTokenizer, DataLoader, InstructionSource,
                               JsonlSource)
    from tony_tpu.models import Transformer, TransformerConfig, generate
    from tony_tpu.parallel import data_parallel_mesh
    from tony_tpu.parallel.sharding import batch_sharding
    from tony_tpu.train import (Trainer, cross_entropy_loss, fit, lora_init,
                                lora_param_count, materialize_lora,
                                wrap_apply_fn)

    distributed.initialize()  # no-op outside a gang
    mesh = data_parallel_mesh()

    data = args.data
    if not data:
        work = os.environ.get("TONY_JOB_DIR") or tempfile.mkdtemp(
            prefix="sft-lora-")
        # per-task filename: gang workers share the job dir, and a late
        # writer truncating a file another worker is reading tears lines
        idx = os.environ.get("TONY_TASK_INDEX", "0")
        data = os.path.join(work, f"sft-{idx}.jsonl")
        with open(data, "w") as f:
            f.write("\n".join(json.dumps(r) for r in PAIRS * 4) + "\n")

    tok = ByteTokenizer()
    src = InstructionSource(JsonlSource(data), tok, seq_len=args.seq_len,
                            eos_id=tok.eos_id)
    loader = DataLoader(src, global_batch_size=args.global_batch, seed=1,
                        num_epochs=None, sharding=batch_sharding(mesh))

    cfg = TransformerConfig(
        vocab_size=tok.vocab_size, d_model=64, n_heads=4, n_layers=2,
        d_ff=128, max_seq_len=args.seq_len, dtype=jnp.float32,
        attention_backend="reference")
    model = Transformer(cfg)
    base = model.init(jax.random.PRNGKey(0),
                      jnp.zeros((1, args.seq_len), jnp.int32))

    def base_apply(params, batch):
        logits = model.apply(params, batch["tokens"])
        return cross_entropy_loss(logits[:, :-1], batch["tokens"][:, 1:],
                                  mask=batch["loss_mask"][:, 1:])

    lora = lora_init(jax.random.PRNGKey(1), base, rank=args.rank,
                     targets=("q", "v", "o", "wi", "wo"))
    print(f"LoRA adapters: {lora_param_count(lora)} params "
          f"(base frozen: {sum(x.size for x in jax.tree.leaves(base))})")
    trainer = Trainer(
        mesh=mesh,
        apply_fn=wrap_apply_fn(base_apply, base, alpha=args.alpha),
        optimizer=optax.adam(1e-2), donate=False)
    result = fit(trainer, lora, loader, num_steps=args.steps,
                 log_every=max(args.steps // 4, 1))

    if args.data:
        return 0  # user datasets have no known answer key to decode against
    served = materialize_lora(base, result.state.params, alpha=args.alpha)
    hits = 0
    for row in PAIRS:
        out = generate(model, served["params"],
                       jnp.asarray([tok.encode(row["prompt"])], jnp.int32),
                       max_new_tokens=1)
        got = tok.decode([int(out[0, 0])])
        hits += got == row["completion"]
        print(f"  {row['prompt']!r} -> {got!r} (want {row['completion']!r})")
    print(f"learned {hits}/{len(PAIRS)} completions after {args.steps} steps")
    return 0 if hits >= len(PAIRS) - 1 else 1


if __name__ == "__main__":
    raise SystemExit(main())
