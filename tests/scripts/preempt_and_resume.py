"""Payload: TPU-preemption contract. Attempt 0 SIGTERMs its own agent
(standing in for the platform's spot-reclaim notice); the agent forwards
SIGTERM to this process, whose handler checkpoints and exits non-zero; the
agent reports the exit as preempted; the coordinator retry resumes.
"""

import os
import signal
import sys
import time

import numpy as np

from tony_tpu.train import CheckpointManager, auto_resume

attempt = int(os.environ["TONY_ATTEMPT_NUMBER"])
ckpt_dir = os.environ["TONY_CHECKPOINT_DIR"]


def init_fn():
    return {"step": np.array(0, np.int32)}


state, manager, resumed = auto_resume(init_fn)

if attempt == 0:
    if resumed:
        sys.exit("attempt 0 must start fresh")

    def on_sigterm(signum, frame):
        # the checkpoint-in-grace-window path every real trainer follows
        mgr = CheckpointManager(ckpt_dir)
        mgr.save(7, {"step": np.array(7, np.int32)}, force=True)
        mgr.wait()
        print("checkpointed step 7 inside the preemption grace window")
        sys.exit(1)

    signal.signal(signal.SIGTERM, on_sigterm)

    # stand-in for the cloud preemption notice: SIGTERM the agent process
    os.kill(int(os.environ["TONY_AGENT_PID"]), signal.SIGTERM)
    time.sleep(30)  # the forwarded SIGTERM interrupts this
    sys.exit("never got the forwarded SIGTERM")

if not resumed or int(state["step"]) != 7:
    sys.exit(f"attempt 1 did not resume from step 7: {state}")
if os.environ.get("TONY_RESUME_STEP") != "7":
    sys.exit(f"TONY_RESUME_STEP={os.environ.get('TONY_RESUME_STEP')!r}")
print("resumed from preemption checkpoint OK")
