"""Fused AdamW kernel tests: bit-closeness to optax.adamw, Trainer
integration (flag = pass FusedAdamW where an optax transform would go),
and the shard_map path over the virtual FSDP mesh.

The reference delegates optimization entirely to the user script
(SURVEY.md §2.5); this optimizer is part of tony-tpu's in-tree compute
stack, built for the TPU decode/update bandwidth roofline
(docs/PERF.md: the optax path measured 21 ms of a 220 ms flagship step
at 71% of the HBM roofline — the fused pass is the floor).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tony_tpu.ops.adamw import FusedAdamW, fused_adamw_update
from tony_tpu.train import Trainer


def _tree_close(a, b, rtol=2e-6, atol=3e-7):
    flat_a = jax.tree.leaves(a)
    flat_b = jax.tree.leaves(b)
    assert len(flat_a) == len(flat_b)
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=rtol, atol=atol)


def _params(key):
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        # big leaf -> pallas kernel path; small/odd leaves -> jnp path
        "big": jax.random.normal(k1, (256, 1024), jnp.float32),
        "w": jax.random.normal(k2, (16, 48), jnp.float32),
        "b": jax.random.normal(k3, (48,), jnp.float32),
    }


def test_fused_adamw_matches_optax_over_steps(monkeypatch):
    # force the pallas-kernel leaf path (default routes all leaves
    # through the XLA-fused jnp body — the measured-faster config)
    monkeypatch.setenv("TONY_FUSED_ADAMW_MIN_ELEMS", "1024")
    opt = FusedAdamW(learning_rate=3e-4, weight_decay=1e-2)
    ref = optax.adamw(3e-4, weight_decay=1e-2)
    p_f = p_r = _params(jax.random.PRNGKey(0))
    state, rstate = opt.init(p_f), ref.init(p_r)
    for step in range(4):
        grads = jax.tree.map(lambda p: jnp.sin(p) * 0.1 + step * 0.01, p_r)
        p_f, state = fused_adamw_update(opt, grads, state, p_f)
        upd, rstate = ref.update(grads, rstate, p_r)
        p_r = optax.apply_updates(p_r, upd)
        _tree_close(p_f, p_r)
    assert int(state.count) == 4
    # moments track optax's internal state too (resume compatibility)
    adam_state = rstate[0] if isinstance(rstate, tuple) else rstate
    _tree_close(state.mu, adam_state.mu)
    _tree_close(state.nu, adam_state.nu)


def test_fused_adamw_traced_lr_schedule(monkeypatch):
    monkeypatch.setenv("TONY_FUSED_ADAMW_MIN_ELEMS", "1024")
    """lr rides in the scalar operand, so a traced schedule value works
    under one compiled update (no recompile per step)."""
    opt0 = FusedAdamW(learning_rate=0.0)
    params = {"big": jnp.ones((131072,), jnp.float32)}
    state = opt0.init(params)

    @jax.jit
    def step(lr, params, state):
        opt = FusedAdamW(learning_rate=lr)
        grads = jax.tree.map(jnp.ones_like, params)
        return fused_adamw_update(opt, grads, state, params)

    p1, _ = step(jnp.float32(0.1), params, state)
    p2, _ = step(jnp.float32(0.0), params, state)
    assert float(jnp.abs(p1["big"] - params["big"]).max()) > 0
    _tree_close(p2, params)


def test_trainer_fused_adamw_matches_optax_trainer():
    """Trainer(optimizer=FusedAdamW(...)) trains identically to
    Trainer(optimizer=optax.adamw(...)) — same loss trajectory."""
    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))
    tokens_x = jax.random.normal(jax.random.PRNGKey(1), (8, 1024))
    target = jax.random.normal(jax.random.PRNGKey(2), (8, 128))
    params = {"w": jax.random.normal(jax.random.PRNGKey(3), (1024, 128))
              * 0.02,
              "big": jax.random.normal(jax.random.PRNGKey(4), (256, 1024))
              * 0.02}

    def apply_fn(p, batch):
        pred = batch["x"] @ (p["big"].T @ p["big"]) @ p["w"] * 1e-3 \
            + batch["x"] @ p["w"]
        return jnp.mean((pred - batch["y"]) ** 2)

    losses = {}
    for name, optimizer in (("fused", FusedAdamW(1e-3, weight_decay=1e-2)),
                            ("optax", optax.adamw(1e-3,
                                                  weight_decay=1e-2))):
        trainer = Trainer(mesh=mesh, apply_fn=apply_fn,
                          optimizer=optimizer, donate=False)
        state = trainer.init_state(jax.tree.map(jnp.copy, params))
        step_fn, placed = trainer.build_step(state)
        batch = {"x": tokens_x, "y": target}
        traj = []
        for _ in range(3):
            placed, metrics = step_fn(placed, batch)
            traj.append(float(metrics["loss"]))
        losses[name] = traj
    np.testing.assert_allclose(losses["fused"], losses["optax"],
                               rtol=1e-5)


@pytest.mark.skipif(jax.device_count() < 8, reason="needs 8-dev mesh")
def test_trainer_fused_adamw_fsdp_shard_map(monkeypatch):
    monkeypatch.setenv("TONY_FUSED_ADAMW_MIN_ELEMS", "1024")
    """FSDP-sharded params route the kernel through shard_map (pallas is
    opaque to GSPMD); result must equal the unsharded update."""
    mesh = jax.sharding.Mesh(
        np.array(jax.devices()).reshape(2, 4), ("data", "fsdp"))

    def apply_fn(p, batch):
        h = batch["x"] @ p["w1"]
        return jnp.mean((jnp.tanh(h) @ p["w2"] - batch["y"]) ** 2)

    params = {
        "w1": jax.random.normal(jax.random.PRNGKey(5), (512, 1024)) * 0.02,
        "w2": jax.random.normal(jax.random.PRNGKey(6), (1024, 8)) * 0.02,
    }
    batch = {"x": jax.random.normal(jax.random.PRNGKey(7), (16, 512)),
             "y": jax.random.normal(jax.random.PRNGKey(8), (16, 8))}

    results = {}
    for fsdp in (True, False):
        trainer = Trainer(mesh=mesh, apply_fn=apply_fn,
                          optimizer=FusedAdamW(1e-3), fsdp=fsdp,
                          donate=False)
        state = trainer.init_state(jax.tree.map(jnp.copy, params))
        step_fn, placed = trainer.build_step(state)
        for _ in range(2):
            placed, metrics = step_fn(placed, batch)
        results[fsdp] = jax.device_get(placed.params)
    # fsdp changes grad-reduction order; AdamW's rsqrt amplifies the
    # few ulps where nu ~ 0 — tolerance covers ordering, not math, drift
    _tree_close(results[True], results[False], rtol=1e-4, atol=2e-5)


def test_trainer_fused_adamw_compute_carry():
    """compute_dtype + FusedAdamW carries a bf16 copy of the params in
    the optimizer state (emitted by the fused pass): the training
    trajectory must track the optax mixed-precision path closely (grads
    round to bf16 once — the documented numerics delta), and the carried
    copy must equal the cast of the fp32 master."""
    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 256))
    y = jax.random.normal(jax.random.PRNGKey(2), (8, 4))
    params = {"big": jax.random.normal(jax.random.PRNGKey(3), (256, 1024))
              * 0.05,
              "head": jax.random.normal(jax.random.PRNGKey(4), (1024, 4))
              * 0.05}

    def apply_fn(p, batch):
        h = jnp.tanh(batch["x"] @ p["big"])
        return jnp.mean((h @ p["head"] - batch["y"]) ** 2)

    losses = {}
    for name, optimizer in (("fused", FusedAdamW(2e-3)),
                            ("optax", optax.adamw(2e-3))):
        trainer = Trainer(mesh=mesh, apply_fn=apply_fn,
                          optimizer=optimizer, donate=False,
                          compute_dtype=jnp.bfloat16)
        state = trainer.init_state(jax.tree.map(jnp.copy, params))
        step_fn, placed = trainer.build_step(state)
        batch = {"x": x, "y": y}
        traj = []
        for _ in range(10):
            placed, metrics = step_fn(placed, batch)
            traj.append(float(metrics["loss"]))
        losses[name] = traj
        if name == "fused":
            cp = placed.opt_state.compute_params
            assert cp is not None
            assert cp["big"].dtype == jnp.bfloat16
            np.testing.assert_array_equal(
                np.asarray(cp["big"], np.float32),
                np.asarray(placed.params["big"].astype(jnp.bfloat16),
                           np.float32))
    # same trajectory within bf16 grad-rounding noise
    np.testing.assert_allclose(losses["fused"], losses["optax"],
                               rtol=0.05)
    assert losses["fused"][-1] < losses["fused"][0] * 0.9  # it learns


def test_fused_adamw_schedule_matches_optax():
    """A callable learning_rate (optax schedule) drops in and matches
    optax.adamw(schedule) step for step."""
    sched = optax.cosine_decay_schedule(1e-2, 10)
    opt = FusedAdamW(learning_rate=sched)
    ref = optax.adamw(sched)
    p_f = p_r = {"w": jnp.ones((8, 16)) * 0.5}
    state, rstate = opt.init(p_f), ref.init(p_r)
    for step in range(4):
        grads = jax.tree.map(lambda p: jnp.cos(p) * 0.1, p_r)
        p_f, state = fused_adamw_update(opt, grads, state, p_f)
        upd, rstate = ref.update(grads, rstate, p_r)
        p_r = optax.apply_updates(p_r, upd)
        _tree_close(p_f, p_r)


def test_trainer_fused_adamw_carry_with_accum():
    """The carry composes with gradient accumulation: per-micro grads
    arrive bf16, the accumulator stays fp32, and the trajectory tracks
    the optax accum path."""
    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))
    x = jax.random.normal(jax.random.PRNGKey(1), (16, 256))
    y = jax.random.normal(jax.random.PRNGKey(2), (16, 4))
    params = {"big": jax.random.normal(jax.random.PRNGKey(3), (256, 1024))
              * 0.05,
              "head": jax.random.normal(jax.random.PRNGKey(4), (1024, 4))
              * 0.05}

    def apply_fn(p, batch):
        h = jnp.tanh(batch["x"] @ p["big"])
        return jnp.mean((h @ p["head"] - batch["y"]) ** 2)

    losses = {}
    for name, optimizer in (("fused", FusedAdamW(2e-3)),
                            ("optax", optax.adamw(2e-3))):
        trainer = Trainer(mesh=mesh, apply_fn=apply_fn,
                          optimizer=optimizer, donate=False,
                          compute_dtype=jnp.bfloat16, accum_steps=4)
        state = trainer.init_state(jax.tree.map(jnp.copy, params))
        step_fn, placed = trainer.build_step(state)
        batch = {"x": x, "y": y}
        traj = []
        for _ in range(8):
            placed, metrics = step_fn(placed, batch)
            traj.append(float(metrics["loss"]))
        losses[name] = traj
        if name == "fused":
            cp = placed.opt_state.compute_params
            assert cp is not None and cp["big"].dtype == jnp.bfloat16
    np.testing.assert_allclose(losses["fused"], losses["optax"],
                               rtol=0.05)
    assert losses["fused"][-1] < losses["fused"][0]


def test_fused_adamw_tuple_axis_partition_spec(monkeypatch):
    """A PartitionSpec entry that is a TUPLE of axis names
    (P(('data','fsdp')) — what batch_sharding emits on multi-axis
    meshes) must divide the local element count by EVERY named axis,
    not raise KeyError (ADVICE r5)."""
    monkeypatch.setenv("TONY_FUSED_ADAMW_MIN_ELEMS", "1024")
    from jax.sharding import Mesh, PartitionSpec as P

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device virtual mesh")
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "fsdp"))
    opt = FusedAdamW(learning_rate=3e-4, weight_decay=1e-2)
    params = {"big": jax.random.normal(jax.random.PRNGKey(0),
                                       (256, 1024), jnp.float32)}
    grads = jax.tree.map(lambda p: jnp.sin(p) * 0.1, params)
    state = opt.init(params)
    specs = {"big": P(("data", "fsdp"), None)}
    p_sharded, _ = fused_adamw_update(opt, grads, state, params,
                                      mesh=mesh, param_specs=specs)
    # same math as the unsharded update
    p_plain, _ = fused_adamw_update(opt, grads, opt.init(params), params)
    _tree_close(p_sharded, p_plain)


def test_fused_adamw_compute_params_nonfloat_leaf_tracks_params():
    """With compute_dtype set, a NON-floating leaf must carry the same
    value in params and compute_params after the update — a stale
    pre-update copy in compute_params would make the tree the next step
    differentiates diverge from the master (ADVICE r5)."""
    opt = FusedAdamW(learning_rate=0.5, weight_decay=0.0)
    params = {"w": jnp.ones((8, 16), jnp.float32),
              "steps": jnp.asarray([10, 20], jnp.int32)}
    state = opt.init(params, compute_dtype=jnp.bfloat16)
    grads = {"w": jnp.ones((8, 16), jnp.float32),
             "steps": jnp.asarray([100, 100], jnp.int32)}
    new_p, new_state = fused_adamw_update(opt, grads, state, params,
                                          compute_dtype=jnp.bfloat16)
    np.testing.assert_array_equal(np.asarray(new_p["steps"]),
                                  np.asarray(
                                      new_state.compute_params["steps"]))
    # float leaves carry the bf16 copy of the updated master
    assert new_state.compute_params["w"].dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(new_state.compute_params["w"], np.float32),
        np.asarray(new_p["w"]), rtol=1e-2)
