"""Per-dispatch engine timeline: what every device program launch cost.

The serving engine's counters (prefills / decode_steps / dispatches)
say HOW MUCH device work ran; this module records WHEN and HOW LONG —
one ``DispatchRecord`` per engine dispatch (prefill, hit-admit, decode
chunk, spec-verify), with the live-slot occupancy, the program's shape
knob (prefill bucket / chunk depth / verify window), the tokens the
dispatch actually landed, and a first-call flag separating compile
(or compile-cache-load) time from steady state. This is the direct
sensor for ROADMAP item 4's dispatch-overhead attack: the roofline gap
shows up here as host-side milliseconds per dispatch that the per-op
xplane view cannot see.

Durations are HOST WALL time from just before the dispatch call to
just after the engine's host sync of its outputs — on an async backend
that includes device execution plus transfer, which is exactly the
latency a request experiences. The ``compile`` flag marks the first
record of each (kind, shape) pair on this engine; with a warm
in-process jit cache or a persistent compile cache the flagged call
may be cheap — the flag means "first call", the duration says whether
it compiled.

A bounded ring keeps recent records for trace attachment and debug;
cumulative per-kind aggregates survive eviction, so ``summary()`` (the
``/stats`` ``dispatches`` block) is lifetime-accurate. Appending is a
lock plus a dataclass — cheap enough to leave on in production, which
the obs overhead gate (bench ``extras.obs``) pins.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any


@dataclass
class DispatchRecord:
    """One engine dispatch. ``kind`` is "prefill" | "hit_admit" |
    "cow_admit" | "decode" | "verify" — cow_admit is the PAGED
    exact-prefix-hit admission (pages aliased host-side, one sampling
    dispatch): its own kind so per-kind ``tokens_per_dispatch`` never
    counts an aliasing admit as prefill work. ``bucket`` is the
    program's static shape knob (prefill bucket length, chunk depth,
    verify window — 0 for hit_admit/cow_admit); ``tokens`` counts
    tokens the dispatch landed for requests (trimmed overshoot
    excluded); ``request_id`` is set on admit dispatches (the engine
    id of the admitted request)."""

    kind: str
    t0: float          # time.monotonic() at dispatch start
    dur_ms: float      # host wall: dispatch + output sync
    occupancy: int     # live slots at dispatch time
    bucket: int
    tokens: int
    compile: bool      # first (kind, bucket) call on this engine
    request_id: Any = None
    tags: dict = field(default_factory=dict)
    seq: int = 0       # assigned by the timeline, monotonically


class DispatchTimeline:
    """Ring of recent ``DispatchRecord``s + lifetime per-kind
    aggregates. Thread-safe; the engine records from its owner thread,
    readers (``/stats``, the trace attacher) snapshot from others."""

    def __init__(self, capacity: int = 1024):
        self._lock = threading.Lock()
        self._ring: deque[DispatchRecord] = deque(maxlen=max(1, capacity))
        self._seq = 0
        # kind -> [count, total_ms, max_ms, compiles, compile_ms, tokens]
        self._agg: dict[str, list[float]] = {}

    def record(self, rec: DispatchRecord) -> None:
        with self._lock:
            self._seq += 1
            rec.seq = self._seq
            self._ring.append(rec)
            agg = self._agg.setdefault(rec.kind, [0, 0.0, 0.0, 0, 0.0, 0])
            agg[0] += 1
            agg[1] += rec.dur_ms
            agg[2] = max(agg[2], rec.dur_ms)
            if rec.compile:
                agg[3] += 1
                agg[4] += rec.dur_ms
            agg[5] += rec.tokens

    def take_new(self, cursor: int) -> tuple[list[DispatchRecord], int]:
        """Records with ``seq > cursor`` still in the ring, plus the new
        cursor — the trace attacher's incremental read. Records evicted
        before being read are simply gone (bounded memory beats
        completeness for a debug surface). O(new), not O(ring): this
        runs on the replica scheduler loop every iteration under the
        same lock ``record()`` needs, so a full-ring scan per step
        would be pure hot-loop waste."""
        with self._lock:
            if self._seq == cursor:
                return [], cursor
            new = []
            for rec in reversed(self._ring):  # deque ends are O(1)
                if rec.seq <= cursor:
                    break
                new.append(rec)
            new.reverse()
            return new, self._seq

    def recent(self, n: int = 64) -> list[DispatchRecord]:
        with self._lock:
            return list(self._ring)[-n:]

    def summary(self) -> dict:
        """The ``/stats`` ``dispatches`` block: lifetime per-kind
        aggregates with compile time split out, so steady-state
        mean_ms answers "what does one dispatch cost" without the
        first-call spike polluting it."""
        out: dict = {}
        with self._lock:
            items = {k: list(v) for k, v in self._agg.items()}
        for kind, (count, ms, max_ms, compiles, compile_ms, toks) in \
                sorted(items.items()):
            steady_n = count - compiles
            steady_ms = ms - compile_ms
            out[kind] = {
                "count": int(count),
                "ms": round(ms, 3),
                "max_ms": round(max_ms, 3),
                "compiles": int(compiles),
                "compile_ms": round(compile_ms, 3),
                "steady_mean_ms": round(steady_ms / steady_n, 3)
                if steady_n else 0.0,
                "tokens": int(toks),
                "tokens_per_dispatch": round(toks / count, 3)
                if count else 0.0,
            }
        return out

    @staticmethod
    def merge(summaries: list[dict]) -> dict:
        """Sum per-kind summaries across replicas (the fleet view the
        gateway's ``/stats`` carries): counts/ms/tokens add, max_ms
        maxes, means are recomputed from the merged totals."""
        merged: dict = {}
        for s in summaries:
            for kind, v in s.items():
                m = merged.setdefault(kind, {
                    "count": 0, "ms": 0.0, "max_ms": 0.0, "compiles": 0,
                    "compile_ms": 0.0, "tokens": 0})
                m["count"] += v["count"]
                m["ms"] += v["ms"]
                m["max_ms"] = max(m["max_ms"], v["max_ms"])
                m["compiles"] += v["compiles"]
                m["compile_ms"] += v["compile_ms"]
                m["tokens"] += v["tokens"]
        for kind, m in merged.items():
            steady_n = m["count"] - m["compiles"]
            steady_ms = m["ms"] - m["compile_ms"]
            m["ms"] = round(m["ms"], 3)
            m["compile_ms"] = round(m["compile_ms"], 3)
            m["steady_mean_ms"] = round(steady_ms / steady_n, 3) \
                if steady_n else 0.0
            m["tokens_per_dispatch"] = round(m["tokens"] / m["count"], 3) \
                if m["count"] else 0.0
        return merged
