"""Payload assertion: worker_env./shell-env props must reach the task env."""
import os
import sys

sys.exit(0 if os.environ.get("WF_CANARY") == "present" else 1)
