"""Pressure-driven session rebalancing: close the loop between the
fleet's occupancy sensors and live migration.

PR 18 made in-flight sessions MOVABLE (``Gateway.migrate_session``
freezes a decode slot mid-stream and re-admits it elsewhere,
token-exact); retirement and failover already use that machinery, but
only topology CHANGES triggered it. A fleet whose topology is stable
can still be badly packed: a connection storm lands on whichever
replicas were routable at the time, a scale-up adds a cold empty
replica that only NEW sessions discover, and long streams pin their
slots for minutes. The result is one replica decoding at full batch
while its neighbour idles — the exact shape TonY's control plane
exists to fix (acquire/release resources to MATCH the job, not the
job's arrival order).

``Rebalancer`` is the missing loop, built like ``AutoScaler`` (one
consistent signals read per tick, pure ``decide()``, streak
hysteresis, per-direction cooldowns) but actuating migration instead
of membership:

- every ``interval_s`` it reads ``Gateway.rebalance_signals()`` — one
  consistent per-replica view of slot occupancy, queue depth, and the
  in-flight ticket set;
- the fleet counts as SKEWED when the hottest replica's occupancy
  fraction exceeds the coldest's by ``skew_frac`` AND the hot replica
  holds at least ``min_sessions`` more active sessions AND the cold
  one has a free slot (moving onto a full replica is churn, not
  balance);
- hysteresis: ``stable`` consecutive skewed ticks before acting, then
  a ``cooldown_s`` lockout (``fail_cooldown_s`` after a move that
  found nothing to migrate — a broken condition must not hot-loop);
- the victim is chosen by PREFIX HEAT: each of the hot replica's
  in-flight prompts is scored with the cold replica's
  ``prefix_match_len`` probe (local radix walk, or the heartbeat
  summary for remote stubs), and the session the cold side already
  holds pages for wins — its migration ships the least KV, and with
  delta trimming (this PR) possibly only its suffix. Ties fall to the
  session with the MOST remaining work, so one move transfers the
  most future load;
- the move itself is ``gateway.migrate_session(rid)`` — the ordinary
  routing stack places it, so prefix affinity and least-outstanding
  tie-breaks steer it toward the cold replica without this loop ever
  naming a destination (routing policy stays in ONE place).

Every decision — moved or skipped, with the skew it saw — lands in
the ring behind /stats ``rebalance``, in ``tony_rebalance_*``
metrics, and (with history on) in ``metrics/rebalance.jsonl``, so
"why did request 17 jump replicas at 14:02" is answerable from the
job record.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque

log = logging.getLogger(__name__)


class Rebalancer:
    """The gateway's session-packing control loop. Construct with a
    started ``Gateway``, then ``start()``; ``stop()`` is idempotent
    and also called by ``Gateway.drain()``.

    Knobs:

    - ``interval_s``: tick period.
    - ``skew_frac``: minimum (hot - cold) occupancy-fraction gap that
      counts as skew (0.5 = hot replica 50 points fuller).
    - ``min_sessions``: the hot replica must hold at least this many
      more ACTIVE sessions than the cold one (fraction gaps on tiny
      batch sizes are noise).
    - ``stable``: consecutive skewed ticks before a move (hysteresis).
    - ``cooldown_s`` / ``fail_cooldown_s``: lockout after a successful
      / failed move.
    - ``max_moves``: sessions migrated per acting tick (default 1 —
      one move changes the signals; re-deciding on fresh ones beats
      batch-moving on stale ones).
    """

    def __init__(self, gateway, *, interval_s: float = 1.0,
                 skew_frac: float = 0.5, min_sessions: int = 2,
                 stable: int = 2, cooldown_s: float = 5.0,
                 fail_cooldown_s: float = 10.0, max_moves: int = 1,
                 decisions_kept: int = 64):
        if not 0.0 < skew_frac <= 1.0:
            raise ValueError(f"skew_frac must be in (0, 1], "
                             f"got {skew_frac}")
        self.gateway = gateway
        self.interval_s = max(0.01, interval_s)
        self.skew_frac = skew_frac
        self.min_sessions = max(1, min_sessions)
        self.stable = max(1, stable)
        self.cooldown_s = cooldown_s
        self.fail_cooldown_s = fail_cooldown_s
        self.max_moves = max(1, max_moves)
        # decision state
        self._streak = 0
        self._cooldown_until = 0.0
        self.moves = 0
        self.move_failures = 0
        self.errors = 0
        self.ticks = 0
        self.decisions: deque[dict] = deque(maxlen=max(1, decisions_kept))
        self._stop_evt = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()  # guards status vs the loop
        gateway.rebalancer = self  # surface on /stats; stopped by drain()

    # -------------------------------------------------------- lifecycle

    def start(self) -> "Rebalancer":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._loop,
                                        name="gateway-rebalancer",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float | None = None) -> None:
        """Idempotent; joins the loop thread. A migration in flight
        finishes first — the loop checks the stop flag between ticks,
        not inside an action."""
        self._stop_evt.set()
        t = self._thread
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout if timeout is not None
                   else 10 * self.interval_s + 30)

    def _loop(self) -> None:
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the control loop must
                # survive anything: a broken tick is a logged error
                # plus a missed beat, never a dead rebalancer
                self.errors += 1
                log.exception("rebalancer tick failed")

    # --------------------------------------------------------- decisions

    def tick(self) -> int:
        """One control iteration (public for tests: drive the loop by
        hand). Returns the number of sessions moved this tick."""
        sig = self.gateway.rebalance_signals()
        self.ticks += 1
        plan, reasons = self.decide(sig, sig["now"])
        if plan is None:
            return 0
        return self._execute(plan, sig, reasons)

    def decide(self, sig: dict, now: float) -> tuple[dict | None, list]:
        """Pure decision half (unit-testable): classify the tick as
        skewed or not, advance the hysteresis streak, and return the
        (hot, cold) pair once the streak crosses ``stable`` outside
        the cooldown. The pair is a PLAN, not a promise — victim
        choice and the move itself happen in ``_execute``."""
        skew = self._skew(sig)
        if skew is None:
            self._streak = 0
            return None, []
        hot, cold, gap = skew
        self._streak += 1
        reasons = [f"skew {gap:.2f} (replica {hot['index']} "
                   f"{hot['active']}/{hot['slots']} vs replica "
                   f"{cold['index']} {cold['active']}/{cold['slots']})"]
        if now < self._cooldown_until or self._streak < self.stable:
            return None, reasons
        return {"hot": hot, "cold": cold, "gap": gap}, reasons

    def _skew(self, sig: dict) -> tuple[dict, dict, float] | None:
        """The skew classifier: (hot row, cold row, fraction gap) when
        the fleet is imbalanced enough to act on, else None."""
        rows = [r for r in sig["replicas"] if r["slots"] > 0]
        if len(rows) < 2:
            return None
        hot = max(rows, key=lambda r: (r["active"] / r["slots"],
                                       r["active"]))
        cold = min(rows, key=lambda r: (r["active"] / r["slots"],
                                        r["active"]))
        if hot["index"] == cold["index"]:
            return None
        gap = hot["active"] / hot["slots"] - cold["active"] / cold["slots"]
        if gap < self.skew_frac:
            return None
        if hot["active"] < cold["active"] + self.min_sessions:
            return None
        if cold["active"] >= cold["slots"]:
            # nowhere for the session to land: routing would put it
            # right back (or worse, on the hot replica's queue)
            return None
        if not hot["tickets"]:
            # active slots but no gateway tickets: sessions the
            # gateway cannot name (mid-admission) — wait them out
            return None
        return hot, cold, gap

    def _victims(self, plan: dict) -> list:
        """Rank the hot replica's in-flight sessions by how cheaply
        the COLD side could adopt them: longest cached prefix first
        (those migrations ship the least KV — with delta trimming,
        only the suffix), most remaining work as the tie-break (one
        move should transfer the most future load)."""
        cold = next((r for r in self.gateway.live_replicas
                     if r.index == plan["cold"]["index"]), None)
        probe = getattr(cold.server, "prefix_match_len", None) \
            if cold is not None and cold.server is not None else None
        scored = []
        for row in plan["hot"]["tickets"]:
            heat = 0
            if probe is not None and row["prompt"]:
                try:
                    heat = int(probe(row["prompt"]))
                except Exception:  # noqa: BLE001 — a failed probe
                    # costs a 0 score, never a dead tick
                    log.exception("rebalance prefix probe failed")
            scored.append((heat, row["remaining"], row["rid"]))
        scored.sort(key=lambda s: (-s[0], -s[1]))
        return [rid for _, _, rid in scored]

    # ----------------------------------------------------------- actions

    def _execute(self, plan: dict, sig: dict, reasons: list) -> int:
        moved = 0
        t0 = time.monotonic()
        for rid in self._victims(plan):
            try:
                ok = self.gateway.migrate_session(rid)
            except Exception as e:  # noqa: BLE001 — a failed move is a
                # recorded decision + cooldown, never a dead loop
                self.errors += 1
                log.exception("rebalance migration failed")
                self._record("move_failed", sig, reasons, rid=rid,
                             error=str(e))
                self._after_action(ok=False)
                return moved
            if ok:
                moved += 1
                self.moves += 1
                self._record("move", sig, reasons, rid=rid,
                             from_replica=plan["hot"]["index"],
                             gap=round(plan["gap"], 3),
                             took_s=round(time.monotonic() - t0, 3))
                log.warning("rebalancer: migrated request %s off "
                            "replica %d (%s)", rid,
                            plan["hot"]["index"], "; ".join(reasons))
                if moved >= self.max_moves:
                    break
            # not ok: the session finished or left its slot between
            # the signals read and the freeze — try the next victim
        if moved == 0:
            self.move_failures += 1
            self._record("no_victim", sig, reasons)
        self._after_action(ok=moved > 0)
        return moved

    def _after_action(self, ok: bool) -> None:
        self._cooldown_until = time.monotonic() + \
            (self.cooldown_s if ok else self.fail_cooldown_s)
        self._streak = 0

    # ------------------------------------------------------ observability

    def _record(self, action: str, sig: dict, reasons: list,
                **extra) -> None:
        row = {
            "t": round(time.time(), 3),
            "action": action,
            "reasons": list(reasons),
            "occupancy": [[r["index"], r["active"], r["slots"]]
                          for r in sig["replicas"]],
            **extra,
        }
        with self._lock:
            self.decisions.append(row)
        history = getattr(self.gateway, "history", None)
        if history is not None:
            try:
                history.record_rebalance(row)
            except Exception:  # noqa: BLE001 — same contract as every
                # other history write: never let a disk hiccup near
                # the serving path
                log.exception("history rebalance write failed")

    def status(self) -> dict:
        """The /stats ``rebalance`` block."""
        with self._lock:
            decisions = list(self.decisions)[-8:]
        return {
            "enabled": True,
            "interval_s": self.interval_s,
            "skew_frac": self.skew_frac,
            "min_sessions": self.min_sessions,
            "moves": self.moves,
            "move_failures": self.move_failures,
            "errors": self.errors,
            "ticks": self.ticks,
            "streak": self._streak,
            "cooldown_s": round(
                max(0.0, self._cooldown_until - time.monotonic()), 3),
            "last_decisions": decisions,
        }
