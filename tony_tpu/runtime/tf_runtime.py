"""TensorFlow runtime: TF_CONFIG injection.

Reference: runtime/TFRuntime.java:45-59 + Utils.constructTFConfig
(util/Utils.java:503-520): gang mode only; the spec strips the
``tensorboard`` role always, and strips ``evaluator`` for non-evaluator
tasks (estimator semantics).
"""

from __future__ import annotations

import json

from tony_tpu import constants as C
from tony_tpu.runtime.base import Runtime, TaskAdapter, TaskContext


def construct_tf_config(cluster_spec: dict[str, list[str]], role: str,
                        index: int) -> str:
    cluster = {
        r: list(slots)
        for r, slots in cluster_spec.items()
        if r != C.TENSORBOARD_JOB_NAME
        and not (r == C.EVALUATOR_JOB_NAME and role != C.EVALUATOR_JOB_NAME)
    }
    return json.dumps(
        {
            "cluster": cluster,
            "task": {"type": role, "index": index},
            "environment": "cloud",
        }
    )


class TFTaskAdapter(TaskAdapter):
    def build_task_env(self, ctx: TaskContext) -> dict[str, str]:
        env = super().build_task_env(ctx)
        mode = str(ctx.conf.get("tony.application.distributed-mode"))
        if mode == C.GANG:  # TF_CONFIG only meaningful with the full gang
            env[C.TF_CONFIG] = construct_tf_config(ctx.cluster_spec, ctx.role, ctx.index)
        return env


class TFRuntime(Runtime):
    name = "tensorflow"
    task_adapter_cls = TFTaskAdapter
