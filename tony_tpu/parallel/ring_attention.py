"""Ring attention: sequence/context parallelism over the ``seq`` mesh axis.

Absent from the reference entirely (SURVEY.md section 5.7: TonY scales
workers, never sequence length) — first-class here. Each device holds a
sequence shard of Q/K/V; K/V blocks rotate around the ring via
``lax.ppermute`` (XLA collective-permute over ICI neighbors) while every
device accumulates its queries' attention with an online-softmax running
state, so peak memory is O(L/n) and comm overlaps compute around the ring
(Liu et al., Ring Attention with Blockwise Transformers; public pattern,
re-implemented for shard_map).

Differentiable end-to-end: the scan + ppermute compose with jax autodiff
(ppermute's transpose is the reverse permute).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from tony_tpu.utils.compat import shard_map

from tony_tpu.parallel.mesh import SEQ

NEG_INF = -1e30


def _block_attn(q, k, v, m, l, o, mask):
    """One online-softmax accumulation step.

    q: [B, Lq, H, D]; k/v: [B, Lk, H, D]; m/l: [B, H, Lq]; o like q.
    mask: boolean (True = attend), [Lq, Lk] shared across the batch or
    [B, Lq, Lk] per-example (segment masking), or None.
    """
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        bmask = mask[None, None] if mask.ndim == 2 else mask[:, None]
        s = jnp.where(bmask, s, NEG_INF)
    m_blk = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    # guard fully-masked rows (all NEG_INF): exp underflows to 0 safely
    p = jnp.exp(s - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1)
    o_new = o * corr[..., None].transpose(0, 2, 1, 3) + jnp.einsum(
        "bhqk,bkhd->bqhd", p, v)
    return m_new, l_new, o_new


def _ring_attention_local(q, k, v, segments, *, axis_name: str, causal: bool,
                          window: int):
    """Per-shard body under shard_map. Shapes are the local shards:
    q/k/v: [B, L_local, H, D]; segments: [B, L_local] int or None.

    ``window``/``segments`` masking is positional, and every ring step
    knows the global positions of the visiting K/V block from its source
    shard index — so the sliding-window cut and packed-document masks are
    exact across shard boundaries. Segment ids rotate around the ring
    with their K/V block (one extra int ppermute per step, negligible
    next to the K/V traffic).
    """
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, lq, h, d = q.shape
    m = jnp.full((b, h, lq), NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((b, h, lq), dtype=jnp.float32)
    o = jnp.zeros((b, lq, h, d), dtype=jnp.float32)
    q32 = q.astype(jnp.float32)

    pos_q = my_idx * lq + jnp.arange(lq)
    perm = [(j, (j - 1) % n) for j in range(n)]
    seg_blk0 = segments if segments is not None else jnp.zeros((b, 0),
                                                               jnp.int32)

    def step(carry, i):
        k_blk, v_blk, seg_blk, m, l, o = carry
        src_idx = (my_idx + i) % n  # which shard this k/v block came from
        pos_k = src_idx * lq + jnp.arange(lq)
        mask = None
        if causal:
            mask = pos_q[:, None] >= pos_k[None, :]
        if window > 0:
            delta = pos_q[:, None] - pos_k[None, :]
            wmask = (delta >= 0) & (delta < window)
            mask = wmask if mask is None else mask & wmask
        if segments is not None:
            same = segments[:, :, None] == seg_blk[:, None, :]
            mask = same if mask is None else mask[None] & same
        m, l, o = _block_attn(q32, k_blk.astype(jnp.float32),
                              v_blk.astype(jnp.float32), m, l, o, mask)
        # rotate k/v to the next ring position (receive from right neighbor)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        if segments is not None:
            seg_blk = lax.ppermute(seg_blk, axis_name, perm)
        return (k_blk, v_blk, seg_blk, m, l, o), None

    (k, v, _, m, l, o), _ = lax.scan(step, (k, v, seg_blk0, m, l, o),
                                     jnp.arange(n))
    out = o / jnp.maximum(l[..., None].transpose(0, 2, 1, 3), 1e-30)
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, *, axis_name: str = SEQ,
                   causal: bool = True,
                   batch_spec: P | None = None,
                   window: int = 0, segment_ids=None):
    """Sequence-parallel attention.

    q/k/v: [B, L, H, D] globally, sharded along L over ``axis_name``.
    Returns [B, L, H, D] with the same sharding. ``window`` > 0 applies
    sliding-window masking (key visible iff 0 <= q_pos - k_pos < window);
    ``segment_ids`` [B, L] (sharded like the sequence) restricts attention
    to keys in the same segment (packed documents).
    """
    qspec = P(batch_spec, axis_name, None, None) if batch_spec else \
        P(None, axis_name, None, None)
    sspec = P(batch_spec, axis_name) if batch_spec else P(None, axis_name)
    local = functools.partial(_ring_attention_local, axis_name=axis_name,
                              causal=causal, window=window)
    if segment_ids is None:
        fn = shard_map(lambda q, k, v: local(q, k, v, None), mesh=mesh,
                       in_specs=(qspec, qspec, qspec), out_specs=qspec,
                       check_vma=False)
        return fn(q, k, v)
    fn = shard_map(local, mesh=mesh, in_specs=(qspec, qspec, qspec, sspec),
                   out_specs=qspec, check_vma=False)
    return fn(q, k, v, segment_ids.astype(jnp.int32))


def blockwise_attention(q, k, v, *, block_size: int = 512, causal: bool = True,
                        window: int = 0, segment_ids=None):
    """Single-device memory-efficient attention: the same online-softmax
    accumulation over K/V chunks without the ring — the long-context path
    when seq fits one device but the full [L, L] score matrix does not.

    window > 0 restricts each query to the last ``window`` keys (sliding
    window, HF Mistral semantics: key visible iff 0 <= q_pos - k_pos <
    window); 0 means full causal/bidirectional.

    segment_ids [B, L] (packed-document training) restricts attention to
    keys in the SAME segment — documents packed into one window never
    attend across their boundaries.
    """
    b, lq, h, d = q.shape
    lk = k.shape[1]
    block = min(block_size, lk)
    n_blocks = (lk + block - 1) // block
    pad = n_blocks * block - lk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    if segment_ids is not None:
        seg_q = segment_ids
        # pad with -1: padded keys match no real segment
        seg_k = jnp.pad(segment_ids, ((0, 0), (0, pad)),
                        constant_values=-1) if pad else segment_ids
    q32 = q.astype(jnp.float32)
    m = jnp.full((b, h, lq), NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((b, h, lq), dtype=jnp.float32)
    o = jnp.zeros((b, lq, h, d), dtype=jnp.float32)
    pos_q = jnp.arange(lq)

    def step(carry, i):
        m, l, o = carry
        k_blk = lax.dynamic_slice_in_dim(k, i * block, block, axis=1)
        v_blk = lax.dynamic_slice_in_dim(v, i * block, block, axis=1)
        pos_k = i * block + jnp.arange(block)
        mask = pos_k[None, :] < lk  # mask padding
        if causal:
            mask = mask & (pos_q[:, None] >= pos_k[None, :])
        else:
            mask = jnp.broadcast_to(mask, (lq, block))
        if window > 0:
            # documented bound 0 <= q_pos - k_pos < window: the lower half
            # must hold even under causal=False
            delta = pos_q[:, None] - pos_k[None, :]
            mask = mask & (delta >= 0) & (delta < window)
        if segment_ids is not None:
            seg_k_blk = lax.dynamic_slice_in_dim(seg_k, i * block, block,
                                                 axis=1)
            mask = mask[None] & (seg_q[:, :, None] == seg_k_blk[:, None, :])
        m, l, o = _block_attn(q32, k_blk.astype(jnp.float32),
                              v_blk.astype(jnp.float32), m, l, o, mask)
        return (m, l, o), None

    (m, l, o), _ = lax.scan(step, (m, l, o), jnp.arange(n_blocks))
    out = o / jnp.maximum(l[..., None].transpose(0, 2, 1, 3), 1e-30)
    return out.astype(q.dtype)


def reference_attention(q, k, v, *, causal: bool = True, window: int = 0,
                        segment_ids=None):
    """O(L^2)-memory reference for tests. ``window``/``segment_ids`` as in
    blockwise_attention."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    lq, lk = q.shape[1], k.shape[1]
    pos_q, pos_k = jnp.arange(lq)[:, None], jnp.arange(lk)[None, :]
    if causal:
        s = jnp.where((pos_q >= pos_k)[None, None], s, NEG_INF)
    if window > 0:
        visible = (pos_q >= pos_k) & (pos_q - pos_k < window)
        s = jnp.where(visible[None, None], s, NEG_INF)
    if segment_ids is not None:
        same = segment_ids[:, :, None] == segment_ids[:, None, :]
        s = jnp.where(same[:, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)
