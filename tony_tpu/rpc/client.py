"""RPC client with reconnect + poll helpers.

Reference: rpc/impl/ApplicationRpcClient.java (singleton per AM address) and
the pollTillNonNull registration loop (TaskExecutor.java:294-296 /
util/Utils.java:96-129).
"""

from __future__ import annotations

import logging
import socket
import threading
import time
from typing import Any, Callable

from tony_tpu.rpc import wire

log = logging.getLogger(__name__)


class RpcError(RuntimeError):
    """Server-side error returned for a call."""


class RpcClient:
    def __init__(self, host: str, port: int, secret: str | None = None,
                 timeout: float = 30.0, tls_fingerprint: str | None = None):
        """``tls_fingerprint``: pin the coordinator's per-job self-signed
        cert by SHA-256 digest (rpc/tls.py); connections whose served cert
        doesn't match are refused."""
        self.host = host
        self.port = port
        self.secret = secret
        self.timeout = timeout
        self.tls_fingerprint = tls_fingerprint
        self._sock: socket.socket | None = None
        self._req_id = 0
        self._lock = threading.Lock()

    # -- connection ---------------------------------------------------------
    def _connect(self) -> socket.socket:
        if self._sock is None:
            sock = socket.create_connection((self.host, self.port), timeout=self.timeout)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self.tls_fingerprint:
                from tony_tpu.rpc.tls import client_wrap

                try:
                    sock = client_wrap(sock, self.tls_fingerprint)
                except BaseException:
                    # handshake failure: the raw fd is not yet tracked in
                    # self._sock — close it here or every retry leaks one
                    sock.close()
                    raise
            self._sock = sock
        return self._sock

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                finally:
                    self._sock = None

    # -- calls --------------------------------------------------------------
    def call(self, method: str, retries: int = 2, **params: Any) -> Any:
        """Invoke ``method`` on the server; reconnects once per retry on
        connection-level failure. Server-side errors raise RpcError."""
        with self._lock:
            last: Exception | None = None
            for _ in range(retries + 1):
                try:
                    sock = self._connect()
                    self._req_id += 1
                    wire.send_frame(
                        sock, wire.make_request(self._req_id, method, params, self.secret)
                    )
                    resp = wire.recv_frame(sock)
                    if resp is None:
                        raise ConnectionError("server closed connection")
                    if "error" in resp:
                        raise RpcError(resp["error"])
                    return resp.get("result")
                except (ConnectionError, TimeoutError, OSError) as e:
                    last = e
                    self._sock = None
                    time.sleep(0.2)
            raise ConnectionError(f"RPC {method} to {self.host}:{self.port} failed: {last}")

    def poll_till_non_null(self, fn: Callable[[], Any], interval_s: float = 0.5,
                           timeout_s: float | None = None) -> Any:
        """Reference: Utils.pollTillNonNull (util/Utils.java:96)."""
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        while True:
            value = fn()
            if value is not None:
                return value
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("poll_till_non_null timed out")
            time.sleep(interval_s)
