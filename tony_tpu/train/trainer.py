"""Training-step builders: pjit'd SPMD train loops over a named mesh.

The compute-side counterpart of the control plane: where the reference
delegates "training" entirely to the user script + NCCL/Gloo
(SURVEY.md section 2.5), tony-tpu ships an in-tree trainer whose gradient
exchange is XLA collectives inserted by pjit from sharding annotations.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tony_tpu.ops.adamw import FusedAdamW, fused_adamw_update
from tony_tpu.parallel.sharding import batch_sharding, shard_params_by_size


@dataclass
class TrainState:
    step: jnp.ndarray
    params: Any
    opt_state: Any

    def tree_flatten(self):
        return (self.step, self.params, self.opt_state), None


jax.tree_util.register_pytree_node(
    TrainState,
    lambda s: ((s.step, s.params, s.opt_state), None),
    lambda _, c: TrainState(*c),
)


def cross_entropy_loss(logits, labels, mask=None):
    """logits: [..., V], labels: [...] int. ``mask`` (same shape as labels,
    0/1 or bool) drops positions from the mean — e.g. packed-document
    training masking the cross-boundary target after each EOS."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    if mask is None:
        return -jnp.mean(ll)
    mask = mask.astype(jnp.float32)
    return -jnp.sum(ll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


@dataclass
class Trainer:
    """Builds a jitted SPMD train step.

    apply_fn(params, batch) -> loss (scalar). Shardings: params via the
    FSDP-by-size heuristic (or replicated), batch sharded on (data, fsdp).
    """

    mesh: Mesh
    apply_fn: Callable[[Any, Any], jnp.ndarray]
    optimizer: optax.GradientTransformation
    fsdp: bool = False
    donate: bool = True
    # mixed precision: keep fp32 master params + optimizer state, run the
    # forward/backward in `compute_dtype` (bf16 on TPU: MXU-native, halves
    # activation HBM). The cast happens inside the differentiated function,
    # so XLA fuses it into the first consumer of each param and autodiff
    # casts gradients back to fp32 before the optimizer — no loss scaling
    # needed on TPU since bf16 keeps fp32's exponent range.
    compute_dtype: Any = None
    # gradient accumulation: the incoming batch's leading dim is split into
    # `accum_steps` microbatches scanned inside the jitted step (grads
    # averaged, ONE optimizer update) — the way to train at a global batch
    # whose activations don't fit HBM without changing the data pipeline
    accum_steps: int = 1
    # opt-in telemetry: global_norm re-reads every grad leaf (an extra
    # full-params HBM pass per step), so the DEFAULT step computes exactly
    # the math the model requires and nothing else — the framework step
    # must cost what a hand-written step costs (BASELINE north star)
    log_grad_norm: bool = False
    # batch input shardings: None = batch dim over (data, fsdp) for every
    # leaf. A pytree (e.g. {"tokens": sh, "segments": sh2}) overrides per
    # leaf — sequence-parallel training lands seq-sharded inputs (packed
    # segment ids, pre-split sequences) without a per-step relayout.
    batch_shardings: Any = None

    def init_state(self, params) -> TrainState:
        if isinstance(self.optimizer, FusedAdamW):
            # compute-dtype carry (under accum the per-micro grads are
            # bf16 but the accumulator stays fp32 — see compile_step)
            opt_state = self.optimizer.init(
                params, compute_dtype=self.compute_dtype)
        else:
            opt_state = self.optimizer.init(params)
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=opt_state,
        )

    def state_shardings(self, state: TrainState):
        if self.fsdp:
            p_sh = shard_params_by_size(self.mesh, state.params)
        else:
            p_sh = jax.tree.map(
                lambda _: NamedSharding(self.mesh, P()), state.params)
        o_sh = _opt_shardings_like(self.mesh, state.opt_state, p_sh,
                                   state.params)
        return TrainState(
            step=NamedSharding(self.mesh, P()),
            params=p_sh,
            opt_state=o_sh,
        )

    def compile_step(self, shardings):
        """The jitted step for a given TrainState sharding tree (shardings
        may come from a real or an abstract — jax.eval_shape — state)."""
        b_sh = self.batch_shardings if self.batch_shardings is not None \
            else batch_sharding(self.mesh)
        accum = max(self.accum_steps, 1)

        if self.compute_dtype is not None:
            cdtype = self.compute_dtype

            def to_compute(tree):
                # batch floats must be cast too: one fp32 operand would
                # promote every downstream op back to fp32 and silently
                # undo the bf16 compute/activation savings
                return jax.tree.map(
                    lambda x: x.astype(cdtype)
                    if jnp.issubdtype(x.dtype, jnp.floating) else x,
                    tree)

            def loss_fn(params, batch):
                # fp32 loss: keeps the logged metric at full precision and
                # matches the accum>1 path's f32 scan carry
                return self.apply_fn(
                    to_compute(params), to_compute(batch)).astype(jnp.float32)
        else:
            loss_fn = self.apply_fn

        def grads_of(params, batch):
            if accum == 1:
                return jax.value_and_grad(loss_fn)(params, batch)

            def micro(x, sh):
                b = x.shape[0]
                if b % accum:
                    raise ValueError(
                        f"batch dim {b} not divisible by accum_steps {accum}")
                # strided split: row i -> microbatch i % accum, so each
                # device contributes an equal local slice to EVERY
                # microbatch and the sharding constraint is a local
                # relayout, not a cross-device reshard (a contiguous split
                # would move ~(accum-1)/accum of the batch over the
                # interconnect each step; row assignment is arbitrary
                # since grads are averaged over all microbatches)
                x = x.reshape(b // accum, accum, *x.shape[1:]).swapaxes(0, 1)
                return jax.lax.with_sharding_constraint(
                    x, NamedSharding(self.mesh, P(None, *sh.spec)))

            if isinstance(b_sh, NamedSharding):
                micros = jax.tree.map(lambda x: micro(x, b_sh), batch)
            else:
                # b_sh is a pytree PREFIX of batch (same contract as jit
                # in_shardings): broadcast each sharding over its subtree
                micros = jax.tree.map(
                    lambda sh, sub: jax.tree.map(
                        lambda x: micro(x, sh), sub),
                    b_sh, batch,
                    is_leaf=lambda x: isinstance(x, NamedSharding))

            def body(carry, mb):
                loss_sum, grad_sum = carry
                loss, grads = jax.value_and_grad(loss_fn)(params, mb)
                return (loss_sum + loss,
                        jax.tree.map(jnp.add, grad_sum, grads)), None

            # fp32 accumulator even when the compute carry delivers bf16
            # per-micro grads (jnp.add promotes): bf16 accumulation
            # across micros would compound rounding
            zeros = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32)
                if jnp.issubdtype(p.dtype, jnp.floating)
                else jnp.zeros_like(p), params)
            (loss_sum, grad_sum), _ = jax.lax.scan(
                body, (jnp.zeros((), jnp.float32), zeros), micros)
            scale = 1.0 / accum
            return loss_sum * scale, jax.tree.map(
                lambda g: g * scale, grad_sum)

        fused = isinstance(self.optimizer, FusedAdamW)
        # compute-dtype carry: the fused update emits the bf16 copy of
        # the new params from the SAME pass that writes the fp32 master;
        # the next step forwards/backwards through that copy. The
        # separate master->bf16 cast pass disappears, the backward
        # writes bf16 grad leaves, and the update reads them as bf16 —
        # ~3 GB/step less HBM traffic at the 386M flagship.
        carry_compute = fused and self.compute_dtype is not None
        if fused:
            # the fused path needs each param's PartitionSpec so sharded
            # leaves run their pallas update under shard_map (a pallas
            # call is opaque to GSPMD — bare pjit would all-gather)
            param_specs = jax.tree.map(lambda s: s.spec, shardings.params)

        def step_fn(state: TrainState, batch):
            # under the carry, forward/backward run through the bf16
            # copy the previous update emitted: per-(micro)batch grads
            # arrive in compute dtype (the one numerics change — one
            # rounding per grad leaf; the products were bf16 with f32
            # accumulation either way) and no master->bf16 cast pass
            # ever materializes. loss_fn is reused as-is: its
            # to_compute on the carried bf16 params is an identity
            # cast XLA elides.
            diff_params = state.opt_state.compute_params \
                if carry_compute else state.params
            loss, grads = grads_of(diff_params, batch)
            if fused:
                # single fused read+write pass over g/p/mu/nu — no
                # materialized updates tree between transforms
                params, opt_state = fused_adamw_update(
                    self.optimizer, grads, state.opt_state, state.params,
                    mesh=self.mesh, param_specs=param_specs,
                    compute_dtype=self.compute_dtype
                    if carry_compute else None)
            else:
                updates, opt_state = self.optimizer.update(
                    grads, state.opt_state, state.params)
                params = optax.apply_updates(state.params, updates)
            metrics = {"loss": loss}
            if self.log_grad_norm:
                # fp32 accumulation even when the carry delivers bf16
                # grads: the metric must stay comparable across the
                # optimizer flag (squares at 8-bit mantissa drift)
                metrics["grad_norm"] = optax.global_norm(
                    jax.tree.map(lambda g_: g_.astype(jnp.float32),
                                 grads))
            new_state = TrainState(step=state.step + 1, params=params,
                                   opt_state=opt_state)
            return new_state, metrics

        metric_sh = {"loss": NamedSharding(self.mesh, P())}
        if self.log_grad_norm:
            metric_sh["grad_norm"] = NamedSharding(self.mesh, P())
        # b_sh is a pytree prefix: one sharding broadcast over the batch tree
        return jax.jit(
            step_fn,
            in_shardings=(shardings, b_sh),
            out_shardings=(shardings, metric_sh),
            donate_argnums=(0,) if self.donate else (),
        )

    def build_step(self, state: TrainState):
        """Returns (step_fn, placed_state). step_fn(state, batch) ->
        (state, metrics)."""
        shardings = self.state_shardings(state)
        return self.compile_step(shardings), jax.device_put(state, shardings)


def build_train_step(mesh: Mesh, apply_fn, optimizer, params, fsdp=False):
    """One-call convenience: returns (step_fn, state)."""
    trainer = Trainer(mesh=mesh, apply_fn=apply_fn, optimizer=optimizer,
                      fsdp=fsdp)
    state = trainer.init_state(params)
    return trainer.build_step(state)


def _opt_shardings_like(mesh, opt_state, param_shardings, params):
    """Optimizer-state shardings: leaves shaped like a param get that
    param's sharding (momentum/adam moments); everything else replicated."""
    flat_params, _ = jax.tree_util.tree_flatten(params)
    flat_shard, _ = jax.tree_util.tree_flatten(param_shardings)
    by_shape, by_shape_only = {}, {}
    for p, s in zip(flat_params, flat_shard):
        by_shape.setdefault((p.shape, p.dtype), s)
        # dtype-blind fallback: FusedAdamW's compute_params mirror the
        # params at compute dtype and must shard identically
        by_shape_only.setdefault(p.shape, s)

    def pick(leaf):
        if hasattr(leaf, "shape"):
            s = by_shape.get((leaf.shape, leaf.dtype)) \
                or by_shape_only.get(leaf.shape)
            if s is not None:
                return s
        return NamedSharding(mesh, P())

    return jax.tree.map(pick, opt_state)
