"""Version-bridging shims for the jax surface this repo targets.

The codebase is written against the modern API (``jax.shard_map`` with
its ``check_vma`` flag); older jax releases ship the same primitive as
``jax.experimental.shard_map.shard_map`` with the flag spelled
``check_rep``. Every ``shard_map`` import in the repo goes through this
module so exactly one place owns the difference — a missing top-level
``jax.shard_map`` must degrade to the experimental spelling, not take
the whole test suite down at collection time.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):

    def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)

else:  # jax < 0.6: experimental module, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)
