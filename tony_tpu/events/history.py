"""History file naming + parsing.

Reference: util/HistoryFileUtils.java (name codec) + util/ParserUtils.java
(isValidHistFileName :67, parseMetadata :153, parseConfig :181,
parseEvents :258). Layout (ref: EventHandler + portal HistoryFileMover):

  <history>/intermediate/<app_id>/<app_id>-<started>.jhist.jsonl.inprogress
  <history>/finished/yyyy/mm/dd/<app_id>/<app_id>-<started>-<completed>-<user>-<STATUS>.jhist.jsonl

plus ``tony-final.json`` and ``metadata.json`` alongside.
"""

from __future__ import annotations

import json
import os
import re
import time

from tony_tpu import constants as C
from tony_tpu.events.event import Event, JobMetadata

_FINAL_RE = re.compile(
    r"^(?P<app>application_[A-Za-z0-9_]+)-(?P<started>\d+)-(?P<completed>\d+)"
    r"-(?P<user>[^-]+)-(?P<status>SUCCEEDED|FAILED|KILLED)"
    + re.escape(C.JHIST_SUFFIX)
    + r"$"
)
_INPROGRESS_RE = re.compile(
    r"^(?P<app>application_[A-Za-z0-9_]+)-(?P<started>\d+)"
    + re.escape(C.JHIST_SUFFIX)
    + re.escape(C.INPROGRESS_SUFFIX)
    + r"$"
)


def inprogress_name(app_id: str, started_ms: int) -> str:
    return f"{app_id}-{started_ms}{C.JHIST_SUFFIX}{C.INPROGRESS_SUFFIX}"


def finished_name(app_id: str, started_ms: int, completed_ms: int, user: str,
                  status: str) -> str:
    return f"{app_id}-{started_ms}-{completed_ms}-{user}-{status}{C.JHIST_SUFFIX}"


def is_valid_history_name(name: str) -> bool:
    return bool(_FINAL_RE.match(name) or _INPROGRESS_RE.match(name))


def parse_history_name(name: str) -> dict | None:
    m = _FINAL_RE.match(name)
    if m:
        d = m.groupdict()
        return {
            "app_id": d["app"],
            "started": int(d["started"]),
            "completed": int(d["completed"]),
            "user": d["user"],
            "status": d["status"],
            "inprogress": False,
        }
    m = _INPROGRESS_RE.match(name)
    if m:
        d = m.groupdict()
        return {
            "app_id": d["app"],
            "started": int(d["started"]),
            "completed": -1,
            "user": "",
            "status": "RUNNING",
            "inprogress": True,
        }
    return None


def intermediate_dir(history_root: str, app_id: str) -> str:
    return os.path.join(history_root, C.HISTORY_INTERMEDIATE, app_id)


def finished_dir(history_root: str, completed_ms: int, app_id: str) -> str:
    t = time.localtime(completed_ms / 1000)
    return os.path.join(
        history_root,
        C.HISTORY_FINISHED,
        f"{t.tm_year:04d}",
        f"{t.tm_mon:02d}",
        f"{t.tm_mday:02d}",
        app_id,
    )


def parse_events(jhist_path: str) -> list[Event]:
    events = []
    with open(jhist_path) as f:
        for line in f:
            line = line.strip()
            if line:
                events.append(Event.from_dict(json.loads(line)))
    return events


def parse_metadata(job_dir: str) -> JobMetadata | None:
    p = os.path.join(job_dir, C.METADATA_FILE)
    if not os.path.isfile(p):
        return None
    with open(p) as f:
        return JobMetadata.from_dict(json.load(f))


def parse_config(job_dir: str) -> dict | None:
    p = os.path.join(job_dir, C.TONY_FINAL_CONF)
    if not os.path.isfile(p):
        return None
    with open(p) as f:
        return json.load(f)


def list_jobs(history_root: str) -> list[dict]:
    """Scan intermediate/ + finished/**/ for job dirs, newest first
    (ref: portal jobs index via CacheWrapper + ParserUtils)."""
    out = []
    inter = os.path.join(history_root, C.HISTORY_INTERMEDIATE)
    if os.path.isdir(inter):
        for app in os.listdir(inter):
            out.extend(_scan_job_dir(os.path.join(inter, app)))
    fin = os.path.join(history_root, C.HISTORY_FINISHED)
    for root, _dirs, files in os.walk(fin) if os.path.isdir(fin) else []:
        if any(is_valid_history_name(f) for f in files):
            out.extend(_scan_job_dir(root))
    out.sort(key=lambda d: d["started"], reverse=True)
    return out


def _scan_job_dir(job_dir: str) -> list[dict]:
    found = []
    if not os.path.isdir(job_dir):
        return found
    for name in os.listdir(job_dir):
        parsed = parse_history_name(name)
        if parsed:
            parsed["dir"] = job_dir
            parsed["jhist"] = os.path.join(job_dir, name)
            found.append(parsed)
    return found
