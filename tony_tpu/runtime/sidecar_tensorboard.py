"""Built-in sidecar TensorBoard launcher.

Reference: resources/sidecar_tensorboard.py:1-31 — a tiny bootstrap the
client ships automatically for a ``tensorboard`` role with no user command
(TonyClient.setSidecarTBResources :571-600). Reads ``TB_LOG_DIR`` and
``TB_PORT`` from the env injected by the agent and launches TensorBoard
bound to all interfaces; the agent registers the URL with the coordinator.
Test mode (``TONY_TEST_TB_SLEEP``) sleeps instead so e2e tests can run
without tensorboard installed — same trick as the reference's test flag.

Deliberately standalone (stdlib only, no tony_tpu imports): the client
copies this file into the job dir at stage time, mirroring the reference's
resource-localization of its launcher script, so it runs under any task
interpreter in local/ssh/docker launch modes.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
import time


def main() -> int:
    log_dir = os.environ.get("TB_LOG_DIR", "")
    port = os.environ.get("TB_PORT", "")
    test_sleep = os.environ.get("TONY_TEST_TB_SLEEP", "")
    if test_sleep:
        # e2e mode: pretend to serve until the coordinator reaps us
        time.sleep(float(test_sleep))
        return 0
    if not log_dir:
        print("sidecar_tensorboard: TB_LOG_DIR not set", file=sys.stderr)
        return 1
    cmd = [sys.executable, "-m", "tensorboard.main"]
    if shutil.which("tensorboard"):
        cmd = ["tensorboard"]
    cmd += ["--logdir", log_dir, "--host", "0.0.0.0"]
    if port:
        cmd += ["--port", port]
    try:
        return subprocess.call(cmd)
    except FileNotFoundError:
        print("sidecar_tensorboard: tensorboard not installed", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
