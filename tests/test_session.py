"""Session model tests (ref: tensorflow/TestTonySession.java + policy paths
exercised by TestTonyE2E chief-kill / non-chief / ps-crash cases)."""

from tony_tpu.config import TonyConf
from tony_tpu.session import Session, SessionStatus, TaskStatus


def make_conf(**roles):
    conf = TonyConf()
    for role, n in roles.items():
        conf.set(f"tony.{role}.instances", n)
    return conf


def test_lazy_allocation_and_ids():
    s = Session(make_conf(worker=2, ps=1))
    t0 = s.init_task("worker")
    t1 = s.init_task("worker")
    assert (t0.id, t1.id) == ("worker:0", "worker:1")
    assert s.init_task("worker") is None  # slots exhausted
    assert s.init_task("nope") is None


def test_registration_and_cluster_spec():
    s = Session(make_conf(worker=2))
    s.add_expected(2)
    s.init_task("worker")
    s.init_task("worker")
    assert not s.all_registered()
    s.register("worker:0", "hostA:1111")
    s.register("worker:1", "hostB:2222")
    assert s.all_registered()
    assert s.cluster_spec() == {"worker": ["hostA:1111", "hostB:2222"]}


def test_chief_semantics():
    s = Session(make_conf(chief=1, worker=2))
    assert s.is_chief("chief", 0)
    assert not s.is_chief("worker", 0)
    s2 = Session(make_conf(worker=2, ps=1))
    assert s2.is_chief("worker", 0)
    assert not s2.is_chief("worker", 1)
    s3 = Session(make_conf(head=1, actor=2))
    assert s3.is_chief("head", 0)


def test_chief_failure_short_circuits():
    s = Session(make_conf(worker=2))
    s.init_task("worker")
    s.init_task("worker")
    s.on_task_completed("worker", 0, 1)
    assert s.status == SessionStatus.FAILED
    assert "chief" in s.failure_reason


def test_non_chief_failure_tolerated():
    """Ref: TestTonyE2E testNonChiefWorkerFailureTolerated (:323)."""
    s = Session(make_conf(worker=2))
    s.init_task("worker")
    s.init_task("worker")
    s.on_task_completed("worker", 1, 1)  # non-chief fails
    assert s.status == SessionStatus.RUNNING
    s.on_task_completed("worker", 0, 0)
    assert s.training_finished()
    assert s.update_session_status() == SessionStatus.SUCCEEDED


def test_untracked_ps_crash_fails_fast():
    """Ref: TestTonyE2E testPSCrashShouldFailFast (:467)."""
    conf = make_conf(worker=1, ps=1)
    s = Session(conf)
    s.init_task("worker")
    s.init_task("ps")
    assert s.is_untracked("ps")
    s.on_task_completed("ps", 0, 1)
    assert s.status == SessionStatus.FAILED


def test_sidecar_crash_tolerated():
    """Ref: TestTonyE2E testSidecarCrashTolerated (:499)."""
    conf = make_conf(worker=1, tensorboard=1)
    s = Session(conf)
    s.init_task("worker")
    s.init_task("tensorboard")
    s.on_task_completed("tensorboard", 0, 1)
    assert s.status == SessionStatus.RUNNING
    s.on_task_completed("worker", 0, 0)
    assert s.update_session_status() == SessionStatus.SUCCEEDED


def test_stop_on_failure_roles():
    conf = make_conf(worker=2, reader=1)
    conf.set("tony.application.stop-on-failure.jobtypes", "reader")
    s = Session(conf)
    for _ in range(2):
        s.init_task("worker")
    s.init_task("reader")
    s.on_task_completed("reader", 0, 3)
    assert s.status == SessionStatus.FAILED


def test_fail_on_any_worker():
    conf = make_conf(worker=3)
    conf.set("tony.application.fail-on-worker-failure-enabled", True)
    s = Session(conf)
    for _ in range(3):
        s.init_task("worker")
    s.on_task_completed("worker", 2, 1)
    assert s.status == SessionStatus.FAILED


def test_all_tracked_failed_fails():
    s = Session(make_conf(worker=2))
    s.init_task("worker")
    s.init_task("worker")
    s.on_task_completed("worker", 1, 1)
    s.tasks["worker"][0].set_exit_status(1)  # chief marked failed w/o policy
    assert s.update_session_status() == SessionStatus.FAILED


def test_zero_instance_chief_role_disables_chief_semantics():
    """A chief role configured with 0 instances still occupies the role map,
    so no other task inherits chief status."""
    s = Session(make_conf(worker=1, chief=0))
    assert not s.is_chief("worker", 0)
    s.init_task("worker")
    s.on_task_completed("worker", 0, 1)  # non-chief failure tolerated
    assert s.status == SessionStatus.RUNNING
    assert s.update_session_status() == SessionStatus.FAILED  # but nothing succeeded


def test_task_infos_attention_sorted():
    s = Session(make_conf(worker=2))
    s.init_task("worker")
    s.init_task("worker")
    s.register("worker:0", "h:1")
    s.on_task_completed("worker", 1, 1)
    infos = s.task_infos()
    assert infos[0].status == "FAILED"  # failures sort first
    assert infos[0].index == 1


def test_late_registration_after_completion_ignored():
    s = Session(make_conf(worker=1))
    s.init_task("worker")
    s.register("worker:0", "h:1")
    s.on_task_completed("worker", 0, 0)
    assert s.register("worker:0", "h2:2") is None
    assert s.tasks["worker"][0].status == TaskStatus.FINISHED


def test_malformed_registrations_rejected():
    s = Session(make_conf(worker=1))
    s.init_task("worker")
    assert s.register("worker:0", "hostA:-5") is None  # negative port
    assert s.register("worker:0", "hostA") is None  # no port
    assert s.register("worker:-1", "h:1") is None  # negative index
    assert not s.tasks["worker"][0].registered
    assert s.get_task("worker", -1) is None


def test_exit_status_idempotent():
    s = Session(make_conf(worker=1))
    t = s.init_task("worker")
    t.set_exit_status(0)
    t.set_exit_status(1)  # second completion ignored
    assert t.status == TaskStatus.FINISHED
