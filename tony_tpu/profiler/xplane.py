"""Direct xplane trace parsing — device-busy time without TensorBoard.

The reference has no profiling subsystem at all (SURVEY.md §5.1); this
is the analysis half of tony-tpu's greenfield tracing design
(``profiler.py`` is the capture half). Motivation, measured on the
tunneled TPU backend: wall-clock microbenches of small kernels are
dominated by ~4.5 ms/launch of dispatch overhead — a 0.9 ms kernel
"measures" 5.4 ms, and kernel A/B ratios swing 40% between identical
runs. Device-busy time from the profiler's xplane trace has no launch
overhead in it, so ratios derived from it are stable run-to-run.

Parsing is done directly from the ``*.xplane.pb`` protos that
``jax.profiler.start_trace`` writes:

- ``tensorboard_plugin_profile``'s converter is broken in this image
  (protobuf/pywrap mismatch), so we read the proto ourselves via
  ``tensorflow.tsl.profiler.protobuf.xplane_pb2``.
- ``PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python`` must be exported
  before the first ``google.protobuf`` import or the C++ descriptor
  pool rejects the generated code; it is set LAZILY in ``load_xspace``
  (not at module import, so merely importing the profiler never forces
  the slower python protobuf impl on processes that parse no xplanes).
- The device plane is named ``/device:TPU:N``; its ``XLA Ops`` line
  carries one event per executed HLO op with ``duration_ps``. Summing
  durations is safe WITHIN a plane: ops on one TPU core's line are
  serialized. Across planes it is not — ``device_busy_ms`` reports the
  busiest plane (critical-path chip), never the cross-chip sum, which
  would inflate by n_devices on multi-chip traces.

Everything degrades to ``None``/empty off-TPU or when tensorflow is
absent, so callers can fall back to wall-clock.
"""

from __future__ import annotations

import glob
import os

_PS_PER_MS = 1e9

_warned_degraded = False


def _warn_degraded(reason: str) -> None:
    """One-time (per process) warning when xplane parsing degrades to
    None: callers fall back to wall-clock ratios, which on the tunneled
    backend carry ~4.5 ms/launch of dispatch noise — that silent
    downgrade must be visible in the bench log."""
    global _warned_degraded
    if _warned_degraded:
        return
    _warned_degraded = True
    import warnings

    warnings.warn(
        f"xplane trace parsing degraded to None ({reason}); timing "
        "ratios fall back to wall-clock, which includes dispatch/launch "
        "overhead", RuntimeWarning, stacklevel=3)


def xplane_files(logdir: str) -> list[str]:
    """All xplane dumps under a trace logdir, oldest -> newest."""
    files = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                      recursive=True)
    return sorted(files, key=os.path.getmtime)


def load_xspace(path: str):
    """Parse one ``*.xplane.pb`` into an XSpace proto. None if the
    tensorflow proto stubs are unavailable OR the file is truncated/
    corrupt (e.g. a killed earlier trace session) — degrade, don't
    abort a caller's whole bench run."""
    # the env var must be exported before the FIRST google.protobuf
    # import or the C++ descriptor pool rejects the generated code; set
    # it here (not at module import) so merely importing tony_tpu
    # .profiler does not force the slower python protobuf impl on
    # processes that never parse xplanes
    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION",
                          "python")
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2

        space = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            space.ParseFromString(f.read())
        return space
    except Exception:
        return None


def device_planes(space) -> list:
    """TPU (or GPU) device planes of an XSpace, excluding host planes."""
    return [p for p in space.planes
            if p.name.startswith("/device:") and "CUSTOM" not in p.name]


_OPKIND_RE = None


def hlo_op_kind(name: str) -> str:
    """HLO op KIND from an xplane op-metadata name. The name is the
    whole HLO statement ('%step.85 = (f32[...]) custom-call(%a, %b)'):
    the op name left of '=' is arbitrary (custom calls inherit jax fn
    names — a function named ``while_scanner`` yields
    '%while_scanner.3'), and the operand list mentions other ops'
    names, so the only reliable token is the kind between the result
    type and '('. Falls back to the name stem when the type expression
    defeats the regex (nested layout parens)."""
    global _OPKIND_RE
    if _OPKIND_RE is None:
        import re

        _OPKIND_RE = re.compile(
            r"=\s*(?:\([^)]*\)|[^\s(]+)\s+([a-z][a-z0-9_-]*)\(")
    m = _OPKIND_RE.search(name)
    if m:
        return m.group(1)
    return name.split("=", 1)[0].strip().lstrip("%").split(".")[0]


def _plane_op_totals(plane, line_name: str,
                     drop_control_flow: bool) -> dict[str, float] | None:
    """Per-op busy ms on ONE device plane's ``line_name`` line. None
    when the plane has no such line (not a device-op plane)."""
    totals: dict[str, float] = {}
    found = False
    meta = {m.id: m.name for m in plane.event_metadata.values()}
    for line in plane.lines:
        if line.name != line_name:
            continue
        found = True
        for ev in line.events:
            name = meta.get(ev.metadata_id, str(ev.metadata_id))
            # ' while(' / ' conditional(' can only be the HLO op
            # kind (op names contain no spaces; operand refs are
            # not followed by '('), so this cannot swallow a
            # custom call from a jax fn NAMED while_*; the
            # prefix check covers dumps whose metadata carries
            # only the op name — 'while.3' never collides with
            # 'while_scanner.3' (dot vs underscore)
            if drop_control_flow and (
                    " while(" in name or " conditional(" in name
                    or name.lstrip("%").startswith(
                        ("while.", "conditional."))):
                continue
            totals[name] = totals.get(name, 0.0) \
                + ev.duration_ps / _PS_PER_MS
    return totals if found else None


def op_totals_ms(logdir: str, line_name: str = "XLA Ops",
                 drop_control_flow: bool = True) \
        -> dict[str, float] | None:
    """Total device-busy ms per op name, summed over every device plane
    and xplane file under ``logdir``. None when nothing parseable.
    NOTE: the per-op SUM spans all chips (the per-op breakdown view);
    for wall-comparable busy time use ``device_busy_ms``, which
    aggregates per plane.

    ``drop_control_flow`` (default): skip while/conditional events —
    their duration INCLUDES the nested body ops, which the XLA Ops line
    logs separately per dynamic execution, so keeping both would count
    every loop body twice (measured: a scan-heavy step summed to ~2x
    its wall time before this filter). Filtering is by parsed HLO op
    KIND, not name prefix — a custom call from a jax fn named
    ``while_*`` must not vanish from the totals."""
    per_plane = per_plane_op_totals_ms(logdir, line_name,
                                       drop_control_flow)
    if per_plane is None:
        return None
    totals: dict[str, float] = {}
    for plane_totals in per_plane.values():
        for name, ms in plane_totals.items():
            totals[name] = totals.get(name, 0.0) + ms
    return totals


def per_plane_op_totals_ms(logdir: str, line_name: str = "XLA Ops",
                           drop_control_flow: bool = True) \
        -> dict[str, dict[str, float]] | None:
    """Per-device-plane per-op busy ms across every xplane file under
    ``logdir`` (plane name -> {op name -> ms}). None when nothing
    parseable — degrade, don't abort the caller's bench run."""
    per_plane: dict[str, dict[str, float]] = {}
    for path in xplane_files(logdir):
        space = load_xspace(path)
        if space is None:
            continue  # unparseable dump: skip it, keep what parses
        for plane in device_planes(space):
            totals = _plane_op_totals(plane, line_name, drop_control_flow)
            if totals is None:
                continue
            agg = per_plane.setdefault(plane.name, {})
            for name, ms in totals.items():
                agg[name] = agg.get(name, 0.0) + ms
    if not per_plane:
        _warn_degraded("no parseable device plane under " + logdir)
        return None
    return per_plane


def device_busy_ms(logdir: str, line_name: str = "XLA Ops") -> float | None:
    """Busy ms of the BUSIEST device across the trace (per-plane sum of
    the per-op line — serialized per core, so a plane's sum IS that
    core's busy time; the max across planes is the critical-path chip,
    the number comparable to wall clock). Summing across planes instead
    would over-report by n_devices on a multi-chip trace — a 4-chip
    data-parallel step would read as 4x "busier" than the wall it fits
    in (ADVICE r5). None when the trace has no device plane (e.g. CPU
    backend) or protos are unavailable."""
    per_plane = per_plane_op_totals_ms(logdir, line_name)
    if per_plane is None:
        return None
    return max(sum(t.values()) for t in per_plane.values())


def trace_device_ms(fn, args=(), steps: int = 10,
                    logdir: str | None = None) -> float | None:
    """Device-busy ms per call of ``fn(*args)`` over ``steps`` traced
    dispatches. The caller must have already compiled/warmed ``fn`` —
    tracing starts immediately. Returns None off-TPU (no device plane).

    The closing barrier is a scalar host fetch (the un-fakeable barrier:
    on the tunneled backend block_until_ready can resolve before queued
    work runs); its tiny convert program lands in the trace too, but at
    nanoseconds it is noise against any kernel worth tracing.
    """
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    owned = logdir is None
    logdir = logdir or tempfile.mkdtemp(prefix="tony_xplane_")
    try:
        jax.profiler.start_trace(logdir)
        try:
            out = None
            for _ in range(steps):
                out = fn(*args)
            # first leaf: fn may return a pytree, not a bare array
            leaf = jax.tree.leaves(out)[0]
            float(jnp.asarray(leaf).reshape(-1)[0].astype(jnp.float32))
        finally:
            jax.profiler.stop_trace()
        busy = device_busy_ms(logdir)
        return busy / steps if busy is not None else None
    finally:
        if owned:
            shutil.rmtree(logdir, ignore_errors=True)


def hbm_estimate_bytes(jitted, *args) -> int:
    """Compile-time HBM footprint of a jitted step: argument + output +
    temp bytes from XLA's memory analysis. On the tunneled backend
    ``device.memory_stats()`` returns nothing (peak reads 0), but the
    compile-time analysis is exact about what the executable will
    reserve — it correctly predicted this repo's OOM boundaries.
    Returns 0 when the backend offers no analysis."""
    try:
        return memory_bytes_of_compiled(jitted.lower(*args).compile())
    except Exception:
        return 0


def memory_bytes_of_compiled(compiled) -> int:
    """HBM bytes from an already-compiled executable's memory analysis
    (callers that also need cost_analysis should lower+compile ONCE and
    feed the result here — a flagship-sized re-trace costs minutes over
    the tunnel). 0 when the backend offers no analysis."""
    try:
        ma = compiled.memory_analysis()
        if ma is None:
            return 0
        peak = int(getattr(ma, "peak_memory_in_bytes", 0) or 0)
        if peak > 0:
            # measured >= argument+output+temp-alias on this backend:
            # the compiler's own peak covers live buffers and temps
            return peak
        return int(getattr(ma, "argument_size_in_bytes", 0)
                   + getattr(ma, "output_size_in_bytes", 0)
                   + getattr(ma, "temp_size_in_bytes", 0)
                   - getattr(ma, "alias_size_in_bytes", 0))
    except Exception:
        return 0
