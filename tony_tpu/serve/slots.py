"""SlotCache: batch_size resident KV-cache slots + per-slot decode state.

The device side is ONE fixed-shape cache pytree (``init_cache`` at
``batch_size``) that the resident decode step updates in place; the
host side is a handful of small per-slot arrays (length, last token,
sampling knobs, rng) the scheduler reads and writes between steps.
Admit copies a freshly prefilled single-row cache into a free slot with
one jitted dynamic-update-slice per leaf (slot index traced — one
compile total); evict is pure host bookkeeping (the row's stale K/V is
masked by the slot's length going inactive and fully overwritten by the
next admit, so no device work is ever spent clearing it).
"""

from __future__ import annotations

import threading
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from tony_tpu.models.generate import init_cache


def cache_batch_axis(path, leaf) -> int | None:
    """Batch (slot) axis of a cache leaf, or None for non-batched leaves.

    KV buffers are [..., b, max_len, kvh, dh] — batch is 4th-from-last;
    their quant scales are [..., b, max_len, kvh] — 3rd-from-last.
    scan_layers models prepend an n_layers axis, which this arithmetic
    skips (keying on axis 0 would slice the LAYERS axis). Index counters
    (cache_index/pos_index) carry no batch dim: per-slot decode neither
    reads nor advances them (positions live host-side)."""
    name = str(path[-1].key if hasattr(path[-1], "key") else path[-1])
    if name in ("cached_key", "cached_value"):
        return leaf.ndim - 4
    if name in ("cached_key_scale", "cached_value_scale"):
        return leaf.ndim - 3
    return None


def write_slot_row(cache: Any, row: Any, slot) -> Any:
    """Copy a batch-1 cache ``row`` into slot ``slot`` of ``cache``
    (pure tree transform, traceable — the ONE place that knows how to
    place a row; the engine's fused prefill-admit and the standalone
    jitted copy below both call it)."""
    def write(path, leaf, rleaf):
        ax = cache_batch_axis(path, leaf)
        if ax is None:
            return leaf  # shared counters: per-slot mode ignores them
        start = [jnp.int32(0)] * leaf.ndim
        start[ax] = jnp.asarray(slot, jnp.int32)
        return jax.lax.dynamic_update_slice(leaf, rleaf.astype(leaf.dtype),
                                            tuple(start))

    return jax.tree_util.tree_map_with_path(write, cache, row)


@jax.jit
def _write_slot(cache: Any, row: Any, slot) -> Any:
    """Jitted ``write_slot_row``; ``slot`` is traced — every admit
    reuses one compiled program."""
    return write_slot_row(cache, row, slot)


def read_slot_row(cache: Any, slot) -> Any:
    """Extract slot ``slot`` of ``cache`` as a batch-1 row — the exact
    inverse of ``write_slot_row`` (write then read round-trips every
    batched leaf). Non-batched leaves (the shared counters per-slot
    decode neither reads nor advances) pass through unchanged; a
    consumer seeding a prefill from the row re-seeds them anyway. The
    prefix store (serve/prefix.py) uses this to donate a finished
    slot's sequence back to the cache."""
    def read(path, leaf):
        ax = cache_batch_axis(path, leaf)
        if ax is None:
            return leaf
        return jax.lax.dynamic_slice_in_dim(
            leaf, jnp.asarray(slot, jnp.int32), 1, axis=ax)

    return jax.tree_util.tree_map_with_path(read, cache)


@jax.jit
def _read_slot(cache: Any, slot) -> Any:
    """Jitted ``read_slot_row``; ``slot`` is traced — every donation
    reuses one compiled program."""
    return read_slot_row(cache, slot)


# --------------------------------------------------------- paged cache


def _alloc_sharded(structs: Any, mesh) -> Any:
    """Allocate a cache pytree of zeros DIRECTLY under its kv-head
    shardings (``parallel.sharding.kv_cache_shardings``): one jitted
    nullary program with out_shardings, so each chip only ever
    materializes its own shard. The naive order — allocate dense,
    then ``device_put`` to the shardings — holds the WHOLE pool on
    one chip transiently at boot, which OOMs exactly the
    bigger-than-one-chip configurations the mesh exists to serve."""
    from tony_tpu.parallel.sharding import kv_cache_shardings

    shardings = kv_cache_shardings(mesh, structs)
    make = jax.jit(
        lambda: jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                             structs),
        out_shardings=shardings)
    return make()


def paged_cache(model, params, n_pages: int, page_size: int,
                mesh=None) -> Any:
    """A PAGED cache pytree: every batched leaf of a batch-1
    ``init_cache`` tree — KV buffers ``[.., 1, max_len, kvh, dh]``,
    int8 scales ``[.., 1, max_len, kvh]`` — becomes a page POOL with
    ``(batch, max_len)`` replaced by ``(n_pages, page_size)``; shared
    counters pass through (per-slot decode neither reads nor advances
    them). The tree STRUCTURE is unchanged, so ``model.apply`` with a
    ``page_table`` consumes it directly (flax returns the supplied
    value — the declared init shape only matters on the init pass),
    and scan_layers' stacked ``[n_layers, ...]`` leading axis is
    preserved by the same from-the-right axis arithmetic
    ``cache_batch_axis`` uses.

    ``mesh`` (sharded serving, ISSUE-14): the pool allocates DIRECTLY
    under its kv-head shardings — shapes come from ``eval_shape`` (no
    dense batch-1 init pass materializes either), so one chip never
    holds more than its shard (``_alloc_sharded``)."""
    def remap(path, leaf):
        ax = cache_batch_axis(path, leaf)
        if ax is None:
            return leaf
        shape = leaf.shape[:ax] + (n_pages, page_size) + leaf.shape[ax + 2:]
        return jnp.zeros(shape, leaf.dtype)

    if mesh is not None:
        base = jax.eval_shape(lambda p: init_cache(model, p, 1), params)

        def remap_struct(path, leaf):
            ax = cache_batch_axis(path, leaf)
            shape = leaf.shape if ax is None else \
                leaf.shape[:ax] + (n_pages, page_size) \
                + leaf.shape[ax + 2:]
            return jax.ShapeDtypeStruct(shape, leaf.dtype)

        return _alloc_sharded(
            jax.tree_util.tree_map_with_path(remap_struct, base), mesh)

    return jax.tree_util.tree_map_with_path(
        remap, init_cache(model, params, 1))


def default_page_size(cfg) -> int:
    """The auto ``kv_page_size`` for a model config: 64 tokens, scaled
    down (floor 16, never past max_seq_len) for short-context models —
    the ONE place this rule lives; ``Server`` and the CLI resolvers
    both call it so their page geometries can never drift apart."""
    ps = min(64, max(16, cfg.max_seq_len // 4))
    return max(1, min(ps, cfg.max_seq_len))


def kv_page_nbytes(cfg, page_size: int) -> int:
    """Analytic bytes of ONE KV page for a model config (agrees with
    ``page_nbytes`` of the built pool): n_layers x (K + V) x page_size
    x kv_heads x head_dim at the cache dtype, plus the int8 mode's
    fp32 scales. Lets the CLIs size ``--kv-pages`` from HBM before any
    device allocation exists."""
    item = 1 if cfg.kv_cache_quant else jnp.dtype(cfg.dtype).itemsize
    per = 2 * page_size * cfg.kv_heads * cfg.head_dim * item
    if cfg.kv_cache_quant:
        per += 2 * page_size * cfg.kv_heads * 4
    return cfg.n_layers * per


def page_nbytes(cache: Any) -> int:
    """Bytes ONE page occupies across a paged cache tree's pool leaves
    (all layers; scales included) — the unit the allocator's stats and
    the prefix store's paged byte budget account in."""
    total = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(cache)[0]:
        ax = cache_batch_axis(path, leaf)
        if ax is not None:
            nbytes = int(np.prod(leaf.shape)) * leaf.dtype.itemsize
            total += nbytes // leaf.shape[ax]
    return total


def copy_page(cache: Any, src, dst) -> Any:
    """Copy pool page ``src`` onto page ``dst`` in every paged leaf —
    the copy-on-write FORK: a slot aliasing a shared page that it must
    write into (a prefix boundary falling mid-page) gets its own copy
    of the whole page and writes there; the shared original stays
    byte-identical for every other holder. Pure tree transform,
    traceable (``_copy_page`` jits it with traced indices — one
    compile ever)."""
    def cp(path, leaf):
        ax = cache_batch_axis(path, leaf)
        if ax is None:
            return leaf
        row = jax.lax.dynamic_index_in_dim(leaf, jnp.asarray(src, jnp.int32),
                                           axis=ax, keepdims=False)
        return jax.lax.dynamic_update_index_in_dim(
            leaf, row, jnp.asarray(dst, jnp.int32), axis=ax)

    return jax.tree_util.tree_map_with_path(cp, cache)


@jax.jit
def _copy_page(cache: Any, src, dst) -> Any:
    return copy_page(cache, src, dst)


def gather_pages(cache: Any, idx) -> Any:
    """Stack the CONTENT of pool pages ``idx`` ([n] int32) into a
    standalone pytree: every paged leaf ``[.., n_pages, ps, ..]``
    becomes ``[.., n, ps, ..]`` — the portable form of a page list,
    shared by the role-split handoff (device->device between two
    replicas' pools, or over the agent wire) and the host-RAM tier
    (device->host spill). Out-of-range entries clamp (padding rows
    carry junk the consumer drops); non-paged leaves (the shared
    counters) pass through so the tree STRUCTURE round-trips."""
    def g(path, leaf):
        ax = cache_batch_axis(path, leaf)
        if ax is None:
            return leaf
        safe = jnp.clip(idx, 0, leaf.shape[ax] - 1)
        return jnp.take(leaf, safe, axis=ax)

    return jax.tree_util.tree_map_with_path(g, cache)


@jax.jit
def _gather_pages(cache: Any, idx) -> Any:
    """Jitted ``gather_pages``; ``idx`` is traced, so one program
    compiles per (pow2-bucketed) page count."""
    return gather_pages(cache, idx)


def scatter_pages(cache: Any, payload: Any, idx) -> Any:
    """Inverse of ``gather_pages``: write ``payload``'s page rows onto
    pool pages ``idx`` of ``cache``. Sentinel entries (``>= n_pages``)
    DROP — the bucket-padding discipline every paged scatter here
    follows — so a pow2-padded payload lands exactly its real pages.
    The round trip gather -> (optional host hop) -> scatter is
    bitwise: both directions are pure copies, no arithmetic touches
    the values (tests/test_tier.py pins it across dtype x scan_layers
    x int8-KV scale leaves)."""
    def sc(path, leaf, pleaf):
        ax = cache_batch_axis(path, leaf)
        if ax is None:
            return leaf  # dest counters win; payload's ride-alongs drop
        p2 = jnp.moveaxis(leaf, ax, 0)
        v2 = jnp.moveaxis(jnp.asarray(pleaf).astype(leaf.dtype), ax, 0)
        p2 = p2.at[idx].set(v2, mode="drop")
        return jnp.moveaxis(p2, 0, ax)

    return jax.tree_util.tree_map_with_path(sc, cache, payload)


@jax.jit
def _scatter_pages(cache: Any, payload: Any, idx) -> Any:
    """Jitted ``scatter_pages``; ``idx`` traced — one program per
    page-count bucket."""
    return scatter_pages(cache, payload, idx)


def paged_view(cache: Any, table, max_len: int) -> Any:
    """Gather each slot's pages into an UNPAGED-looking cache: every
    pool leaf ``[.., n_pages, ps, ..]`` becomes ``[.., b, span, ..]``
    via one gather through ``table`` [b, cols] (sentinel entries clamp
    to junk pages the visibility mask hides). The decode chunk runs
    its whole lax.scan against this view — the per-micro-step compute
    is then literally the unpaged program (bitwise parity for free: a
    masked column contributes softmax weight exactly 0.0, so a view
    holding fewer junk columns than the full buffer sums to the exact
    same attention output), and the gather cost is paid once per
    DISPATCH instead of once per micro-step (``paged_write_back``
    returns the chunk's new K/V to the pool afterwards).

    The engine passes a COLUMN-SLICED table covering a power-of-two
    bucket of the live slots' extent, so the view — and with it every
    micro-step's attention read — is O(actual tokens), not
    O(max_seq_len): the fixed-shape path's biggest per-step waste
    (scanning a mostly-empty [max_seq_len] buffer) disappears along
    with the residency waste."""
    def to_view(path, leaf):
        ax = cache_batch_axis(path, leaf)
        if ax is None:
            return leaf
        safe = jnp.clip(table, 0, leaf.shape[ax] - 1)
        v = jnp.take(leaf, safe, axis=ax)  # [.., b, cols, ps, ..]
        shape = v.shape[:ax] + (v.shape[ax],
                                v.shape[ax + 1] * v.shape[ax + 2]) \
            + v.shape[ax + 3:]
        v = v.reshape(shape)
        # span = cols * ps may exceed max_len (page-size rounding);
        # the unpaged per-slot branch sizes its drop-redirect index by
        # max_len, so never exceed it
        limit = min(max_len, shape[ax + 1])
        return jax.lax.slice_in_dim(v, 0, limit, axis=ax + 1)

    return jax.tree_util.tree_map_with_path(to_view, cache)


def paged_write_back(pool: Any, view: Any, table, start, n_steps: int,
                     max_len: int) -> Any:
    """Return a decode chunk's writes from the gathered ``view`` to the
    page ``pool``: slot i's micro-step j wrote position ``start[i] + j``
    (start < 0 = empty slot), so only those ``b x n_steps`` tokens move
    — everything else in the view is an unmodified copy the pool
    already holds. Out-of-range positions and sentinel table entries
    drop, exactly like the direct paged scatter."""
    b = table.shape[0]
    pos_w = jnp.where(start[:, None] >= 0,
                      start[:, None]
                      + jnp.arange(n_steps, dtype=jnp.int32)[None, :], -1)
    rows = jnp.arange(b)[:, None]

    def wb(path, pleaf, vleaf):
        ax = cache_batch_axis(path, pleaf)
        if ax is None:
            return pleaf
        n_pg, ps = pleaf.shape[ax], pleaf.shape[ax + 1]
        # the view (and a column-sliced table) may be shorter than
        # max_len; positions past either bound must drop, never clamp
        limit = min(max_len, table.shape[1] * ps, vleaf.shape[ax + 1])
        valid = (pos_w >= 0) & (pos_w < limit)
        safe = jnp.where(valid, pos_w, 0)
        page = jnp.take_along_axis(table, safe // ps, axis=1)
        page = jnp.where(valid, page, n_pg)  # drop via OOB
        off = safe % ps
        v2 = jnp.moveaxis(vleaf, (ax, ax + 1), (0, 1))
        vals = v2[rows, safe]                # [b, n_steps, ..rest]
        p2 = jnp.moveaxis(pleaf, (ax, ax + 1), (0, 1))
        p2 = p2.at[page, off].set(vals, mode="drop")
        return jnp.moveaxis(p2, (0, 1), (ax, ax + 1))

    return jax.tree_util.tree_map_with_path(wb, pool, view)


class PagePool:
    """Block-granular KV-cache pages + a host-side free-list allocator.

    The device side is ONE paged cache pytree (``paged_cache``): KV
    leaves are ``[n_pages, page_size, kvh, dh]`` pools shared by every
    slot AND the prefix store — built here, then handed off to the
    owning ``SlotCache`` (which keeps the LIVE tree across dispatches;
    ``self.cache`` is None afterwards so the t=0 allocation is not
    pinned twice). The host side owns which page belongs to
    whom: a free list, a per-page refcount (a page may be held by one
    slot table and any number of prefix-store entries — copy-on-write
    sharing), and a RESERVATION ledger.

    Reservations are the no-preemption admission discipline: a slot
    reserves its worst-case page count (prompt + clamped max_new,
    minus aliased prefix pages) up front and allocates lazily from
    that reservation as decode advances, so a mid-stream allocation
    can never fail — ``free >= reserved`` is the invariant (allocation
    from a reservation consumes one unit of each; unref only grows
    free). Admission blocks (stays pending) when a reservation cannot
    be granted, after the engine has squeezed the prefix store; it
    never kills an in-flight request.

    With ``shared=True`` the pool is a FLEET resource lent to several
    co-located engines at once (live session migration, ISSUE-18): the
    pool RETAINS ownership of the device tree — every attached
    ``SlotCache`` delegates its ``cache`` attribute here, so one
    engine's dispatch reassignment is immediately visible to the
    others, and moving a session between two attached engines is a
    pure page-table/refcount swap with zero KV bytes copied.

    Concurrency is TWO locks at two granularities (ISSUE-19; the old
    discipline serialized every co-located engine's whole step through
    one pool-wide writer lock):

    - every allocator mutation (free list, refcounts, the reservation
      ledger) is atomic under the internal fine lock ``_mu`` — held
      for microseconds, never across device work — so engines
      alloc/free/share concurrently and ``free >= reserved`` holds
      under any interleaving (tests/test_paged.py pins it with a
      multi-thread churn property test);
    - ``lock`` guards only the shared device TREE's
      read-dispatch-reassign window: an engine takes it to read
      ``pool.cache``, enqueue ONE dispatch against that version, and
      reassign the result. Page ownership is disjoint by construction
      (each slot writes only its own table's pages), so two engines'
      dispatches chain safely through tree versions — engine B's
      dispatch reads engine A's output buffers, XLA sequences them —
      and the lock is released before the host ever blocks on the
      result. What it prevents is two engines reading the SAME version
      and both reassigning (the second would silently drop the first's
      writes).
    """

    def __init__(self, model, params, n_pages: int, page_size: int,
                 mesh=None, shared: bool = False):
        if n_pages < 1 or page_size < 1:
            raise ValueError("n_pages and page_size must be >= 1")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self.shared = bool(shared)
        # the TREE lock (see class docstring): reentrant, because a
        # shared-pool engine's dispatch window may nest an evict /
        # adopt that takes it again on the same thread
        self.lock = threading.RLock()
        # the fine ALLOCATOR lock: free list + refcounts + reservation
        # ledger mutate atomically under it; reentrant so compound ops
        # (stats -> cow_shared, reserve -> available) self-nest
        self._mu = threading.RLock()
        self.cache = paged_cache(model, params, n_pages, page_size,
                                 mesh=mesh)
        self.page_nbytes = page_nbytes(self.cache)
        self.refcount = np.zeros(self.n_pages, np.int32)
        # LIFO free list: recently freed pages are re-issued first
        # (their content is junk either way; reuse keeps the hot set
        # small)
        self._free = list(range(self.n_pages - 1, -1, -1))
        self.reserved = 0   # granted-not-yet-allocated pages
        self.allocs = 0     # pages handed out, lifetime
        self.frees = 0      # pages returned to the free list, lifetime
        self.forks = 0      # copy-on-write page copies, lifetime
        self.peak_used = 0  # high-water mark of allocated pages

    # ------------------------------------------------------ accounting

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_used(self) -> int:
        return self.n_pages - len(self._free)

    def available(self) -> int:
        """Pages grantable to a NEW reservation right now."""
        with self._mu:
            return len(self._free) - self.reserved

    def cow_shared(self) -> int:
        """Pages currently held by more than one owner (a slot table
        plus prefix-store entries, or several entries) — the
        copy-on-write sharing the fixed-shape path paid row copies
        for."""
        with self._mu:
            return int((self.refcount > 1).sum())

    # ------------------------------------------------------ allocation

    def reserve(self, n: int) -> bool:
        """Set aside ``n`` future pages; False when they are not there
        (the caller sheds load or frees store pages and retries)."""
        with self._mu:
            if n > self.available():
                return False
            self.reserved += n
            return True

    def cancel(self, n: int) -> None:
        """Return ``n`` unused reserved pages (evict, or a request
        finishing under its worst case)."""
        with self._mu:
            if n > self.reserved:
                raise ValueError(f"cancel({n}) exceeds reserved "
                                 f"{self.reserved}")
            self.reserved -= n

    def alloc(self, n: int, *, from_reservation: bool = False) -> list[int]:
        """Pop ``n`` pages (refcount 1 each). ``from_reservation``
        consumes previously reserved units — guaranteed to succeed by
        the invariant; a bare alloc must fit ``available()``."""
        with self._mu:
            if from_reservation:
                if n > self.reserved:
                    raise RuntimeError(
                        f"alloc({n}) exceeds reservation {self.reserved}"
                        " — engine reservation accounting bug")
                self.reserved -= n
            elif n > self.available():
                raise RuntimeError(
                    f"alloc({n}) exceeds available {self.available()}")
            pages = [self._free.pop() for _ in range(n)]
            self.refcount[pages] = 1
            self.allocs += n
            self.peak_used = max(self.peak_used, self.n_used)
            return pages

    def share(self, pages) -> None:
        """One more holder for each of ``pages`` (aliasing a prefix
        entry's pages into a slot table, or pinning a slot's pages
        into a store entry — the refcount bump that replaced
        ``read_slot_row``/``write_slot_row`` copies)."""
        with self._mu:
            for p in pages:
                if self.refcount[p] <= 0:
                    raise ValueError(f"share() of free page {p}")
                self.refcount[p] += 1

    def unref(self, pages) -> None:
        """Drop one holder; pages reaching refcount 0 return to the
        free list (their content is junk from that moment)."""
        with self._mu:
            for p in pages:
                if self.refcount[p] <= 0:
                    raise ValueError(f"unref() of free page {p}")
                self.refcount[p] -= 1
                if self.refcount[p] == 0:
                    self._free.append(p)
                    self.frees += 1

    # ----------------------------------------------------------- stats

    def stats(self) -> dict:
        with self._mu:
            return {
                "total": self.n_pages,
                "used": self.n_used,
                "free": self.n_free,
                "reserved": self.reserved,
                "cow_shared": self.cow_shared(),
                "page_size": self.page_size,
                "page_nbytes": self.page_nbytes,
                "bytes_resident": self.n_used * self.page_nbytes,
                "allocs": self.allocs,
                "frees": self.frees,
                "forks": self.forks,
                "peak_used": self.peak_used,
            }


class SlotCache:
    """``batch_size`` cache slots + per-slot length/rng/EOS-side state.

    Host arrays are numpy (the scheduler mutates them every iteration);
    the cache pytree stays on device across the whole serve session.

    With ``pool`` (a ``PagePool``) the cache is PAGED: ``self.cache``
    is the pool's page tree, and each slot additionally owns a page
    table row ``[max_pages] int32`` (unallocated tail = the
    ``pool.n_pages`` sentinel, which the device scatter drops and the
    gather clamps), a count of allocated pages, and the remainder of
    its admission-time page reservation. Admit never copies a row —
    prefill writes land straight in the slot's pages; evict returns
    the slot's page references (shared pages survive under their other
    holders) and cancels its remaining reservation.
    """

    def __init__(self, model, params, batch_size: int,
                 pool: PagePool | None = None, mesh=None):
        self.batch_size = batch_size
        self.max_seq_len = model.cfg.max_seq_len
        self.pool = pool
        self._cache = None
        if pool is not None:
            if not pool.shared:
                # take OWNERSHIP of the device tree: the live pools are
                # reassigned onto self.cache after every dispatch, and a
                # reference left on the pool would pin the t=0
                # allocation (a full duplicate of the KV pool) for the
                # server's life
                self.cache = pool.cache
                pool.cache = None
            # shared pool: ownership stays with the pool — several
            # SlotCaches delegate to pool.cache through the property
            # below, so no duplicate reference exists to pin
            self.max_pages = -(-self.max_seq_len // pool.page_size)
            self.page_table = np.full((batch_size, self.max_pages),
                                      pool.n_pages, np.int32)
            self.n_slot_pages = np.zeros(batch_size, np.int32)
            self.reserve_left = np.zeros(batch_size, np.int32)
        elif mesh is not None:
            # fixed-shape rows, sharded serving: allocate the cache
            # directly under its kv-head shardings (see _alloc_sharded
            # — no dense transient on one chip)
            self.cache = _alloc_sharded(
                jax.eval_shape(
                    lambda p: init_cache(model, p, batch_size), params),
                mesh)
        else:
            self.cache = init_cache(model, params, batch_size)
        self.lengths = np.zeros(batch_size, np.int32)
        self.active = np.zeros(batch_size, bool)
        self.last_token = np.zeros(batch_size, np.int32)
        self.temperature = np.zeros(batch_size, np.float32)
        self.top_k = np.zeros(batch_size, np.int32)
        self.rng = np.zeros((batch_size, 2), np.uint32)

    @property
    def cache(self) -> Any:
        pool = self.pool
        if pool is not None and pool.shared:
            return pool.cache
        return self._cache

    @cache.setter
    def cache(self, value: Any) -> None:
        pool = self.pool
        if pool is not None and pool.shared:
            pool.cache = value
        else:
            self._cache = value

    def free_slots(self) -> list[int]:
        return [i for i in range(self.batch_size) if not self.active[i]]

    @property
    def n_active(self) -> int:
        return int(self.active.sum())

    def positions(self) -> np.ndarray:
        """Per-slot decode positions for the next step: the slot's
        current length (where the next token is written and up to which
        attention looks), -1 for empty slots (no visible keys)."""
        return np.where(self.active, self.lengths, -1).astype(np.int32)

    def admit(self, slot: int, length: int, last_token: int,
              temperature: float, top_k: int, rng_key,
              row_cache: Any = None) -> None:
        """Arm ``slot``'s per-slot state; with ``row_cache`` also copy
        that prefilled batch-1 cache row into the slot (the serving
        engine fuses the copy into its prefill dispatch instead and
        passes None). ``length`` = real prompt length (bucket padding
        beyond it is invisible: masked now, overwritten as the slot
        advances). ``last_token`` is the first sampled continuation —
        the next step feeds it at position ``length``."""
        if self.active[slot]:
            raise ValueError(f"slot {slot} is occupied")
        if not 0 < length <= self.max_seq_len:
            raise ValueError(f"bad prompt length {length}")
        if row_cache is not None:
            if self.pool is not None:
                raise ValueError("paged slots take no row_cache — "
                                 "prefill writes land in the slot's "
                                 "pages directly")
            self.cache = _write_slot(self.cache, row_cache,
                                     jnp.int32(slot))
        self.lengths[slot] = length
        self.last_token[slot] = last_token
        self.temperature[slot] = temperature
        self.top_k[slot] = top_k
        self.rng[slot] = np.asarray(rng_key, np.uint32).reshape(2)
        self.active[slot] = True

    def evict(self, slot: int) -> None:
        """Free a slot (EOS / budget exhausted). Device state is left in
        place — an inactive slot's position is -1, so nothing reads it,
        and the next admit overwrites the whole row. Paged: the slot's
        page references are dropped (pages a prefix-store entry also
        holds stay resident under their remaining refcount) and its
        unspent reservation is returned."""
        self.active[slot] = False
        self.lengths[slot] = 0
        self.last_token[slot] = 0
        self.temperature[slot] = 0.0
        self.top_k[slot] = 0
        self.rng[slot] = 0
        if self.pool is not None:
            self.release_pages(slot)

    # --------------------------------------------------- paged helpers

    def release_pages(self, slot: int) -> None:
        """Drop the slot's page references + unspent reservation (also
        used directly for an admitted-then-immediately-finished request
        whose slot was never armed)."""
        n = int(self.n_slot_pages[slot])
        if n:
            self.pool.unref(self.page_table[slot, :n].tolist())
        self.pool.cancel(int(self.reserve_left[slot]))
        self.page_table[slot] = self.pool.n_pages
        self.n_slot_pages[slot] = 0
        self.reserve_left[slot] = 0

    def seed_pages(self, slot: int, pages: list, seed_len: int,
                   reserve: int) -> bool:
        """Arm a fresh slot's table with a prefix-store entry's shared
        pages covering positions ``[0, seed_len)`` plus a reservation
        of ``reserve`` future pages. When ``seed_len`` falls mid-page,
        the boundary page — shared, but about to be written at offsets
        ``>= seed_len % page_size`` — is FORKED: one page copy on
        device, the original stays pinned for its other holders.
        Returns whether a fork happened. ``reserve`` must already be
        granted by ``pool.reserve()`` and include the fork page."""
        ps = self.pool.page_size
        n_alias = -(-seed_len // ps) if seed_len else 0
        use = [int(p) for p in pages[:n_alias]]
        self.pool.share(use)
        self.reserve_left[slot] = reserve
        self.n_slot_pages[slot] = n_alias
        self.page_table[slot, :n_alias] = use
        self.page_table[slot, n_alias:] = self.pool.n_pages
        if seed_len % ps == 0:
            return False
        (fresh,) = self.pool.alloc(1, from_reservation=True)
        self.reserve_left[slot] -= 1
        shared = use[-1]
        # the fork's read-dispatch-reassign window on the (possibly
        # shared) device tree — see PagePool docstring; reentrant, so
        # callers already inside their own window nest harmlessly
        with self.pool.lock:
            self.cache = _copy_page(self.cache, jnp.int32(shared),
                                    jnp.int32(fresh))
        self.pool.unref([shared])
        self.page_table[slot, n_alias - 1] = fresh
        with self.pool._mu:
            self.pool.forks += 1
        return True

    def ensure_pages(self, slot: int, upto_pos: int) -> None:
        """Grow the slot's table (from its reservation) until its pages
        cover positions ``[0, upto_pos)`` — called before any dispatch
        that writes those positions. Never allocates past the
        reservation: positions beyond it are budget overshoot whose
        writes the device scatter drops through the sentinel."""
        ps = self.pool.page_size
        have = int(self.n_slot_pages[slot])
        want = min(-(-upto_pos // ps), self.max_pages)
        grow = min(want - have, int(self.reserve_left[slot]))
        if grow <= 0:
            return
        pages = self.pool.alloc(grow, from_reservation=True)
        self.reserve_left[slot] -= grow
        self.page_table[slot, have:have + grow] = pages
        self.n_slot_pages[slot] = have + grow

    def slot_pages(self, slot: int, n_tokens: int) -> list[int]:
        """The slot's page ids covering positions ``[0, n_tokens)``
        (all allocated by construction — donation reads only written
        extents)."""
        n = -(-n_tokens // self.pool.page_size)
        if n > int(self.n_slot_pages[slot]):
            raise ValueError(
                f"slot {slot} holds {int(self.n_slot_pages[slot])} pages, "
                f"{n} needed for {n_tokens} tokens")
        return self.page_table[slot, :n].tolist()

    def reset(self) -> None:
        """Evict everything (a fresh serving session on the same cache
        allocation — no reallocation, no recompile)."""
        for i in range(self.batch_size):
            self.evict(i)
