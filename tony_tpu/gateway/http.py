"""The gateway's network face: route logic + the threaded HTTP server.

Two servers share the route logic in this module:

- ``GatewayHTTP`` (here): the original stdlib ``ThreadingHTTPServer``
  face — one handler thread per connection. Kept as the
  ``--edge threaded`` A/B control: a slow reader stalls only its own
  thread, but ten thousand readers are ten thousand OS threads.
- ``GatewayEdge`` (gateway/edge.py): the event-driven face — tens of
  thousands of concurrent connections on one loop thread plus a small
  fixed worker pool (``--edge event``, the default).

Both serve the SAME contracts through the module-level helpers
(``get_route`` / ``parse_generate`` / ``finish_doc`` /
``profile_request``), so every test and smoke round carries over
between them. Endpoints:

  POST /v1/generate   submit one request; JSON body (see parse_generate)
                      {"stream": true} -> chunked NDJSON: one
                      {"id", "token_ids": [delta...]} line per step,
                      a {"keepalive": true} line when the stream idles
                      past the keepalive interval (clients filter these
                      out of token reassembly), then a final line with
                      finish_reason/metrics. Otherwise one JSON object
                      when done.
  GET  /healthz       liveness: 200 while the process serves at all;
                      body = per-replica breaker state + heartbeat age
                      ("ok" / "degraded" / "down" — the early-warning
                      signal before /readyz flips)
  GET  /readyz        admission: 200 accepting / 503 draining OR zero
                      healthy replicas (the load-balancer signal
                      during graceful shutdown and total outage)
  GET  /stats         the Gateway.snapshot() JSON (counters, queue
                      depths, p50/p95/p99 queue-wait/TTFT/TPOT, the
                      engine rollup, and — behind the event edge — the
                      ``edge`` connection-plane block)
  GET  /metrics       Prometheus text exposition (0.0.4) of the same
                      numbers /stats carries: counters, gauges, and
                      lifetime TTFT/TPOT/queue-wait/e2e histograms —
                      what an autoscaler or scrape agent consumes
  GET  /v1/stream/<request_id>?offset=N   resume a stream (ISSUE-20):
                      chunked NDJSON of the request's ABSOLUTE token
                      sequence from offset N — {"request_id",
                      "offset", "token_ids"} windows, keepalives, then
                      the terminal {"done": true, "metrics"} line (or
                      the shed line with its status/reason). Works for
                      any admitted request — a dropped connection, a
                      second watcher, or a client reconnecting after a
                      gateway crash+--recover all land here; finished
                      requests stay resumable for --park-ttl. Unknown
                      or reaped ids 404.
  GET  /debug/trace   {"request_ids": [...]} — recently traced requests
  GET  /debug/traces  the browsable listing: buffered trace ids PLUS
                      terminal tags (outcome, finish_reason, tokens,
                      attempts) — how you find the trace worth opening
  GET  /debug/trace/<id>  one request's span tree as Chrome trace-event
                      JSON (load it in chrome://tracing or Perfetto);
                      failovers show as the request hopping attempt rows
  GET  /debug/goodput the roofline ledger report: wall clock decomposed
                      into useful/compile/padding/overshoot/
                      spec-rejected/idle bucket fractions (sum <= 1),
                      fleet + per replica, largest waste bucket named;
                      per-kind HBM-BW%/MFU where a roofline reference
                      is known (null on CPU)
  POST /debug/profile?steps=N  arm a jax.profiler capture of the fleet's
                      next N working scheduler iterations; returns the
                      logdir the xplane files land in (409 while a
                      capture is already pending/active). With remote
                      replicas the request FANS OUT: each agent host
                      arms its own capture (POST /v1/profile; xplane
                      files land on that host) and the response's
                      "remote" map reports per-host armed/logdir/error
  GET  /debug/profile capture status (active/steps_left/captures/
                      last_logdir/last_error), plus a per-agent
                      "remote" status map when the fleet has remote
                      replicas
  GET  /debug/bundle  the flight recorder (ISSUE-15): one self-
                      contained JSON debug bundle — active/recent
                      alerts, the judged signal snapshot, fleet +
                      per-replica goodput, per-replica stats rows
                      (dispatch timeline; transport/obs blocks for
                      remote hosts), supervision counters, recent
                      traces (remote spans included). The same
                      document a FIRING alert dumps automatically
                      into the history job dir (bundles/*.json)

Multi-tenant admission fields on POST /v1/generate (docs/SERVING.md):
``priority`` names a weighted-fair-queuing tier (``interactive`` /
``standard`` / ``batch`` by default; unknown -> 400) and ``tenant``
keys the per-tenant token-rate quota — a tenant past its rate gets an
immediate 429 whose ``Retry-After`` header says when its bucket
refills (``core.QuotaExceeded``), distinct from the queue-bound 429.

Shed mapping (core.Shed.http_status): 400 bad request, 429 admission
queue full OR tenant quota (the quota flavor carries Retry-After),
503 draining, 504 deadline exceeded. In streaming mode the status
line is only committed at the FIRST event, so a request shed while
queued still gets its real status code, not a 200 with an error
trailer.

Stream keepalives: the agent already emits idle NDJSON keepalive lines
on its resumable stream (serve/agent.py); the client-facing stream
used to go silent between tokens, so a slow decode behind a proxy/LB
idle timeout dropped healthy streams. Both edges now emit the same
``{"keepalive": true}`` doc once the COMMITTED stream idles past the
keepalive interval (pre-commit silence is preserved — the lazy status
contract needs it). Clients reassembling tokens must skip keepalive
lines (tests pin this).
"""

from __future__ import annotations

import itertools
import json
import logging
import math
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable
from urllib.parse import parse_qsl, unquote

from tony_tpu.gateway.core import Gateway, GenRequest, Shed

log = logging.getLogger(__name__)

# the client-facing stream keepalive cadence (seconds of committed-
# stream silence before a {"keepalive": true} line) — generous enough
# to be invisible in normal traffic, tight enough to beat common LB
# idle timeouts; both edges and the CLI knob default to it
STREAM_KEEPALIVE_S = 15.0


# --------------------------------------------------------------------
# shared route logic (both network faces serve exactly this)
# --------------------------------------------------------------------

def readyz_doc(gateway: Gateway) -> tuple[int, dict]:
    """The /readyz contract: 200 accepting; 503 draining/starting OR
    zero healthy replicas (every breaker open — shed clean 503s until
    a probe rejoins one)."""
    if gateway.ready and gateway.n_healthy > 0:
        return 200, {"status": "ready"}
    if gateway.ready:
        return 503, {"status": "no healthy replicas"}
    return 503, {"status": "draining" if gateway.draining
                 else "starting"}


def get_route(gateway: Gateway, path: str) -> tuple[int, dict] | None:
    """Dispatch one JSON GET route; None = not a JSON GET route here
    (/metrics is text and stays with the caller; unknown paths 404 at
    the caller too, after it checks its own extras)."""
    if path == "/healthz":
        return 200, gateway.health()
    if path == "/readyz":
        return readyz_doc(gateway)
    if path == "/stats":
        return 200, gateway.snapshot()
    if path == "/debug/trace":
        if gateway.traces is None:
            return 404, {"error": "tracing disabled"}
        return 200, {"request_ids": gateway.traces.ids()}
    if path == "/debug/traces":
        # the browsable listing: ids PLUS terminal tags (outcome,
        # finish_reason, tokens, attempts) — /debug/trace/<id>
        # required already knowing the id; this is how you find it
        if gateway.traces is None:
            return 404, {"error": "tracing disabled"}
        return 200, {"capacity": gateway.traces.capacity,
                     "traces": gateway.traces.summaries()}
    if path == "/debug/goodput":
        return 200, gateway.goodput_report()
    if path.startswith("/debug/trace/"):
        if gateway.traces is None:
            return 404, {"error": "tracing disabled"}
        rid = unquote(path[len("/debug/trace/"):])
        trace = gateway.traces.get(rid)
        if trace is None:
            return 404, {"error": f"no trace for request_id {rid!r} "
                         f"(buffer keeps the most recent "
                         f"{gateway.traces.capacity})"}
        return 200, trace.to_chrome()
    if path == "/debug/profile":
        status = gateway.profiler.status()
        remote = gateway.remote_profile_status()
        if remote:
            status["remote"] = remote
        return 200, status
    if path == "/debug/bundle":
        return 200, gateway.debug_bundle()
    return None


def profile_request(gateway: Gateway, query: str) -> tuple[int, dict]:
    """POST /debug/profile?steps=N[&logdir=<subdir>] — arm an
    on-demand serving profile (profiler.ServeProfiler). The body is
    ignored; the knobs ride the query string so `curl -XPOST
    .../debug/profile?steps=20` is the whole interface. ``logdir``
    is a RELATIVE name under the server's configured profile dir —
    an absolute or traversing path would hand any HTTP client an
    arbitrary-directory write primitive, so it 400s instead."""
    import os

    params = dict(parse_qsl(query))
    logdir = None
    sub = params.get("logdir")
    if sub:
        base = os.path.realpath(gateway.profiler.default_logdir)
        logdir = os.path.realpath(os.path.join(base, sub))
        if logdir != base and not logdir.startswith(base + os.sep):
            return 400, {"error": "logdir must be a relative subpath "
                                  "of the server's profile dir "
                                  "(--profile-dir)"}
        # fresh timestamped dir per capture: the xplane parsers sum
        # every *.xplane.pb under a logdir, so re-using a name would
        # silently double-count across captures
        logdir = os.path.join(logdir,
                              f"profile-{int(time.time() * 1000)}")
    try:
        steps = int(params.get("steps", 10))
        if steps < 1:
            raise ValueError("steps must be >= 1")
    except ValueError as e:
        return 400, {"error": str(e)}
    has_remote = gateway.has_remote_replicas
    local_error = None
    armed_logdir = None
    if gateway.has_local_replicas:
        # mixed/local fleets arm this process's profiler too; a
        # PURE-ROUTER fleet skips it — there is no local jax work
        # worth capturing. jax's one-global-session constraint is
        # PER PROCESS, so a local capture already in flight must
        # not block arming the agents (separate processes): on a
        # fleet with remotes the local refusal is reported in the
        # response instead of 409ing the whole fan-out; a
        # local-only fleet keeps the 409 contract.
        try:
            armed_logdir = gateway.profiler.request(steps, logdir)
        except RuntimeError as e:  # a capture is already in flight
            if not has_remote:
                return 409, {"error": str(e)}
            local_error = str(e)
        except ValueError as e:
            return 400, {"error": str(e)}
    out = {"armed": armed_logdir is not None, "steps": steps,
           "logdir": armed_logdir}
    if local_error is not None:
        out["local_error"] = local_error
    # remote replicas: fan the capture out to every agent host
    # (ISSUE-15) — best-effort per host, reported per host; the
    # xplane files land on each agent's own machine
    remote = gateway.arm_remote_profiles(steps)
    if remote:
        out["remote"] = remote
        out["armed"] = out["armed"] or any(
            v.get("armed") for v in remote.values())
    return 200, out


def parse_generate(d: dict,
                   encode: Callable | None) -> tuple[GenRequest, bool]:
    """POST /v1/generate body -> (GenRequest, stream flag). Raises
    ValueError/TypeError on anything malformed — both edges map that
    to a 400."""
    if not isinstance(d, dict):
        raise ValueError("request must be a JSON object")
    if "token_ids" in d:
        ids = [int(x) for x in d["token_ids"]]
    elif "prompt" in d:
        if encode is None:
            raise ValueError(
                "text prompt needs a tokenizer in the model dir; "
                "send token_ids instead")
        ids = encode(str(d["prompt"]))
    else:
        raise ValueError("request needs token_ids or prompt")
    ttl = d.get("ttl_s", d.get("timeout_s"))
    # "request_id" is the documented spelling; "id" accepted for
    # back-compat. Absent -> the gateway mints a UUID, echoed in
    # every response/stats/history/trace surface so the client can
    # correlate its request with the server-side records.
    rid = d.get("request_id", d.get("id"))
    tenant = d.get("tenant")
    priority = d.get("priority")
    return GenRequest(
        ids,
        max_new_tokens=int(d.get("max_new_tokens", 64)),
        temperature=float(d.get("temperature", 0.0)),
        top_k=int(d.get("top_k", 0)),
        seed=int(d.get("seed", 0)),
        id=rid,
        ttl_s=float(ttl) if ttl is not None else None,
        session=d.get("session"),
        # multi-tenant admission: tier + quota identity (validated
        # by the gateway — unknown priority names are a 400)
        tenant=str(tenant) if tenant is not None else None,
        priority=str(priority) if priority is not None else None,
    ), bool(d.get("stream", False))


def finish_doc(res, metrics: dict, decode: Callable | None) -> dict:
    """The terminal response document (unary body / stream last line)."""
    out = {"id": res.id, "request_id": res.id,
           "token_ids": list(res.prompt) + list(res.tokens),
           "finish_reason": res.finish_reason, "metrics": metrics}
    if decode is not None:
        out["text"] = decode(out["token_ids"])
    return out


def shed_headers(e: Shed) -> dict | None:
    """Retry-After for the quota 429: an honest machine-readable
    backoff (whole seconds, ceil'd, floor 1 — "0" reads as "now")."""
    retry = getattr(e, "retry_after_s", None)
    if retry is None:
        return None
    return {"Retry-After": str(max(1, math.ceil(retry)))}


# --------------------------------------------------------------------
# the threaded face (--edge threaded; the A/B control)
# --------------------------------------------------------------------

class GatewayHandler(BaseHTTPRequestHandler):
    # bound by GatewayHTTP: the shared Gateway plus optional tokenizer
    # hooks (encode: str -> [ids]; decode: [ids] -> str)
    gateway: Gateway
    encode: Callable | None = None
    decode: Callable | None = None
    keepalive_s: float = STREAM_KEEPALIVE_S
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet: requests are metrics,
        log.debug(fmt, *args)  # not stderr noise

    # ------------------------------------------------------------- GET

    def do_GET(self):
        path, _, query = self.path.partition("?")
        if path == "/metrics":
            from tony_tpu.obs import prometheus_text

            return self._send_text(200, prometheus_text(self.gateway))
        if path.startswith("/v1/stream/"):
            return self._respond_resume(path, query)
        route = get_route(self.gateway, path)
        if route is None:
            return self._send(404, {"error": "not found"})
        return self._send(*route)

    # ------------------------------------------------------------ POST

    def do_POST(self):
        t_receive = time.monotonic()
        path, _, query = self.path.partition("?")
        if path == "/debug/profile":
            return self._profile_request(query)
        if path != "/v1/generate":
            return self._send(404, {"error": "not found"})
        try:
            body = self._read_body()
            req, stream = parse_generate(body, self.encode)
            req.t_receive = t_receive  # the trace's http_receive span
        except (TypeError, ValueError) as e:
            # TypeError too: int()/float()/iteration over wrong-typed
            # JSON values ({"token_ids": 123}, {"temperature": null})
            # must be a 400, not a handler-thread crash + reset socket
            return self._send(400, {"error": str(e)})
        try:
            ticket = self.gateway.submit(req)
        except Shed as e:
            return self._send(e.http_status, {"error": e.reason},
                              headers=shed_headers(e))
        try:
            if stream:
                self._respond_stream(ticket)
            else:
                self._respond_unary(ticket)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away; the request finishes server-side
            # and its deadline/shed path handles abandoned successors

    def _profile_request(self, query: str) -> None:
        length = int(self.headers.get("Content-Length") or 0)
        if length > 1 << 20:
            # refusing to drain an arbitrarily large body; 413 closes
            # the connection (the _send >=400 path), so the unread tail
            # can never desync a keep-alive socket
            return self._send(413, {"error": "request body too large"})
        if length > 0:  # drain: unread body bytes would desync a
            self.rfile.read(length)  # keep-alive socket
        return self._send(*profile_request(self.gateway, query))

    def _read_body(self) -> dict:
        length = int(self.headers.get("Content-Length") or 0)
        if length <= 0:
            raise ValueError("missing request body")
        if length > 8 << 20:
            raise ValueError("request body too large")
        raw = self.rfile.read(length)
        try:
            body = json.loads(raw)
        except json.JSONDecodeError as e:
            raise ValueError(f"invalid JSON: {e}") from None
        if not isinstance(body, dict):
            raise ValueError("request must be a JSON object")
        return body

    # -------------------------------------------------------- responses

    def _respond_unary(self, ticket) -> None:
        try:
            res = ticket.result()
        except Shed as e:
            return self._send(e.http_status, {"error": e.reason})
        # ticket.metrics is the replica's canonical per-request record
        # (same dict the stream's final line and /stats window carry)
        self._send(200, finish_doc(res, ticket.metrics or {},
                                   self.decode))

    def _respond_stream(self, ticket) -> None:
        """Chunked NDJSON. Headers are sent lazily at the first event
        so sheds keep their real status code; once committed, idle
        gaps longer than the keepalive interval emit a keepalive line
        (filtered by clients) so slow decodes survive LB idle
        timeouts."""
        import queue as _queue

        headers_sent = False
        while True:
            try:
                # pre-commit: block without keepalives (nothing may be
                # written before the status line)
                kind, *rest = ticket.events.get(
                    timeout=self.keepalive_s if headers_sent else None)
            except _queue.Empty:
                self._chunk({"keepalive": True})
                continue
            if kind == "tokens":
                if not headers_sent:
                    self._start_stream()
                    headers_sent = True
                self._chunk({"id": ticket.request.id,
                             "request_id": ticket.request.id,
                             "token_ids": rest[0]})
            elif kind == "done":
                res, metrics = rest
                if not headers_sent:
                    self._start_stream()
                    headers_sent = True
                self._chunk(finish_doc(res, metrics, self.decode))
                self.wfile.write(b"0\r\n\r\n")
                return
            elif kind == "shed":
                status, reason = rest
                if headers_sent:  # mid-stream shed: error line + close
                    self._chunk({"id": ticket.request.id, "error": reason,
                                 "status": status})
                    self.wfile.write(b"0\r\n\r\n")
                else:
                    self._send(status, {"error": reason})
                return

    def _respond_resume(self, path: str, query: str) -> None:
        """GET /v1/stream/<request_id>?offset=N — re-attach to a live
        (or recently finished) request's ABSOLUTE token sequence from
        offset N. Unlike _respond_stream this never consumes the
        ticket's single-consumer event queue: it polls the resume
        buffer, so any number of watchers (including a client
        reconnecting after a gateway crash + --recover) can follow the
        same request without stealing each other's deltas."""
        rid = unquote(path[len("/v1/stream/"):])
        if not rid:
            return self._send(404, {"error": "not found"})
        offset = 0
        for key, val in parse_qsl(query):
            if key == "offset":
                try:
                    offset = int(val)
                except ValueError:
                    return self._send(
                        400, {"error": "offset must be an integer"})
        if offset < 0:
            return self._send(400, {"error": "offset must be >= 0"})
        gen = self.gateway.resume_events(rid, offset,
                                         keepalive_s=self.keepalive_s)
        first = next(gen)
        if first.get("gone"):
            return self._send(
                404, {"error": f"unknown or reaped request {rid!r}"})
        try:
            self._start_stream()
            for doc in itertools.chain([first], gen):
                if doc.get("shed"):
                    self._chunk({"id": rid, "request_id": rid,
                                 "error": doc.get("reason", "shed"),
                                 "status": doc.get("status", 503)})
                    break
                if doc.get("done"):
                    self._chunk({"id": rid, "request_id": rid,
                                 "done": True,
                                 "metrics": doc.get("metrics") or {}})
                    break
                doc.setdefault("id", rid)
                doc.setdefault("request_id", rid)
                self._chunk(doc)
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            pass  # watcher went away; the request itself is unaffected

    def _start_stream(self) -> None:
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("Cache-Control", "no-store")
        self.end_headers()

    def _chunk(self, doc: dict) -> None:
        data = (json.dumps(doc) + "\n").encode()
        self.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")
        self.wfile.flush()

    def _send_text(self, code: int, text: str) -> None:
        """Plain-text response — the Prometheus exposition format
        (which is NOT JSON; scrapers parse the text format directly)."""
        data = text.encode()
        self.send_response(code)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send(self, code: int, doc: dict,
              headers: dict | None = None) -> None:
        data = json.dumps(doc).encode()
        if code >= 400:
            # error replies may leave a POST body unread; under
            # HTTP/1.1 keep-alive those bytes would be parsed as the
            # NEXT request line — close instead of desyncing
            self.close_connection = True
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        if code >= 400:
            self.send_header("Connection", "close")
        self.end_headers()
        self.wfile.write(data)


class GatewayHTTP:
    """Binds a Gateway to a ThreadingHTTPServer; start()/stop()."""

    def __init__(self, gateway: Gateway, host: str = "127.0.0.1",
                 port: int = 0, encode: Callable | None = None,
                 decode: Callable | None = None,
                 keepalive_s: float = STREAM_KEEPALIVE_S):
        handler = type("BoundGatewayHandler", (GatewayHandler,),
                       {"gateway": gateway, "encode": staticmethod(encode)
                        if encode else None,
                        "decode": staticmethod(decode) if decode else None,
                        "keepalive_s": max(0.05, keepalive_s)})
        self.server = ThreadingHTTPServer((host, port), handler)
        self.server.daemon_threads = True
        self.host, self.port = self.server.server_address[:2]
        self._thread: threading.Thread | None = None

    def start(self) -> "GatewayHTTP":
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        name="gateway-http", daemon=True)
        self._thread.start()
        log.info("gateway http at http://%s:%d", self.host, self.port)
        return self

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
