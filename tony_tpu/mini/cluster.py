"""In-process test cluster harness — tony-mini MiniCluster equivalent.

Reference: tony-mini MiniCluster.java:24-87 boots MiniYARNCluster +
MiniDFSCluster in-process so E2E tests submit real jobs without a cluster.
Here there is no RM/NM to fake: the local launcher already runs agents as
subprocesses, so the harness provides (a) an isolated staging/history root,
(b) fast control-plane timings, (c) a ``submit`` helper mirroring
TestTonyE2E's client usage, and (d) CPU-forcing env for jax payloads.
"""

from __future__ import annotations

import os
import shutil
import tempfile

from tony_tpu.client import TonyClient
from tony_tpu.config import TonyConf


class MiniTonyCluster:
    def __init__(self, fast_ms: int = 100):
        self.fast_ms = fast_ms
        self.root: str = ""

    def __enter__(self) -> "MiniTonyCluster":
        self.root = tempfile.mkdtemp(prefix="minitony_")
        # the local harness is CPU-only by contract; override any TPU
        # platform the session env carries so payload scripts don't dial
        # it, and drop the sitecustomize trigger that would re-register a
        # TPU plugin inside subprocesses regardless of JAX_PLATFORMS
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        return self

    def __exit__(self, *exc) -> None:
        shutil.rmtree(self.root, ignore_errors=True)

    def base_conf(self) -> TonyConf:
        conf = TonyConf()
        conf.set("tony.staging-dir", os.path.join(self.root, "staging"))
        conf.set("tony.history.location", os.path.join(self.root, "history"))
        conf.set("tony.task.heartbeat-interval-ms", self.fast_ms)
        conf.set("tony.coordinator.monitor-interval-ms", self.fast_ms)
        conf.set("tony.client.poll-interval-ms", self.fast_ms)
        conf.set("tony.coordinator.registration-timeout-ms", 60_000)
        return conf

    def adopt(self, conf: TonyConf) -> TonyConf:
        """Overlay this cluster's staging/history/timing keys onto an
        externally-built conf (the one merge both `tony-tpu local` and the
        test harness use)."""
        base = self.base_conf()
        for key in ("tony.staging-dir", "tony.history.location",
                    "tony.task.heartbeat-interval-ms",
                    "tony.coordinator.monitor-interval-ms",
                    "tony.client.poll-interval-ms",
                    "tony.coordinator.registration-timeout-ms"):
            conf.set(key, base.get(key))
        return conf

    def make_client(self, conf: TonyConf) -> TonyClient:
        return TonyClient(conf)

    def submit(self, conf: TonyConf) -> TonyClient:
        """Run a job to completion; returns the client (check
        ``client.final_status``)."""
        client = self.make_client(conf)
        client.run()
        return client


def script_conf(cluster: MiniTonyCluster, script: str, roles: dict[str, int],
                framework: str = "jax", **extra) -> TonyConf:
    """Conf for a payload-script job (TestTonyE2E helper shape)."""
    conf = cluster.base_conf()
    conf.set("tony.application.executes", script)
    conf.set("tony.application.framework", framework)
    for role, n in roles.items():
        conf.set(f"tony.{role}.instances", n)
    for k, v in extra.items():
        conf.set(k, v)
    return conf
