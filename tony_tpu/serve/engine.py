"""Continuous-batching scheduler over one resident jitted decode step.

Design (the Orca/vLLM iteration-level result, on the TPU static-shape
path):

- ONE decode step of fixed shape [batch_size, 1] over the fixed
  [batch_size, max_seq_len] cache compiles once and serves the whole
  session. Per-slot positions ride in as a traced [b] vector
  (``Transformer.__call__(..., positions=...)``); per-request
  temperature/top-k are traced too, so a new mix of requests NEVER
  recompiles anything.
- Prefill runs as a separate batch-1 jit at a few BUCKETED lengths
  (powers of two): O(log max_seq_len) compiles ever, right-padded —
  causal attention keeps pad junk out of the real positions' K/V, and
  the slot's length masks the tail until decode overwrites it.
- Each ``step()``: admit pending prompts into free slots (prefill,
  slot copy and first-token sample FUSED into one dispatch per
  request), run a CHUNK of K batched decode micro-steps as one
  lax.scan dispatch (K adapts to the live slots' remaining budgets,
  rounded to a power of two so at most log2(chunk_steps)+1 programs
  ever compile), sample per-slot inside the chunk, then detect EOS /
  budget per slot host-side, evict finished slots and return their
  results. A finished slot is refilled the SAME iteration — mixed-
  length traffic never waits on the longest sequence in the batch (the
  fixed-batch ``generate()`` failure mode). Chunking amortizes the
  per-dispatch host cost over K tokens; a slot that finishes mid-chunk
  decodes garbage until the chunk ends (its row is independent — no
  other slot sees it) which the host trims before reporting, so
  results are unaffected and the waste is bounded by K-1 slot-steps
  per finish.

Greedy outputs are token-for-token identical to a solo ``generate()``
of the same prompt (the exactness contract tests/test_serve.py pins):
prefill math is position-exact under bucket padding and the per-slot
step runs the same attention reduction over the same [max_seq_len]
buffer as the scalar-index path.
"""

from __future__ import annotations

import functools
import itertools
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Iterable, Iterator

import jax
import jax.numpy as jnp
import numpy as np

from tony_tpu.models.generate import (init_cache, normalize_eos_ids,
                                      single_decode_step)
from tony_tpu.serve.slots import SlotCache


def bucket_len(n: int, max_len: int, minimum: int = 16) -> int:
    """Smallest power-of-two bucket >= n (floor ``minimum``, cap
    ``max_len``): prefill compiles once per bucket, not once per length."""
    b = minimum
    while b < n:
        b *= 2
    return min(b, max_len)


@functools.partial(jax.jit, static_argnames=("model",))
def _prefill(model, params, prompt, length):
    """Prefill ONE request's prompt [1, Lb] (right-padded to its bucket)
    into a fresh batch-1 cache. Returns (row_cache, logits [1, V] at the
    REAL last prompt position ``length - 1`` — the padded tail's logits
    are junk and never sampled)."""
    cache = init_cache(model, params, 1)
    logits, vars_ = model.apply({"params": params, "cache": cache},
                                prompt, decode=True, mutable=["cache"])
    last = jax.lax.dynamic_slice_in_dim(logits, length - 1, 1, axis=1)
    return vars_["cache"], last[:, 0]


@functools.partial(jax.jit, static_argnames=("model",))
def _prefill_admit(model, params, cache, prompt, length, slot, temp,
                   top_k, key):
    """The fused admit: prefill [1, Lb], copy the row into ``slot`` of
    the resident cache, sample the first continuation token — ONE
    dispatch per admitted request (three separate dispatches measured
    ~3x the whole per-request host cost at CPU proxy sizes). Compiles
    once per prefill bucket; slot / length / sampling knobs are traced."""
    from tony_tpu.serve.slots import write_slot_row

    row, last = _prefill(model, params, prompt, length)
    cache = write_slot_row(cache, row, slot)
    tok, key = _sample_rows(last, key[None],
                            jnp.asarray(temp, jnp.float32)[None],
                            jnp.asarray(top_k, jnp.int32)[None])
    return cache, tok[0].astype(jnp.int32), key[0]


def _sample_rows(logits, rngs, temps, top_ks):
    """Per-row sampling with TRACED temperature/top-k — one compiled
    program serves every request mix. Greedy rows (temp == 0) take
    argmax; sampled rows apply a per-row top-k cut by rank (ties beyond
    rank k are dropped, vs sample_logits' static-k threshold keeping
    them — indistinguishable for continuous logits), then draw from
    their own rng. Returns (tokens, advanced rngs).

    GATED on the live mix (lax.cond, traced preds): an all-greedy batch
    — the serving default — skips the rng splits and both sort passes
    entirely (measured 0.89 -> 0.04 ms per step at CPU proxy sizes,
    most of the micro-step gap to generate()'s scan body); the top-k
    sorts additionally skip whenever no live row requests a cut. Greedy
    rows never consume rng, so a request's draws stay reproducible
    regardless of what it is co-scheduled with."""
    greedy = jnp.argmax(logits, axis=-1)

    def sampled(_):
        scaled = logits / jnp.maximum(temps[:, None], 1e-6)

        def topk_cut(x):
            order = jnp.argsort(-x, axis=-1)
            ranks = jnp.argsort(order, axis=-1)
            keep = (top_ks[:, None] <= 0) | (ranks < top_ks[:, None])
            return jnp.where(keep, x, -1e30)

        cut = jax.lax.cond(jnp.any(top_ks > 0), topk_cut,
                           lambda x: x, scaled)
        pair = jax.vmap(lambda k: jax.random.split(k, 2))(rngs)
        drawn = jax.vmap(jax.random.categorical)(pair[:, 1], cut)
        return jnp.where(temps == 0.0, greedy, drawn), pair[:, 0]

    return jax.lax.cond(jnp.any(temps > 0.0), sampled,
                        lambda _: (greedy, rngs), None)


@functools.partial(jax.jit, static_argnames=("model", "n_steps"))
def _decode_chunk(model, params, cache, tok, positions, temps, top_ks,
                  rngs, *, n_steps: int):
    """The resident serving step: ``n_steps`` decode micro-steps for
    EVERY slot as one lax.scan dispatch (empty slots compute garbage
    that nothing reads — the price of a never-recompiled static shape).
    Per-slot sampling and rng advance ride inside the scan; returns
    (cache, tokens [b, n_steps], rngs). ``n_steps`` is static (the
    scheduler quantizes it to powers of two, so at most
    log2(chunk_steps)+1 programs ever compile)."""

    def body(carry, _):
        cache, tok, positions, rngs = carry
        cache, last = single_decode_step(model, params, cache, tok,
                                         positions=positions)
        nxt, rngs = _sample_rows(last, rngs, temps, top_ks)
        nxt = nxt.astype(jnp.int32)
        positions = jnp.where(positions >= 0, positions + 1, positions)
        return (cache, nxt, positions, rngs), nxt

    carry = (cache, tok, positions, rngs)
    if n_steps > 1:
        carry, toks = jax.lax.scan(body, carry, None, length=n_steps)
        toks = jnp.moveaxis(toks, 0, 1)  # [steps, b] -> [b, steps]
    else:
        carry, tok1 = body(carry, None)
        toks = tok1[:, None]
    cache, _, _, rngs = carry
    return cache, toks, rngs


class QueueFull(RuntimeError):
    """``submit()`` refused: the pending queue is at ``max_pending``.

    The typed backpressure signal — callers (the gateway's admission
    layer, the JSONL loop) translate it into 429/shedding instead of
    letting the queue grow without bound and OOMing the host."""


@dataclass
class Request:
    """One generation request. ``prompt`` is token ids; sampling knobs
    are per-request (greedy default). ``id`` is echoed on the Result
    (auto-assigned when None)."""

    prompt: list
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    seed: int = 0
    id: Any = None


@dataclass
class Result:
    """A finished request: ``tokens`` = generated ids (the EOS token,
    when hit, included as the last element); ``finish_reason`` is
    "eos" or "length"."""

    id: Any
    prompt: list
    tokens: list
    finish_reason: str


@dataclass
class _Live:
    request: Request
    generated: list = field(default_factory=list)


class Server:
    """Slot-based continuous-batching server.

    ``submit()`` enqueues; ``step()`` runs one scheduler iteration
    (admit -> batched decode -> per-slot EOS/evict) and returns whatever
    finished; ``run()`` drives to completion as a generator. ``params``
    is the bare param tree (the ``generate()`` convention).

    eos_id follows generate(): an int (-1 = none) or a list/tuple
    (stop on any).

    Threading contract: ONE thread owns the decode loop (``step()`` /
    ``drain()`` / ``run()`` — the device cache and per-slot host arrays
    are single-writer), while ``submit()`` may be called from any
    thread: the pending queue is lock-protected, so a network front
    door can feed requests while the owner thread keeps stepping.
    ``max_pending`` bounds the queue; past it ``submit()`` raises
    ``QueueFull`` instead of growing without bound.
    """

    def __init__(self, model, params, *, batch_size: int = 4, eos_id=-1,
                 min_bucket: int = 16, chunk_steps: int = 8,
                 max_pending: int = 1024):
        if model.cfg.quantized:
            # nothing structural in the way — the q8 apply is the same
            # model.apply — but untested here; fail loud, not wrong
            raise NotImplementedError(
                "serve over int8 weight-only models is untested")
        self.model = model
        self.params = params
        self.eos_ids = normalize_eos_ids(eos_id)
        self.min_bucket = min_bucket
        # upper bound on decode micro-steps fused into one dispatch;
        # 1 = token-at-a-time (lowest latency to each token, highest
        # per-token dispatch cost — the right setting for streaming)
        self.chunk_steps = max(1, chunk_steps)
        self.max_pending = max(1, max_pending)
        self.slots = SlotCache(model, params, batch_size)
        self.pending: deque[Request] = deque()
        self._pending_lock = threading.Lock()
        self._live: list[_Live | None] = [None] * batch_size
        self._ids = itertools.count()
        self.steps = 0       # decode micro-steps executed (chunk sum)
        self.dispatches = 0  # chunk dispatches
        self.prefills = 0    # prefill dispatches (== admits attempted)

    # ------------------------------------------------------------ intake

    def submit(self, request: Request):
        """Enqueue a request; returns its id. Rejects prompts the cache
        cannot hold; clamps max_new_tokens to the remaining capacity
        (the generate() overflow contract, per slot). Raises
        ``QueueFull`` past ``max_pending`` queued requests — the
        caller's backpressure signal. Safe to call from any thread."""
        p = list(request.prompt)
        max_len = self.model.cfg.max_seq_len
        if not p:
            raise ValueError("empty prompt")
        if len(p) >= max_len:
            raise ValueError(
                f"prompt ({len(p)}) leaves no room for generation in "
                f"max_seq_len ({max_len})")
        if request.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if request.id is None:
            request.id = next(self._ids)
        request.max_new_tokens = min(request.max_new_tokens,
                                     max_len - len(p))
        with self._pending_lock:
            if len(self.pending) >= self.max_pending:
                raise QueueFull(
                    f"pending queue at max_pending={self.max_pending}")
            self.pending.append(request)
        return request.id

    @property
    def n_pending(self) -> int:
        return len(self.pending)

    @property
    def n_active(self) -> int:
        return self.slots.n_active

    @property
    def done(self) -> bool:
        return not self.pending and self.slots.n_active == 0

    # --------------------------------------------------------- scheduling

    def _admit_one(self, req: Request, finished: list) -> None:
        """Prefill ``req`` into a free slot (prefill + slot copy +
        first-token sample fused into one dispatch) — or finish it on
        the spot when the FIRST token already ends it (EOS, or a budget
        of one): no slot is burned on a request with nothing to decode."""
        s = self.slots
        p = np.asarray(req.prompt, np.int32)
        lb = bucket_len(len(p), self.model.cfg.max_seq_len,
                        self.min_bucket)
        padded = np.zeros((1, lb), np.int32)
        padded[0, :len(p)] = p
        slot = s.free_slots()[0]
        cache, tok, key = _prefill_admit(
            self.model, self.params, s.cache, jnp.asarray(padded),
            jnp.int32(len(p)), jnp.int32(slot),
            jnp.float32(req.temperature), jnp.int32(req.top_k),
            jax.random.PRNGKey(req.seed))
        self.prefills += 1
        tok = int(tok)
        if tok in self.eos_ids or req.max_new_tokens == 1:
            # the slot row was written but never armed — the next admit
            # simply overwrites it
            reason = "eos" if tok in self.eos_ids else "length"
            finished.append(Result(req.id, list(req.prompt), [tok],
                                   reason))
            s.cache = cache
            return
        s.cache = cache
        s.admit(slot, len(p), tok, req.temperature, req.top_k, key)
        self._live[slot] = _Live(req, [tok])

    def _chunk_size(self) -> int:
        """Decode micro-steps for this iteration: enough for the
        longest-remaining live slot but never past ``chunk_steps``,
        quantized DOWN to a power of two (bounded compile count). Slots
        finishing mid-chunk overshoot and are trimmed — overshoot
        slot-steps are free (the batched step runs every row
        regardless); a too-long chunk would only waste WHOLE-batch
        steps at the very tail, which the max-remaining bound prevents."""
        rem = max(live.request.max_new_tokens - len(live.generated)
                  for live in self._live if live is not None)
        k = 1
        while k * 2 <= min(self.chunk_steps, rem):
            k *= 2
        return k

    def step(self) -> list[Result]:
        """One scheduler iteration; returns requests that finished."""
        finished: list[Result] = []
        while self.slots.free_slots():
            with self._pending_lock:
                if not self.pending:
                    break
                req = self.pending.popleft()
            self._admit_one(req, finished)
        if self.slots.n_active == 0:
            return finished
        finished.extend(self._decode_round())
        return finished

    def _decode_round(self) -> list[Result]:
        """One batched decode chunk over the live slots + EOS/evict —
        ``step()`` minus admission (``drain()`` runs it alone)."""
        finished: list[Result] = []
        s = self.slots
        k = self._chunk_size()
        cache, toks, rng = _decode_chunk(
            self.model, self.params, s.cache,
            jnp.asarray(s.last_token), jnp.asarray(s.positions()),
            jnp.asarray(s.temperature), jnp.asarray(s.top_k),
            jnp.asarray(s.rng), n_steps=k)
        self.steps += k
        self.dispatches += 1
        s.cache = cache
        toks = np.asarray(toks)  # [b, k]
        # np.array, not asarray: device arrays view as read-only and the
        # next admit writes its slot's key in place
        s.rng = np.array(rng, np.uint32)

        for slot in range(s.batch_size):
            live = self._live[slot]
            if live is None:
                continue
            req = live.request
            reason = None
            for j in range(k):
                tok = int(toks[slot, j])
                live.generated.append(tok)
                if tok in self.eos_ids:
                    reason = "eos"
                elif len(live.generated) >= req.max_new_tokens:
                    reason = "length"
                if reason:
                    # tokens past this point are chunk overshoot: the
                    # slot kept decoding garbage into its own (about to
                    # be evicted) row — trimmed, never reported
                    break
            if reason is None:
                # the chunk wrote k tokens at advancing positions; the
                # slot's visible cache grew by k
                s.lengths[slot] += k
                s.last_token[slot] = int(toks[slot, k - 1])
                continue
            finished.append(Result(req.id, list(req.prompt),
                                   live.generated, reason))
            self._live[slot] = None
            s.evict(slot)
        return finished

    def drain(self) -> list[Result]:
        """Finish every IN-FLIGHT slot (no new admissions) and return
        their results. Pending requests stay queued — the caller
        decides whether to reject them, hand them to another replica,
        or resume stepping. The graceful-shutdown hook: a front door
        stops feeding, calls drain(), and every request that already
        holds a slot completes instead of being dropped mid-decode."""
        finished: list[Result] = []
        while self.slots.n_active:
            finished.extend(self._decode_round())
        return finished

    def live_progress(self, since: dict | None = None) -> dict:
        """{request_id: tokens generated so far} for every in-flight
        request — the streaming hook: the loop owner snapshots it after
        each ``step()`` and emits the delta per request. ``since``
        (request_id -> count already seen) returns only each request's
        TAIL, keeping a long generation's repeated snapshots O(new
        tokens) instead of O(length^2). Copies, so the caller can hold
        them across the next step."""
        out = {}
        for live in self._live:
            if live is not None:
                start = since.get(live.request.id, 0) if since else 0
                out[live.request.id] = live.generated[start:]
        return out

    def reset(self) -> None:
        """Hard reset after a failed ``step()``: drop pending and
        in-flight bookkeeping and free every slot (pure host work — the
        next admit overwrites device rows). Dropped requests never get
        a Result; the caller sheds them. ``slots.reset()`` alone leaves
        the engine inconsistent (``_live`` ghosts would decode garbage
        and emit phantom results), so external callers use this."""
        with self._pending_lock:
            self.pending.clear()
        self._live = [None] * self.slots.batch_size
        self.slots.reset()

    def run(self, requests: Iterable[Request] = ()) -> Iterator[Result]:
        """Submit ``requests`` and drive the loop until everything
        (including anything submitted earlier) finishes."""
        for r in requests:
            self.submit(r)
        while not self.done:
            yield from self.step()
