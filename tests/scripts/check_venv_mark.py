"""Exit non-zero unless launched through the shipped venv's interpreter
(the shim exports TONY_VENV_MARK; ref: check_env_and_venv.py)."""
import os
import sys

sys.exit(0 if os.environ.get("TONY_VENV_MARK") == "1" else 1)
