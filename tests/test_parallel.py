"""Parallelism tests on the 8-device virtual CPU mesh: mesh building,
sharding presets, ring attention vs reference, pipeline schedule, MoE."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tony_tpu.parallel import (
    MeshSpec,
    MoEConfig,
    blockwise_attention,
    data_parallel_mesh,
    init_moe_params,
    make_mesh,
    moe_layer,
    pipeline_apply,
    reference_attention,
    ring_attention,
    shard_params_by_size,
    stack_stage_params,
    top_k_gating,
)
from tony_tpu.parallel.mesh import DATA, EXPERT, FSDP, PIPE, SEQ, TENSOR


def test_devices_available():
    assert jax.device_count() == 8


def test_mesh_spec_resolve():
    assert MeshSpec().resolve(8)[DATA] == 8
    sizes = MeshSpec(data=-1, tensor=2).resolve(8)
    assert sizes[DATA] == 4 and sizes[TENSOR] == 2
    with pytest.raises(ValueError):
        MeshSpec(data=3, tensor=2, fsdp=1).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec(data=-1, fsdp=-1).resolve(8)


def test_make_mesh_axes():
    mesh = make_mesh(MeshSpec(data=2, tensor=2, seq=2))
    assert mesh.shape[DATA] == 2 and mesh.shape[TENSOR] == 2
    assert mesh.shape[SEQ] == 2
    mesh2 = make_mesh(MeshSpec(data=4, tensor=2), drop_trivial=True)
    assert set(mesh2.axis_names) == {DATA, TENSOR}


def test_shard_params_by_size():
    mesh = make_mesh(MeshSpec(data=2, fsdp=4))
    params = {"big": jnp.zeros((128, 256)), "small": jnp.zeros((4,))}
    sh = shard_params_by_size(mesh, params)
    assert sh["big"].spec == P(None, FSDP) or sh["big"].spec == P(FSDP, None)
    assert sh["small"].spec == P()


def test_ring_attention_matches_reference():
    mesh = make_mesh(MeshSpec(data=1, seq=8), drop_trivial=False)
    key = jax.random.PRNGKey(0)
    b, l, h, d = 2, 64, 4, 16
    q, k, v = (jax.random.normal(kk, (b, l, h, d), jnp.float32)
               for kk in jax.random.split(key, 3))
    out_ref = reference_attention(q, k, v, causal=True)
    out_ring = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_non_causal():
    mesh = make_mesh(MeshSpec(data=-1, seq=4))
    key = jax.random.PRNGKey(1)
    b, l, h, d = 1, 32, 2, 8
    q, k, v = (jax.random.normal(kk, (b, l, h, d), jnp.float32)
               for kk in jax.random.split(key, 3))
    out_ref = reference_attention(q, k, v, causal=False)
    out_ring = ring_attention(q, k, v, mesh, causal=False)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_differentiable():
    mesh = make_mesh(MeshSpec(data=-1, seq=4))
    key = jax.random.PRNGKey(2)
    b, l, h, d = 1, 16, 2, 8
    q, k, v = (jax.random.normal(kk, (b, l, h, d), jnp.float32)
               for kk in jax.random.split(key, 3))

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=5e-5, rtol=5e-5)


def test_blockwise_attention_matches():
    key = jax.random.PRNGKey(3)
    b, l, h, d = 2, 100, 2, 16  # non-divisible by block to test padding
    q, k, v = (jax.random.normal(kk, (b, l, h, d), jnp.float32)
               for kk in jax.random.split(key, 3))
    out_ref = reference_attention(q, k, v, causal=True)
    out_blk = blockwise_attention(q, k, v, block_size=32, causal=True)
    np.testing.assert_allclose(np.asarray(out_blk), np.asarray(out_ref),
                               atol=2e-5, rtol=2e-5)


def test_pipeline_matches_sequential():
    n_stages = 4
    mesh = make_mesh(MeshSpec(data=2, pipe=n_stages))
    key = jax.random.PRNGKey(4)
    d = 16

    def stage_fn(params, x):
        return jnp.tanh(x @ params["w"] + params["b"])

    per_stage = []
    for i in range(n_stages):
        k1, k2, key = jax.random.split(key, 3)
        per_stage.append({
            "w": jax.random.normal(k1, (d, d)) * 0.5,
            "b": jax.random.normal(k2, (d,)) * 0.1,
        })
    stacked = stack_stage_params(per_stage)
    x = jax.random.normal(key, (8, d))

    out_pipe = pipeline_apply(stage_fn, stacked, x, mesh=mesh, n_microbatches=4)
    out_seq = x
    for p in per_stage:
        out_seq = stage_fn(p, out_seq)
    np.testing.assert_allclose(np.asarray(out_pipe), np.asarray(out_seq),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_batch_validation():
    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    stacked = {"w": jnp.zeros((4, 2, 2))}
    with pytest.raises(ValueError, match="n_microbatches"):
        pipeline_apply(lambda p, x: x, stacked, jnp.zeros((7, 2)), mesh=mesh,
                       n_microbatches=4)


def test_top_k_gating_shapes_and_capacity():
    logits = jax.random.normal(jax.random.PRNGKey(5), (32, 4))
    dispatch, combine, aux = top_k_gating(logits, k=2, capacity=8)
    assert dispatch.shape == (32, 4, 8)
    assert combine.shape == (32, 4, 8)
    # each expert slot holds at most one token
    assert np.asarray(dispatch.sum(axis=0)).max() <= 1.0 + 1e-6
    assert float(aux) > 0


def test_moe_layer_forward_and_grad():
    cfg = MoEConfig(num_experts=4, d_model=16, d_ff=32, top_k=2)
    params = init_moe_params(jax.random.PRNGKey(6), cfg)
    x = jax.random.normal(jax.random.PRNGKey(7), (2, 8, 16))
    out, aux = moe_layer(params, x, cfg)
    assert out.shape == x.shape
    g = jax.grad(lambda p: moe_layer(p, x, cfg)[0].sum() )(params)
    assert all(jnp.all(jnp.isfinite(v)) for v in jax.tree.leaves(g))


def test_dp_gradient_sync_end_to_end():
    """pjit DP training-step parity with single-device step (the Horovod
    all-reduce replacement, north-star semantics)."""
    mesh = data_parallel_mesh()
    w = jnp.ones((4, 4))
    x = jax.random.normal(jax.random.PRNGKey(8), (16, 4))
    y = jax.random.normal(jax.random.PRNGKey(9), (16, 4))

    def loss(w, x, y):
        return jnp.mean((x @ w - y) ** 2)

    grad_single = jax.grad(loss)(w, x, y)
    sharded = jax.jit(
        jax.grad(loss),
        in_shardings=(NamedSharding(mesh, P()),
                      NamedSharding(mesh, P(DATA)),
                      NamedSharding(mesh, P(DATA))),
        out_shardings=NamedSharding(mesh, P()),
    )(w, x, y)
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(grad_single),
                               atol=1e-5, rtol=1e-5)


def test_ulysses_attention_matches_reference():
    from tony_tpu.parallel import ulysses_attention

    mesh = make_mesh(MeshSpec(data=-1, seq=4))
    key = jax.random.PRNGKey(3)
    b, l, h, d = 2, 32, 4, 8  # h divisible by seq=4
    q, k, v = (jax.random.normal(kk, (b, l, h, d), jnp.float32)
               for kk in jax.random.split(key, 3))
    for causal in (True, False):
        out_ref = reference_attention(q, k, v, causal=causal)
        out_uly = ulysses_attention(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(out_uly), np.asarray(out_ref),
                                   atol=2e-5, rtol=2e-5)


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_ulysses_attention_differentiable():
    from tony_tpu.parallel import ulysses_attention

    mesh = make_mesh(MeshSpec(data=-1, seq=2))
    key = jax.random.PRNGKey(4)
    b, l, h, d = 1, 16, 2, 8
    q, k, v = (jax.random.normal(kk, (b, l, h, d), jnp.float32)
               for kk in jax.random.split(key, 3))

    def loss_uly(q, k, v):
        return jnp.sum(ulysses_attention(q, k, v, mesh, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    g_uly = jax.grad(loss_uly, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_uly, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=5e-5, rtol=5e-5)


def test_ulysses_rejects_indivisible_heads():
    from tony_tpu.parallel import ulysses_attention

    mesh = make_mesh(MeshSpec(data=-1, seq=4))
    q = jnp.ones((1, 16, 3, 8))  # 3 heads not divisible by 4
    import pytest

    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, q, q, mesh)


def test_multislice_mesh_single_slice_degenerates():
    """On single-slice (CPU test) hardware, multislice_mesh == make_mesh."""
    from tony_tpu.parallel import MeshSpec, multislice_mesh, num_slices

    assert num_slices() == 1
    mesh = multislice_mesh(MeshSpec(data=-1, tensor=2))
    assert mesh.shape["tensor"] == 2
    assert mesh.shape["data"] == len(jax.devices()) // 2


def test_multislice_mesh_hybrid_shape_math():
    """DCN axis spans fake slices; ICI axes stay within-slice (the
    create_hybrid_device_mesh call itself needs real TPU coords, so this
    validates num_slices + the per-slice/DCN size resolution)."""
    from types import SimpleNamespace

    from tony_tpu.parallel import MeshSpec, num_slices

    devs = [SimpleNamespace(id=i, slice_index=i // 4) for i in range(8)]
    assert num_slices(devs) == 2
    # 2 slices x 4 devices, tensor=2 on ICI: per-slice wildcard data=2,
    # final data axis = 2 (ICI) x 2 (DCN slices) = 4
    spec = MeshSpec(data=-1, tensor=2)
    ici = spec.resolve(4)
    assert ici["data"] == 2 and ici["tensor"] == 2


def test_multislice_mesh_branch_with_fake_slices(monkeypatch):
    """Exercise the n_slices>1 branch end-to-end with fake sliced devices
    and a stubbed create_hybrid_device_mesh that checks the shapes it is
    handed (real hybrid meshes need a physical multi-slice pod)."""
    import numpy as np

    from jax.experimental import mesh_utils
    from tony_tpu.parallel import MeshSpec, multislice_mesh
    from tony_tpu.parallel.mesh import ALL_AXES

    class FakeDev:  # default object hash: Mesh requires hashable devices
        def __init__(self, i):
            self.id = i
            self.slice_index = i // 4

    devs = [FakeDev(i) for i in range(8)]
    captured = {}

    def fake_hybrid(mesh_shape, dcn_mesh_shape, devices):
        captured["mesh_shape"] = list(mesh_shape)
        captured["dcn"] = list(dcn_mesh_shape)
        total = [a * b for a, b in zip(mesh_shape, dcn_mesh_shape)]
        return np.array(devices, dtype=object).reshape(total)

    monkeypatch.setattr(mesh_utils, "create_hybrid_device_mesh", fake_hybrid)
    mesh = multislice_mesh(MeshSpec(data=-1, tensor=2), devices=devs)
    # ICI: per-slice 4 devices -> data=2 x tensor=2; DCN: data axis x2 slices
    assert captured["mesh_shape"] == [2, 1, 2, 1, 1, 1]
    assert captured["dcn"] == [2, 1, 1, 1, 1, 1]
    assert mesh.axis_names == ALL_AXES
    assert mesh.shape["data"] == 4 and mesh.shape["tensor"] == 2


def test_multislice_mesh_virtual_slices_executes():
    """n_slices forces the DCNxICI layout on plain CPU devices (no
    slice_index): the mesh must be runnable, with the dcn axis spanning
    the virtual slice groups."""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tony_tpu.parallel import MeshSpec, multislice_mesh

    mesh = multislice_mesh(MeshSpec(data=-1, tensor=2),
                           devices=jax.devices()[:8], n_slices=2)
    assert mesh.shape["data"] == 4 and mesh.shape["tensor"] == 2
    x = jnp.arange(8.0).reshape(4, 2)
    xs = jax.device_put(x, NamedSharding(mesh, P("data", "tensor")))
    total = jax.jit(lambda v: v.sum())(xs)  # cross-slice + ICI reduction
    assert float(total) == float(x.sum())
    # virtual slice 0 = first half of the device list, stacked on data
    arr = np.asarray(mesh.devices)
    first_ids = {d.id for d in arr[:2].flatten()}
    assert first_ids == {d.id for d in jax.devices()[:4]}


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_pipeline_remat_matches_and_differentiates():
    n_stages = 4
    mesh = make_mesh(MeshSpec(data=2, pipe=n_stages))
    key = jax.random.PRNGKey(7)
    d = 8

    def stage_fn(params, x):
        return jnp.tanh(x @ params["w"])

    per_stage = []
    for i in range(n_stages):
        k, key = jax.random.split(key)
        per_stage.append({"w": jax.random.normal(k, (d, d)) * 0.5})
    stacked = stack_stage_params(per_stage)
    x = jax.random.normal(key, (8, d))

    out_plain = pipeline_apply(stage_fn, stacked, x, mesh=mesh,
                               n_microbatches=4)
    out_remat = pipeline_apply(stage_fn, stacked, x, mesh=mesh,
                               n_microbatches=4, remat=True)
    np.testing.assert_allclose(np.asarray(out_plain), np.asarray(out_remat),
                               atol=1e-6, rtol=1e-6)

    def loss(p, use_remat):
        out = pipeline_apply(stage_fn, p, x, mesh=mesh, n_microbatches=4,
                             remat=use_remat)
        return jnp.sum(out ** 2)

    g_plain = jax.grad(lambda p: loss(p, False))(stacked)
    g_remat = jax.grad(lambda p: loss(p, True))(stacked)
    np.testing.assert_allclose(np.asarray(g_plain["w"]),
                               np.asarray(g_remat["w"]), atol=1e-5, rtol=1e-5)


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_circular_pipeline_matches_sequential():
    """Interleaved schedule (R=2, 8 virtual stages on 4 devices) must equal
    running all 8 stages sequentially."""
    n_stages, R = 4, 2
    mesh = make_mesh(MeshSpec(data=2, pipe=n_stages))
    key = jax.random.PRNGKey(11)
    d = 8
    per_stage = []
    for i in range(n_stages * R):
        k, key = jax.random.split(key)
        per_stage.append({"w": jax.random.normal(k, (d, d)) * 0.4})
    stacked = stack_stage_params(per_stage)

    def stage_fn(params, x):
        return jnp.tanh(x @ params["w"])

    for n_micro in (4, 8, 6):  # full group, multi-group, partial group
        x = jax.random.normal(key, (24, d))
        out = pipeline_apply(stage_fn, stacked, x, mesh=mesh,
                             n_microbatches=n_micro, circular_repeats=R)
        seq = x
        for p in per_stage:
            seq = stage_fn(p, seq)
        np.testing.assert_allclose(np.asarray(out), np.asarray(seq),
                                   atol=1e-5, rtol=1e-5,
                                   err_msg=f"n_micro={n_micro}")


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_circular_pipeline_differentiable():
    n_stages, R = 4, 2
    mesh = make_mesh(MeshSpec(data=2, pipe=n_stages))
    key = jax.random.PRNGKey(12)
    d = 8
    per_stage = [{"w": jax.random.normal(jax.random.fold_in(key, i),
                                         (d, d)) * 0.4}
                 for i in range(n_stages * R)]
    stacked = stack_stage_params(per_stage)
    x = jax.random.normal(key, (8, d))

    def stage_fn(params, xx):
        return jnp.tanh(xx @ params["w"])

    def loss_pipe(p):
        out = pipeline_apply(stage_fn, p, x, mesh=mesh, n_microbatches=4,
                             circular_repeats=R)
        return jnp.sum(out ** 2)

    def loss_seq(p):
        out = x
        for i in range(n_stages * R):
            out = stage_fn(jax.tree.map(lambda q: q[i], p), out)
        return jnp.sum(out ** 2)

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    np.testing.assert_allclose(np.asarray(g_pipe["w"]),
                               np.asarray(g_seq["w"]),
                               atol=1e-5, rtol=1e-5)


def test_circular_pipeline_validates_stage_count():
    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    stacked = {"w": jnp.zeros((4, 2, 2))}  # 4 stages, but R=2 needs 8
    with pytest.raises(ValueError, match="virtual stages"):
        pipeline_apply(lambda p, x: x, stacked, jnp.zeros((8, 2)), mesh=mesh,
                       n_microbatches=4, circular_repeats=2)


def test_circular_pipeline_pre_interleaved():
    from tony_tpu.parallel.pipeline import interleave_stage_params

    n_stages, R = 4, 2
    mesh = make_mesh(MeshSpec(data=2, pipe=n_stages))
    key = jax.random.PRNGKey(13)
    d = 8
    per_stage = [{"w": jax.random.normal(jax.random.fold_in(key, i),
                                         (d, d)) * 0.4}
                 for i in range(n_stages * R)]
    stacked = stack_stage_params(per_stage)
    x = jax.random.normal(key, (8, d))

    def stage_fn(p, xx):
        return jnp.tanh(xx @ p["w"])

    a = pipeline_apply(stage_fn, stacked, x, mesh=mesh, n_microbatches=4,
                       circular_repeats=R)
    pre = interleave_stage_params(stacked, n_stages, R)
    b = pipeline_apply(stage_fn, pre, x, mesh=mesh, n_microbatches=4,
                       circular_repeats=R, interleaved=True)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               atol=1e-6, rtol=1e-6)


def test_gpipe_rejects_wrong_stage_count():
    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    stacked = {"w": jnp.zeros((8, 2, 2))}  # 8 stages on 4 devices, R=1
    with pytest.raises(ValueError, match="virtual stages"):
        pipeline_apply(lambda p, x: x, stacked, jnp.zeros((8, 2)), mesh=mesh,
                       n_microbatches=4)


def test_ulysses_sliding_window_matches_reference():
    from tony_tpu.parallel.ulysses import ulysses_attention

    mesh = make_mesh(MeshSpec(data=2, seq=4))
    rng = jax.random.PRNGKey(21)
    q, k, v = (jax.random.normal(key, (2, 32, 4, 8))
               for key in jax.random.split(rng, 3))
    from tony_tpu.parallel.ring_attention import reference_attention

    ref = reference_attention(q, k, v, causal=True, window=7)
    out = jax.jit(lambda q, k, v: ulysses_attention(
        q, k, v, mesh, causal=True, block_size=8, window=7))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def _packed_segments(b, l, rng):
    """Random monotone segment ids [B, L] with 2-4 documents per row."""
    seg = np.zeros((b, l), np.int32)
    rs = np.random.RandomState(rng)
    for i in range(b):
        cuts = np.sort(rs.choice(np.arange(1, l), size=rs.randint(1, 4),
                                 replace=False))
        seg[i] = np.searchsorted(cuts, np.arange(l), side="right")
    return jnp.asarray(seg)


def test_ring_attention_sliding_window_matches_reference():
    mesh = make_mesh(MeshSpec(data=2, seq=4))
    rng = jax.random.PRNGKey(31)
    q, k, v = (jax.random.normal(kk, (2, 32, 4, 8))
               for kk in jax.random.split(rng, 3))
    ref = reference_attention(q, k, v, causal=True, window=7)
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh, causal=True, window=7))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_ring_attention_segments_match_reference():
    mesh = make_mesh(MeshSpec(data=2, seq=4))
    rng = jax.random.PRNGKey(32)
    q, k, v = (jax.random.normal(kk, (2, 32, 4, 8))
               for kk in jax.random.split(rng, 3))
    seg = _packed_segments(2, 32, 7)
    ref = reference_attention(q, k, v, causal=True, segment_ids=seg)
    out = jax.jit(lambda q, k, v, s: ring_attention(
        q, k, v, mesh, causal=True, segment_ids=s))(q, k, v, seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_ring_attention_window_and_segments_gradients():
    mesh = make_mesh(MeshSpec(data=-1, seq=4))
    rng = jax.random.PRNGKey(33)
    q, k, v = (jax.random.normal(kk, (1, 16, 2, 8))
               for kk in jax.random.split(rng, 3))
    seg = _packed_segments(1, 16, 9)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=True, window=5,
                                      segment_ids=seg) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True, window=5,
                                           segment_ids=seg) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=5e-5, rtol=5e-5)


def test_ulysses_segments_and_window_match_reference():
    from tony_tpu.parallel.ulysses import ulysses_attention

    mesh = make_mesh(MeshSpec(data=2, seq=4))
    rng = jax.random.PRNGKey(34)
    q, k, v = (jax.random.normal(kk, (2, 32, 4, 8))
               for kk in jax.random.split(rng, 3))
    seg = _packed_segments(2, 32, 11)
    ref = reference_attention(q, k, v, causal=True, window=9,
                              segment_ids=seg)
    out = jax.jit(lambda q, k, v, s: ulysses_attention(
        q, k, v, mesh, causal=True, block_size=8, window=9,
        segment_ids=s))(q, k, v, seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_transformer_train_step_ring_window_segments():
    """The FULL transformer forward/backward under sp: ring backend with
    sliding_window + packed segment_ids must match the reference backend
    logits AND gradients (VERDICT r3 weak #3: sp used to reject both)."""
    from tony_tpu.models.transformer import Transformer, TransformerConfig

    mesh = make_mesh(MeshSpec(data=2, seq=4))
    base = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
                max_seq_len=32, dtype=jnp.float32, sliding_window=6)
    cfg_ref = TransformerConfig(**base, attention_backend="reference")
    cfg_ring = TransformerConfig(**base, attention_backend="ring", mesh=mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(40), (2, 32), 0, 64)
    seg = _packed_segments(2, 32, 13)
    model_ref, model_ring = Transformer(cfg_ref), Transformer(cfg_ring)
    params = model_ref.init(jax.random.PRNGKey(41), tokens)

    def loss(model, params):
        logits = model.apply(params, tokens, segment_ids=seg)
        return jnp.mean(logits.astype(jnp.float32) ** 2)

    l_ref, g_ref = jax.value_and_grad(lambda p: loss(model_ref, p))(params)
    l_ring, g_ring = jax.value_and_grad(lambda p: loss(model_ring, p))(params)
    np.testing.assert_allclose(float(l_ring), float(l_ref), rtol=1e-5)
    flat_ref = jax.tree_util.tree_leaves(g_ref)
    flat_ring = jax.tree_util.tree_leaves(g_ring)
    for a, b_ in zip(flat_ring, flat_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=5e-5, rtol=5e-4)


# -- combined-axis training (VERDICT r3 weak #5) ------------------------------


def _mlp_stage_tp(axis):
    """Megatron-style tensor-parallel residual MLP stage for pipeline
    tests: w1 column-sharded, w2 row-sharded, one psum over ``axis``."""
    def stage_fn(p, x):
        h = jnp.tanh(x @ p["w1"])
        return x + jax.lax.psum(h @ p["w2"], axis)
    return stage_fn


def _mlp_stage_seq():
    def stage_fn(p, x):
        return x + jnp.tanh(x @ p["w1"]) @ p["w2"]
    return stage_fn


def _stage_params(n_stages, d, f, seed):
    ks = jax.random.split(jax.random.PRNGKey(seed), 2 * n_stages)
    return stack_stage_params([
        {"w1": jax.random.normal(ks[2 * i], (d, f)) * 0.3,
         "w2": jax.random.normal(ks[2 * i + 1], (f, d)) * 0.3}
        for i in range(n_stages)])


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_pipeline_composes_with_data_and_tensor_axes():
    """pp=2 x tp=2 x dp=2 on 8 devices: forward AND one full optimizer
    step match the sequential single-axis run."""
    import optax

    mesh = make_mesh(MeshSpec(data=2, tensor=2, pipe=2))
    d, f, batch = 8, 16, 8
    stacked = _stage_params(2, d, f, 50)
    x = jax.random.normal(jax.random.PRNGKey(51), (batch, d))
    target = jax.random.normal(jax.random.PRNGKey(52), (batch, d))

    specs = {"w1": P(PIPE, None, TENSOR), "w2": P(PIPE, TENSOR, None)}

    def loss_pp(params, x):
        out = pipeline_apply(_mlp_stage_tp(TENSOR), params, x, mesh=mesh,
                             n_microbatches=2, batch_axis=DATA,
                             param_specs=specs)
        return jnp.mean((out - target) ** 2)

    def loss_seq(params, x):
        out = x
        for s in range(2):
            out = _mlp_stage_seq()(
                jax.tree.map(lambda p: p[s], params), out)
        return jnp.mean((out - target) ** 2)

    opt = optax.adamw(1e-2)

    def train_step(loss_fn):
        def step(params, opt_state, x):
            loss, grads = jax.value_and_grad(loss_fn)(params, x)
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss
        return step

    l_pp, g_pp = jax.value_and_grad(loss_pp)(stacked, x)
    l_seq, g_seq = jax.value_and_grad(loss_seq)(stacked, x)
    np.testing.assert_allclose(float(l_pp), float(l_seq), rtol=1e-6)
    for a, b_ in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=1e-5, rtol=1e-5)

    # one jitted optimizer step end-to-end on the combined mesh
    opt_state = opt.init(stacked)
    p_pp, _, l1_pp = jax.jit(train_step(loss_pp))(stacked, opt_state, x)
    p_seq, _, l1_seq = train_step(loss_seq)(stacked, opt_state, x)
    np.testing.assert_allclose(float(l1_pp), float(l1_seq), rtol=1e-6)
    for a, b_ in zip(jax.tree.leaves(p_pp), jax.tree.leaves(p_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=1e-5, rtol=1e-5)


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_moe_transformer_train_step_ep_tp_dp():
    """Full MoE transformer optimizer step on a data=2 x tensor=2 x
    expert=2 mesh (ep_tp preset): loss matches the replicated
    single-device run."""
    import optax

    from tony_tpu.models.transformer import (
        Transformer, TransformerConfig, logical_axis_rules_tree,
        moe_aux_loss)
    from tony_tpu.parallel.sharding import tree_shardings
    from tony_tpu.train import cross_entropy_loss

    mesh = make_mesh(MeshSpec(data=2, tensor=2, expert=2))
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=32, dtype=jnp.float32, moe_every=2,
        moe_num_experts=4, moe_top_k=2, moe_dropless=True)
    model = Transformer(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(60), (4, 16), 0, 64)
    params = model.init(jax.random.PRNGKey(61), tokens)

    def loss_fn(p, tokens):
        logits, mut = model.apply(p, tokens, mutable=["losses"])
        return cross_entropy_loss(logits[:, :-1], tokens[:, 1:]) + \
            moe_aux_loss(mut["losses"])

    opt = optax.adamw(1e-3)

    def step(p, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(p, tokens)
        updates, opt_state = opt.update(grads, opt_state, p)
        return optax.apply_updates(p, updates), opt_state, loss

    # replicated single-run reference
    p_ref, _, l_ref = step(params, opt.init(params), tokens)

    sh = tree_shardings(mesh, logical_axis_rules_tree(params), "ep_tp")
    placed = jax.device_put(params, sh)
    opt_state = opt.init(placed)
    tok_sh = jax.device_put(tokens, NamedSharding(mesh, P(DATA)))
    p_mesh, _, l_mesh = jax.jit(step)(placed, opt_state, tok_sh)
    np.testing.assert_allclose(float(l_mesh), float(l_ref), rtol=1e-5)
    # expert weights actually landed ep x tp sharded
    moe_wi = [x for path, x in
              jax.tree_util.tree_flatten_with_path(p_mesh)[0]
              if "/wi" in "/" + "/".join(
                  getattr(q, "key", str(q)) for q in path)
              and x.ndim == 3]
    assert moe_wi, "no MoE expert weights found"
    spec = moe_wi[0].sharding.spec
    assert spec[0] == EXPERT and spec[2] == TENSOR, spec
    for a, b_ in zip(jax.tree.leaves(p_mesh), jax.tree.leaves(p_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=2e-5, rtol=2e-4)
