"""Threaded RPC server dispatching to a handler object.

Reference: rpc/ApplicationRpcServer.java:26 (random-port bind :38-41,
protobuf service build :123-134) and rpc/impl/MetricsRpcServer.java:22-43.
One server class serves both roles; the coordinator runs two instances with
different handler objects, mirroring the reference's two-server layout.

A handler is any object whose public methods (not starting with ``_``) are
the RPC verbs; params are passed as kwargs. Unknown methods and handler
exceptions return an error frame rather than killing the connection.
"""

from __future__ import annotations

import logging
import socket
import socketserver
import threading

from tony_tpu.rpc import wire

log = logging.getLogger(__name__)


class RpcServer:
    def __init__(self, handler: object, host: str = "127.0.0.1", port: int = 0,
                 secret: str | None = None,
                 tls: tuple[str, str] | None = None):
        """``tls`` = (cert_path, key_path): serve the per-job self-signed
        cert; peers pin its fingerprint (rpc/tls.py — the SASL-transport
        analog of ApplicationMaster.java:484-504)."""
        self.handler = handler
        self.secret = secret
        self._ssl_ctx = None
        if tls:
            from tony_tpu.rpc.tls import server_context

            self._ssl_ctx = server_context(*tls)
        outer = self

        class _Conn(socketserver.BaseRequestHandler):
            def handle(self) -> None:  # one connection, many frames
                sock: socket.socket = self.request
                sock.settimeout(600)
                if outer._ssl_ctx is not None:
                    try:
                        sock = outer._ssl_ctx.wrap_socket(sock,
                                                          server_side=True)
                    except (OSError, ConnectionError) as e:
                        # plaintext/garbled handshake must not kill the
                        # server thread pool
                        log.warning("TLS handshake failed: %s", e)
                        return
                try:
                    while True:
                        req = wire.recv_frame(sock)
                        if req is None:
                            return
                        wire.send_frame(sock, outer._dispatch(req))
                except (ConnectionError, TimeoutError, OSError):
                    return

        class _Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = _Server((host, port), _Conn)
        self.host, self.port = self._server.server_address[:2]
        self._thread: threading.Thread | None = None

    # -- dispatch -----------------------------------------------------------
    def _dispatch(self, req: dict) -> dict:
        req_id = req.get("id", 0)
        method = str(req.get("method", ""))
        params = req.get("params") or {}
        if method.startswith("_") or not hasattr(self.handler, method):
            return wire.make_response(req_id, error=f"unknown method: {method}")
        if self.secret and not wire.verify(self.secret, method, params, req.get("sig", "")):
            log.warning("rejecting unauthenticated call to %s", method)
            return wire.make_response(req_id, error="authentication failed")
        try:
            result = getattr(self.handler, method)(**params)
            return wire.make_response(req_id, result=result)
        except Exception as e:  # handler bug must not kill the control plane
            log.exception("RPC handler error in %s", method)
            return wire.make_response(req_id, error=f"{type(e).__name__}: {e}")

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "RpcServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, name=f"rpc-{self.port}", daemon=True
        )
        self._thread.start()
        log.info("RPC server listening on %s:%d", self.host, self.port)
        return self

    def stop(self) -> None:
        if self._thread is not None:  # shutdown() deadlocks if never started
            self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)
