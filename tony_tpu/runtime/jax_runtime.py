"""JAX runtime: the flagship, TPU-native rendezvous.

This is the in-tree replacement for the reference's HorovodRuntime + NCCL
path (runtime/HorovodRuntime.java, 357 LoC + HorovodDriver + rendezvous
server): on TPU there is no rendezvous *server* at all — the chief task's
registered host:port becomes the jax.distributed coordinator address, each
task's global process id is its flat index in the cluster spec, and all
collectives are XLA over ICI/DCN. The entire HorovodDriver/slot-plan
machinery collapses into env injection (SURVEY.md section 5.8).

User scripts call ``tony_tpu.distributed.initialize()`` (reads this env) or
``jax.distributed.initialize()`` with the values below.
"""

from __future__ import annotations


from tony_tpu import constants as C
from tony_tpu.config import ConfError, TonyConf
from tony_tpu.runtime.base import AMAdapter, Runtime, TaskAdapter, TaskContext


def coordinator_address(cluster_spec: dict[str, list[str]]) -> str:
    """The chief's host:port doubles as the jax coordinator address: chief
    role's task 0 if present, else the first role's task 0."""
    for role in (C.CHIEF_JOB_NAME, C.WORKER_JOB_NAME):
        slots = cluster_spec.get(role)
        if slots and slots[0]:
            return slots[0]
    for slots in cluster_spec.values():
        if slots and slots[0]:
            return slots[0]
    raise ValueError("empty cluster spec: no coordinator candidate")


class JaxAMAdapter(AMAdapter):
    def validate_and_update_config(self, conf: TonyConf) -> None:
        if conf.get("tony.application.distributed-mode") != C.GANG:
            # jax.distributed barrier-initializes: every process must attend
            raise ConfError("jax runtime requires GANG distributed mode")


def flat_slots(cluster_spec: dict[str, list[str]]) -> list[str]:
    """All host:port slots in flat-index order (the same role-order walk
    as TaskContext.flat_index — the two MUST agree for slice grouping)."""
    out: list[str] = []
    for slots in cluster_spec.values():
        out.extend(slots)
    return out


def multislice_env(conf, cluster_spec: dict[str, list[str]], pid: int,
                   num: int) -> dict[str, str]:
    """The real multi-slice Cloud TPU env contract (VERDICT r2 #4).

    A >1 ``tony.tpu.num-slices`` job groups its processes into contiguous
    equal slices: within a slice, collectives ride ICI; across slices,
    libtpu's megascale transport rides DCN, discovered via the
    ``MEGASCALE_*`` env (the TPU-native analog of the reference's
    NCCL/Gloo rendezvous env, SURVEY.md section 2.5):

    - ``MEGASCALE_COORDINATOR_ADDRESS``: slice-0 host 0 at the megascale
      port — every slice dials it to exchange DCN endpoints;
    - ``MEGASCALE_NUM_SLICES`` / ``MEGASCALE_SLICE_ID``: the DCN mesh
      shape, consumed by jax as ``jax.devices()[i].slice_index`` which
      ``parallel.mesh.multislice_mesh`` lays out over the dcn axis;
    - ``TPU_WORKER_HOSTNAMES`` / ``TPU_WORKER_ID``: libtpu's WITHIN-slice
      host list (ICI ring bring-up) — per slice, not global.
    """
    n_slices = conf.get_int("tony.tpu.num-slices", 1)
    if n_slices <= 1:
        return {}
    if num % n_slices:
        raise ConfError(
            f"tony.tpu.num-slices={n_slices} does not divide the "
            f"{num}-process gang into equal slices")
    per = num // n_slices
    slots = flat_slots(cluster_spec)
    hosts = [s.rsplit(":", 1)[0] for s in slots]
    mport = conf.get_int("tony.tpu.megascale-port", 8080)
    slice_id = pid // per
    return {
        "MEGASCALE_COORDINATOR_ADDRESS": f"{hosts[0]}:{mport}",
        "MEGASCALE_NUM_SLICES": str(n_slices),
        "MEGASCALE_SLICE_ID": str(slice_id),
        "TPU_WORKER_HOSTNAMES": ",".join(
            hosts[slice_id * per:(slice_id + 1) * per]),
        "TPU_WORKER_ID": str(pid % per),
    }


class JaxTaskAdapter(TaskAdapter):
    def build_task_env(self, ctx: TaskContext) -> dict[str, str]:
        env = super().build_task_env(ctx)
        addr = coordinator_address(ctx.cluster_spec)
        pid = ctx.flat_index()
        num = ctx.total_tasks()
        env[C.COORDINATOR_ADDRESS] = addr
        env[C.PROCESS_ID] = str(pid)
        env[C.NUM_PROCESSES] = str(num)
        # ICI-topology hints for multi-host TPU slices
        topology = str(ctx.conf.get("tony.tpu.topology", ""))
        if topology:
            env["TONY_TPU_TOPOLOGY"] = topology
        env.update(multislice_env(ctx.conf, ctx.cluster_spec, pid, num))
        return env


class JaxRuntime(Runtime):
    name = "jax"
    am_adapter_cls = JaxAMAdapter
    task_adapter_cls = JaxTaskAdapter
