"""Flash-decode: single-query KV-cache attention as a pallas TPU kernel.

The decode hot loop is HBM-bound (docs/PERF.md "Decode roofline"): every
generated token re-reads the whole KV cache once. This kernel is the
cache-side counterpart of the int8 weight path (ops/quant.py):

- one grid step per (batch, kv_head, kv block): K/V tiles are DMA'd
  HBM->VMEM once — sliced straight out of the cache's NATIVE
  [B, S, KVH, D] layout by the BlockSpec index maps (the r13 relayout
  fix: the old path materialized transposed copies of the FULL cache
  before every call; the only relayout left is the GQA int8 path's
  scale tensors, 4/D of the cache bytes, kept so each head instance
  reads an exact per-head tile) — and consumed by an online-softmax
  accumulation held in VMEM scratch: no [S] score tensor round-trips
  to HBM, and the softmax/weighted-sum fuse into the tile pass (XLA's
  decode attention materializes scores + probabilities in HBM at
  small batch);
- the cache may be stored **int8 with per-(position, head) scales**
  (quantize-on-write in models/transformer._decode_attention): tiles
  cross HBM as int8 — HALF the cache traffic of bf16, the dominant
  decode bytes at long context — and dequantize in VMEM right before
  the MXU, exactly the ops/quant.py recipe for weights;
- GQA: the q-head group [G, D] of each kv head rides one kernel
  instance, so cache tiles are read ONCE per kv head (never repeated to
  n_heads), preserving the GQA bandwidth saving end-to-end;
- cache positions at/after ``length`` (and behind the sliding window)
  are masked; blocks entirely outside [start, length) skip their FLOPs
  via ``@pl.when`` predication.

No reference analog (TonY ships no kernels; SURVEY.md section 2.5 —
the data plane is delegated). Falls back to the pallas interpreter
off-TPU so CPU tests pin exactness against the jax reference path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from tony_tpu.ops.platform import interpret_mode

NEG_INF = -1e30


def quantize_kv(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """[B, L, H, D] float -> (int8 values, fp32 scales [B, L, H]).
    Symmetric absmax per (batch, position, head) — the KV analog of
    ops/quant.quantize_q8's per-output-channel recipe; dequant is
    ``q * scale[..., None]``."""
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_kv(q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    return q.astype(jnp.float32) * scale[..., None]


def _vmem(shape):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, jnp.float32)


def _decode_kernel(q_ref, k_ref, v_ref, len_ref, *rest,
                   block_k: int, scale: float, window: int,
                   quant: bool):
    """Grid (batch, kv_head, kv_block); K/V arrive in their NATIVE
    [B, S, KVH, D] cache layout — the BlockSpec index maps slice one
    head's [block_k, D] tile per instance (the r13 relayout fix: no
    materialized cache-sized transpose). The int8 scales DO arrive
    pre-transposed [B, KVH, S] (tiny — 4/D of the cache bytes): a
    native-layout scale tile would carry ALL kvh lane columns and be
    re-fetched once per head instance, a kvh-fold tax on the
    hot-loop's HBM reads, where the transpose hands every instance an
    exact (1, 1, block_k) per-head tile."""
    if quant:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # lengths live whole in SMEM (scalars don't tile: a (1, 1) VMEM
    # block of an [B, 1] array fails Mosaic's sublane rule on-chip);
    # indexed dynamically per grid row instead of via BlockSpec
    length = len_ref[pl.program_id(0), 0]
    start = jnp.maximum(length - window, 0) if window > 0 else 0

    def _body():
        q = q_ref[0, 0]        # [Gp, D]
        k = k_ref[0, :, 0, :]  # [block_k, D] (int8 when quant)
        v = v_ref[0, :, 0, :]
        if quant:
            kf = k.astype(jnp.float32) * ks_ref[0, 0][:, None]
            vf = v.astype(jnp.float32) * vs_ref[0, 0][:, None]
        else:
            kf, vf = k, v
        s = jax.lax.dot_general(
            q, kf, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [Gp, block_k]
        pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        visible = pos < length
        if window > 0:
            visible = visible & (pos >= start)
        s = jnp.where(visible, s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        m_scr[:] = m_new
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(vf.dtype), vf, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    # skip FLOPs for blocks wholly past `length` or behind the window
    # (their DMA is already issued by BlockSpec — static grid — so this
    # saves compute, not traffic; the traffic win comes from int8 tiles)
    in_range = ki * block_k < length
    if window > 0:
        in_range = in_range & (ki * block_k + block_k > start)

    @pl.when(in_range)
    def _run():
        _body()

    @pl.when(ki == nk - 1)
    def _finalize():
        l_safe = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def _decode_kernel_mha(q_ref, k_ref, v_ref, len_ref, *rest,
                       block_k: int, scale: float, window: int,
                       quant: bool, hb: int):
    """Head-blocked variant for MHA decode (group == 1).

    The GQA kernel pads each kv head's single query row to 8 sublanes
    and runs one grid instance per (batch x head) — at short cache
    that is b*h tiny instances whose fixed cost (DMA setup, grid step)
    beats the useful work, exactly where the XLA einsum used to win
    (VERDICT r4 #1/#4: 0.89x at cache 512). Here ``hb`` HEADS of one
    batch ride one instance (grid = (batch, kvh/hb, kv_block)): real
    query rows fill the sublanes padding wasted, K/V tiles arrive in
    their NATIVE [B, S, KVH, D] layout as one [block_k, hb, D] DMA
    (the r13 relayout fix — no materialized transpose), and the
    instance count drops hb-fold. All rows share the batch, so ONE
    SMEM length serves the whole instance (the old flattened-row
    variant assembled per-row length columns). Per-head score/value
    contractions are statically unrolled plain 2-D dots — no batched
    dot_general, no in-VMEM transpose, Mosaic-safe by construction.

    Tile legality: ``hb`` is either the FULL kvh dim (kvh <= 8; a
    full-dim block is always legal) or 8 (a sublane multiple) — the
    caller falls back to the GQA kernel for any other head count (a
    partial sublane tile only compiles in the CPU interpreter). int8
    scales arrive pre-transposed ``[B, KVH, S]`` as ``(1, hb, bk)``
    tiles (sublane hb, lane bk — legal by the same rule) and FOLD
    onto the score/probability rows instead of dequantizing tiles:
    the per-(position, head) scale distributes over the
    d-contraction, exactly the einsum path's trick
    (models/transformer._decode_attention)."""
    if quant:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    length = len_ref[pl.program_id(0), 0]
    start = jnp.maximum(length - window, 0) if window > 0 else 0

    def _body():
        q = q_ref[0].astype(jnp.float32)  # [hb, D]
        k = k_ref[0]                      # [block_k, hb, D]
        v = v_ref[0]
        # statically unrolled per head (hb <= 8): each head's score is
        # a plain [1, D] x [D, block_k] dot against its own K tile —
        # same per-element reduction as the GQA kernel
        rows = []
        for hh in range(hb):
            s_h = jax.lax.dot_general(
                q[hh:hh + 1, :], k[:, hh, :].astype(jnp.float32),
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if quant:
                # fold the K scale onto the lane-major score row (it
                # distributes over the d-contraction) — no
                # sublane-major scale column is ever needed
                s_h = s_h * ks_ref[0, hh:hh + 1, :]
            rows.append(s_h)
        s = jnp.concatenate(rows, axis=0) * scale  # [hb, block_k]
        pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        visible = pos < length
        if window > 0:
            visible = visible & (pos >= start)
        s = jnp.where(visible, s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        m_scr[:] = m_new
        pv = []
        for hh in range(hb):
            p_h = p[hh:hh + 1, :]
            if quant:
                # likewise fold the V scale into the probabilities
                p_h = p_h * vs_ref[0, hh:hh + 1, :]
            pv.append(jax.lax.dot_general(
                p_h, v[:, hh, :].astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))
        acc_scr[:] = acc_scr[:] * corr + jnp.concatenate(pv, axis=0)

    in_range = ki * block_k < length
    if window > 0:
        in_range = in_range & (ki * block_k + block_k > start)

    @pl.when(in_range)
    def _run():
        _body()

    @pl.when(ki == nk - 1)
    def _finalize():
        l_safe = jnp.maximum(l_scr[:], 1e-30)
        # a length-0 batch (an empty continuous-batching slot) never
        # runs _body: m stays NEG_INF and the mask pins its rows to
        # the exact zeros the reference path emits
        valid = m_scr[:] > NEG_INF * 0.5
        o_ref[0] = jnp.where(valid, acc_scr[:] / l_safe,
                             0.0).astype(o_ref.dtype)


def _pick_block_k(limit: int, s: int) -> int:
    """Largest multiple-of-8 divisor of ``s`` within ``limit``; a whole-
    length single block is legal too (mosaic pads a full-dim block). Any
    other non-8-multiple would be a sublane-misaligned TPU tile that only
    the CPU interpreter accepts, so it is an error, not a fallback."""
    if s <= limit:
        return s
    b = limit
    for cand in range(b - b % 8, 7, -8):
        if s % cand == 0:
            return cand
    raise ValueError(
        f"no usable flash-decode block for cache length {s} (need a "
        f"divisor <= {limit} that is a multiple of 8, or the whole "
        f"length; pad max_seq_len to a multiple of 8)")


@functools.partial(jax.jit, static_argnames=("window", "block_k",
                                             "interpret"))
def flash_decode(q, k, v, length, *, window: int = 0, block_k: int = 512,
                 k_scale=None, v_scale=None, interpret: bool | None = None):
    """Single-step decode attention over a static KV cache.

    q: [B, H, D] — the one new query per sequence (head-grouped GQA ok).
    k/v: [B, S, KVH, D] cache buffers — float, or int8 with
      ``k_scale``/``v_scale`` [B, S, KVH] fp32 per-(position, head)
      scales (quantize-on-write; see models/quantize.quantize_kv).
    length: [B] int32 — valid cache length per sequence (query sits at
      position ``length - 1``); positions >= length are masked. Lengths
      are PER-SLOT state: a serving batch may mix any lengths, and a
      length of 0 marks an EMPTY continuous-batching slot — its output
      row is exact zeros (both kernels; see _finalize), never NaN, so
      empty slots ride a live batch for free.
    window: sliding window (key visible iff 0 <= q_pos - k_pos < window).
    Returns [B, H, D] in q's dtype.
    """
    b, h, d = q.shape
    bs, s, kvh, dk = k.shape
    if bs != b or dk != d or v.shape != k.shape:
        raise ValueError(f"shape mismatch: q{q.shape} k{k.shape} v{v.shape}")
    if h % kvh:
        raise ValueError(f"q heads {h} not divisible by kv heads {kvh}")
    quant = k.dtype == jnp.int8
    if quant != (v.dtype == jnp.int8):
        raise ValueError("k and v must both be int8 or both float")
    if quant and (k_scale is None or v_scale is None):
        raise ValueError("int8 cache needs k_scale and v_scale")
    group = h // kvh
    gp = -(-group // 8) * 8  # pad query rows to a legal sublane multiple
    scale = d ** -0.5
    if interpret is None:
        interpret = interpret_mode()
    bk = _pick_block_k(block_k, s)

    from jax.experimental.pallas import tpu as pltpu

    # K/V feed the kernels in their NATIVE [B, S, KVH, D] cache
    # layout: the BlockSpec index maps slice per-(batch, head, block)
    # tiles straight out of HBM — the r13 relayout fix (the old path
    # materialized two transposed copies of the FULL cache per call,
    # per layer, per token). Only the GQA path's int8 scales (4/D of
    # the cache bytes) still pre-transpose — see that branch.
    len2 = jnp.broadcast_to(jnp.asarray(length, jnp.int32).reshape(-1, 1),
                            (b, 1))  # scalar length broadcasts per batch

    if group == 1 and (kvh <= 8 or kvh % 8 == 0):
        # MHA: hb heads of one batch per instance — real query rows
        # fill the sublanes the GQA kernel pads, instances drop
        # hb-fold, and one [block_k, hb, D] DMA feeds hb heads (the
        # short-cache regime where per-instance cost dominated).
        # hb is the FULL head dim (kvh <= 8: a full-dim block is
        # always tile-legal) or 8 (a sublane multiple); other head
        # counts (e.g. 12) fall through to the GQA kernel — their
        # partial sublane tile only compiles in the CPU interpreter.
        hb = kvh if kvh <= 8 else 8
        kernel = functools.partial(
            _decode_kernel_mha, block_k=bk, scale=scale, window=window,
            quant=quant, hb=hb)
        in_specs = [
            pl.BlockSpec((1, hb, d), lambda bi, hi, ki: (bi, hi, 0)),
            pl.BlockSpec((1, bk, hb, d),
                         lambda bi, hi, ki: (bi, ki, hi, 0)),
            pl.BlockSpec((1, bk, hb, d),
                         lambda bi, hi, ki: (bi, ki, hi, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ]
        operands = [q, k, v, len2]
        if quant:
            # scales pre-transpose [B, S, KVH] -> [B, KVH, S] (tiny —
            # 4/D of the cache bytes) so the tile is (1, hb, bk):
            # sublane hb (full dim or 8), lane bk — Mosaic-legal at
            # every head count this branch accepts. The kernel folds
            # them onto scores/probabilities.
            in_specs += [
                pl.BlockSpec((1, hb, bk),
                             lambda bi, hi, ki: (bi, hi, ki)),
                pl.BlockSpec((1, hb, bk),
                             lambda bi, hi, ki: (bi, hi, ki)),
            ]
            operands += [k_scale.transpose(0, 2, 1),
                         v_scale.transpose(0, 2, 1)]
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((b, kvh, d), q.dtype),
            grid=(b, kvh // hb, s // bk),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, hb, d),
                                   lambda bi, hi, ki: (bi, hi, 0)),
            scratch_shapes=[_vmem((hb, 1)), _vmem((hb, 1)),
                            _vmem((hb, d))],
            interpret=interpret,
        )(*operands)
        return out  # [B, KVH, D] == [B, H, D] under MHA

    # GQA — and the MHA head counts with no tile-legal head block
    # (kvh > 8, kvh % 8 != 0): [B, H, D] -> [B, KVH, Gp, D] (a pure
    # reshape + a tiny pad of the single-token q — no cache-sized
    # relayout)
    qr = q.reshape(b, kvh, group, d)
    if gp != group:
        qr = jnp.pad(qr, ((0, 0), (0, 0), (0, gp - group), (0, 0)))

    kernel = functools.partial(_decode_kernel, block_k=bk, scale=scale,
                               window=window, quant=quant)
    in_specs = [
        pl.BlockSpec((1, 1, gp, d), lambda bi, hi, ki: (bi, hi, 0, 0)),
        pl.BlockSpec((1, bk, 1, d), lambda bi, hi, ki: (bi, ki, hi, 0)),
        pl.BlockSpec((1, bk, 1, d), lambda bi, hi, ki: (bi, ki, hi, 0)),
        pl.BlockSpec(memory_space=pltpu.SMEM),
    ]
    operands = [qr, k, v, len2]
    if quant:
        # the ONE remaining relayout, scales only (tiny — 4/D of the
        # cache bytes): [B, S, KVH] -> [B, KVH, S] hands each head
        # instance an exact per-head (1, 1, bk) tile; native-layout
        # scales would be re-fetched kvh times per block (see the
        # kernel docstring). S in the lane dim also keeps the tile
        # Mosaic-legal, the pre-r14 layout's argument.
        ksr = k_scale.transpose(0, 2, 1)
        vsr = v_scale.transpose(0, 2, 1)
        in_specs += [
            pl.BlockSpec((1, 1, bk), lambda bi, hi, ki: (bi, hi, ki)),
            pl.BlockSpec((1, 1, bk), lambda bi, hi, ki: (bi, hi, ki)),
        ]
        operands += [ksr, vsr]
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, kvh, gp, d), q.dtype),
        grid=(b, kvh, s // bk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, gp, d),
                               lambda bi, hi, ki: (bi, hi, 0, 0)),
        scratch_shapes=[_vmem((gp, 1)), _vmem((gp, 1)), _vmem((gp, d))],
        interpret=interpret,
    )(*operands)
    out = out[:, :, :group]
    return out.reshape(b, h, d)
