"""Host-RAM KV page tier (serve/tier.py + the engine's spill/page-in).

Two layers of pinning, mirroring test_paged.py's discipline: the
BITWISE round-trip property — device -> host -> device through
``gather_pages`` / ``pages_to_host`` / ``pad_host_pages`` /
``scatter_pages`` preserves every byte across the dtype x scan_layers
x int8-KV-scale-leaf matrix — and the end-to-end exactness anchor: an
engine whose prefix store spills to the tier and pages back in on a
hit produces token-identical greedy output to a no-tier control,
while actually registering spills, page-ins, and the extra prefix
hits the tier exists for. Plus the kv_host_thrash alert rule's
fire-once / resolve-after-2 semantics and its surfaces (alerts.jsonl
row shape, /metrics presence). CPU-only.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import Transformer, TransformerConfig
from tony_tpu.serve import PagePool, Request, Server
from tony_tpu.serve.slots import (cache_batch_axis, gather_pages,
                                  scatter_pages)
from tony_tpu.serve.tier import (HostPageTier, decode_array,
                                 decode_payload, encode_array,
                                 encode_payload, pad_host_pages,
                                 pages_to_host, payload_pages)


def _model(dtype=jnp.float32, scan_layers=False, kv_int8=False):
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=64,
                            dtype=dtype, scan_layers=scan_layers,
                            kv_cache_quant=kv_int8,
                            attention_backend="reference")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _randomize_pool(pool: PagePool, seed: int = 0) -> None:
    """Fill every paged leaf with random values of its own dtype, so
    the round trip is checked over real bit patterns (int8 quant
    codes, fp32 scales, bf16 K/V) instead of zeros."""
    rng = np.random.default_rng(seed)

    def rnd(path, leaf):
        if cache_batch_axis(path, leaf) is None:
            return leaf
        if jnp.issubdtype(leaf.dtype, jnp.integer):
            vals = rng.integers(-120, 120, size=leaf.shape)
        else:
            vals = rng.standard_normal(leaf.shape)
        return jnp.asarray(vals).astype(leaf.dtype)

    pool.cache = jax.tree_util.tree_map_with_path(rnd, pool.cache)


def _page_bytes(tree, idx):
    """The raw bytes of pages ``idx`` across every paged leaf — the
    bitwise-comparison form (float views can hide NaN-payload bits;
    bytes cannot)."""
    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        ax = cache_batch_axis(path, leaf)
        if ax is None:
            continue
        a = np.asarray(leaf)
        out.append(np.take(a, idx, axis=ax).tobytes())
    return out


@pytest.mark.parametrize("dtype,scan_layers,kv_int8", [
    (jnp.float32, False, False),
    (jnp.float32, True, False),
    (jnp.float32, False, True),
    (jnp.float32, True, True),
    (jnp.bfloat16, False, False),
    (jnp.bfloat16, False, True),
])
def test_page_roundtrip_bitwise(dtype, scan_layers, kv_int8):
    """device -> host -> device is BITWISE across the layout matrix:
    gather three pages, slice to host numpy, zero-pad back to the pow2
    bucket, scatter onto three OTHER pages — the destination pages'
    bytes equal the sources' exactly, for every paged leaf (int8 K/V
    codes and their fp32 scale leaves included)."""
    model, params = _model(dtype, scan_layers, kv_int8)
    pool = PagePool(model, params, n_pages=7, page_size=8)
    _randomize_pool(pool)
    src, dst = [1, 3, 4], [0, 2, 5]
    before = _page_bytes(pool.cache, src)

    payload = gather_pages(pool.cache, jnp.asarray(src + [4], jnp.int32))
    assert payload_pages(payload) == 4
    host = pages_to_host(payload, 3)          # the tier's stored form
    padded = pad_host_pages(host, 4)          # back to the pow2 bucket
    cache2 = scatter_pages(pool.cache, padded,
                           jnp.asarray(dst + [pool.n_pages], jnp.int32))
    assert _page_bytes(cache2, dst) == before
    # the sentinel-padded row dropped: no fourth page was touched
    untouched = [i for i in range(pool.n_pages) if i not in dst]
    assert _page_bytes(cache2, untouched) == \
        _page_bytes(pool.cache, untouched)


def test_wire_codec_bitwise_including_bf16():
    """The /v1/handoff wire form (base64 leaves) is bitwise too —
    including the ml_dtypes bfloat16 numpy round trip."""
    model, params = _model(jnp.bfloat16, False, True)
    pool = PagePool(model, params, n_pages=4, page_size=8)
    _randomize_pool(pool, seed=1)
    payload = gather_pages(pool.cache, jnp.asarray([0, 2], jnp.int32))
    doc = encode_payload(payload)
    treedef = jax.tree_util.tree_structure(pool.cache)
    back = decode_payload(doc, treedef)
    for a, b in zip(jax.tree_util.tree_leaves(payload),
                    jax.tree_util.tree_leaves(back)):
        assert str(np.asarray(a).dtype) == str(b.dtype)
        assert np.asarray(a).tobytes() == b.tobytes()
    logits = np.asarray(jax.random.normal(jax.random.PRNGKey(0),
                                          (1, 64)))
    assert decode_array(encode_array(logits)).tobytes() \
        == logits.tobytes()
    # relay passthrough: an already-encoded doc is returned verbatim
    assert encode_payload(doc) is doc


def test_tier_requires_paged_and_prefix_store():
    model, params = _model()
    with pytest.raises(ValueError, match="prefix store"):
        Server(model, params, batch_size=2, kv_host_mb=4.0)
    with pytest.raises(ValueError, match="paged"):
        Server(model, params, batch_size=2, paged=False,
               prefix_cache_mb=2.0, kv_host_mb=4.0)


def _run_workload(model, params, prompts, *, kv_host_mb,
                  prefix_mb=0.025):
    """Serial workload through one engine: distinct prompts that evict
    each other out of a deliberately tiny device store, then exact
    repeats of the first two."""
    srv = Server(model, params, batch_size=2, paged=True, kv_page_size=8,
                 prefix_cache_mb=prefix_mb, kv_host_mb=kv_host_mb,
                 prefix_donate=False)
    outs = []
    for i, p in enumerate(prompts):
        srv.submit(Request(list(p), 4, id=i))
        for res in srv.run():
            outs.append(res.tokens)
    return outs, srv


def test_spill_then_prefix_hit_greedy_parity():
    """The e2e exactness anchor: a store squeezed to ~2 entries spills
    evictions to the tier; resubmitting the spilled prompts pages them
    back in (counters prove it) and greedy outputs stay byte-identical
    to a no-tier control that re-prefilled everything."""
    model, params = _model()
    rng = np.random.default_rng(2)
    distinct = [rng.integers(1, 64, size=24).tolist() for _ in range(3)]
    workload = distinct + [distinct[0], distinct[1]]
    outs_off, srv_off = _run_workload(model, params, workload,
                                      kv_host_mb=0.0)
    outs_on, srv_on = _run_workload(model, params, workload,
                                    kv_host_mb=8.0)
    assert outs_on == outs_off
    st = srv_on.host_tier.stats()
    assert st["spills"] >= 2 and st["page_ins"] >= 1, st
    assert st["bytes_spilled"] > 0 and st["bytes_paged_in"] > 0, st
    # the tier turned would-be re-prefills into prefix hits
    assert srv_on.prefix_hit_tokens > srv_off.prefix_hit_tokens
    counters = srv_on.counters()
    assert counters["kv_host_page_ins"] == st["page_ins"]
    assert counters["kv_host_spills"] == st["spills"]
    # pool conservation still holds after all the page churn
    pool = srv_on.slots.pool
    assert pool.n_free + pool.n_used == pool.n_pages
    assert (pool.refcount >= 0).all()


def test_spill_roundtrip_int8_scan_layers_parity():
    """The same spill-then-hit anchor on the gnarliest layout (int8 KV
    with fp32 scale leaves + stacked scan_layers axes): the pin that
    the tier's byte moves respect every leaf's geometry."""
    model, params = _model(scan_layers=True, kv_int8=True)
    rng = np.random.default_rng(3)
    distinct = [rng.integers(1, 64, size=24).tolist() for _ in range(3)]
    workload = distinct + [distinct[0]]
    # int8 pages are ~3x smaller: squeeze the device store to ~2
    # entries so evictions (and thus spills) actually happen
    outs_off, _ = _run_workload(model, params, workload,
                                kv_host_mb=0.0, prefix_mb=0.009)
    outs_on, srv_on = _run_workload(model, params, workload,
                                    kv_host_mb=8.0, prefix_mb=0.009)
    assert outs_on == outs_off
    assert srv_on.host_tier.stats()["page_ins"] >= 1


def test_tier_lru_eviction_under_budget():
    """The tier's own byte budget evicts LRU host entries — host RAM
    is bounded too, just bigger."""
    tier = HostPageTier(budget_bytes=2048)
    a = {"x": np.zeros((1, 8, 2, 16), np.float32)}  # 1024 B
    assert tier.insert(np.arange(8, dtype=np.int32), a, None)
    assert tier.insert(np.arange(8, 16, dtype=np.int32), a, None)
    assert tier.insert(np.arange(16, 24, dtype=np.int32), a, None)
    st = tier.stats()
    assert st["entries"] == 2 and st["evictions"] == 1, st
    assert st["bytes"] <= 2048
    # the freshest two survived
    assert tier.match_len(np.arange(16, 24, dtype=np.int32)) == 8
    assert tier.match_len(np.arange(8, dtype=np.int32)) == 0


def test_async_spill_fifo_ordering_and_accounting():
    """ISSUE-18 satellite: ``spill_async`` queues the device->host
    copy for the background worker. The contract pinned here: (a)
    counters move at DISPATCH time and equal the landed totals after
    ``flush()``; (b) ``has()`` sees queued content immediately, so
    the engine never re-spills a sequence already in flight; (c) the
    single-worker FIFO lands inserts in eviction order — under a
    2-entry budget the FIRST-queued entry is the one evicted; (d)
    only the ``n`` REAL pages are charged and stored, the gather's
    pow2 padding is dropped."""
    def payload(v):
        # a 4-page gather (pow2 bucket) of which only 2 are real
        return {"cached_key": np.full((4, 8, 2, 16), v, np.float32),
                "cached_value": np.full((4, 8, 2, 16), v, np.float32)}

    # host charge per entry: 2 leaves x 2 real pages x 1024 B = 4 KiB;
    # budget holds exactly two entries
    tier = HostPageTier(budget_bytes=10_000)
    toks = [np.arange(8 * i, 8 * i + 8, dtype=np.int32)
            for i in range(3)]
    for i, t in enumerate(toks):
        tier.spill_async(t, payload(float(i + 1)), 2, None)
        assert tier.has(t)  # pending or landed: either way visible
    assert tier.spills == 3
    assert tier.bytes_spilled == 3 * 4096
    assert tier.flush(timeout=10.0)
    st = tier.stats()
    assert st["entries"] == 2 and st["evictions"] == 1, st
    # FIFO: the first-queued sequence was first in, first evicted
    assert tier.match_len(toks[0]) == 0
    assert tier.match_len(toks[1]) == 8
    assert tier.match_len(toks[2]) == 8
    # the landed rows are the n=2 REAL pages, bitwise, padding gone
    match, entry = tier.acquire(toks[2])
    assert match == 8 and entry is not None
    try:
        for leaf in entry.row.values():
            assert leaf.shape[0] == 2
            np.testing.assert_array_equal(
                leaf, np.full((2, 8, 2, 16), 3.0, np.float32))
    finally:
        tier.release(entry)


# ------------------------------------------------- kv_host_thrash alert


def _signals(page_in_bytes, free=1, reserved=0, total=20, active=2):
    return {
        "kv_host_page_in_bytes": page_in_bytes,
        "kv_pages_total": total,
        "kv_pages_free": free,
        "kv_pages_reserved": reserved,
        "active_slots": active,
        "depth": 0,
        "now": 0.0,
    }


def test_kv_host_thrash_fires_once_and_resolves_after_two():
    """Restore churn + pool pressure together fire ONCE; either side
    clearing resolves after the standard 2 clean ticks."""
    from tony_tpu.obs.alerts import AlertBus, KvHostThrashRule

    bus = AlertBus([KvHostThrashRule(thrash_bytes=1000)])
    assert bus.evaluate(_signals(0)) == []          # no delta yet
    events = bus.evaluate(_signals(5000))           # +5000 B, pressured
    assert [e.state for e in events] == ["firing"]
    assert events[0].alert == "kv_host_thrash"
    assert events[0].detail["page_in_bytes_tick"] == 5000
    assert "free_after_reserve_frac" in events[0].detail
    # still thrashing: active alert, no re-fire
    assert bus.evaluate(_signals(10000)) == []
    # churn continues but the pool is NOT pressured -> not thrash
    assert bus.evaluate(_signals(15000, free=18)) == []
    events = bus.evaluate(_signals(20000, free=18))
    assert [e.state for e in events] == ["resolved"]
    # pressure without churn never fires it
    for _ in range(3):
        assert bus.evaluate(_signals(20000)) == []


def test_kv_host_thrash_row_and_metrics_presence(tmp_path):
    """The alert's two export surfaces: a history alerts.jsonl row
    with the standard shape, and the rule present in the /metrics
    fired/resolved families of a LIVE gateway with the tier armed."""
    import json

    from tony_tpu.gateway import Gateway, GatewayHistory, GenRequest
    from tony_tpu.obs.alerts import AlertBus, KvHostThrashRule
    from tony_tpu.obs.export import prometheus_text

    bus = AlertBus([KvHostThrashRule(thrash_bytes=1000)])
    bus.evaluate(_signals(0), t_wall=100.0)
    (event,) = bus.evaluate(_signals(5000), t_wall=101.0)
    history = GatewayHistory(str(tmp_path))
    history.record_alert(event.to_row())
    history.close()
    rows = [json.loads(line) for line in
            open(history.job_dir + "/metrics/alerts.jsonl")]
    assert rows[0]["alert"] == "kv_host_thrash"
    assert rows[0]["state"] == "firing"
    assert rows[0]["detail_page_in_bytes_tick"] == 5000

    model, params = _model()
    srv = Server(model, params, batch_size=2, paged=True,
                 kv_page_size=8, prefix_cache_mb=0.025, kv_host_mb=4.0)
    gw = Gateway([srv]).start()
    try:
        gw.submit(GenRequest([1, 2, 3], 2, id="m")).result(timeout=300)
        text = prometheus_text(gw)
        assert 'tony_alerts_fired_total{alert="kv_host_thrash"}' in text
        assert 'tony_alerts_resolved_total{alert="kv_host_thrash"}' \
            in text
        assert "tony_kv_host_enabled 1" in text
        assert "tony_kv_host_spills_total" in text
        assert "tony_kv_host_bytes" in text
    finally:
        gw.drain(timeout=60)


def test_prefix_summary_on_stats_replica_rows():
    """Satellite: the per-replica radix summary (entries, bytes, the
    new nodes/max_depth shape fields) exports under
    /stats replicas[i].prefix, and kv_host rides next to it when the
    tier is armed."""
    from tony_tpu.gateway import Gateway, GenRequest

    model, params = _model()
    srv = Server(model, params, batch_size=2, paged=True,
                 kv_page_size=8, prefix_cache_mb=1.0, kv_host_mb=4.0)
    gw = Gateway([srv]).start()
    try:
        gw.submit(GenRequest(list(range(1, 20)), 3,
                             id="p")).result(timeout=300)
        row = gw.snapshot()["replicas"][0]
        assert row["prefix"]["entries"] >= 1
        assert row["prefix"]["nodes"] >= 2  # root + at least one edge
        assert row["prefix"]["max_depth"] >= 19
        assert "kv_host" in row and row["kv_host"]["budget_bytes"] > 0
        assert gw.snapshot()["engine"]["kv_host"]["enabled"]
    finally:
        gw.drain(timeout=60)
