"""PrefixStore: radix-keyed KV-cache rows for automatic prefix reuse.

The RadixAttention idea (SGLang; vLLM's prefix caching) on this repo's
static-shape serving path: thousands of requests share a system prompt
or few-shot preamble, and every one of them re-prefills the same
tokens. The store keeps previously prefilled batch-1 cache rows keyed
by their TOKEN SEQUENCE in a radix tree; on admit the engine looks up
the longest cached prefix of the incoming prompt and either skips
prefill entirely (exact-prompt hit: copy the row into the slot, sample
the first token from the stored last-position logits) or seeds the
slot from the row and prefills only the bucketed SUFFIX at a position
offset (engine._prefill with ``offset``/``row``).

Why a whole stored row is usable even on a PARTIAL match: a cache
position's K/V depends only on tokens at-or-before it (causal
attention), so a row stored for sequence S is position-exact over
``[0, k)`` for any prompt sharing S's first ``k`` tokens. Content
beyond the matched region is junk to the consumer — and harmless: the
suffix prefill overwrites ``[k, k+suffix_bucket)``, the slot's length
masks everything past the prompt, and decode overwrites each position
before it ever becomes visible. Masked scores are set to -1e30, whose
softmax weight underflows to exactly 0.0, so junk K/V contributes
nothing — greedy outputs through the store are token-for-token
identical to store-off serving (tests/test_prefix.py pins it).

Bookkeeping contract:

- Entries are REF-COUNTED: ``acquire()`` pins the matched entry until
  ``release()``; eviction never touches an entry with a nonzero
  refcount (an admit that is mid-copy must not lose its row).
- An explicit BYTE BUDGET, computed from the stored pytrees' leaf
  sizes, bounds device memory; inserts past it evict the
  least-recently-used unreferenced entries, and an insert that cannot
  fit (all remaining bytes pinned, or the entry alone exceeds the
  budget) is refused rather than overflowing.
- Single-writer like the engine: the owning scheduler thread drives
  acquire/insert/release. The internal lock only keeps cross-thread
  STAT reads (gateway /stats) consistent.
"""

from __future__ import annotations

import itertools
import logging
import threading
import zlib
from typing import Any

import jax
import numpy as np

log = logging.getLogger(__name__)


def tree_nbytes(tree: Any) -> int:
    """Total bytes of a pytree's array leaves (shape x itemsize — the
    device-memory cost the store's budget accounts in)."""
    return sum(int(np.prod(leaf.shape)) * leaf.dtype.itemsize
               for leaf in jax.tree_util.tree_leaves(tree))


class _Entry:
    """One stored sequence: a batch-1 cache row covering exactly
    ``tokens``, optionally the last-position logits (prefill-donated
    entries have them — the exact-hit fast path needs them to sample
    the first continuation; EOS-donated rows don't).

    PAGED stores (``PrefixStore(pool=...)``) keep ``pages`` — the page
    ids whose concatenation covers ``tokens`` — instead of a copied
    ``row``: the entry is a refcount on live pool pages, so donation
    costs no device work and a hit aliases pages instead of copying
    them (copy-on-write, serve/slots.PagePool)."""

    __slots__ = ("tokens", "row", "logits", "pages", "nbytes", "node",
                 "refcount", "tick")

    def __init__(self, tokens: np.ndarray, row: Any, logits: Any,
                 nbytes: int, node: "_Node", tick: int,
                 pages: list | None = None):
        self.tokens = tokens
        self.row = row
        self.logits = logits
        self.pages = pages
        self.nbytes = nbytes
        self.node = node
        self.refcount = 0
        self.tick = tick


class _Node:
    """Radix-tree node: ``edge`` is the token run from the parent
    (root's is empty); an entry, when present, covers exactly the path
    from the root through this node."""

    __slots__ = ("edge", "children", "entry", "parent")

    def __init__(self, edge: np.ndarray, parent: "_Node | None"):
        self.edge = edge
        self.children: dict[int, _Node] = {}
        self.entry: _Entry | None = None
        self.parent = parent


def _common_len(a: np.ndarray, b: np.ndarray) -> int:
    n = min(len(a), len(b))
    neq = np.nonzero(a[:n] != b[:n])[0]
    return int(neq[0]) if neq.size else n


class PrefixStore:
    """Radix store of prefilled cache rows under a byte budget.

    With ``pool`` (a ``serve.slots.PagePool``) the store holds PAGE
    REFERENCES instead of copied rows: an insert pins the sequence's
    pool pages (one ``pool.share()`` per entry — zero device work), an
    eviction unpins them, and the byte budget counts each UNIQUE page
    once (entries sharing a prefix share its pages; double-charging
    them would make the budget lie about pool residency). The byte
    budget bounds how much of the pool the store may hog; the engine
    additionally squeezes it (``evict_one``) when a slot admission
    cannot reserve pages."""

    def __init__(self, budget_bytes: int, pool: Any = None):
        self.budget_bytes = max(0, int(budget_bytes))
        self.bytes_used = 0
        self.pool = pool
        # eviction hook (serve/tier.py): called with the dying entry
        # BEFORE its pages are unpinned, so a host-RAM tier can copy
        # the content out. Runs under this store's lock on the owning
        # engine thread; a raising hook must never break the eviction.
        self.on_evict = None
        self._page_refs: dict[int, int] = {}  # page -> #entries holding
        self.tokens_stored = 0
        self.root = _Node(np.empty(0, np.int32), None)
        self._entries: dict[bytes, _Entry] = {}
        self._lock = threading.Lock()
        self._ticks = itertools.count(1)
        self.lookups = 0
        self.matched = 0
        self.inserts = 0
        self.evictions = 0
        self.rejected = 0

    # ------------------------------------------------------------ lookup

    def acquire(self, tokens) -> tuple[int, _Entry | None]:
        """Longest stored prefix of ``tokens``: ``(match_len, entry)``
        with the entry's refcount bumped (caller MUST ``release()``),
        or ``(0, None)`` on a miss. ``entry.tokens[:match_len] ==
        tokens[:match_len]`` always holds; ``match_len`` may be shorter
        than the entry's own sequence (partial match — usable, see the
        module docstring) or equal to ``len(tokens)`` against a LONGER
        entry (a donated conversation the new prompt extends)."""
        tokens = np.asarray(tokens, np.int32)
        with self._lock:
            self.lookups += 1
            hit = self._lookup(tokens)
            if hit is None:
                return 0, None
            match, entry = hit
            entry.refcount += 1
            entry.tick = next(self._ticks)
            self.matched += 1
            return match, entry

    def release(self, entry: _Entry) -> None:
        with self._lock:
            if entry.refcount <= 0:
                raise ValueError("release() without matching acquire()")
            entry.refcount -= 1

    def match_len(self, tokens) -> int:
        """Longest stored prefix of ``tokens`` WITHOUT pinning the
        entry or moving the lookup counters — the gateway's
        prefix-affinity routing probe (a routing decision must not
        skew this replica's admission hit rate)."""
        tokens = np.asarray(tokens, np.int32)
        with self._lock:
            hit = self._lookup(tokens)
            return 0 if hit is None else hit[0]

    def has(self, tokens) -> bool:
        """Whether this exact sequence is stored (the host tier's
        skip-the-copy check before a spill)."""
        key = np.asarray(tokens, np.int32).tobytes()
        with self._lock:
            return key in self._entries

    def _lookup(self, tokens: np.ndarray) -> tuple[int, _Entry] | None:
        node, consumed = self.root, 0
        best: tuple[int, _Entry] | None = None
        while True:
            if node.entry is not None and consumed > 0:
                best = (consumed, node.entry)
            if consumed == len(tokens):
                # the whole prompt matched a stored path: the node's own
                # entry is the EXACT match (preferred — it may carry
                # logits); otherwise any longer entry below covers it
                if node.entry is None:
                    deeper = _freshest_entry(node)
                    if deeper is not None:
                        best = (consumed, deeper)
                return best
            child = node.children.get(int(tokens[consumed]))
            if child is None:
                # dead end at a node: every entry below it still shares
                # the ``consumed`` tokens walked so far (node.entry,
                # when present, was already recorded at the same depth)
                if consumed > 0 and (best is None or best[0] < consumed):
                    deeper = _freshest_entry(node)
                    if deeper is not None:
                        best = (consumed, deeper)
                return best
            common = _common_len(child.edge, tokens[consumed:])
            if common < len(child.edge):
                # partial way down an edge: every entry in the child's
                # subtree shares exactly consumed+common tokens
                deeper = _freshest_entry(child)
                if deeper is not None:
                    best = (consumed + common, deeper)
                return best
            node = child
            consumed += len(child.edge)

    # ------------------------------------------------------------ insert

    def wants(self, tokens, nbytes: int) -> bool:
        """Cheap pre-check before a donor pays the row-extraction
        dispatch: False when the sequence is already stored or when
        ``nbytes`` cannot fit even after evicting every unreferenced
        entry."""
        key = np.asarray(tokens, np.int32).tobytes()
        with self._lock:
            if key in self._entries:
                return False
            pinned = sum(e.nbytes for e in self._entries.values()
                         if e.refcount > 0)
            return nbytes + pinned <= self.budget_bytes

    def insert(self, tokens, row: Any = None, logits: Any = None,
               pages: list | None = None) -> bool:
        """Store ``row`` (a batch-1 cache pytree covering exactly
        ``tokens``) with optional last-position ``logits``. Returns
        False when refused (budget); re-inserting an existing sequence
        just refreshes its LRU position.

        Paged stores take ``pages`` instead of ``row``: the pool pages
        covering ``tokens``, pinned by refcount — pages already held
        by another entry cost zero additional budget."""
        tokens = np.asarray(tokens, np.int32)
        if tokens.size == 0 or self.budget_bytes <= 0:
            return False
        if (pages is not None) != (self.pool is not None):
            raise ValueError("pages= requires a pool-backed store "
                             "(and vice versa)")
        key = tokens.tobytes()
        with self._lock:
            existing = self._entries.get(key)
            if existing is not None:
                existing.tick = next(self._ticks)
                return True
            if pages is not None:
                return self._insert_pages(tokens, key, list(pages), logits)
            nbytes = tree_nbytes(row)
            if logits is not None:
                nbytes += tree_nbytes(logits)
            if not self._make_room(nbytes):
                self.rejected += 1
                return False
            node = self._insert_node(tokens)
            entry = _Entry(tokens, row, logits, nbytes, node,
                           next(self._ticks))
            node.entry = entry
            self._entries[key] = entry
            self.bytes_used += nbytes
            self.tokens_stored += int(tokens.size)
            self.inserts += 1
            return True

    def _insert_pages(self, tokens: np.ndarray, key: bytes,
                      pages: list, logits: Any) -> bool:
        """Paged insert under ``self._lock``. The bytes a paged entry
        charges depend on what is ALREADY pinned (shared pages are
        free), and evicting an LRU entry can un-share a page — so the
        charge is recomputed after every eviction instead of once."""
        logits_b = tree_nbytes(logits) if logits is not None else 0
        while True:
            fresh = sum(1 for p in set(pages)
                        if self._page_refs.get(p, 0) == 0)
            nbytes = fresh * self.pool.page_nbytes + logits_b
            if self.bytes_used + nbytes <= self.budget_bytes:
                break
            victim = min(
                (e for e in self._entries.values() if e.refcount == 0),
                key=lambda e: e.tick, default=None)
            if victim is None or nbytes > self.budget_bytes:
                self.rejected += 1
                return False
            self._evict(victim)
        node = self._insert_node(tokens)
        entry = _Entry(tokens, None, logits, nbytes, node,
                       next(self._ticks), pages=pages)
        node.entry = entry
        self._entries[key] = entry
        self.pool.share(pages)
        for p in pages:
            self._page_refs[p] = self._page_refs.get(p, 0) + 1
        self.bytes_used += nbytes
        self.tokens_stored += int(tokens.size)
        self.inserts += 1
        return True

    def evict_one(self) -> bool:
        """Evict the least-recently-used unpinned entry (the engine's
        pool-pressure squeeze: a slot admission that cannot reserve
        pages frees store pages before giving up). False when every
        entry is pinned by an in-flight acquire (or the store is
        empty)."""
        with self._lock:
            victim = min(
                (e for e in self._entries.values() if e.refcount == 0),
                key=lambda e: e.tick, default=None)
            if victim is None:
                return False
            self._evict(victim)
            return True

    def _insert_node(self, tokens: np.ndarray) -> _Node:
        node, consumed = self.root, 0
        while consumed < len(tokens):
            first = int(tokens[consumed])
            child = node.children.get(first)
            if child is None:
                leaf = _Node(tokens[consumed:].copy(), node)
                node.children[first] = leaf
                return leaf
            common = _common_len(child.edge, tokens[consumed:])
            if common < len(child.edge):
                # split the edge at the divergence point; the next loop
                # iteration hangs the new sequence's tail under ``mid``
                # (or, when the tokens are exhausted, ``mid`` IS the
                # new sequence's node)
                mid = _Node(child.edge[:common].copy(), node)
                node.children[first] = mid
                child.edge = child.edge[common:]
                child.parent = mid
                mid.children[int(child.edge[0])] = child
                node = mid
            else:
                node = child
            consumed += common
        return node

    # ---------------------------------------------------------- eviction

    def _make_room(self, nbytes: int) -> bool:
        if nbytes > self.budget_bytes:
            return False
        while self.bytes_used + nbytes > self.budget_bytes:
            victim = min(
                (e for e in self._entries.values() if e.refcount == 0),
                key=lambda e: e.tick, default=None)
            if victim is None:  # everything left is pinned
                return False
            self._evict(victim)
        return True

    def _evict(self, entry: _Entry) -> None:
        if self.on_evict is not None:
            # before any unpinning: the hook may still read the
            # entry's pages/row off the device
            try:
                self.on_evict(entry)
            except Exception:
                log.exception("prefix on_evict hook failed")
        del self._entries[entry.tokens.tobytes()]
        if entry.pages is not None:
            # release the entry's page pins; only pages no OTHER entry
            # still holds stop being charged (and, once every holder —
            # store entries and slot tables alike — lets go, return to
            # the pool's free list)
            released = 0
            for p in entry.pages:
                self._page_refs[p] -= 1
                if self._page_refs[p] == 0:
                    del self._page_refs[p]
                    released += self.pool.page_nbytes
            self.pool.unref(entry.pages)
            if entry.logits is not None:
                released += tree_nbytes(entry.logits)
            self.bytes_used -= released
        else:
            self.bytes_used -= entry.nbytes
        self.tokens_stored -= int(entry.tokens.size)
        self.evictions += 1
        node = entry.node
        node.entry = None
        # prune entry-less leaves, then merge single-child pass-throughs
        # so the tree stays proportional to what is stored
        while node.parent is not None and node.entry is None \
                and not node.children:
            parent = node.parent
            del parent.children[int(node.edge[0])]
            node = parent
        if node.parent is not None and node.entry is None \
                and len(node.children) == 1:
            (child,) = node.children.values()
            child.edge = np.concatenate([node.edge, child.edge])
            child.parent = node.parent
            node.parent.children[int(child.edge[0])] = child

    # ----------------------------------------------------------- summary

    def summary(self, max_items: int = 512, grain: int = 8) -> list:
        """Bounded wire summary of what this store could seed:
        ``[[n_tokens, crc32], ...]`` pairs, one per stored-sequence
        PREFIX on a ``grain``-token grid (plus each entry's full
        length), most-recent entries first, deduplicated. Shipped on
        the agent heartbeat (ISSUE-18) so the gateway's prefix-
        affinity probe can score a REMOTE replica's warmth via
        ``summary_match_len`` without shipping the radix tree. The
        grid makes PARTIAL matches visible — a prompt sharing only
        the system preamble of a longer stored conversation still
        hashes equal at the preamble's grid points."""
        with self._lock:
            entries = sorted(self._entries.values(),
                             key=lambda e: e.tick, reverse=True)
        out: list = []
        seen: set = set()
        for e in entries:
            n = int(e.tokens.size)
            lens = list(range(grain, n + 1, grain))
            if not lens or lens[-1] != n:
                lens.append(n)
            for ln in reversed(lens):
                item = (ln, zlib.crc32(e.tokens[:ln].tobytes()))
                if item in seen:
                    continue
                seen.add(item)
                out.append([item[0], item[1]])
                if len(out) >= max_items:
                    return out
        return out

    # ------------------------------------------------------------- stats

    def __len__(self) -> int:
        return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            # radix shape (root included; depth in TOKENS): what the
            # gateway's affinity router exports per replica — a tree
            # whose max_depth dwarfs its entry count is one long
            # conversation, a bushy shallow tree is a shared preamble
            nodes, max_depth = 0, 0
            stack: list[tuple[_Node, int]] = [(self.root, 0)]
            while stack:
                node, depth = stack.pop()
                nodes += 1
                max_depth = max(max_depth, depth)
                for child in node.children.values():
                    stack.append((child, depth + len(child.edge)))
            return {
                "entries": len(self._entries),
                "bytes": self.bytes_used,
                "budget_bytes": self.budget_bytes,
                "tokens": self.tokens_stored,
                "nodes": nodes,
                "max_depth": max_depth,
                "lookups": self.lookups,
                "matched": self.matched,
                "inserts": self.inserts,
                "evictions": self.evictions,
                "rejected": self.rejected,
            }


def summary_match_len(summary, tokens) -> int:
    """Longest summarized prefix of ``tokens`` — the probe side of
    ``PrefixStore.summary()``, run by the gateway's remote stub against
    the pairs a heartbeat shipped. Hashing convention (int32 bytes,
    crc32) matches the producer exactly; a crc collision costs one
    mis-routed request, never a wrong token."""
    toks = np.asarray(tokens, np.int32)
    by_len: dict[int, set] = {}
    for ln, crc in summary or ():
        if 0 < int(ln) <= toks.size:
            by_len.setdefault(int(ln), set()).add(int(crc))
    for ln in sorted(by_len, reverse=True):
        if zlib.crc32(toks[:ln].tobytes()) in by_len[ln]:
            return ln
    return 0


def _freshest_entry(node: _Node) -> _Entry | None:
    """Most-recently-used entry in ``node``'s subtree (ties on LRU
    keep hot rows hot; any entry is equally CORRECT for a partial
    match)."""
    best = node.entry
    for child in node.children.values():
        e = _freshest_entry(child)
        if e is not None and (best is None or e.tick > best.tick):
            best = e
    return best
