"""SlotCache: batch_size resident KV-cache slots + per-slot decode state.

The device side is ONE fixed-shape cache pytree (``init_cache`` at
``batch_size``) that the resident decode step updates in place; the
host side is a handful of small per-slot arrays (length, last token,
sampling knobs, rng) the scheduler reads and writes between steps.
Admit copies a freshly prefilled single-row cache into a free slot with
one jitted dynamic-update-slice per leaf (slot index traced — one
compile total); evict is pure host bookkeeping (the row's stale K/V is
masked by the slot's length going inactive and fully overwritten by the
next admit, so no device work is ever spent clearing it).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from tony_tpu.models.generate import init_cache


def cache_batch_axis(path, leaf) -> int | None:
    """Batch (slot) axis of a cache leaf, or None for non-batched leaves.

    KV buffers are [..., b, max_len, kvh, dh] — batch is 4th-from-last;
    their quant scales are [..., b, max_len, kvh] — 3rd-from-last.
    scan_layers models prepend an n_layers axis, which this arithmetic
    skips (keying on axis 0 would slice the LAYERS axis). Index counters
    (cache_index/pos_index) carry no batch dim: per-slot decode neither
    reads nor advances them (positions live host-side)."""
    name = str(path[-1].key if hasattr(path[-1], "key") else path[-1])
    if name in ("cached_key", "cached_value"):
        return leaf.ndim - 4
    if name in ("cached_key_scale", "cached_value_scale"):
        return leaf.ndim - 3
    return None


def write_slot_row(cache: Any, row: Any, slot) -> Any:
    """Copy a batch-1 cache ``row`` into slot ``slot`` of ``cache``
    (pure tree transform, traceable — the ONE place that knows how to
    place a row; the engine's fused prefill-admit and the standalone
    jitted copy below both call it)."""
    def write(path, leaf, rleaf):
        ax = cache_batch_axis(path, leaf)
        if ax is None:
            return leaf  # shared counters: per-slot mode ignores them
        start = [jnp.int32(0)] * leaf.ndim
        start[ax] = jnp.asarray(slot, jnp.int32)
        return jax.lax.dynamic_update_slice(leaf, rleaf.astype(leaf.dtype),
                                            tuple(start))

    return jax.tree_util.tree_map_with_path(write, cache, row)


@jax.jit
def _write_slot(cache: Any, row: Any, slot) -> Any:
    """Jitted ``write_slot_row``; ``slot`` is traced — every admit
    reuses one compiled program."""
    return write_slot_row(cache, row, slot)


def read_slot_row(cache: Any, slot) -> Any:
    """Extract slot ``slot`` of ``cache`` as a batch-1 row — the exact
    inverse of ``write_slot_row`` (write then read round-trips every
    batched leaf). Non-batched leaves (the shared counters per-slot
    decode neither reads nor advances) pass through unchanged; a
    consumer seeding a prefill from the row re-seeds them anyway. The
    prefix store (serve/prefix.py) uses this to donate a finished
    slot's sequence back to the cache."""
    def read(path, leaf):
        ax = cache_batch_axis(path, leaf)
        if ax is None:
            return leaf
        return jax.lax.dynamic_slice_in_dim(
            leaf, jnp.asarray(slot, jnp.int32), 1, axis=ax)

    return jax.tree_util.tree_map_with_path(read, cache)


@jax.jit
def _read_slot(cache: Any, slot) -> Any:
    """Jitted ``read_slot_row``; ``slot`` is traced — every donation
    reuses one compiled program."""
    return read_slot_row(cache, slot)


class SlotCache:
    """``batch_size`` cache slots + per-slot length/rng/EOS-side state.

    Host arrays are numpy (the scheduler mutates them every iteration);
    the cache pytree stays on device across the whole serve session.
    """

    def __init__(self, model, params, batch_size: int):
        self.batch_size = batch_size
        self.max_seq_len = model.cfg.max_seq_len
        self.cache = init_cache(model, params, batch_size)
        self.lengths = np.zeros(batch_size, np.int32)
        self.active = np.zeros(batch_size, bool)
        self.last_token = np.zeros(batch_size, np.int32)
        self.temperature = np.zeros(batch_size, np.float32)
        self.top_k = np.zeros(batch_size, np.int32)
        self.rng = np.zeros((batch_size, 2), np.uint32)

    def free_slots(self) -> list[int]:
        return [i for i in range(self.batch_size) if not self.active[i]]

    @property
    def n_active(self) -> int:
        return int(self.active.sum())

    def positions(self) -> np.ndarray:
        """Per-slot decode positions for the next step: the slot's
        current length (where the next token is written and up to which
        attention looks), -1 for empty slots (no visible keys)."""
        return np.where(self.active, self.lengths, -1).astype(np.int32)

    def admit(self, slot: int, length: int, last_token: int,
              temperature: float, top_k: int, rng_key,
              row_cache: Any = None) -> None:
        """Arm ``slot``'s per-slot state; with ``row_cache`` also copy
        that prefilled batch-1 cache row into the slot (the serving
        engine fuses the copy into its prefill dispatch instead and
        passes None). ``length`` = real prompt length (bucket padding
        beyond it is invisible: masked now, overwritten as the slot
        advances). ``last_token`` is the first sampled continuation —
        the next step feeds it at position ``length``."""
        if self.active[slot]:
            raise ValueError(f"slot {slot} is occupied")
        if not 0 < length <= self.max_seq_len:
            raise ValueError(f"bad prompt length {length}")
        if row_cache is not None:
            self.cache = _write_slot(self.cache, row_cache,
                                     jnp.int32(slot))
        self.lengths[slot] = length
        self.last_token[slot] = last_token
        self.temperature[slot] = temperature
        self.top_k[slot] = top_k
        self.rng[slot] = np.asarray(rng_key, np.uint32).reshape(2)
        self.active[slot] = True

    def evict(self, slot: int) -> None:
        """Free a slot (EOS / budget exhausted). Device state is left in
        place — an inactive slot's position is -1, so nothing reads it,
        and the next admit overwrites the whole row."""
        self.active[slot] = False
        self.lengths[slot] = 0
        self.last_token[slot] = 0
        self.temperature[slot] = 0.0
        self.top_k[slot] = 0
        self.rng[slot] = 0

    def reset(self) -> None:
        """Evict everything (a fresh serving session on the same cache
        allocation — no reallocation, no recompile)."""
        for i in range(self.batch_size):
            self.evict(i)
