"""Distributed least-squares — the smallest possible tony-tpu job.

Reference analog: tony-examples/linearregression-mxnet, which fits a
linear model with MXNet's KVStore parameter server (DMLC_* roles). On TPU
the KVStore disappears: each worker computes the gradient on its shard and
one cross-process gather-and-mean averages them — no server role.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))  # repo root, for standalone runs

import jax
import jax.numpy as jnp
import numpy as np


def main() -> int:
    import tony_tpu.distributed as dist

    spec = dist.initialize()
    role, index = dist.task_identity()
    nproc = spec["num_processes"] if spec else 1

    # each worker's private shard of y = 3x + 2 + noise
    rng = np.random.default_rng(index)
    x = rng.normal(size=(512, 1)).astype(np.float32)
    y = 3.0 * x + 2.0 + 0.01 * rng.normal(size=x.shape).astype(np.float32)

    def local_grad(w, b, x, y):
        pred = x @ w + b
        err = pred - y
        return (x.T @ err / len(x)), jnp.mean(err)

    w, b = jnp.zeros((1, 1)), jnp.zeros(())
    step = jax.jit(lambda w, b, x, y: local_grad(w, b, x, y))
    for _ in range(200):
        gw, gb = step(w, b, jnp.asarray(x), jnp.asarray(y))
        # gradient averaging across the gang rides process-level psum when
        # launched multi-process; standalone it is the identity
        if nproc > 1:
            from jax.experimental import multihost_utils
            gw = multihost_utils.process_allgather(gw).mean(axis=0)
            gb = multihost_utils.process_allgather(gb).mean(axis=0)
        w -= 0.1 * gw
        b -= 0.1 * gb

    w_hat, b_hat = float(w[0, 0]), float(b)
    print(f"{role}:{index} fitted w={w_hat:.3f} b={b_hat:.3f}")
    return 0 if abs(w_hat - 3.0) < 0.1 and abs(b_hat - 2.0) < 0.1 else 1


if __name__ == "__main__":
    raise SystemExit(main())
