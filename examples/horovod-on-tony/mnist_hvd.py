"""Horovod-compat training example.

Reference analog: tony-examples/horovod-on-tony/tensorflow2_mnist.py. The
tony-tpu horovod runtime reproduces the full reference contract — an
in-tree gloo-style rendezvous server on the hidden driver task, and the
per-slot HOROVOD_RANK / LOCAL_RANK / CROSS_RANK env on every worker
(ref: runtime/HorovodRuntime.java:312-350) — so `import horovod` scripts
run unchanged where horovod is installed.

This example keeps the data-parallel structure but uses only the injected
env, so it also runs in environments without horovod: each slot trains on
its rank's shard and rank 0 reports.
"""

from __future__ import annotations

import os
import sys

import numpy as np


def main() -> int:
    rank = int(os.environ.get("HOROVOD_RANK", "0"))
    size = int(os.environ.get("HOROVOD_SIZE", "1"))
    local_rank = int(os.environ.get("HOROVOD_LOCAL_RANK", "0"))
    in_gang = "HOROVOD_RANK" in os.environ
    if in_gang:
        addr = os.environ["HOROVOD_GLOO_RENDEZVOUS_ADDR"]
        port = int(os.environ["HOROVOD_GLOO_RENDEZVOUS_PORT"])
        print(f"slot rank={rank}/{size} local_rank={local_rank} "
              f"rendezvous={addr}:{port}")
    else:
        print("standalone run (no HOROVOD_* env injected)")

    try:
        import horovod.tensorflow as hvd  # noqa: F401 — real horovod path
    except ImportError:
        hvd = None

    # rank's shard of a least-squares problem; with horovod installed the
    # gradient average would be hvd.allreduce — without it, each shard is
    # consistent by construction so the fit still converges
    rng = np.random.default_rng(rank)
    x = rng.normal(size=(256, 1)).astype(np.float32)
    y = 3.0 * x + 2.0
    w, b = 0.0, 0.0
    for _ in range(200):
        pred = w * x + b
        gw = float(((pred - y) * x).mean())
        gb = float((pred - y).mean())
        w -= 0.1 * gw
        b -= 0.1 * gb
    print(f"rank {rank}: w={w:.3f} b={b:.3f}")
    return 0 if abs(w - 3.0) < 0.1 and abs(b - 2.0) < 0.1 else 1


if __name__ == "__main__":
    sys.exit(main())
