"""Standalone runtime: single task, no rendezvous env.

Reference: runtime/StandaloneRuntime.java:29-101 — validate enforces exactly
one task instance total; no TB port, no framework env.
"""

from __future__ import annotations

from tony_tpu.config import ConfError, TonyConf
from tony_tpu.runtime.base import AMAdapter, Runtime, TaskAdapter, TaskContext


class StandaloneAMAdapter(AMAdapter):
    def validate_and_update_config(self, conf: TonyConf) -> None:
        total = sum(int(conf.role_get(r, "instances")) for r in conf.roles())
        if total != 1:
            raise ConfError(f"standalone runtime requires exactly 1 task, got {total}")


class StandaloneTaskAdapter(TaskAdapter):
    def need_reserve_rdzv_port(self, ctx_role: str, conf: TonyConf) -> bool:
        return False

    def need_reserve_tb_port(self, ctx_role: str, is_chief: bool, conf: TonyConf) -> bool:
        return False

    def build_task_env(self, ctx: TaskContext) -> dict[str, str]:
        return super().build_task_env(ctx)


class StandaloneRuntime(Runtime):
    name = "standalone"
    am_adapter_cls = StandaloneAMAdapter
    task_adapter_cls = StandaloneTaskAdapter
