"""TPU device discovery — the GpuDiscoverer equivalent.

Reference: util/gpu/GpuDiscoverer.java:43 shells out to ``nvidia-smi -x -q``
(binary found via a configurable path + default search dirs), JAXB-parses
the XML into POJOs, and gives up after 10 consecutive failures. The TPU
analog discovers chips and their HBM/duty-cycle metrics from, in order:

1. an external info command (``tpu-info``-style; path configurable via
   ``tony.tpu.info-exec-path``) emitting the JSON contract below,
2. the VM's accelerator device files (``/dev/accel*`` / ``/dev/vfio``),
3. the TPU-VM metadata env (``TPU_ACCELERATOR_TYPE``,
   ``TPU_CHIPS_PER_HOST_BOUNDS``, ``TPU_WORKER_ID``).

JSON contract for the info command (wrap ``tpu-info`` or libtpu's metrics
service on :8431 with a few lines of shell to produce it)::

    {"accelerator_type": "v5p-32",
     "chips": [{"device_id": 0, "hbm_used_bytes": 1024,
                "hbm_total_bytes": 99857989632, "duty_cycle_pct": 93.1}]}
"""

from __future__ import annotations

import glob
import json
import logging
import os
import subprocess
from dataclasses import dataclass, field

log = logging.getLogger(__name__)

DEFAULT_INFO_COMMAND = "tpu-info"
# ref: GpuDiscoverer's DEFAULT_BINARY_SEARCH_DIRS (/usr/bin,/bin,...)
DEFAULT_SEARCH_DIRS = ("/usr/bin", "/bin", "/usr/local/bin")
MAX_REPEATED_ERRORS = 10  # ref: GpuDiscoverer error cap
ACCEL_DEVICE_GLOBS = ("/dev/accel*", "/dev/vfio/[0-9]*")


@dataclass
class PerTpuChipInformation:
    """Ref shape: PerGpuDeviceInformation (utilization + fb memory)."""

    device_id: int
    hbm_used_bytes: int = 0
    hbm_total_bytes: int = 0
    duty_cycle_pct: float = -1.0

    def to_dict(self) -> dict:
        return {
            "device_id": self.device_id,
            "hbm_used_bytes": self.hbm_used_bytes,
            "hbm_total_bytes": self.hbm_total_bytes,
            "duty_cycle_pct": self.duty_cycle_pct,
        }


@dataclass
class TpuDeviceInformation:
    """Ref shape: GpuDeviceInformation (list of per-device POJOs)."""

    accelerator_type: str = ""
    chips: list[PerTpuChipInformation] = field(default_factory=list)
    source: str = "none"  # info-command | device-files | env | none

    @property
    def chip_count(self) -> int:
        return len(self.chips)

    def to_dict(self) -> dict:
        return {
            "accelerator_type": self.accelerator_type,
            "source": self.source,
            "chips": [c.to_dict() for c in self.chips],
        }


class TpuInfoException(Exception):
    """Ref: GpuInfoException."""


def parse_tpu_info_json(text: str) -> TpuDeviceInformation:
    """Parse the info command's JSON (ref: GpuDeviceInformationParser)."""
    try:
        data = json.loads(text)
    except json.JSONDecodeError as e:
        raise TpuInfoException(f"malformed tpu info JSON: {e}") from e
    if not isinstance(data, dict) or not isinstance(data.get("chips"), list):
        raise TpuInfoException("tpu info JSON missing 'chips' list")
    chips = []
    for i, chip in enumerate(data["chips"]):
        if not isinstance(chip, dict):
            raise TpuInfoException(f"chip entry {i} is not an object")
        chips.append(PerTpuChipInformation(
            device_id=int(chip.get("device_id", i)),
            hbm_used_bytes=int(chip.get("hbm_used_bytes", 0)),
            hbm_total_bytes=int(chip.get("hbm_total_bytes", 0)),
            duty_cycle_pct=float(chip.get("duty_cycle_pct", -1.0)),
        ))
    return TpuDeviceInformation(
        accelerator_type=str(data.get("accelerator_type", "")),
        chips=chips,
        source="info-command",
    )


def _chips_from_device_files() -> int:
    seen = set()
    for pattern in ACCEL_DEVICE_GLOBS:
        for path in glob.glob(pattern):
            seen.add(path)
    return len(seen)


def _chips_from_env() -> tuple[int, str]:
    """TPU-VM metadata env: bounds like '2,2,1' mean 4 chips per host."""
    accel = os.environ.get("TPU_ACCELERATOR_TYPE", "")
    bounds = os.environ.get("TPU_CHIPS_PER_HOST_BOUNDS", "")
    count = 0
    if bounds:
        try:
            dims = [int(d) for d in bounds.split(",") if d.strip()]
            count = 1
            for d in dims:
                count *= d
        except ValueError:
            count = 0
    return count, accel


class TpuDiscoverer:
    """Cached, error-capped discovery (ref: GpuDiscoverer.getGpuDeviceInformation
    :88 + the consecutive-error cap)."""

    def __init__(self, info_exec_path: str = "",
                 search_dirs: tuple[str, ...] = DEFAULT_SEARCH_DIRS,
                 timeout_s: float = 10.0):
        self.info_exec_path = info_exec_path
        self.search_dirs = search_dirs
        self.timeout_s = timeout_s
        self.error_count = 0
        self._binary: str | None = None
        self.last: TpuDeviceInformation | None = None

    def _resolve_binary(self) -> str | None:
        if self._binary is not None:
            return self._binary or None
        if self.info_exec_path:
            self._binary = self.info_exec_path if os.path.exists(
                self.info_exec_path) else ""
        else:
            self._binary = ""
            for d in self.search_dirs:
                cand = os.path.join(d, DEFAULT_INFO_COMMAND)
                if os.path.exists(cand):
                    self._binary = cand
                    break
        return self._binary or None

    def _run_info_command(self) -> TpuDeviceInformation | None:
        binary = self._resolve_binary()
        if binary is None or self.error_count >= MAX_REPEATED_ERRORS:
            return None
        try:
            out = subprocess.run(
                [binary, "--format", "json"], capture_output=True, text=True,
                timeout=self.timeout_s, check=True).stdout
            info = parse_tpu_info_json(out)
            self.error_count = 0
            return info
        except (subprocess.SubprocessError, OSError, TpuInfoException) as e:
            self.error_count += 1
            if self.error_count == MAX_REPEATED_ERRORS:
                log.warning("tpu info command failed %d times; giving up "
                            "(last: %s)", self.error_count, e)
            return None

    def get_device_information(self) -> TpuDeviceInformation:
        info = self._run_info_command()
        if info is None:
            n_files = _chips_from_device_files()
            n_env, accel = _chips_from_env()
            if n_files:
                info = TpuDeviceInformation(
                    accelerator_type=accel,
                    chips=[PerTpuChipInformation(i) for i in range(n_files)],
                    source="device-files")
            elif n_env:
                info = TpuDeviceInformation(
                    accelerator_type=accel,
                    chips=[PerTpuChipInformation(i) for i in range(n_env)],
                    source="env")
            else:
                info = TpuDeviceInformation(accelerator_type=accel)
        self.last = info
        return info

    def device_metrics(self) -> dict[str, float]:
        """Aggregate util/hbm for the metrics sampler: mean duty cycle over
        chips reporting one, summed HBM bytes in use."""
        info = self.get_device_information()
        duty = [c.duty_cycle_pct for c in info.chips if c.duty_cycle_pct >= 0]
        out: dict[str, float] = {}
        if duty:
            out["util"] = sum(duty) / len(duty)
        hbm = sum(c.hbm_used_bytes for c in info.chips)
        if hbm:
            out["hbm"] = float(hbm)
        return out
