"""Serving alert/event bus: a small rule engine over fleet signals.

The TonY portal answers "what happened to my job" after the fact; an
operator running a serving fleet needs the same story LIVE — not a
wall of gauges, but a short list of named conditions that are
currently true, each with a fire event when it started and a resolve
event when it stopped. This module is that list:

- ``Rule``: a named predicate over the gateway's consistent signal
  snapshot (``Gateway.alert_signals()`` — the same read the
  autoscaler's ``scale_signals()`` builds on, so an alert and a scale
  decision can never disagree about what they saw). Stateful rules
  (SLO burn needs histogram deltas, flap detection needs a failure
  window, goodput collapse needs a trailing baseline) keep their state
  inside the rule object — the bus itself is stateless per rule
  beyond active/pending bookkeeping.
- ``AlertBus``: evaluates every rule per tick and emits STRUCTURED,
  DEDUPLICATED transitions: one ``firing`` event when a rule's
  condition has held for ``fire_after`` consecutive ticks, one
  ``resolved`` event after ``resolve_after`` consecutive clear ticks —
  never a re-fire while active, never a flap on a single noisy tick.
  Events carry wall-clock time, severity, a human message, and the
  signal detail the rule matched on; they land in history
  ``metrics/alerts.jsonl`` (next to requests/scaling, portal-rendered),
  the ``/stats`` ``alerts`` block (active + recent), and ``/metrics``
  (``tony_alerts_*``).

Default rules (thresholds overridable via ``default_rules()``):

| rule                  | fires when                                   |
| --------------------- | -------------------------------------------- |
| ``queue_aging``       | oldest queued wait exceeds ``queue_wait_s``  |
| ``kv_pages_pressure`` | free-after-reservation KV pages under        |
|                       | ``kv_free_frac`` of the pool while work is   |
|                       | live/queued                                  |
| ``kv_host_thrash``    | host-tier page-in bytes per tick over        |
|                       | ``host_thrash_bytes`` WHILE the pool is also |
|                       | pressured (spill/restore churn: the HBM pool |
|                       | is undersized for the prefix working set)    |
| ``ttft_slo_burn``     | >``burn_frac`` of a tick's completions over  |
|                       | ``ttft_slo_s`` (histogram delta; off at 0)   |
| ``breaker_flap``      | >= ``flap_failures`` replica failures inside |
|                       | ``flap_window_s`` (states alone never fire — |
|                       | probe admission is the routine scale-up path)|
| ``goodput_collapse``  | per-tick useful fraction under               |
|                       | ``collapse_frac`` x its trailing baseline    |
|                       | while tokens are flowing                     |
| ``gateway_recovery``  | the gateway restarted through ``--recover``  |
|                       | within the last ``recovery_recent_s``        |
|                       | (repeat firings = a crash loop)              |
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field


@dataclass
class AlertEvent:
    """One transition: ``state`` is "firing" or "resolved".
    ``t_wall`` is epoch seconds (jsonl rows must survive process
    restarts, so no monotonic here)."""

    alert: str
    severity: str
    state: str
    message: str
    t_wall: float
    detail: dict = field(default_factory=dict)

    def to_row(self) -> dict:
        return {
            "t": round(self.t_wall, 3),
            "alert": self.alert,
            "severity": self.severity,
            "state": self.state,
            "message": self.message,
            **{f"detail_{k}": v for k, v in self.detail.items()},
        }


class Rule:
    """Base rule: subclass (or pass ``check``) to implement
    ``evaluate(signals) -> dict | None`` — a detail dict means the
    condition holds this tick, None means it does not. ``fire_after``
    / ``resolve_after`` are the bus-side debounce (consecutive
    ticks)."""

    def __init__(self, name: str, severity: str = "warning",
                 check=None, fire_after: int = 1,
                 resolve_after: int = 2, message: str = ""):
        self.name = name
        self.severity = severity
        self._check = check
        self.fire_after = max(1, fire_after)
        self.resolve_after = max(1, resolve_after)
        self.message = message or name

    def evaluate(self, signals: dict):
        return self._check(signals) if self._check is not None else None


class QueueAgingRule(Rule):
    def __init__(self, queue_wait_s: float = 5.0, **kw):
        super().__init__("queue_aging",
                         message="admission queue is aging", **kw)
        self.queue_wait_s = queue_wait_s

    def evaluate(self, signals):
        wait = signals.get("oldest_wait_s", 0.0)
        if wait > self.queue_wait_s:
            return {"oldest_wait_s": wait,
                    "threshold_s": self.queue_wait_s,
                    "depth": signals.get("depth", 0)}
        return None


class KvPagesPressureRule(Rule):
    """Fires when the page pool's free-after-reservation headroom is
    under ``kv_free_frac`` of the pool WHILE work is live or queued —
    the reservation gate is about to start delaying admissions (the
    stay-pending backpressure PR 7 built). A full-but-idle pool (the
    prefix store pinning donated pages with nothing running) is
    residency, not pressure, and must resolve once load stops."""

    def __init__(self, kv_free_frac: float = 0.15, **kw):
        super().__init__("kv_pages_pressure",
                         message="KV page pool under pressure", **kw)
        self.kv_free_frac = kv_free_frac

    def evaluate(self, signals):
        total = signals.get("kv_pages_total", 0)
        if not total:
            return None
        busy = signals.get("active_slots", 0) > 0 \
            or signals.get("depth", 0) > 0
        headroom = (signals.get("kv_pages_free", 0)
                    - signals.get("kv_pages_reserved", 0)) / total
        if busy and headroom < self.kv_free_frac:
            return {"free_after_reserve_frac": round(headroom, 4),
                    "threshold_frac": self.kv_free_frac,
                    "kv_pages_total": total,
                    "kv_pages_free": signals.get("kv_pages_free", 0),
                    "kv_pages_reserved":
                        signals.get("kv_pages_reserved", 0)}
        return None


class KvHostThrashRule(Rule):
    """Host-tier RESTORE churn while the device pool is already under
    pressure: page-in bytes this tick over ``thrash_bytes`` AND the
    ``kv_pages_pressure`` condition simultaneously true. Each signal
    alone is healthy — page-ins are the tier paying for itself, and
    pressure is the reservation gate doing its job — but together they
    mean spill -> restore -> spill churn: the HBM pool is undersized
    for the live prefix working set (raise --kv-pages or lower
    --prefix-cache-mb). Reuses the pressure rule's own predicate (same
    thresholds) so this rule and that one can never disagree about
    what \"pressured\" means."""

    def __init__(self, thrash_bytes: float = float(1 << 20),
                 kv_free_frac: float = 0.15, **kw):
        super().__init__("kv_host_thrash",
                         message="host page tier thrashing", **kw)
        self.thrash_bytes = thrash_bytes
        self._pressure = KvPagesPressureRule(kv_free_frac=kv_free_frac)
        self._prev: float | None = None  # cumulative page-in bytes

    def evaluate(self, signals):
        total = signals.get("kv_host_page_in_bytes")
        prev, self._prev = self._prev, total
        if total is None or prev is None:
            return None
        delta = total - prev
        if delta < self.thrash_bytes:
            return None
        pressure = self._pressure.evaluate(signals)
        if pressure is None:
            return None
        return {"page_in_bytes_tick": delta,
                "threshold_bytes": self.thrash_bytes,
                "free_after_reserve_frac":
                    pressure["free_after_reserve_frac"]}


class TtftSloBurnRule(Rule):
    """Histogram-delta SLO burn, the autoscaler's signal as an alert:
    per tick, the fraction of NEW completions whose TTFT exceeded
    ``ttft_slo_s``, computed by the SAME ``obs/prom.hist_over_edge``
    helper the ``AutoScaler``'s burn signal uses (SLO rounded UP to
    the next bucket edge; one implementation, so an alert and a scale
    decision can never disagree about the same histogram).
    ``ttft_slo_s = 0`` disables the rule (it evaluates to None)."""

    def __init__(self, ttft_slo_s: float = 0.0, burn_frac: float = 0.10,
                 min_samples: int = 5, **kw):
        kw.setdefault("severity", "critical")
        super().__init__("ttft_slo_burn",
                         message="TTFT SLO burning", **kw)
        self.ttft_slo_s = ttft_slo_s
        self.burn_frac = burn_frac
        self.min_samples = max(1, min_samples)
        self._prev: tuple | None = None  # (over, total)

    def evaluate(self, signals):
        if self.ttft_slo_s <= 0:
            return None
        from tony_tpu.obs.prom import hist_over_edge

        over, total = hist_over_edge(signals.get("ttft_hist") or {},
                                     self.ttft_slo_s)
        prev, self._prev = self._prev, (over, total)
        if prev is None:
            return None
        d_total = total - prev[1]
        if d_total < self.min_samples:
            return None
        burned = over - prev[0]
        frac = burned / d_total
        if frac > self.burn_frac:
            return {"burn_frac": round(frac, 4),
                    "threshold_frac": self.burn_frac,
                    "ttft_slo_s": self.ttft_slo_s,
                    "completions": d_total, "over_slo": burned}
        return None


class BreakerFlapRule(Rule):
    """Replica FAILURES clustering in time: the supervision story is
    working, but somebody should look at WHY it keeps having to.
    Deliberately counts only the failure counter, never breaker
    STATES: a broken/probing replica is also the routine scale-up
    admission path (``add_replica(probe=True)`` enters BROKEN and
    probes its way into routing), and a critical alert on every
    healthy elastic scale-up would train operators to ignore the
    rule. A replica that got broken via real failures already moved
    the counter."""

    def __init__(self, flap_failures: int = 2,
                 flap_window_s: float = 60.0, **kw):
        kw.setdefault("severity", "critical")
        super().__init__("breaker_flap",
                         message="replica breakers flapping", **kw)
        self.flap_failures = max(1, flap_failures)
        self.flap_window_s = flap_window_s
        # pruned by TIME, not a fixed maxlen: a fixed ring at
        # sub-second alert intervals would silently shrink the window
        # (256 samples at 0.2 s cover 51 s of a configured 60)
        self._samples: deque = deque()  # (t, failures_total)

    def evaluate(self, signals):
        now = signals.get("now", time.monotonic())
        failures = signals.get("replica_failures", 0)
        self._samples.append((now, failures))
        horizon = now - self.flap_window_s
        while self._samples and self._samples[0][0] < horizon:
            self._samples.popleft()
        recent = failures - self._samples[0][1]
        if recent >= self.flap_failures:
            unhealthy = [s for s in signals.get("states", ())
                         if s in ("broken", "probing")]
            return {"failures_in_window": recent,
                    "window_s": self.flap_window_s,
                    "unhealthy_replicas": len(unhealthy)}
        return None


class ShedStormRule(Rule):
    """Capacity sheds clustering in time: the gateway is 429/503-ing
    clients faster than ``storm_count`` per ``storm_window_s`` — a
    connection storm is hitting the admission gate and real requests
    are bouncing off it. Fires on the RATE of the cumulative
    ``shed_capacity_total`` counter (quota 429s are already excluded
    upstream — a tenant over its own rate limit is policy, not an
    incident), with the same time-pruned sample window as
    ``BreakerFlapRule``: a fixed-length ring at sub-second alert
    intervals would silently shrink the window. Before this rule, a
    storm's sheds moved /stats and the autoscaler but never the alert
    bus — the one surface operators actually page on.

    Counts BOTH planes (ISSUE-20 satellite, closing the ROADMAP-3
    residue): admission-layer capacity sheds AND the network edge's
    connection-cap 429s (``edge_conn_limit_sheds``) — a pure
    connection storm bounces off the edge without ever reaching
    admission, and used to be invisible here."""

    def __init__(self, storm_count: int = 50,
                 storm_window_s: float = 10.0, **kw):
        kw.setdefault("severity", "critical")
        super().__init__("shed_storm",
                         message="capacity sheds storming", **kw)
        self.storm_count = max(1, storm_count)
        self.storm_window_s = storm_window_s
        self._samples: deque = deque()  # (t, sheds incl. edge)

    def evaluate(self, signals):
        now = signals.get("now", time.monotonic())
        shed = signals.get("shed_capacity_total", 0) \
            + signals.get("edge_conn_limit_sheds", 0)
        self._samples.append((now, shed))
        horizon = now - self.storm_window_s
        while self._samples and self._samples[0][0] < horizon:
            self._samples.popleft()
        recent = shed - self._samples[0][1]
        if recent >= self.storm_count:
            return {"sheds_in_window": recent,
                    "window_s": self.storm_window_s,
                    "threshold": self.storm_count}
        return None


class GatewayRecoveryRule(Rule):
    """The gateway came back from a CRASH (``--recover`` replayed a
    journal) within the last ``recent_s`` — informational, but an
    operator should KNOW the process died and restarted even when
    recovery made it invisible to clients: repeated firings are a
    crash loop. Fires immediately (``fire_after=1``) and resolves on
    its own once the recovery ages out of the window."""

    def __init__(self, recent_s: float = 60.0, **kw):
        kw.setdefault("severity", "warning")
        super().__init__("gateway_recovery",
                         message="gateway restarted from crash "
                                 "recovery", **kw)
        self.recent_s = recent_s

    def evaluate(self, signals):
        ago = signals.get("recovered_ago_s")
        if ago is not None and ago <= self.recent_s:
            return {"recovered_ago_s": ago}
        return None


class GoodputCollapseRule(Rule):
    """Fleet useful fraction dropping hard below its own trailing
    baseline while real work is running — the "the fleet is busy but
    the work is going somewhere else" alarm (a compile storm, a
    padding regression, a speculation meltdown). Works on PER-TICK
    DELTAS of the ledger's useful vs DISPATCH milliseconds — "of the
    time the engines spent dispatching this tick, how much landed
    kept tokens" — never the since-boot cumulative fraction and never
    a wall-clock denominator: the cumulative ratio would fire falsely
    on the first request after a long idle lull and lag real
    collapses by the whole uptime, and a wall denominator would read
    trickle traffic (one short request in a mostly-idle second) as a
    collapse. Ticks with under ``min_dispatch_ms`` of dispatch
    activity are not judged at all. The baseline is an EMA over
    judged ticks, armed after ``min_updates``; a collapse tick does
    NOT update the baseline (it must not chase the regression
    down)."""

    def __init__(self, collapse_frac: float = 0.5,
                 min_updates: int = 5, decay: float = 0.8,
                 min_dispatch_ms: float = 20.0, **kw):
        kw.setdefault("severity", "critical")
        super().__init__("goodput_collapse",
                         message="goodput collapsed vs baseline", **kw)
        self.collapse_frac = collapse_frac
        self.min_updates = max(1, min_updates)
        self.decay = decay
        self.min_dispatch_ms = min_dispatch_ms
        self.baseline: float | None = None
        self._updates = 0
        self._prev_tokens = 0
        self._prev_ms: tuple | None = None  # (useful_ms, dispatch_ms)

    def evaluate(self, signals):
        useful_ms = signals.get("goodput_useful_ms")
        dispatch_ms = signals.get("goodput_dispatch_ms")
        tokens = signals.get("tokens_out", 0)
        flowing = tokens > self._prev_tokens
        self._prev_tokens = tokens
        if useful_ms is None or dispatch_ms is None:
            return None
        prev, self._prev_ms = self._prev_ms, (useful_ms, dispatch_ms)
        if prev is None or not flowing:
            return None
        d_disp = dispatch_ms - prev[1]
        if d_disp < self.min_dispatch_ms:
            return None  # not enough device work this tick to judge
        frac = min(1.0, max(0.0, useful_ms - prev[0]) / d_disp)
        armed = self._updates >= self.min_updates
        collapsed = (armed and self.baseline is not None
                     and self.baseline > 0
                     and frac < self.collapse_frac * self.baseline)
        if not collapsed:
            self.baseline = frac if self.baseline is None else \
                self.decay * self.baseline + (1 - self.decay) * frac
            self._updates += 1
            return None
        return {"useful_fraction": round(frac, 4),
                "baseline": round(self.baseline, 4),
                "collapse_frac": self.collapse_frac}


def default_rules(thresholds: dict | None = None) -> list[Rule]:
    """The stock rule set; ``thresholds`` overrides any of
    queue_wait_s / kv_free_frac / ttft_slo_s / burn_frac /
    flap_failures / flap_window_s / shed_storm_count /
    shed_storm_window_s / collapse_frac."""
    t = thresholds or {}
    return [
        QueueAgingRule(queue_wait_s=t.get("queue_wait_s", 5.0)),
        KvPagesPressureRule(kv_free_frac=t.get("kv_free_frac", 0.15)),
        KvHostThrashRule(
            thrash_bytes=t.get("host_thrash_bytes", float(1 << 20)),
            kv_free_frac=t.get("kv_free_frac", 0.15)),
        TtftSloBurnRule(ttft_slo_s=t.get("ttft_slo_s", 0.0),
                        burn_frac=t.get("burn_frac", 0.10)),
        BreakerFlapRule(flap_failures=t.get("flap_failures", 2),
                        flap_window_s=t.get("flap_window_s", 60.0)),
        ShedStormRule(storm_count=t.get("shed_storm_count", 50),
                      storm_window_s=t.get("shed_storm_window_s", 10.0)),
        GoodputCollapseRule(
            collapse_frac=t.get("collapse_frac", 0.5)),
        GatewayRecoveryRule(
            recent_s=t.get("recovery_recent_s", 60.0)),
    ]


class AlertBus:
    """Rule evaluation + transition dedup + bounded event history.
    Thread-safe: the gateway's alert loop evaluates, any HTTP thread
    snapshots."""

    def __init__(self, rules: list[Rule] | None = None,
                 recent_capacity: int = 128):
        self.rules = list(rules) if rules is not None \
            else default_rules()
        self._lock = threading.Lock()
        self._active: dict[str, AlertEvent] = {}
        self._streak: dict[str, int] = {}   # +n firing / -n clear
        self._recent: deque[AlertEvent] = deque(maxlen=recent_capacity)
        self.fired: dict[str, int] = {}
        self.resolved: dict[str, int] = {}
        self.evaluations = 0

    def evaluate(self, signals: dict,
                 t_wall: float | None = None) -> list[AlertEvent]:
        """One tick over every rule; returns the TRANSITIONS (fire /
        resolve events) this tick produced. A rule that raises is
        counted clear — a broken rule must never take the serving
        loop's monitor down with it."""
        t_wall = time.time() if t_wall is None else t_wall
        out: list[AlertEvent] = []
        with self._lock:
            self.evaluations += 1
            for rule in self.rules:
                try:
                    detail = rule.evaluate(signals)
                except Exception:  # noqa: BLE001 — see docstring
                    detail = None
                streak = self._streak.get(rule.name, 0)
                if detail is not None:
                    streak = streak + 1 if streak > 0 else 1
                    active = self._active.get(rule.name)
                    if active is None and streak >= rule.fire_after:
                        ev = AlertEvent(rule.name, rule.severity,
                                        "firing", rule.message, t_wall,
                                        detail)
                        self._active[rule.name] = ev
                        self._recent.append(ev)
                        self.fired[rule.name] = \
                            self.fired.get(rule.name, 0) + 1
                        out.append(ev)
                    elif active is not None:
                        active.detail = detail  # live detail refresh
                else:
                    streak = streak - 1 if streak < 0 else -1
                    active = self._active.get(rule.name)
                    if active is not None \
                            and -streak >= rule.resolve_after:
                        ev = AlertEvent(rule.name, rule.severity,
                                        "resolved", rule.message,
                                        t_wall,
                                        {"fired_at": active.t_wall})
                        del self._active[rule.name]
                        self._recent.append(ev)
                        self.resolved[rule.name] = \
                            self.resolved.get(rule.name, 0) + 1
                        out.append(ev)
                self._streak[rule.name] = streak
        return out

    def active(self) -> list[AlertEvent]:
        with self._lock:
            return list(self._active.values())

    def snapshot(self) -> dict:
        """The ``/stats`` ``alerts`` block."""
        with self._lock:
            return {
                "rules": [r.name for r in self.rules],
                "evaluations": self.evaluations,
                "active": [{
                    "alert": e.alert, "severity": e.severity,
                    "since": round(e.t_wall, 3),
                    "message": e.message, "detail": dict(e.detail),
                } for e in self._active.values()],
                "recent": [e.to_row() for e in self._recent],
                "fired": dict(self.fired),
                "resolved": dict(self.resolved),
            }
