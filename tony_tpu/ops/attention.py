"""Fused flash attention as a pallas TPU kernel.

The hot op of the transformer stack (no reference analog — TonY has no
kernels; this is the TPU-first replacement for what torch users get from
SDPA/FlashAttention-CUDA). Design per the pallas TPU playbook:

- grid = (batch*heads, q_blocks, kv_blocks); kv is the innermost
  "arbitrary" (sequential) dimension so VMEM scratch carries the online-
  softmax running state (m, l) and the fp32 output accumulator across kv
  steps
- q/k/v blocks are DMA'd HBM->VMEM by BlockSpec; matmuls hit the MXU in
  fp32 accumulation; block sizes default to MXU/VPU-friendly 128
- causal masking prunes fully-masked kv blocks via @pl.when

Falls back to the interpreter off-TPU (tests run it on CPU), and exposes a
custom_vjp whose backward recomputes attention blockwise (memory-efficient
remat backward; forward stays fused).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from tony_tpu.parallel.ring_attention import blockwise_attention

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                  causal: bool, block_q: int, block_k: int, scale: float):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _body():
        q = q_ref[0].astype(jnp.float32)  # [block_q, d]
        k = k_ref[0].astype(jnp.float32)  # [block_k, d]
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            pos_q = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            pos_k = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(pos_q >= pos_k, s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        m_scr[:] = m_new
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    if causal:
        # skip kv blocks strictly above the diagonal
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _run():
            _body()
    else:
        _body()

    @pl.when(ki == nk - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:] / jnp.maximum(l_scr[:], 1e-30)).astype(o_ref.dtype)


def _flash_forward(q, k, v, *, causal: bool, block_q: int, block_k: int,
                   interpret: bool):
    b, lq, h, d = q.shape
    lk = k.shape[1]
    kvh = k.shape[2]
    if lq % block_q or lk % block_k:
        raise ValueError(
            f"seq lens ({lq},{lk}) must divide block sizes ({block_q},{block_k})")
    if h % kvh:
        raise ValueError(f"q heads {h} not divisible by kv heads {kvh}")
    group = h // kvh
    scale = d ** -0.5
    # [B, L, H, D] -> [B*H, L, D]
    qr = q.transpose(0, 2, 1, 3).reshape(b * h, lq, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * kvh, lk, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * kvh, lk, d)
    grid = (b * h, lq // block_q, lk // block_k)

    def kv_index(bh, qi, ki):
        # GQA: q head -> its kv group's row; the same kv block is DMA'd for
        # each of the `group` q heads instead of materializing a repeat
        return (bh // h) * kvh + (bh % h) // group, ki, 0

    kernel = functools.partial(_flash_kernel, causal=causal, block_q=block_q,
                               block_k=block_k, scale=scale)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * h, lq, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        scratch_shapes=[
            pl.pallas_tpu_scratch_vmem((block_q, 1), jnp.float32)
            if hasattr(pl, "pallas_tpu_scratch_vmem") else _vmem((block_q, 1)),
            _vmem((block_q, 1)),
            _vmem((block_q, d)),
        ],
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(b, h, lq, d).transpose(0, 2, 1, 3)


def _vmem(shape):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, jnp.float32)


def _compiler_params():
    try:
        from jax.experimental.pallas import tpu as pltpu

        return pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    except Exception:
        return None


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True, block_q: int = 128,
                    block_k: int = 128, interpret: bool | None = None):
    """Fused attention. q: [B, L, H, D]; k/v: [B, L, KVH, D] with
    H % KVH == 0 (GQA: the kernel indexes each q head's kv group directly —
    no repeated K/V is ever materialized). Returns [B, L, H, D].

    interpret=None auto-selects: compiled on TPU, interpreter elsewhere.
    """
    if interpret is None:
        interpret = not _on_tpu()
    bq = min(block_q, q.shape[1])
    bk = min(block_k, k.shape[1])
    return _flash_forward(q, k, v, causal=causal, block_q=bq, block_k=bk,
                          interpret=interpret)


def _fwd(q, k, v, causal, block_q, block_k, interpret):
    out = flash_attention(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _bwd(causal, block_q, block_k, interpret, res, g):
    """Remat backward through the blockwise implementation — O(L) memory,
    numerically identical attention math. For GQA the recompute broadcasts
    K/V to H heads and group-sums the grads back to KVH."""
    q, k, v = res
    b, lk, kvh, d = k.shape
    h = q.shape[2]
    group = h // kvh
    kf = jnp.repeat(k, group, axis=2) if group > 1 else k
    vf = jnp.repeat(v, group, axis=2) if group > 1 else v
    _, vjp = jax.vjp(
        lambda q, k, v: blockwise_attention(q, k, v, block_size=block_k,
                                            causal=causal), q, kf, vf)
    dq, dkf, dvf = vjp(g)
    if group > 1:
        dkf = dkf.reshape(b, lk, kvh, group, d).sum(axis=3)
        dvf = dvf.reshape(b, lk, kvh, group, d).sum(axis=3)
    return dq, dkf, dvf


flash_attention.defvjp(_fwd, _bwd)
