"""Workflow jobtype tests (tony-azkaban equivalent).

Reference analog: tony-azkaban's TonyJob prop collection + tag injection
(TonyJob.java:55-70) and TonyJobArg prop->arg mapping.
"""

import json
import os

import pytest

from tony_tpu.mini import MiniTonyCluster
from tony_tpu.workflow import FlowContext, TonyTpuOperator, WorkflowJob

SCRIPTS = os.path.join(os.path.dirname(__file__), "scripts")


def test_collects_tony_props_and_standard_args(tmp_path):
    job = WorkflowJob(
        job_id="train",
        props={
            "tony.worker.instances": "3",
            "tony.application.framework": "pytorch",
            "executes": "train.py",
            "task_params": "--epochs 2",
            "python_binary_path": "python3.12",
            "unrelated.prop": "ignored",
        },
        working_dir=str(tmp_path),
    )
    conf = job.build_conf()
    assert conf.get_int("tony.worker.instances") == 3
    assert conf.get("tony.application.framework") == "pytorch"
    assert conf.get("tony.application.executes") == "train.py"
    assert conf.get("tony.application.task-params") == "--epochs 2"
    assert conf.get("tony.application.python-command") == "python3.12"
    assert conf.get("unrelated.prop") is None


def test_flow_tags_injected(tmp_path):
    job = WorkflowJob(
        job_id="j1", props={}, working_dir=str(tmp_path),
        flow=FlowContext(execution_id="42", flow_id="nightly",
                         project_name="ml", scheduler_host="sched:8081"))
    conf = job.build_conf()
    tags = str(conf.get("tony.application.tags"))
    assert "execution_id:42" in tags
    assert "flow_id:nightly" in tags
    assert "project_name:ml" in tags
    # flow id becomes the app name when the user didn't set one
    assert conf.get("tony.application.name") == "nightly"


def test_worker_env_props_become_shell_env(tmp_path):
    job = WorkflowJob(
        job_id="j2",
        props={"worker_env.FOO": "bar", "worker_env.BAZ": "1",
               "shell_env": "USER_SET=x"},
        working_dir=str(tmp_path))
    conf = job.build_conf()
    shell_env = str(conf.get("tony.application.shell-env"))
    assert "USER_SET=x" in shell_env
    assert "FOO=bar" in shell_env
    assert "BAZ=1" in shell_env


def test_generated_conf_written(tmp_path):
    job = WorkflowJob(job_id="j3", props={"tony.worker.instances": "2"},
                      working_dir=str(tmp_path))
    path = job.write_generated_conf(job.build_conf())
    assert os.path.exists(path)
    with open(path) as f:
        data = json.load(f)
    assert data["tony.worker.instances"] == 2


def test_operator_end_to_end_submits():
    """The operator runs a real job through the mini cluster, and the
    shell-env prop reaches the task (payload asserts it)."""
    check = os.path.join(SCRIPTS, "check_shell_env.py")
    with MiniTonyCluster() as cluster:
        base = cluster.base_conf()
        op = TonyTpuOperator(
            task_id="wf-train",
            executes=check,
            props={
                "tony.worker.instances": "1",
                "worker_env.WF_CANARY": "present",
                "tony.staging-dir": str(base.get("tony.staging-dir")),
                "tony.history.location": str(base.get("tony.history.location")),
                "tony.task.heartbeat-interval-ms": "100",
                "tony.coordinator.monitor-interval-ms": "100",
                "tony.client.poll-interval-ms": "100",
            },
            working_dir=os.path.join(cluster.root, "wf"),
        )
        assert op.execute({"dag_run": None, "dag": None}) is True


def test_operator_raises_on_failure():
    with MiniTonyCluster() as cluster:
        base = cluster.base_conf()
        op = TonyTpuOperator(
            task_id="wf-fail",
            executes=os.path.join(SCRIPTS, "exit_1.py"),
            props={
                "tony.worker.instances": "1",
                "tony.staging-dir": str(base.get("tony.staging-dir")),
                "tony.history.location": str(base.get("tony.history.location")),
                "tony.task.heartbeat-interval-ms": "100",
                "tony.coordinator.monitor-interval-ms": "100",
                "tony.client.poll-interval-ms": "100",
            },
            working_dir=os.path.join(cluster.root, "wf"),
        )
        with pytest.raises(RuntimeError):
            op.execute()
