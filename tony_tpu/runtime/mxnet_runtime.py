"""MXNet runtime: DMLC parameter-server env.

Reference: runtime/MXNetRuntime.java:44-66 + Utils.parseClusterSpecForMXNet
(util/Utils.java:610-633): resolves the ``scheduler`` role's host to an
address, sets DMLC_PS_ROOT_URI/PORT, server/worker counts, DMLC_ROLE,
DMLC_LOCAL=0.
"""

from __future__ import annotations

import socket

from tony_tpu import constants as C
from tony_tpu.config import ConfError, TonyConf
from tony_tpu.runtime.base import AMAdapter, Runtime, TaskAdapter, TaskContext

SCHEDULER = "scheduler"
SERVER = "server"


class MXNetAMAdapter(AMAdapter):
    def validate_and_update_config(self, conf: TonyConf) -> None:
        roles = conf.roles()
        if SCHEDULER in roles and int(conf.role_get(SCHEDULER, "instances")) > 1:
            raise ConfError("mxnet runtime allows at most one scheduler")


class MXNetTaskAdapter(TaskAdapter):
    def build_task_env(self, ctx: TaskContext) -> dict[str, str]:
        env = super().build_task_env(ctx)
        sched = ctx.cluster_spec.get(SCHEDULER)
        if sched and sched[0]:
            host, _, port = sched[0].rpartition(":")
            try:
                host = socket.gethostbyname(host)  # ref resolves to IP
            except OSError:
                pass
            env[C.MX_DMLC_PS_ROOT_URI] = host
            env[C.MX_DMLC_PS_ROOT_PORT] = port
        env[C.MX_DMLC_ROLE] = ctx.role
        env[C.MX_DMLC_NUM_SERVER] = str(len(ctx.cluster_spec.get(SERVER, [])))
        env[C.MX_DMLC_NUM_WORKER] = str(len(ctx.cluster_spec.get(C.WORKER_JOB_NAME, [])))
        env[C.MX_DMLC_LOCAL] = "0"
        return env


class MXNetRuntime(Runtime):
    name = "mxnet"
    am_adapter_cls = MXNetAMAdapter
    task_adapter_cls = MXNetTaskAdapter
