"""Elastic training — real, where the reference only stubbed it.

Reference state: horovod_driver.py's ``elastic_driver_fn()`` is ``pass``
(resources/horovod_driver.py:28-29) and the proposal doc defers elasticity
(docs/proposals/horovod-on-tony.md:15-17). TPU semantics make in-place
membership change impossible anyway — an XLA gang's size is fixed at
``jax.distributed.initialize`` — so tony-tpu implements elasticity the
TPU-native way: **checkpoint-aware gang restart**.

Flow:
1. anyone calls the coordinator's ``resize_role(role, instances)`` RPC
   verb (client API or ``tony-tpu resize`` CLI);
2. the coordinator queues a ``save_and_exit`` command to every task
   (delivered on heartbeats), waits a grace period, then rebuilds the
   session at the new size (session epoch++), relaunching all tasks;
3. the user loop polls ``save_and_exit_requested()`` each step; when set
   it checkpoints (orbax, ``tony_tpu.train.checkpoint``) and exits with
   ``EXIT_RESIZE``;
4. relaunched tasks see a bumped ``TONY_SESSION_ID`` and resume via
   ``restore_or_init``.

Tasks that ignore the request are killed at the end of the grace period —
correctness then rests on their last periodic checkpoint.
"""

from __future__ import annotations

import os

from tony_tpu.utils.controlfile import (
    control_file_path,
    current_task_id,
    write_control_file,
)

# EX_TEMPFAIL: a cooperative elastic exit, not a failure
EXIT_RESIZE = 75

CONTROL_FILENAME = ".tony_save_and_exit"


def control_path(workdir: str, task_id: str = "") -> str:
    return control_file_path(workdir, CONTROL_FILENAME, task_id)


def write_save_and_exit(workdir: str, task_id: str = "",
                        reason: str = "resize") -> str:
    """Agent side: ask the user process to checkpoint and exit."""
    return write_control_file(control_path(workdir, task_id),
                              {"reason": reason})


def save_and_exit_requested(workdir: str | None = None,
                            task_id: str | None = None) -> bool:
    """User side: poll once per step (one ``os.path.exists`` when idle).
    The file is not consumed — exit is expected to follow."""
    workdir = workdir or os.getcwd()
    task_id = current_task_id() if task_id is None else task_id
    return os.path.exists(control_path(workdir, task_id))


def session_epoch() -> int:
    """The gang generation this process belongs to; bumps on every elastic
    resize or coordinator retry."""
    return int(os.environ.get("TONY_SESSION_ID", "0"))
