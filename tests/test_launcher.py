"""Docker/container launch mode.

Reference: tony.docker.* keys + docker container env
(HadoopCompatibleAdapter.getContainerEnvForDocker). The e2e test runs a
real job through a fake-docker shim that interprets ``docker run`` locally,
so the full coordinator->container->agent->payload path is exercised
without a docker daemon.
"""

import os
import stat
import textwrap

import pytest

from tony_tpu.mini import MiniTonyCluster, script_conf
from tony_tpu.session import Task

SCRIPTS = os.path.join(os.path.dirname(__file__), "scripts")


def test_build_docker_command():
    from tony_tpu.coordinator.launcher import build_docker_command

    task = Task(role="worker", index=0)
    argv = build_docker_command(
        task, {"JOB_NAME": "worker", "TASK_INDEX": "0"},
        image="gcr.io/proj/train:1", mounts=["/data:/data:ro"],
        extra_args=["--shm-size=4g"], workdir="/jobs/app1")
    assert argv[:2] == ["docker", "run"]
    assert "--net=host" in argv and "--privileged" in argv
    assert "tony-s0-worker-0" in argv  # epoch-qualified container name
    assert "/data:/data:ro" in argv
    # job dir is mounted at the same path and set as the workdir
    assert "/jobs/app1:/jobs/app1" in argv
    assert argv[argv.index("-w") + 1] == "/jobs/app1"
    assert "JOB_NAME=worker" in argv and "TASK_INDEX=0" in argv
    assert "--shm-size=4g" in argv
    assert argv[-4:] == ["gcr.io/proj/train:1", "python3", "-m",
                         "tony_tpu.agent"]


def test_build_docker_command_user_mount_covers_workdir():
    """A user mount of the workdir target must suppress the implicit one —
    docker rejects duplicate mount points."""
    from tony_tpu.coordinator.launcher import build_docker_command

    task = Task(role="worker", index=0)
    argv = build_docker_command(
        task, {}, image="img", mounts=["/jobs/app1:/jobs/app1"],
        workdir="/jobs/app1")
    assert argv.count("/jobs/app1:/jobs/app1") == 1
    assert argv[argv.index("-w") + 1] == "/jobs/app1"


def test_docker_launcher_rejects_missing_image():
    from tony_tpu.coordinator.launcher import DockerLauncher

    with pytest.raises(ValueError):
        DockerLauncher("", on_exit=lambda t, c: None)


FAKE_DOCKER = textwrap.dedent("""\
    #!/bin/bash
    # fake docker CLI: "run" interprets the agent container locally;
    # "kill" is a no-op (the local process group dies via the launcher).
    cmd="$1"; shift
    [ "$cmd" = kill ] && exit 0
    [ "$cmd" = run ] || exit 64
    envs=()
    while [ $# -gt 0 ]; do
      case "$1" in
        --rm|--net=host|--privileged) shift;;
        --name|-v|-w) shift 2;;
        -e) envs+=("$2"); shift 2;;
        *) break;;
      esac
    done
    image="$1"; shift  # drop the image; exec the container command locally
    exec env "${envs[@]}" "$@"
    """)


def fake_docker_bin(tmp_path) -> str:
    path = os.path.join(str(tmp_path), "docker")
    with open(path, "w") as f:
        f.write(FAKE_DOCKER)
    os.chmod(path, os.stat(path).st_mode | stat.S_IEXEC)
    return path


def test_docker_mode_e2e(tmp_path):
    """Gang job where every agent is 'containerized' through the shim."""
    with MiniTonyCluster() as cluster:
        conf = script_conf(cluster, os.path.join(SCRIPTS, "check_env.py"),
                           {"worker": 2})
        conf.set("tony.application.launch-mode", "docker")
        conf.set("tony.docker.image", "tony-test-image")
        conf.set("tony.docker.bin", fake_docker_bin(tmp_path))
        client = cluster.submit(conf)
        assert client.final_status["status"] == "SUCCEEDED", \
            client.final_status


def test_docker_enabled_key_requires_image(tmp_path):
    """Missing image fails fast at coordinator startup (ref: config
    validation in validateAndUpdateConfig)."""
    with MiniTonyCluster() as cluster:
        conf = script_conf(cluster, os.path.join(SCRIPTS, "exit_0.py"),
                           {"worker": 1})
        conf.set("tony.docker.enabled", True)
        client = cluster.make_client(conf)
        with pytest.raises(RuntimeError, match="coordinator exited"):
            client.run()


# -- ssh launch mode ---------------------------------------------------------

FAKE_SSH = os.path.join(SCRIPTS, "fake_ssh.sh")
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _wait_for(pred, timeout=15.0):
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return pred()


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False


def test_ssh_launcher_remote_kill(tmp_path, monkeypatch):
    """kill_task must kill the REMOTE process tree (via the recorded pgid),
    not just the local ssh client — otherwise a resized/retried gang
    overlaps the old one until the agent's coordinator-lost horizon
    (ref analog: NM container kill, ApplicationMaster.java:735-777)."""
    from tony_tpu.coordinator import launcher as L

    monkeypatch.setattr(L, "REMOTE_AGENT_CMD", "sleep 300")
    exits = []
    lch = L.SshLauncher(["fakehost"], on_exit=lambda t, c: exits.append((t, c)),
                        ssh_bin=FAKE_SSH)
    task = Task(role="worker", index=0)
    pgid_file = L.remote_pgid_file(task)
    if os.path.exists(pgid_file):
        os.remove(pgid_file)
    lch.launch(task, {"TONY_TEST": "1"}, os.path.join(str(tmp_path), "w.log"))
    assert _wait_for(lambda: os.path.exists(pgid_file)), "pgid never recorded"
    pid = int(open(pgid_file).read().strip())
    assert _alive(pid)
    assert lch.kill_task(task.id)
    assert _wait_for(lambda: not _alive(pid)), \
        "remote tree survived kill_task"
    assert not os.path.exists(pgid_file)  # kill cleans the pgid file


def test_ssh_launcher_stop_all_kills_remote_trees(tmp_path, monkeypatch):
    from tony_tpu.coordinator import launcher as L

    monkeypatch.setattr(L, "REMOTE_AGENT_CMD", "sleep 300")
    exits = []
    lch = L.SshLauncher(["h1", "h2"], on_exit=lambda t, c: exits.append(t),
                        ssh_bin=FAKE_SSH)
    tasks = [Task(role="worker", index=i) for i in range(2)]
    pids = []
    for t in tasks:
        pgid_file = L.remote_pgid_file(t)
        if os.path.exists(pgid_file):
            os.remove(pgid_file)
        lch.launch(t, {}, os.path.join(str(tmp_path), f"{t.id}.log"))
    for t in tasks:
        pgid_file = L.remote_pgid_file(t)
        assert _wait_for(lambda: os.path.exists(pgid_file))
        pids.append(int(open(pgid_file).read().strip()))
    lch.stop_all()
    for pid in pids:
        assert _wait_for(lambda: not _alive(pid)), \
            f"remote pid {pid} survived stop_all"
    assert exits == []  # teardown exits never reach on_exit


def test_ssh_mode_e2e(tmp_path):
    """Full gang over fake ssh: launch, env contract, clean finish."""
    with MiniTonyCluster() as cluster:
        conf = script_conf(cluster, os.path.join(SCRIPTS, "check_env.py"),
                           {"worker": 2})
        conf.set("tony.application.launch-mode", "ssh")
        conf.set("tony.application.hosts", "hostA,hostB")
        conf.set("tony.application.ssh-bin", FAKE_SSH)
        conf.set("tony.application.remote-pythonpath", REPO_ROOT)
        client = cluster.submit(conf)
        assert client.final_status["status"] == "SUCCEEDED", \
            client.final_status


def test_ssh_launcher_packs_hosts_by_free_chips(tmp_path, monkeypatch):
    """Capacity-aware placement: tasks carrying a chip demand land on the
    host with the most free chips and get disjoint TPU_VISIBLE_DEVICES
    subsets; capacity returns only once the ssh client confirms the
    remote tree is gone (the pod-wide analog of the coordinator-host
    ChipAllocator)."""
    from tony_tpu import constants as C
    from tony_tpu.coordinator import launcher as L

    placements = []

    monkeypatch.setattr(
        L, "REMOTE_AGENT_CMD",
        "sh -c 'echo HOSTENV=$TPU_VISIBLE_DEVICES; sleep 60'")
    lch = L.SshLauncher(["h1", "h2"], on_exit=lambda t, c: None,
                        ssh_bin=FAKE_SSH, chips_per_host=4)
    orig_place = lch._place

    def spy(task, env):
        host, env2 = orig_place(task, env)
        placements.append((task.id, host, env2.get(C.TPU_VISIBLE_DEVICES)))
        return host, env2

    monkeypatch.setattr(lch, "_place", spy)
    tasks = [Task(role="worker", index=i) for i in range(4)]
    for t in tasks:
        lch.launch(t, {C.TASK_CHIPS: "2"},
                   os.path.join(str(tmp_path), f"{t.id}.log"))
    by_host = {}
    for tid, host, vis in placements:
        assert vis is not None
        by_host.setdefault(host, []).append(vis)
    # 4 tasks x 2 chips over 2x4-chip hosts: 2 per host, disjoint pairs
    assert sorted(len(v) for v in by_host.values()) == [2, 2]
    for host, subsets in by_host.items():
        assert sorted(subsets) == ["0,1", "2,3"]
    # a 5th task cannot fit anywhere
    with pytest.raises(RuntimeError, match="chips"):
        lch.launch(Task(role="worker", index=4), {C.TASK_CHIPS: "2"},
                   os.path.join(str(tmp_path), "w4.log"))
    # kill returns capacity only after the local ssh client confirms the
    # exit (deferred release: a timed-out remote kill must not let a
    # relaunch share devices with a live agent)
    assert lch.kill_task("worker:0")
    assert _wait_for(lambda: sum(
        p.free_count for p in lch._pools.values()) == 2), \
        "capacity not returned after confirmed kill"
    host, env2 = orig_place(Task(role="worker", index=5),
                            {C.TASK_CHIPS: "2"})
    assert env2[C.TPU_VISIBLE_DEVICES] in ("0,1", "2,3")
    lch.stop_all()


def test_ssh_packing_e2e(tmp_path):
    """Full job: two 2-chip workers packed onto ONE 4-chip ssh host must
    see disjoint TPU_VISIBLE_DEVICES subsets end-to-end."""
    import glob

    from tony_tpu import constants as C

    payload = os.path.join(str(tmp_path), "check_chips.py")
    with open(payload, "w") as f:
        f.write("import os, sys\n"
                "vis = os.environ.get('TPU_VISIBLE_DEVICES', '')\n"
                "ids = [int(x) for x in vis.split(',') if x]\n"
                "print('TPU_VISIBLE_DEVICES =', vis)\n"
                "sys.exit(0 if len(ids) == 2 else 9)\n")
    with MiniTonyCluster() as cluster:
        conf = script_conf(cluster, payload, {"worker": 2})
        conf.set("tony.application.launch-mode", "ssh")
        conf.set("tony.application.hosts", "hX")
        conf.set("tony.application.ssh-bin", FAKE_SSH)
        conf.set("tony.application.remote-pythonpath", REPO_ROOT)
        conf.set("tony.worker.chips", 2)
        conf.set("tony.tpu.chips-per-host", 4)
        client = cluster.submit(conf)
        assert client.final_status["status"] == "SUCCEEDED", \
            client.final_status
        subsets = []
        for lf in glob.glob(os.path.join(client.job_dir, "logs",
                                         "worker-*.log")):
            for line in open(lf):
                if "TPU_VISIBLE_DEVICES =" in line:
                    subsets.append(line.strip().split("= ")[1])
        assert sorted(subsets) == ["0,1", "2,3"], subsets


# -- ssh job-dir shipping (no shared filesystem) -----------------------------


def test_ssh_ship_job_dir_to_host_without_shared_mount(tmp_path, monkeypatch):
    """VERDICT r2 #3 unit: the staged job dir is tar-piped to the host's
    own disk (remote_job_root), and every job-dir path in the task env is
    rewritten to the shipped location (ref: HDFS upload + extractResources,
    TonyClient.java:229-310, util/Utils.java:750)."""
    import json

    from tony_tpu.coordinator import launcher as L

    job = tmp_path / "staging" / "application_ship1"
    job.mkdir(parents=True)
    (job / "tony-final.json").write_text('{"conf": true}')
    (job / "payload.py").write_text("print('hi')")
    (job / "venv").mkdir()
    (job / "venv" / "marker").write_text("v1")
    remote_root = tmp_path / "remote_disk"
    remote_root.mkdir()

    dump = tmp_path / "agent_env.json"
    agent = tmp_path / "dump_env.py"
    agent.write_text("import json, os, sys\n"
                     "json.dump(dict(os.environ), open(sys.argv[1], 'w'))\n")
    monkeypatch.setattr(L, "REMOTE_AGENT_CMD", f"python3 {agent} {dump}")

    exits = []
    lch = L.SshLauncher(
        ["fakehost"], on_exit=lambda t, c: exits.append((t, c)),
        ssh_bin=FAKE_SSH, ship_job_dir=str(job),
        remote_job_root=str(remote_root))
    task = Task(role="worker", index=0)
    lch.launch(task, {"TONY_JOB_DIR": str(job),
                      "TONY_CONF_PATH": str(job / "tony-final.json"),
                      "TONY_TASK_COMMAND": f"{job}/venv/bin/python payload.py"},
               os.path.join(str(tmp_path), "w.log"))
    assert _wait_for(lambda: exits == [("worker:0", 0)]), exits

    shipped = remote_root / "application_ship1"
    assert (shipped / "tony-final.json").read_text() == '{"conf": true}'
    assert (shipped / "payload.py").exists()
    assert (shipped / "venv" / "marker").read_text() == "v1"
    env = json.loads(dump.read_text())
    assert env["TONY_JOB_DIR"] == str(shipped)
    assert env["TONY_CONF_PATH"] == str(shipped / "tony-final.json")
    assert env["TONY_TASK_COMMAND"].startswith(str(shipped))

    # second task on the same host must NOT re-ship (the remote copy is
    # live state by then — e.g. checkpoints)
    (shipped / "tony-final.json").write_text('{"mutated": 1}')
    lch.launch(Task(role="worker", index=1),
               {"TONY_JOB_DIR": str(job)},
               os.path.join(str(tmp_path), "w1.log"))
    assert _wait_for(lambda: len(exits) == 2), exits
    assert (shipped / "tony-final.json").read_text() == '{"mutated": 1}'
    lch.stop_all()


def test_ssh_ship_skips_shared_mount(tmp_path, monkeypatch):
    """A host that already sees the job dir (NFS/GCS-fuse) is probed and
    skipped: no tar stream overwrites the live dir."""
    from tony_tpu.coordinator import launcher as L

    job = tmp_path / "application_shared"
    job.mkdir()
    (job / "tony-final.json").write_text("{}")
    monkeypatch.setattr(L, "REMOTE_AGENT_CMD", "true")

    shipped = []
    exits = []
    lch = L.SshLauncher(["h"], on_exit=lambda t, c: exits.append(t),
                        ssh_bin=FAKE_SSH, ship_job_dir=str(job))
    monkeypatch.setattr(lch, "_ship",
                        lambda host: shipped.append(host))
    lch.launch(Task(role="worker", index=0), {},
               os.path.join(str(tmp_path), "w.log"))
    assert _wait_for(lambda: len(exits) == 1)
    assert shipped == []  # probe found the marker; no stream sent
    lch.stop_all()


def test_ssh_ship_e2e_no_shared_mount(tmp_path):
    """VERDICT r2 #3 e2e: full job where the payload reaches the host ONLY
    via shipping — it is staged from src-dir into the job dir, tar-piped
    to the host's private root, and runs from the shipped copy with a
    rewritten TONY_JOB_DIR."""
    import textwrap

    src = tmp_path / "src"
    src.mkdir()
    (src / "train.py").write_text(textwrap.dedent("""\
        import os, sys
        jd = os.environ["TONY_JOB_DIR"]
        root = os.environ["EXPECT_REMOTE_ROOT"]
        assert jd.startswith(root), (jd, root)
        assert os.getcwd() == jd, (os.getcwd(), jd)
        assert os.path.exists(os.path.join(jd, "tony-final.json"))
        assert os.path.exists(os.path.join(jd, "train.py"))
        sys.exit(0)
        """))
    remote_root = tmp_path / "remote_disk"
    remote_root.mkdir()
    with MiniTonyCluster() as cluster:
        conf = script_conf(cluster, "train.py", {"worker": 2})
        conf.set("tony.application.src-dir", str(src))
        conf.set("tony.application.launch-mode", "ssh")
        conf.set("tony.application.hosts", "hostA")
        conf.set("tony.application.ssh-bin", FAKE_SSH)
        conf.set("tony.application.remote-pythonpath", REPO_ROOT)
        conf.set("tony.ssh.remote-job-root", str(remote_root))
        conf.set("tony.application.shell-env",
                 f"EXPECT_REMOTE_ROOT={remote_root}")
        client = cluster.submit(conf)
        assert client.final_status["status"] == "SUCCEEDED", \
            client.final_status
        # the payload genuinely travelled: the shipped tree exists under
        # the host's own root
        shipped = remote_root / os.path.basename(client.job_dir)
        assert (shipped / "train.py").exists()


def test_ssh_host_down_mid_gang_retry_resume():
    """VERDICT r2 #6: an ssh host dying mid-gang (agent process group
    SIGKILLed, no RPC result — only the dropped ssh client) must drive
    the failure-detection -> retry -> resume path end-to-end: the retry
    epoch relaunches the gang and every worker resumes its progress
    (ref reset semantics: ApplicationMaster.java:612-628)."""
    with MiniTonyCluster() as cluster:
        conf = script_conf(cluster,
                           os.path.join(SCRIPTS, "ssh_host_down_resume.py"),
                           {"worker": 2})
        conf.set("tony.application.launch-mode", "ssh")
        conf.set("tony.application.hosts", "vmA,vmB")
        conf.set("tony.application.ssh-bin", FAKE_SSH)
        conf.set("tony.application.remote-pythonpath", REPO_ROOT)
        conf.set("tony.coordinator.retry-count", 1)
        # SPMD gang semantics: one lost member fails the gang (the
        # reference DEFAULT tolerates partial worker failure,
        # TonySession.java:331-344 — wrong for jax.distributed jobs)
        conf.set("tony.application.fail-on-worker-failure-enabled", True)
        conf.set("tony.application.shell-env", f"TONY_REPO_ROOT={REPO_ROOT}")
        client = cluster.submit(conf)
        assert client.final_status["status"] == "SUCCEEDED", \
            client.final_status
        assert client.final_status["session_id"] == 1, client.final_status
        job_dir = client.job_dir
        for idx in ("0", "1"):
            path = os.path.join(job_dir,
                                f"hostdown-progress-worker-{idx}.txt")
            assert open(path).read().strip() == "15", (idx, path)
        # the relaunched epoch genuinely RESUMED (some log carries the
        # markers; user-process stdout lands in the *-user.log files)
        import glob

        logs = "".join(open(p).read() for p in
                       glob.glob(os.path.join(job_dir, "logs", "*.log")))
        assert "host dying now" in logs
        assert "resumed at step" in logs
