"""DAG scheduler tests (ref: TestTaskScheduler.java: DAG detection,
dependency release; TestTonyE2E job-type DAG scheduling :271)."""

import pytest

from tony_tpu.config import TonyConf
from tony_tpu.scheduler import CycleError, TaskScheduler
from tony_tpu.session import Session


def make(roles: dict, deps: dict | None = None, stages: dict | None = None):
    conf = TonyConf()
    for role, n in roles.items():
        conf.set(f"tony.{role}.instances", n)
    for role, d in (deps or {}).items():
        conf.set(f"tony.{role}.depends-on", d)
    for k, v in (stages or {}).items():
        conf.set(k, v)
    session = Session(conf)
    allocated = []
    sched = TaskScheduler(session, lambda req: allocated.append(req.role), conf)
    return session, sched, allocated


def complete_role(session, sched, role):
    for i in range(len(session.tasks[role])):
        session.init_task(role)
        session.on_task_completed(role, i, 0)
    return sched.on_role_instance_completed(role)


def test_no_deps_all_scheduled():
    _, sched, allocated = make({"worker": 2, "ps": 1})
    sched.schedule()
    assert sorted(allocated) == ["ps", "worker"]
    assert sched.all_scheduled()


def test_dependency_release():
    session, sched, allocated = make(
        {"prep": 1, "worker": 2}, deps={"worker": "prep"}
    )
    sched.schedule()
    assert allocated == ["prep"]
    released = complete_role(session, sched, "prep")
    assert released == ["worker"]
    assert sched.all_scheduled()


def test_chain_release_partial_not_enough():
    session, sched, allocated = make({"a": 2, "b": 1}, deps={"b": "a"})
    sched.schedule()
    session.init_task("a")
    session.on_task_completed("a", 0, 0)
    assert sched.on_role_instance_completed("a") == []  # a:1 still pending
    session.init_task("a")
    session.on_task_completed("a", 1, 0)
    assert sched.on_role_instance_completed("a") == ["b"]


def test_cycle_detected():
    with pytest.raises(CycleError):
        make({"a": 1, "b": 1}, deps={"a": "b", "b": "a"})


def test_unknown_dependency():
    with pytest.raises(CycleError):
        make({"a": 1}, deps={"a": "ghost"})


def test_stage_split_implicit_deps():
    """prepare/training stages add implicit edges (ref: Utils.java:377-403)."""
    session, sched, allocated = make(
        {"etl": 1, "worker": 2},
        stages={
            "tony.application.prepare-stage": "etl",
            "tony.application.training-stage": "worker",
        },
    )
    sched.schedule()
    assert allocated == ["etl"]
    assert sched.blocked_roles() == {"worker"}
    complete_role(session, sched, "etl")
    assert sched.all_scheduled()


def test_stage_autofill_training_when_only_prepare_set():
    """Ref: Utils.ensureStagedTasksIntegrity — one stage set auto-fills the
    other with the remaining roles."""
    session, sched, allocated = make(
        {"etl": 1, "worker": 2},
        stages={"tony.application.prepare-stage": "etl"},
    )
    sched.schedule()
    assert allocated == ["etl"]
    complete_role(session, sched, "etl")
    assert "worker" in allocated


def test_stage_untracked_roles_do_not_gate_training():
    """Untracked prepare roles (long-running ps) must not block training
    (ref: Utils.java:380 excludes untrackedJobTypes)."""
    session, sched, allocated = make(
        {"etl": 1, "ps": 1, "worker": 1},
        stages={
            "tony.application.prepare-stage": "etl,ps",
            "tony.application.training-stage": "worker",
        },
    )
    sched.schedule()
    assert set(allocated) == {"etl", "ps"}
    complete_role(session, sched, "etl")  # ps never completes
    assert "worker" in allocated


def test_stage_unknown_role_rejected():
    with pytest.raises(CycleError, match="unknown roles"):
        make({"worker": 1}, stages={"tony.application.prepare-stage": "et1"})


def test_diamond_dag():
    session, sched, allocated = make(
        {"a": 1, "b": 1, "c": 1, "d": 1},
        deps={"b": "a", "c": "a", "d": "b,c"},
    )
    sched.schedule()
    assert allocated == ["a"]
    complete_role(session, sched, "a")
    assert set(allocated) == {"a", "b", "c"}
    complete_role(session, sched, "b")
    assert "d" not in allocated
    complete_role(session, sched, "c")
    assert "d" in allocated
