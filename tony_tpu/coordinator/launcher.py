"""Task launchers: how the coordinator places agent processes on hosts.

Reference split: YARN RM allocates containers (TaskScheduler ->
amRMClient.addContainerRequest) and the AM's ContainerLauncher starts the
TaskExecutor on the NM (ApplicationMaster.ContainerLauncher.run :1154-1222).
On TPU there is no incremental container negotiation — a slice's hosts are
created *together* (SURVEY.md section 7.9a) — so a Launcher simply places
one agent process per task instance:

- ``LocalProcessLauncher``: agents as local subprocesses (MiniCluster-style
  in-process cluster; also the single-TPU-VM mode where every task shares
  the host and gets a device subset).
- ``SshLauncher``: agents on remote TPU-VM hosts over ssh, one host per
  task round-robin (the gcloud `tpu-vm ssh --worker=all` shape).

Launchers also watch for process exit so a task that dies before
registering its result is still detected (the onContainersCompleted
backup path, ApplicationMaster.java:1050-1068).
"""

from __future__ import annotations

import logging
import os
import shlex
import signal
import subprocess
import sys
import threading
from typing import Callable

from tony_tpu.session import Task

log = logging.getLogger(__name__)

OnExit = Callable[[str, int], None]  # (task_id, exit_code)


class Launcher:
    def launch(self, task: Task, env: dict[str, str], log_path: str) -> None:
        raise NotImplementedError

    def stop_all(self) -> None:
        raise NotImplementedError

    def kill_task(self, task_id: str) -> bool:
        raise NotImplementedError


class LocalProcessLauncher(Launcher):
    """Spawn ``python -m tony_tpu.agent`` per task on this host."""

    def __init__(self, on_exit: OnExit, workdir: str | None = None):
        self.on_exit = on_exit
        self.workdir = workdir
        self._procs: dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()
        # stop_all bumps the generation: exits from a torn-down generation
        # never reach on_exit, while relaunches (coordinator retry, elastic
        # resize) keep working exit detection
        self._gen = 0

    def launch(self, task: Task, env: dict[str, str], log_path: str) -> None:
        full_env = dict(os.environ)
        full_env.update(env)
        os.makedirs(os.path.dirname(log_path) or ".", exist_ok=True)
        out = open(log_path, "ab", buffering=0)
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m", "tony_tpu.agent"],
                env=full_env,
                cwd=self.workdir,
                stdout=out,
                stderr=subprocess.STDOUT,
                start_new_session=True,
            )
        finally:
            out.close()
        with self._lock:
            self._procs[task.id] = proc
            gen = self._gen
        threading.Thread(
            target=self._wait, args=(task.id, proc, gen), daemon=True,
            name=f"wait-{task.id}",
        ).start()
        log.info("launched %s as pid %d (log: %s)", task.id, proc.pid, log_path)

    def pause_exits(self) -> None:
        """Bump the generation so in-flight process exits never reach
        on_exit — wrapper launchers (docker) call this before their own
        teardown kills complete the attached processes."""
        with self._lock:
            self._gen += 1

    def attach(self, task_id: str, proc: subprocess.Popen) -> None:
        """Register an externally-spawned process (ssh/docker wrapper) for
        exit detection under this launcher's generation handshake."""
        with self._lock:
            self._procs[task_id] = proc
            gen = self._gen
        threading.Thread(target=self._wait, args=(task_id, proc, gen),
                         daemon=True, name=f"wait-{task_id}").start()

    def _wait(self, task_id: str, proc: subprocess.Popen, gen: int) -> None:
        code = proc.wait()
        with self._lock:
            if self._procs.get(task_id) is proc:
                self._procs.pop(task_id)
            if gen != self._gen:
                return
        self.on_exit(task_id, code)

    def kill_task(self, task_id: str) -> bool:
        with self._lock:
            proc = self._procs.get(task_id)
        if proc is None:
            return False
        _kill_tree(proc)
        return True

    def stop_all(self) -> None:
        with self._lock:
            self._gen += 1
            procs = list(self._procs.values())
        for proc in procs:
            _kill_tree(proc)


def _kill_tree(proc: subprocess.Popen) -> None:
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        try:
            proc.kill()
        except ProcessLookupError:
            pass


def docker_container_name(task: Task) -> str:
    """Epoch-qualified name: a relaunch after resize/retry must not race
    the async ``--rm`` cleanup of the previous epoch's same-id container."""
    return f"tony-s{task.session_id}-{task.id.replace(':', '-')}"


def build_docker_command(task: Task, env: dict[str, str], image: str,
                         mounts: list[str] | None = None,
                         extra_args: list[str] | None = None,
                         docker_bin: str = "docker",
                         workdir: str = "") -> list[str]:
    """Build the ``docker run`` argv that hosts one agent.

    Reference analog: YARN docker containers via env injection
    (HadoopCompatibleAdapter.getContainerEnvForDocker — ENV_CONTAINER_TYPE,
    image, mounts). On TPU-VMs the accelerator needs ``--privileged`` +
    host networking so the container sees /dev/accel* and the ICI NICs;
    mounts use docker's ``host:container[:ro]`` syntax directly.
    """
    argv = [docker_bin, "run", "--rm", "--name", docker_container_name(task),
            "--net=host", "--privileged"]
    # container paths already covered by user mounts — docker rejects
    # duplicate mount points, so the implicit workdir mount must yield
    user_targets = {m.split(":")[1] for m in mounts or [] if ":" in m}
    if workdir and workdir not in user_targets:
        # the job dir carries the payload script, localized resources, and
        # venv — mount it at the same path and start there, mirroring
        # LocalProcessLauncher's workdir=job_dir
        argv += ["-v", f"{workdir}:{workdir}"]
    if workdir:
        argv += ["-w", workdir]
    for mount in mounts or []:
        argv += ["-v", mount]
    for k, v in env.items():
        argv += ["-e", f"{k}={v}"]
    argv += extra_args or []
    argv += [image, "python3", "-m", "tony_tpu.agent"]
    return argv


class DockerLauncher(Launcher):
    """Run each agent inside a docker container on this host.

    Reference: tony.docker.enabled/tony.docker.containers.image keys +
    docker env injection (TonyConfigurationKeys DOCKER_*,
    HadoopCompatibleAdapter.getContainerEnvForDocker). Exit detection rides
    the local ``docker run`` process (it stays attached); kill goes through
    ``docker kill`` so the in-container process group dies with it.
    """

    def __init__(self, image: str, on_exit: OnExit,
                 mounts: list[str] | None = None,
                 extra_args: list[str] | None = None,
                 docker_bin: str = "docker", workdir: str = ""):
        if not image:
            raise ValueError("DockerLauncher needs an image")
        self.image = image
        self.mounts = mounts or []
        self.extra_args = extra_args or []
        self.docker_bin = docker_bin
        self.workdir = workdir
        self._local = LocalProcessLauncher(on_exit)
        self._names: dict[str, str] = {}
        self._names_lock = threading.Lock()

    def launch(self, task: Task, env: dict[str, str], log_path: str) -> None:
        argv = build_docker_command(task, env, self.image, self.mounts,
                                    self.extra_args, self.docker_bin,
                                    workdir=self.workdir)
        os.makedirs(os.path.dirname(log_path) or ".", exist_ok=True)
        out = open(log_path, "ab", buffering=0)
        try:
            proc = subprocess.Popen(argv, stdout=out,
                                    stderr=subprocess.STDOUT,
                                    start_new_session=True)
        finally:
            out.close()
        with self._names_lock:
            self._names[task.id] = docker_container_name(task)
        self._local.attach(task.id, proc)
        log.info("launched %s in docker image %s (pid %d)", task.id,
                 self.image, proc.pid)

    def _docker_kill(self, name: str) -> None:
        subprocess.run([self.docker_bin, "kill", name],
                       stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                       check=False)

    def kill_task(self, task_id: str) -> bool:
        with self._names_lock:
            name = self._names.get(task_id)
        if name:
            self._docker_kill(name)
        return self._local.kill_task(task_id)

    def stop_all(self) -> None:
        # bump the generation FIRST so teardown exits never reach on_exit
        # (the docker kills below complete each attached `docker run`)
        self._local.pause_exits()
        with self._names_lock:
            names = list(self._names.values())
            self._names.clear()
        for name in names:
            self._docker_kill(name)
        self._local.stop_all()


class SshLauncher(Launcher):
    """Place agents on remote hosts over ssh, round-robin per task.

    The remote host needs the same repo importable at ``remote_pythonpath``
    (TPU-VM images share a disk image, the NFS/GCS-fuse staging dir carries
    the job files). Exit detection rides the local ssh process's exit code.
    """

    def __init__(self, hosts: list[str], on_exit: OnExit,
                 remote_pythonpath: str = "", ssh_opts: list[str] | None = None):
        if not hosts:
            raise ValueError("SshLauncher needs at least one host")
        self.hosts = hosts
        self.on_exit = on_exit
        self.remote_pythonpath = remote_pythonpath
        self.ssh_opts = ssh_opts or ["-o", "StrictHostKeyChecking=no",
                                     "-o", "BatchMode=yes"]
        self._next = 0
        self._local = LocalProcessLauncher(on_exit)

    def launch(self, task: Task, env: dict[str, str], log_path: str) -> None:
        host = self.hosts[self._next % len(self.hosts)]
        self._next += 1
        exports = " ".join(
            f"export {k}={shlex.quote(str(v))};" for k, v in env.items()
        )
        pp = f"export PYTHONPATH={shlex.quote(self.remote_pythonpath)}:$PYTHONPATH;" \
            if self.remote_pythonpath else ""
        remote_cmd = f"{exports} {pp} exec python3 -m tony_tpu.agent"
        os.makedirs(os.path.dirname(log_path) or ".", exist_ok=True)
        out = open(log_path, "ab", buffering=0)
        try:
            proc = subprocess.Popen(
                ["ssh", *self.ssh_opts, host, remote_cmd],
                stdout=out,
                stderr=subprocess.STDOUT,
                start_new_session=True,
            )
        finally:
            out.close()
        self._local.attach(task.id, proc)
        log.info("launched %s on %s via ssh (pid %d)", task.id, host, proc.pid)

    def kill_task(self, task_id: str) -> bool:
        return self._local.kill_task(task_id)

    def stop_all(self) -> None:
        self._local.stop_all()
