from tony_tpu.scheduler.dag import CycleError, TaskScheduler

__all__ = ["TaskScheduler", "CycleError"]
