"""Speculative decoding (prompt-lookup draft + batched verify).

The exactness anchor: greedy outputs with ``speculate_k > 0`` are
token-for-token identical to speculation-off serving and to a solo
``generate()`` — across mixed batches (speculating, non-speculating,
sampled slots in ONE dispatch), prefix-store hits, mid-window EOS, and
donation-after-rejection. The acceptance rule compares drafts against
the verify pass's own greedy verdicts, so a rejected draft costs only
the window positions it rode in on; rewind is pointer arithmetic
(junk K/V beyond the accepted length is invisible under per-row masked
visibility). CPU-only, exact-parity assertions throughout.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import Transformer, TransformerConfig, generate
from tony_tpu.models.generate import multi_decode_step, single_decode_step
from tony_tpu.serve import Request, Server
from tony_tpu.serve.engine import _bucket_pow2, _propose_draft


@pytest.fixture(scope="module")
def tiny():
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=64,
                            dtype=jnp.float32,
                            attention_backend="reference")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _solo(model, params, prompt, n, eos_id=-1):
    out = generate(model, params, jnp.asarray([prompt], jnp.int32),
                   max_new_tokens=n, eos_id=eos_id)
    return np.asarray(out)[0].tolist()


def _run(model, params, reqs, **kw):
    server = Server(model, params, min_bucket=8, **kw)
    return server, {r.id: (r.tokens, r.finish_reason)
                    for r in server.run(reqs)}


# a repetitive prompt is the prompt-lookup sweet spot; greedy decode of
# the tiny random model also falls into cycles the drafter then rides
REP = [1, 2, 3, 4] * 4
REP2 = [5, 6, 7, 5, 6, 7, 5, 6]


# --------------------------------------------------------------- drafter


def test_propose_draft_basics():
    ctx = np.asarray([9, 1, 2, 3, 7, 7, 1, 2, 3], np.int32)
    # suffix [1,2,3] matched at position 1 -> proposes what followed: 7 7 1
    np.testing.assert_array_equal(_propose_draft(ctx, 3), [7, 7, 1])
    # k clamps the proposal length
    np.testing.assert_array_equal(_propose_draft(ctx, 1), [7])
    # proposal never exceeds the context tail
    np.testing.assert_array_equal(
        _propose_draft(ctx, 50), [7, 7, 1, 2, 3])
    # no n-gram recurrence at any n -> empty
    assert _propose_draft(np.arange(8, dtype=np.int32), 4).size == 0
    # degenerate contexts
    assert _propose_draft(np.asarray([5], np.int32), 4).size == 0
    assert _propose_draft(np.asarray([], np.int32), 4).size == 0


def test_propose_draft_prefers_longest_then_most_recent():
    # [2, 3] occurs twice before the suffix; the MOST RECENT occurrence
    # (followed by 8) wins over the older one (followed by 4)
    ctx = np.asarray([1, 2, 3, 4, 2, 3, 8, 0, 2, 3], np.int32)
    np.testing.assert_array_equal(_propose_draft(ctx, 2), [8, 0])
    # a longer suffix match beats a more recent shorter one:
    # suffix [3, 5]; [3, 5] occurs at pos 1 (followed by 9); plain [5]
    # also occurs later — the bigram match must win
    ctx = np.asarray([0, 3, 5, 9, 5, 1, 3, 5], np.int32)
    np.testing.assert_array_equal(_propose_draft(ctx, 1), [9])


def test_bucket_pow2():
    assert [_bucket_pow2(n) for n in (1, 2, 3, 4, 5, 8, 9)] == \
        [1, 2, 4, 4, 8, 8, 16]


# --------------------------------------------- multi-token decode window


@pytest.mark.parametrize("variant", [
    "scan_int8",
    # learned positions are also covered by the mid-window EOS parity
    # test's GPT-2-flavor server; the direct unit is slow-tier
    pytest.param("learned", marks=pytest.mark.slow)])
def test_multi_decode_step_matches_single_steps(variant):
    """The [b, k] window scores and caches exactly what k sequential
    per-slot single steps would (the transformer-level contract the
    verify dispatch builds on). Two configs cover the four risk axes
    in two compiles: scan_layers stacked leaves + int8-KV scales +
    RoPE together, learned positions (the 2-D pos_emb gather) alone;
    the plain-RoPE path is exercised by every serve parity test."""
    kwargs = {
        "learned": dict(positional="learned", norm="layer",
                        use_bias=True),
        "scan_int8": dict(scan_layers=True, kv_cache_quant=True),
    }[variant]
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=32,
                            dtype=jnp.float32,
                            attention_backend="reference", **kwargs)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((2, 4), jnp.int32))["params"]
    from tony_tpu.models import init_cache

    prompt = jnp.asarray([[1, 2, 3, 4], [5, 6, 7, 8]], jnp.int32)
    cache = init_cache(model, params, 2)
    _, vars_ = model.apply({"params": params, "cache": cache}, prompt,
                           decode=True, mutable=["cache"])
    cache0 = vars_["cache"]
    toks = jnp.asarray([[9, 11, 13], [10, 12, 14]], jnp.int32)
    cache_a, seq_logits = cache0, []
    for j in range(3):
        cache_a, last = single_decode_step(
            model, params, cache_a, toks[:, j],
            positions=jnp.asarray([4 + j, 4 + j], jnp.int32))
        seq_logits.append(last)
    seq_logits = jnp.stack(seq_logits, axis=1)
    positions = jnp.asarray([[4, 5, 6], [4, 5, 6]], jnp.int32)
    cache_b, win_logits = multi_decode_step(model, params, cache0, toks,
                                            positions)
    np.testing.assert_allclose(np.asarray(win_logits),
                               np.asarray(seq_logits), atol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(cache_a),
                    jax.tree_util.tree_leaves(cache_b)):
        if a.ndim >= 3:
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                atol=1e-5)


@pytest.mark.slow  # the EMA/donation tier-1 tests exercise padding
# rows on every mixed-width verify dispatch; the direct unit is slow
def test_multi_decode_padding_rows_drop(tiny):
    """Window entries with position -1 leave the cache bit-identical to
    a run without them (a slot drafting less than the batch window must
    not dirty ANY cache position)."""
    model, params = tiny
    from tony_tpu.models import init_cache

    prompt = jnp.asarray([[1, 2, 3, 4], [5, 6, 7, 8]], jnp.int32)
    cache = init_cache(model, params, 2)
    _, vars_ = model.apply({"params": params, "cache": cache}, prompt,
                           decode=True, mutable=["cache"])
    cache0 = vars_["cache"]
    toks = jnp.asarray([[9, 11, 13], [10, 0, 0]], jnp.int32)
    positions = jnp.asarray([[4, 5, 6], [4, -1, -1]], jnp.int32)
    cache_b, _ = multi_decode_step(model, params, cache0, toks, positions)
    cache_c, _ = single_decode_step(
        model, params, cache0, jnp.asarray([9, 10], jnp.int32),
        positions=jnp.asarray([4, 4], jnp.int32))
    for b, c in zip(jax.tree_util.tree_leaves(cache_b),
                    jax.tree_util.tree_leaves(cache_c)):
        if b.ndim >= 4:  # row 1: single write at 4, padding dropped
            # allclose, not equal: the written K/V rides a [b, 3, d]
            # projection here vs [b, 1, d] there — reduction order may
            # differ in the last float bit, junk positions not at all
            np.testing.assert_allclose(np.asarray(b[1]),
                                       np.asarray(c[1]), atol=1e-6)


# ----------------------------------------------------------- exactness


def test_greedy_parity_spec_on_off_mixed_batch(tiny):
    """The acceptance anchor: speculation on vs off vs solo generate,
    token for token, over a mixed batch — two drafting slots, one
    lookup-miss slot, one SAMPLED slot riding the same verify
    dispatches at one real token per round. chunk_steps=2 keeps the
    two drafters' expected yield above the batch-drag gate, so the run
    interleaves verify rounds with chunk rounds (budget tails)."""
    model, params = tiny

    def reqs():
        return [Request(list(REP), max_new_tokens=16, id="rep"),
                Request([7, 9, 11], max_new_tokens=12, id="plain"),
                Request(list(REP2), max_new_tokens=12, id="rep2"),
                Request([9, 9, 2], max_new_tokens=8, temperature=0.9,
                        top_k=8, seed=5, id="samp")]

    off, ro = _run(model, params, reqs(), batch_size=3, chunk_steps=2)
    on, rn = _run(model, params, reqs(), batch_size=3, chunk_steps=2,
                  speculate_k=4)
    assert ro == rn
    assert on.spec_rounds > 0 and on.spec_drafted > 0
    assert 0 <= on.spec_accepted <= on.spec_drafted
    for rid, p, n in [("rep", REP, 16), ("plain", [7, 9, 11], 12)]:
        assert rn[rid][0] == _solo(model, params, p, n), rid


@pytest.mark.slow  # the slow bench datum below asserts the same bound
def test_spec_reduces_dispatches_and_is_exact(tiny):
    """On a repetitive workload at chunk_steps=1 (the streaming
    default) speculation must strictly reduce decode dispatches while
    leaving every output byte-identical."""
    model, params = tiny
    rng = np.random.default_rng(0)
    prompts = [(rng.integers(1, 60, size=3).tolist() * 6)[:14]
               for _ in range(4)]

    def reqs():
        return [Request(list(p), max_new_tokens=16, id=i)
                for i, p in enumerate(prompts)]

    off, ro = _run(model, params, reqs(), batch_size=3, chunk_steps=1)
    on, rn = _run(model, params, reqs(), batch_size=3, chunk_steps=1,
                  speculate_k=8)
    assert ro == rn
    assert on.dispatches < off.dispatches, (on.dispatches,
                                            off.dispatches)
    assert on.spec_accepted > 0


def test_mid_window_eos_trims_exactly():
    """EOS landing inside a verify window: the slot reports up to and
    including the stop token, overshoot past it is trimmed, and the
    result matches spec-off and solo. Needs a model whose greedy
    continuation CHANGES phase (run of one token, then another) so the
    drafter is mid-stride — with rejections — when EOS appears; the
    GPT-2-flavor tiny config does that where the RoPE one collapses to
    a single-token fixed point immediately."""
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=64,
                            dtype=jnp.float32,
                            attention_backend="reference",
                            positional="learned", norm="layer",
                            use_bias=True)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    prompt = [20, 30, 40, 50]
    solo = _solo(model, params, prompt, 18)
    first = {}
    for i, t in enumerate(solo):
        first.setdefault(t, i)
    # the token appearing LATEST for the first time: speculation has
    # been running (and transitioning phases) for many rounds by then
    eos, idx = max(first.items(), key=lambda kv: kv[1])
    assert idx >= 3, (solo, eos, idx)  # the premise of the test
    off, ro = _run(model, params,
                   [Request(list(prompt), max_new_tokens=18, id="e")],
                   batch_size=1, chunk_steps=1, eos_id=eos)
    on, rn = _run(model, params,
                  [Request(list(prompt), max_new_tokens=18, id="e")],
                  batch_size=1, chunk_steps=1, eos_id=eos,
                  speculate_k=6)
    assert ro == rn
    assert rn["e"][0] == solo[:idx + 1]
    assert rn["e"][1] == "eos"
    assert on.spec_rounds > 0 and on.spec_drafted > 0


@pytest.mark.slow  # per-budget solo compiles; the tier-1 parity tests
# already pin exact-budget finishes via finish_reason "length"
def test_budget_cannot_overshoot_under_speculation(tiny):
    """A draft can land accepted+1 tokens, so the drafter clamps to
    remaining-1: exactly max_new_tokens come back, never more, and the
    cache window never writes past max_seq_len."""
    model, params = tiny
    # each budget compiles its own solo-generate program (static
    # max_new_tokens): three cover the degenerate/odd/long cases
    for budget in (1, 3, 10):
        on, rn = _run(model, params,
                      [Request(list(REP), max_new_tokens=budget,
                               id="b")],
                      batch_size=1, chunk_steps=1, speculate_k=8)
        assert len(rn["b"][0]) == budget
        assert rn["b"][0] == _solo(model, params, REP, budget)
    # a prompt near max_seq_len: budget clamps, speculation must not
    # scribble past the cache end (max_seq_len 64)
    long_p = (REP * 4)[:56]
    on, rn = _run(model, params,
                  [Request(list(long_p), max_new_tokens=32, id="l")],
                  batch_size=1, chunk_steps=1, speculate_k=8)
    assert len(rn["l"][0]) == 8  # 64 - 56
    assert rn["l"][0] == _solo(model, params, long_p, 8)


@pytest.mark.slow  # the tier-1 mixed-batch parity test co-schedules
# a sampled slot already; this isolates the draw-chain claim
def test_sampled_requests_keep_their_draw_chain(tiny):
    """A sampled request advances its rng exactly once per emitted
    token in BOTH paths, so co-scheduling with speculating slots never
    moves its draws."""
    model, params = tiny

    def samp():
        return Request([9, 9, 2], max_new_tokens=8, temperature=0.9,
                       top_k=8, seed=7, id="s")

    _, alone = _run(model, params, [samp()], batch_size=2,
                    chunk_steps=1)
    _, mixed = _run(model, params,
                    [samp(), Request(list(REP), max_new_tokens=14,
                                     id="rep")],
                    batch_size=2, chunk_steps=1, speculate_k=6)
    assert mixed["s"] == alone["s"]


def test_prefix_store_hits_with_speculation(tiny):
    """Prefix KV reuse and speculation compose: shared-preamble +
    exact-repeat traffic with both on is byte-identical to both off,
    and both stores register work saved."""
    model, params = tiny
    shared = list(REP)

    def reqs():
        return [Request(shared + [21, 22], max_new_tokens=8, id=0),
                Request(shared + [23, 24], max_new_tokens=8, id=1),
                Request(shared + [21, 22], max_new_tokens=8, id=2)]

    plain, rp = _run(model, params, reqs(), batch_size=1,
                     chunk_steps=1)
    both, rb = _run(model, params, reqs(), batch_size=1, chunk_steps=1,
                    prefix_cache_mb=8, speculate_k=6)
    assert rp == rb
    assert both.prefix_hits > 0
    assert both.spec_rounds > 0


def test_donation_after_rejection_seeds_next_turn(tiny, monkeypatch):
    """Junk drafts are rejected EVERY round (a deliberately wrong
    drafter), scribbling junk K/V past each accepted position — then
    the finished slot donates its row to the prefix store and the next
    turn seeds from it. The donated row must reflect only accepted
    tokens: the second turn's output stays byte-identical to cold
    serving."""
    import tony_tpu.serve.engine as eng

    model, params = tiny
    first = [7, 9, 11, 13]
    solo1 = _solo(model, params, first, 6)
    second = first + solo1 + [3]

    def junk_draft(ctx, k, max_ngram=3):
        # propose the NON-greedy continuation: one token the model will
        # reject (63 unless the context suggests the model wants 63)
        t = 63 if ctx[-1] != 63 else 62
        return np.asarray([t], np.int32)

    monkeypatch.setattr(eng, "_propose_draft", junk_draft)
    server = Server(model, params, batch_size=1, min_bucket=8,
                    chunk_steps=1, prefix_cache_mb=8, speculate_k=4)
    # EMA floor off: keep drafting (and getting rejected) to the end
    server.SPEC_EMA_DISABLE = -1.0
    out1 = {r.id: r for r in server.run(
        [Request(list(first), max_new_tokens=6, id="t1")])}
    assert out1["t1"].tokens == solo1
    assert out1["t1"].drafted > 0 and out1["t1"].accepted == 0
    # turn 2 on the SAME server: prompt extends turn 1's sequence, so
    # it seeds from the donated row (prefix hit) — junk K/V written by
    # the rejected drafts must be invisible
    out2 = {r.id: r for r in server.run(
        [Request(list(second), max_new_tokens=6, id="t2")])}
    assert server.prefix_hits > 0
    assert server.prefix_hit_tokens > 0
    cold, rc = _run(model, params,
                    [Request(list(second), max_new_tokens=6, id="t2")],
                    batch_size=1, chunk_steps=1)
    assert out2["t2"].tokens == rc["t2"][0]


def test_ema_auto_disables_hopeless_drafting(tiny, monkeypatch):
    """A slot whose proposals keep getting rejected stops drafting
    (acceptance EMA falls below the floor), so the worst case decays to
    the plain chunked path plus a host-side lookup."""
    import tony_tpu.serve.engine as eng

    model, params = tiny

    def junk_draft(ctx, k, max_ngram=3):
        t = 63 if ctx[-1] != 63 else 62
        return np.asarray([t], np.int32)

    monkeypatch.setattr(eng, "_propose_draft", junk_draft)
    server = Server(model, params, batch_size=1, min_bucket=8,
                    chunk_steps=1, speculate_k=4)
    out = {r.id: r for r in server.run(
        [Request([7, 9, 11], max_new_tokens=20, id="x")])}
    assert out["x"].tokens == _solo(model, params, [7, 9, 11], 20)
    # EMA 1 -> 0.5 -> 0.25 -> below floor after ~2-3 rejected rounds
    assert server._spec_ema[0] < server.SPEC_EMA_DISABLE
    assert 0 < server.spec_rounds <= 3
    assert server.spec_accepted == 0
    # a fresh tenant in the same slot re-enables drafting
    out2 = {r.id: r for r in server.run(
        [Request([5, 6], max_new_tokens=4, id="y")])}
    assert server.spec_rounds > 0
    assert "y" in out2


@pytest.mark.slow  # deploy-config insurance beyond the named
# acceptance paths; the flash variant interprets pallas off-TPU
@pytest.mark.parametrize("knob", ["flash", "window"])
def test_spec_parity_on_deploy_configs(tiny, knob):
    """Speculation stays exact on deployment configs: the pallas
    flash-decode kernel (chunk rounds run flash, verify windows the
    einsum path — two scorers, one output) and sliding-window
    attention (the per-row window mask bounds intra-window visibility
    too)."""
    import dataclasses

    model, params = tiny
    cfg = dataclasses.replace(model.cfg, **(
        {"decode_attention": "flash"} if knob == "flash"
        else {"sliding_window": 6}))
    m = Transformer(cfg)

    def reqs():
        return [Request([1, 2, 3] * 4, max_new_tokens=8, id="a"),
                Request([7, 9, 11], max_new_tokens=6, id="b")]

    _, off = _run(m, params, reqs(), batch_size=2, chunk_steps=1)
    on, got = _run(m, params, reqs(), batch_size=2, chunk_steps=1,
                   speculate_k=4)
    assert got == off
    assert on.spec_rounds > 0


# -------------------------------------------------------- observability


def test_counters_and_result_fields(tiny):
    model, params = tiny
    server = Server(model, params, batch_size=1, min_bucket=8,
                    chunk_steps=1, speculate_k=4)
    res = next(iter(server.run(
        [Request(list(REP), max_new_tokens=12, id="r")])))
    c = server.counters()
    for key in ("wasted_steps", "spec_rounds", "spec_drafted",
                "spec_accepted"):
        assert key in c and c[key] >= 0
    assert c["spec_drafted"] >= c["spec_accepted"] > 0
    # Result carries the per-request ledger
    assert res.drafted > 0 and 0 <= res.accepted <= res.drafted
    assert res.draft_hit_rate == res.accepted / res.drafted


def test_wasted_steps_counts_chunk_overshoot(tiny):
    """The decode-step utilization satellite, both modes: with
    in-dispatch EOS OFF (the pre-ISSUE-13 control) a slot finishing
    mid-chunk decodes garbage until the chunk ends and the trimmed
    slot-steps surface in counters(); with it ON (the default) the
    same workload freezes the slot in-dispatch — zero wasted_steps,
    the trailing positions counted as frozen re-emits instead, and
    identical outputs. (A SOLO short request never overshoots —
    _chunk_size bounds the chunk by the max remaining budget — so the
    waste needs a mixed-budget batch.)"""
    model, params = tiny

    def reqs():
        # budgets 3 and 10, chunk 8: the long slot forces k=8; the
        # short one consumes 2 decode tokens (1 came at admit) and
        # trims/freezes 6
        return [Request([1, 2, 3], max_new_tokens=3, id="w"),
                Request([5, 9], max_new_tokens=10, id="l")]

    legacy, res_legacy = _run(model, params, reqs(), batch_size=2,
                              chunk_steps=8, in_dispatch_eos=False)
    assert len(res_legacy) == 2
    assert legacy.wasted_steps == 6
    assert legacy.counters()["wasted_steps"] == 6
    assert legacy.frozen_steps == 0

    frozen, res_frozen = _run(model, params, reqs(), batch_size=2,
                              chunk_steps=8)
    assert res_frozen == res_legacy
    assert frozen.wasted_steps == 0
    assert frozen.frozen_steps == 6
    assert frozen.freeze_faults == 0
    assert frozen.counters()["frozen_steps"] == 6


def test_wasted_steps_counts_rejected_drafts(tiny, monkeypatch):
    """The utilization counter's speculation side: draft positions the
    verify pass scored and rejected are decoded-and-thrown-away work,
    reported next to chunk overshoot (bench_spec's wasted_steps_on)."""
    import tony_tpu.serve.engine as eng

    model, params = tiny

    def junk_draft(ctx, k, max_ngram=3):
        t = 63 if ctx[-1] != 63 else 62
        return np.asarray([t], np.int32)

    monkeypatch.setattr(eng, "_propose_draft", junk_draft)
    server, _ = _run(model, params,
                     [Request([7, 9, 11], max_new_tokens=12, id="x")],
                     batch_size=1, chunk_steps=1, speculate_k=4)
    assert server.spec_drafted > 0 and server.spec_accepted == 0
    assert server.wasted_steps == server.spec_drafted


def test_batch_drag_gate_prefers_chunks(tiny):
    """A lone drafter must not drag a mixed batch to one token per
    dispatch in the UNFUSED (in_dispatch_eos=False) path: at
    chunk_steps=8 the expected verify yield (2 slots + a 4-token
    draft) never beats the 16-token chunk dispatch, so the gate keeps
    every round on the chunk path — speculation-on costs exactly
    speculation-off plus the host-side lookups. The co-tenant is
    SAMPLED (greedy cycles of the tiny model would start hitting the
    lookup and make it a second drafter). The fused default needs no
    gate — every slot decodes the full chunk inside the verify
    dispatch — which test_fused_round_never_drags pins."""
    model, params = tiny

    def reqs():
        # budget 17 = 1 admit token + chunks of 8 + 8: no shrunken
        # tail chunk where the gate would (correctly) flip to verify
        return [Request(list(REP), max_new_tokens=17, id="rep"),
                Request([7, 9, 11], max_new_tokens=17, temperature=0.8,
                        top_k=8, seed=3, id="samp")]

    off, ro = _run(model, params, reqs(), batch_size=2, chunk_steps=8,
                   in_dispatch_eos=False)
    on, rn = _run(model, params, reqs(), batch_size=2, chunk_steps=8,
                  speculate_k=4, in_dispatch_eos=False)
    assert rn == ro
    assert on.spec_rounds == 0
    assert on.dispatches == off.dispatches


def test_fused_round_never_drags(tiny):
    """The ISSUE-13 fused speculation round replaces the drag gate:
    the same lone-drafter mixed batch now SPECULATES — the sampled
    co-tenant decodes its full chunk inside the fused dispatch, so
    speculation-on needs no more dispatches than speculation-off (and
    strictly fewer whenever drafts land), with outputs identical."""
    model, params = tiny

    def reqs():
        return [Request(list(REP), max_new_tokens=17, id="rep"),
                Request([7, 9, 11], max_new_tokens=17, temperature=0.8,
                        top_k=8, seed=3, id="samp")]

    off, ro = _run(model, params, reqs(), batch_size=2, chunk_steps=8)
    on, rn = _run(model, params, reqs(), batch_size=2, chunk_steps=8,
                  speculate_k=4)
    assert rn == ro
    assert on.spec_rounds > 0  # the gate is gone: drafts verify
    # every fused round lands >= 1 + chunk tokens per live slot where
    # a chunk round lands exactly chunk — so dispatches never grow by
    # more than the one tail round the accepted drafts can desync off
    # the pow2 budget grid (the chunk_steps=1 dispatch-cut claim is
    # test_spec_reduces_dispatches_and_is_exact's)
    assert on.dispatches <= off.dispatches + 1
    assert on.spec_accepted > 0
    assert on.freeze_faults == 0


@pytest.mark.slow  # gateway plumbing; the engine-level counters test
# above pins the same fields tier-1
def test_gateway_threads_spec_stats(tiny):
    """drafted/accepted ride the per-request metrics into the /stats
    window and the engine.spec rollup."""
    from tony_tpu.gateway import Gateway, GenRequest

    model, params = tiny
    gw = Gateway([Server(model, params, batch_size=2, min_bucket=8,
                         chunk_steps=1, speculate_k=4)],
                 max_queue=8).start()
    try:
        t = gw.submit(GenRequest(list(REP), max_new_tokens=12, id="r"))
        res = t.result(timeout=600)
        assert res.drafted > 0
        assert t.metrics["drafted"] == res.drafted
        assert t.metrics["accepted"] == res.accepted
        assert t.metrics["draft_hit_rate"] == pytest.approx(
            res.draft_hit_rate, abs=1e-4)
        snap = gw.snapshot()
        assert snap["drafted"] == res.drafted
        assert snap["draft_accepted"] == res.accepted
        spec = snap["engine"]["spec"]
        assert spec["enabled"] and spec["rounds"] > 0
        assert spec["drafted"] == res.drafted
        assert spec["accepted"] == res.accepted
        assert 0 < spec["acceptance_rate"] <= 1
        assert "wasted_steps" in snap["engine"]
    finally:
        gw.drain(timeout=60)


@pytest.mark.slow  # bench-shaped; tier-1 runs -m 'not slow'
def test_bench_spec_datum(tiny):
    """The bench.py extras.spec claim at test scale: on the repetitive
    workload speculation reduces decode dispatches (>= 1x asserted; the
    bench records the measured ratio) with outputs identical."""
    from bench import bench_spec

    datum = bench_spec(on_tpu=False)
    assert datum["outputs_identical"]
    assert datum["dispatch_ratio"] >= 1.0, datum
    assert datum["acceptance_rate"] > 0
