"""Continuous-batching serving over the slot-tolerant decode path.

The TPU-serving analog of TonY's job multiplexing (``TonySession`` /
``TaskScheduler`` packing many jobs onto one container pool): many
REQUESTS multiplex onto one resident KV cache. One jitted decode step
of fixed shape runs forever; requests stream through its slots —
admitted into free slots at their own positions, evicted the moment
they hit EOS or their token budget, replaced the same iteration
(Orca/vLLM-style iteration-level scheduling). Static shapes mean the
step compiles ONCE; mixed-length traffic never waits on the longest
sequence in a batch. The cache itself is BLOCK-PAGED by default
(serve/slots.PagePool — the PagedAttention idea on TPU static
shapes): [n_pages, page_size] pools + per-slot page tables + a host
free-list allocator bound HBM residency by actual tokens instead of
batch_size x max_seq_len, with worst-case-reservation admission
(backpressure, never preemption) and copy-on-write page sharing;
``Server(paged=False)`` keeps the classic fixed-shape rows.
Shared-prefix traffic (system
prompts, few-shot preambles, multi-turn) additionally skips prefill
work through the radix ``PrefixStore`` (serve/prefix.py), and
predictable continuations (extractive/repetitive/templated output)
skip sequential decode steps through speculative decoding —
prompt-lookup drafting + one batched multi-token verify dispatch
(``Server(speculate_k=...)``), greedy outputs unchanged.
"""

from tony_tpu.serve.autotune import AutotuneController, KnobBounds
from tony_tpu.serve.engine import (PoolExhausted, QueueFull, Request,
                                   Result, Server, bucket_len)
from tony_tpu.serve.faults import Fault, FaultPlan, InjectedFault
from tony_tpu.serve.prefix import PrefixStore, tree_nbytes
from tony_tpu.serve.slots import (PagePool, SlotCache, cache_batch_axis,
                                  gather_pages, page_nbytes,
                                  paged_cache, read_slot_row,
                                  scatter_pages, write_slot_row)
from tony_tpu.serve.tier import HostPageTier

__all__ = [
    "AutotuneController",
    "Fault",
    "FaultPlan",
    "KnobBounds",
    "HostPageTier",
    "InjectedFault",
    "PagePool",
    "PoolExhausted",
    "PrefixStore",
    "QueueFull",
    "Request",
    "Result",
    "Server",
    "SlotCache",
    "bucket_len",
    "cache_batch_axis",
    "gather_pages",
    "page_nbytes",
    "paged_cache",
    "read_slot_row",
    "scatter_pages",
    "tree_nbytes",
    "write_slot_row",
]
