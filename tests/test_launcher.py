"""Docker/container launch mode.

Reference: tony.docker.* keys + docker container env
(HadoopCompatibleAdapter.getContainerEnvForDocker). The e2e test runs a
real job through a fake-docker shim that interprets ``docker run`` locally,
so the full coordinator->container->agent->payload path is exercised
without a docker daemon.
"""

import os
import stat
import textwrap

import pytest

from tony_tpu.mini import MiniTonyCluster, script_conf
from tony_tpu.session import Task

SCRIPTS = os.path.join(os.path.dirname(__file__), "scripts")


def test_build_docker_command():
    from tony_tpu.coordinator.launcher import build_docker_command

    task = Task(role="worker", index=0)
    argv = build_docker_command(
        task, {"JOB_NAME": "worker", "TASK_INDEX": "0"},
        image="gcr.io/proj/train:1", mounts=["/data:/data:ro"],
        extra_args=["--shm-size=4g"], workdir="/jobs/app1")
    assert argv[:2] == ["docker", "run"]
    assert "--net=host" in argv and "--privileged" in argv
    assert "tony-s0-worker-0" in argv  # epoch-qualified container name
    assert "/data:/data:ro" in argv
    # job dir is mounted at the same path and set as the workdir
    assert "/jobs/app1:/jobs/app1" in argv
    assert argv[argv.index("-w") + 1] == "/jobs/app1"
    assert "JOB_NAME=worker" in argv and "TASK_INDEX=0" in argv
    assert "--shm-size=4g" in argv
    assert argv[-4:] == ["gcr.io/proj/train:1", "python3", "-m",
                         "tony_tpu.agent"]


def test_build_docker_command_user_mount_covers_workdir():
    """A user mount of the workdir target must suppress the implicit one —
    docker rejects duplicate mount points."""
    from tony_tpu.coordinator.launcher import build_docker_command

    task = Task(role="worker", index=0)
    argv = build_docker_command(
        task, {}, image="img", mounts=["/jobs/app1:/jobs/app1"],
        workdir="/jobs/app1")
    assert argv.count("/jobs/app1:/jobs/app1") == 1
    assert argv[argv.index("-w") + 1] == "/jobs/app1"


def test_docker_launcher_rejects_missing_image():
    from tony_tpu.coordinator.launcher import DockerLauncher

    with pytest.raises(ValueError):
        DockerLauncher("", on_exit=lambda t, c: None)


FAKE_DOCKER = textwrap.dedent("""\
    #!/bin/bash
    # fake docker CLI: "run" interprets the agent container locally;
    # "kill" is a no-op (the local process group dies via the launcher).
    cmd="$1"; shift
    [ "$cmd" = kill ] && exit 0
    [ "$cmd" = run ] || exit 64
    envs=()
    while [ $# -gt 0 ]; do
      case "$1" in
        --rm|--net=host|--privileged) shift;;
        --name|-v|-w) shift 2;;
        -e) envs+=("$2"); shift 2;;
        *) break;;
      esac
    done
    image="$1"; shift  # drop the image; exec the container command locally
    exec env "${envs[@]}" "$@"
    """)


def fake_docker_bin(tmp_path) -> str:
    path = os.path.join(str(tmp_path), "docker")
    with open(path, "w") as f:
        f.write(FAKE_DOCKER)
    os.chmod(path, os.stat(path).st_mode | stat.S_IEXEC)
    return path


def test_docker_mode_e2e(tmp_path):
    """Gang job where every agent is 'containerized' through the shim."""
    with MiniTonyCluster() as cluster:
        conf = script_conf(cluster, os.path.join(SCRIPTS, "check_env.py"),
                           {"worker": 2})
        conf.set("tony.application.launch-mode", "docker")
        conf.set("tony.docker.image", "tony-test-image")
        conf.set("tony.docker.bin", fake_docker_bin(tmp_path))
        client = cluster.submit(conf)
        assert client.final_status["status"] == "SUCCEEDED", \
            client.final_status


def test_docker_enabled_key_requires_image(tmp_path):
    """Missing image fails fast at coordinator startup (ref: config
    validation in validateAndUpdateConfig)."""
    with MiniTonyCluster() as cluster:
        conf = script_conf(cluster, os.path.join(SCRIPTS, "exit_0.py"),
                           {"worker": 1})
        conf.set("tony.docker.enabled", True)
        client = cluster.make_client(conf)
        with pytest.raises(RuntimeError, match="coordinator exited"):
            client.run()


# -- ssh launch mode ---------------------------------------------------------

FAKE_SSH = os.path.join(SCRIPTS, "fake_ssh.sh")
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _wait_for(pred, timeout=15.0):
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return pred()


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False


def test_ssh_launcher_remote_kill(tmp_path, monkeypatch):
    """kill_task must kill the REMOTE process tree (via the recorded pgid),
    not just the local ssh client — otherwise a resized/retried gang
    overlaps the old one until the agent's coordinator-lost horizon
    (ref analog: NM container kill, ApplicationMaster.java:735-777)."""
    from tony_tpu.coordinator import launcher as L

    monkeypatch.setattr(L, "REMOTE_AGENT_CMD", "sleep 300")
    exits = []
    lch = L.SshLauncher(["fakehost"], on_exit=lambda t, c: exits.append((t, c)),
                        ssh_bin=FAKE_SSH)
    task = Task(role="worker", index=0)
    pgid_file = L.remote_pgid_file(task)
    if os.path.exists(pgid_file):
        os.remove(pgid_file)
    lch.launch(task, {"TONY_TEST": "1"}, os.path.join(str(tmp_path), "w.log"))
    assert _wait_for(lambda: os.path.exists(pgid_file)), "pgid never recorded"
    pid = int(open(pgid_file).read().strip())
    assert _alive(pid)
    assert lch.kill_task(task.id)
    assert _wait_for(lambda: not _alive(pid)), \
        "remote tree survived kill_task"
    assert not os.path.exists(pgid_file)  # kill cleans the pgid file


def test_ssh_launcher_stop_all_kills_remote_trees(tmp_path, monkeypatch):
    from tony_tpu.coordinator import launcher as L

    monkeypatch.setattr(L, "REMOTE_AGENT_CMD", "sleep 300")
    exits = []
    lch = L.SshLauncher(["h1", "h2"], on_exit=lambda t, c: exits.append(t),
                        ssh_bin=FAKE_SSH)
    tasks = [Task(role="worker", index=i) for i in range(2)]
    pids = []
    for t in tasks:
        pgid_file = L.remote_pgid_file(t)
        if os.path.exists(pgid_file):
            os.remove(pgid_file)
        lch.launch(t, {}, os.path.join(str(tmp_path), f"{t.id}.log"))
    for t in tasks:
        pgid_file = L.remote_pgid_file(t)
        assert _wait_for(lambda: os.path.exists(pgid_file))
        pids.append(int(open(pgid_file).read().strip()))
    lch.stop_all()
    for pid in pids:
        assert _wait_for(lambda: not _alive(pid)), \
            f"remote pid {pid} survived stop_all"
    assert exits == []  # teardown exits never reach on_exit


def test_ssh_mode_e2e(tmp_path):
    """Full gang over fake ssh: launch, env contract, clean finish."""
    with MiniTonyCluster() as cluster:
        conf = script_conf(cluster, os.path.join(SCRIPTS, "check_env.py"),
                           {"worker": 2})
        conf.set("tony.application.launch-mode", "ssh")
        conf.set("tony.application.hosts", "hostA,hostB")
        conf.set("tony.application.ssh-bin", FAKE_SSH)
        conf.set("tony.application.remote-pythonpath", REPO_ROOT)
        client = cluster.submit(conf)
        assert client.final_status["status"] == "SUCCEEDED", \
            client.final_status
