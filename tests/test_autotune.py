"""Adaptive shape controller (serve/autotune.py, ISSUE-13).

Unit-tests the controller against fake engines (hysteresis, bounds,
pow2 grid, never-actuates-when-idle, per-rule signals, new-compile
receipts, convergence), then pins the gateway integration live: an
--autotune gateway under traffic actuates at least once, stays
token-exact vs a static control gateway, surfaces every decision in
/stats + /metrics + history metrics/autotune.jsonl, and goes quiet
(converged) once traffic stops.
"""

import json
import time

import jax
import jax.numpy as jnp
import pytest

from tony_tpu.serve.autotune import AutotuneController, KnobBounds


class _FakeTimeline:
    def __init__(self):
        self.summ = {}

    def summary(self):
        return {k: dict(v) for k, v in self.summ.items()}


class _FakeServer:
    """The attribute surface the controller reads/writes."""

    def __init__(self, chunk_steps=4, speculate_k=0, prefill_chunk=0):
        self.chunk_steps = chunk_steps
        self.speculate_k = speculate_k
        self.prefill_chunk = prefill_chunk
        self.min_bucket = 16
        self.spec_drafted = 0
        self.spec_accepted = 0
        self.timeline = _FakeTimeline()
        self._compiled = set()

    def feed(self, kind, *, count, ms, useful=0.0, padding=0.0,
             overshoot=0.0, rejected=0.0, tokens=0, compile_ms=0.0):
        """Advance the fake cumulative aggregates by one tick's
        worth of traffic."""
        a = self.timeline.summ.setdefault(kind, {
            "count": 0, "ms": 0.0, "compile_ms": 0.0, "tokens": 0,
            "useful_ms": 0.0, "padding_ms": 0.0, "overshoot_ms": 0.0,
            "rejected_ms": 0.0})
        a["count"] += count
        a["ms"] += ms
        a["compile_ms"] += compile_ms
        a["tokens"] += tokens
        a["useful_ms"] += useful
        a["padding_ms"] += padding
        a["overshoot_ms"] += overshoot
        a["rejected_ms"] += rejected


def _ctl(**kw):
    base = dict(chunk_bounds=(1, 16), spec_bounds=(0, 8),
                prefill_bounds=(0, 0), hold_ticks=1, cooldown_ticks=0,
                min_dispatches=2)
    base.update(kw)
    return AutotuneController(**base)


def _busy_clean(srv, n=8):
    """One tick's worth of healthy decode traffic: no overshoot, low
    padding — the grow-chunk condition."""
    srv.feed("decode", count=n, ms=80.0, useful=76.0, padding=4.0,
             tokens=n * srv.chunk_steps)


def test_knob_bounds_clamp():
    b = KnobBounds(2, 16)
    assert b.clamp(1) == 2 and b.clamp(64) == 16 and b.clamp(8) == 8


def test_never_actuates_when_idle():
    ctl = _ctl()
    srv = _FakeServer(chunk_steps=4)
    _busy_clean(srv)
    assert ctl.tick([(0, srv)]) == []  # baseline tick
    # idle ticks forever after: no deltas, no actuations — and the
    # busy tick's pending streak must not survive the idle gap
    for _ in range(10):
        assert ctl.tick([(0, srv)]) == []
    assert srv.chunk_steps == 4
    assert ctl.snapshot()["actuations_total"] == 0
    assert ctl.idle_ticks > 0


def test_grow_shrink_on_pow2_grid_within_bounds():
    ctl = _ctl()
    srv = _FakeServer(chunk_steps=4)
    _busy_clean(srv)
    ctl.tick([(0, srv)])  # baseline
    seen = []
    for _ in range(6):
        _busy_clean(srv)
        ctl.tick([(0, srv)])
        seen.append(srv.chunk_steps)
    # monotone pow2 growth, capped at the bound, then quiet
    assert seen == [8, 16, 16, 16, 16, 16]
    assert ctl.snapshot()["actuations"]["chunk_steps"] == 2
    # heavy overshoot shrinks, one pow2 step per actuation
    srv.feed("decode", count=8, ms=80.0, useful=40.0, overshoot=40.0)
    ctl.tick([(0, srv)])
    assert srv.chunk_steps == 8
    row = ctl.recent[-1]
    assert row["reason"] == "overshoot" and row["from"] == 16
    assert row["signals"]["overshoot_frac"] > 0.4


def test_hysteresis_holds_for_n_ticks():
    ctl = _ctl(hold_ticks=3)
    srv = _FakeServer(chunk_steps=4)
    _busy_clean(srv)
    ctl.tick([(0, srv)])  # baseline
    for i in range(2):
        _busy_clean(srv)
        assert ctl.tick([(0, srv)]) == []  # streak 1, 2: held
        assert srv.chunk_steps == 4
    _busy_clean(srv)
    assert len(ctl.tick([(0, srv)])) == 1  # streak 3: actuates
    assert srv.chunk_steps == 8
    # an idle tick resets the streak — 2 busy + idle + 2 busy never
    # reaches 3 consecutive
    ctl2 = _ctl(hold_ticks=3)
    srv2 = _FakeServer(chunk_steps=4)
    _busy_clean(srv2)
    ctl2.tick([(0, srv2)])
    for _ in range(2):
        _busy_clean(srv2)
        ctl2.tick([(0, srv2)])
    ctl2.tick([(0, srv2)])  # idle
    for _ in range(2):
        _busy_clean(srv2)
        ctl2.tick([(0, srv2)])
    assert srv2.chunk_steps == 4


def test_cooldown_blocks_rejudging():
    ctl = _ctl(cooldown_ticks=3)
    srv = _FakeServer(chunk_steps=4)
    _busy_clean(srv)
    ctl.tick([(0, srv)])  # baseline
    _busy_clean(srv)
    ctl.tick([(0, srv)])
    assert srv.chunk_steps == 8
    for _ in range(3):  # cooldown: proposals ignored
        _busy_clean(srv)
        ctl.tick([(0, srv)])
        assert srv.chunk_steps == 8
    _busy_clean(srv)
    ctl.tick([(0, srv)])
    assert srv.chunk_steps == 16


def test_speculate_k_rules_never_rearm_from_zero():
    ctl = _ctl()
    # rejection-heavy drafting halves k; k=0 never re-arms
    srv = _FakeServer(chunk_steps=4, speculate_k=8)
    srv.feed("verify", count=8, ms=80.0, useful=60.0, rejected=20.0)
    srv.spec_drafted, srv.spec_accepted = 40, 10
    ctl.tick([(0, srv)])  # baseline
    srv.feed("verify", count=8, ms=80.0, useful=60.0, rejected=20.0)
    srv.spec_drafted += 40
    srv.spec_accepted += 10  # 75% rejected this tick
    ctl.tick([(0, srv)])
    assert srv.speculate_k == 4
    assert ctl.recent[-1]["reason"] == "spec_rejected"
    # high acceptance grows k (fresh controller: no cooldown state)
    ctl2 = _ctl()
    srv2 = _FakeServer(chunk_steps=4, speculate_k=2)
    srv2.feed("verify", count=8, ms=80.0, useful=78.0)
    srv2.spec_drafted, srv2.spec_accepted = 40, 38
    ctl2.tick([(0, srv2)])
    srv2.feed("verify", count=8, ms=80.0, useful=78.0)
    srv2.spec_drafted += 40
    srv2.spec_accepted += 38
    ctl2.tick([(0, srv2)])
    assert srv2.speculate_k == 4
    # disabled speculation produces no draft data -> never re-armed
    ctl3 = _ctl()
    srv3 = _FakeServer(chunk_steps=16, speculate_k=0)
    _busy_clean(srv3)
    ctl3.tick([(0, srv3)])
    _busy_clean(srv3)
    ctl3.tick([(0, srv3)])
    assert srv3.speculate_k == 0


def test_prefill_chunk_rules():
    ctl = _ctl(prefill_bounds=(0, 512))
    srv = _FakeServer(chunk_steps=16, prefill_chunk=128)
    srv.feed("prefill_chunk", count=4, ms=40.0, useful=10.0,
             padding=30.0)
    ctl.tick([(0, srv)])  # baseline
    srv.feed("prefill_chunk", count=4, ms=40.0, useful=10.0,
             padding=30.0)  # 75% padding: windows wider than prompts
    ctl.tick([(0, srv)])
    assert srv.prefill_chunk == 64
    assert ctl.recent[-1]["reason"] == "prefill_padding"
    # pad-free chunked prefill grows the budget back toward the bound
    srv.feed("prefill_chunk", count=8, ms=80.0, useful=80.0)
    ctl.tick([(0, srv)])
    assert srv.prefill_chunk == 128
    assert ctl.recent[-1]["reason"] == "prefill_interleave"
    # the floor is the engine's bucket minimum, never below
    srv2 = _FakeServer(chunk_steps=16, prefill_chunk=16)
    ctl2 = _ctl(prefill_bounds=(0, 512))
    srv2.feed("prefill_chunk", count=4, ms=40.0, padding=40.0)
    ctl2.tick([(0, srv2)])
    srv2.feed("prefill_chunk", count=4, ms=40.0, padding=40.0)
    ctl2.tick([(0, srv2)])
    assert srv2.prefill_chunk == 16


def test_new_compile_receipt():
    ctl = _ctl()
    srv = _FakeServer(chunk_steps=4)
    srv._compiled = {("decode", 8, 0), ("decode", 4, 0)}
    _busy_clean(srv)
    ctl.tick([(0, srv)])  # baseline
    _busy_clean(srv)
    ctl.tick([(0, srv)])
    assert srv.chunk_steps == 8
    assert ctl.recent[-1]["new_compile"] is False  # bucket pre-warmed
    _busy_clean(srv)
    ctl.tick([(0, srv)])
    assert srv.chunk_steps == 16
    assert ctl.recent[-1]["new_compile"] is True  # deliberate, logged
    assert ctl.snapshot()["new_compiles"] == 1


def test_convergence_on_steady_traffic():
    """The acceptance pin: actuations STOP within a bounded number of
    ticks on steady traffic — the knob reaches its bound (or dead
    zone) and the controller reports converged."""
    ctl = _ctl()
    srv = _FakeServer(chunk_steps=1)
    _busy_clean(srv)
    ctl.tick([(0, srv)])  # baseline
    for _ in range(12):
        _busy_clean(srv)
        ctl.tick([(0, srv)])
    assert srv.chunk_steps == 16  # at the bound
    last = ctl.last_actuation_tick
    for _ in range(6):
        _busy_clean(srv)
        ctl.tick([(0, srv)])
    assert ctl.last_actuation_tick == last  # quiet ever since
    assert ctl.snapshot()["converged"] is True


def test_replicas_without_timeline_are_skipped():
    class Remote:  # a RemoteServer stub has no local timeline
        chunk_steps = 4
        timeline = None

    ctl = _ctl()
    assert ctl.tick([(0, Remote()), (1, None)]) == []
    assert ctl.snapshot()["actuations_total"] == 0


# ------------------------------------------------- gateway integration


@pytest.fixture(scope="module")
def tiny():
    from tony_tpu.models import Transformer, TransformerConfig

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=64,
                            dtype=jnp.float32,
                            attention_backend="reference")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def test_gateway_autotune_actuates_token_exact(tiny, tmp_path):
    """The live pin: an --autotune gateway under steady traffic
    actuates at least once (chunk grows off the ledger's clean
    overshoot signal), every output stays byte-identical to a static
    control gateway, the decisions land in /stats engine.autotune and
    history metrics/autotune.jsonl, and the controller converges
    (goes quiet) when traffic stops."""
    from tony_tpu.gateway import Gateway, GatewayHistory, GenRequest
    from tony_tpu.models import Transformer  # noqa: F401 — fixture dep
    from tony_tpu.serve import Server

    model, params = tiny

    def traffic(gw):
        outs = {}
        for wave in range(4):
            ts = [gw.submit(GenRequest([1 + i + wave, 2, 3],
                                       max_new_tokens=14,
                                       id=f"{wave}-{i}"))
                  for i in range(3)]
            for t in ts:
                outs[t.request.id] = t.result(timeout=120).tokens
        return outs

    control = Gateway([Server(model, params, batch_size=2, eos_id=-1,
                              chunk_steps=1, min_bucket=8)],
                      alerts=False).start()
    try:
        expect = traffic(control)
    finally:
        assert control.drain(timeout=120)

    hist = GatewayHistory(str(tmp_path))
    srv = Server(model, params, batch_size=2, eos_id=-1,
                 chunk_steps=1, min_bucket=8)
    gw = Gateway([srv], alerts=False, history=hist, autotune=True,
                 autotune_interval_s=0.05,
                 # hi=4 keeps the actuation ladder to at most two new
                 # chunk programs — the pin is >=1 actuation +
                 # token-exactness, not how far the knob can climb
                 autotune_config={"chunk_bounds": (1, 4),
                                  "hold_ticks": 1, "cooldown_ticks": 0,
                                  "min_dispatches": 2}).start()
    try:
        got = traffic(gw)
        assert got == expect  # actuations never change outputs
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            snap = gw.snapshot()["engine"]["autotune"]
            if snap["actuations_total"] >= 1:
                break
            time.sleep(0.02)
        assert snap["actuations_total"] >= 1, snap
        assert snap["enabled"] and snap["replicas"][0]["chunk_steps"] > 1
        assert snap["recent"][-1]["knob"] == "chunk_steps"
        # idle: the controller goes quiet and reports convergence.
        # Settle first: the last wave's deltas may still be one tick
        # away from judgment when the actuation above lands.
        time.sleep(0.3)
        before = gw.snapshot()["engine"]["autotune"]["actuations_total"]
        time.sleep(0.4)
        snap2 = gw.snapshot()["engine"]["autotune"]
        assert snap2["actuations_total"] == before
        assert snap2["converged"] is True
        # /metrics carries the same numbers
        from tony_tpu.obs.export import prometheus_text

        text = prometheus_text(gw)
        assert "tony_autotune_enabled 1" in text
        assert 'tony_autotune_knob{replica="0",knob="chunk_steps"}' \
            in text
    finally:
        assert gw.drain(timeout=120)
    rows = [json.loads(ln) for ln in open(hist._autotune_path)
            if ln.strip()]
    assert rows and rows[0]["knob"] == "chunk_steps"
    assert {"from", "to", "reason", "signals", "new_compile"} \
        <= set(rows[0])


def test_gateway_without_autotune_reports_disabled(tiny):
    from tony_tpu.gateway import Gateway, GenRequest
    from tony_tpu.serve import Server

    model, params = tiny
    gw = Gateway([Server(model, params, batch_size=2, eos_id=-1,
                         min_bucket=8)], alerts=False).start()
    try:
        gw.submit(GenRequest([1, 2, 3], max_new_tokens=3,
                             id="x")).result(timeout=60)
        assert gw.snapshot()["engine"]["autotune"] == {
            "enabled": False}
    finally:
        assert gw.drain(timeout=60)
