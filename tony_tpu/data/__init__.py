from tony_tpu.data.loader import DataLoader, device_prefetch
from tony_tpu.data.sources import (
    ArraySource,
    MixtureSource,
    InstructionSource,
    JsonlSource,
    PackedTokenSource,
    SyntheticImageSource,
    SyntheticTokenSource,
)
from tony_tpu.data.tokenize import (
    ByteTokenizer,
    encode_corpus_to_bin,
    encode_files_to_bin,
)

__all__ = [
    "ArraySource",
    "ByteTokenizer",
    "DataLoader",
    "device_prefetch",
    "encode_corpus_to_bin",
    "encode_files_to_bin",
    "InstructionSource",
    "JsonlSource",
    "MixtureSource",
    "PackedTokenSource",
    "SyntheticImageSource",
    "SyntheticTokenSource",
]
