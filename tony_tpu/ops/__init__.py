from tony_tpu.ops.adamw import (
    FusedAdamW,
    FusedAdamWState,
    fused_adamw_update,
)
from tony_tpu.ops.attention import flash_attention
from tony_tpu.ops.fused import add_rmsnorm, rmsnorm
from tony_tpu.ops.quant import dequantize_q8, q8_matmul, quantize_q8
from tony_tpu.ops.xent import chunked_cross_entropy, full_cross_entropy

__all__ = ["FusedAdamW", "FusedAdamWState", "fused_adamw_update",
           "flash_attention", "rmsnorm", "add_rmsnorm",
           "chunked_cross_entropy", "full_cross_entropy",
           "quantize_q8", "dequantize_q8", "q8_matmul"]
