"""Airflow operator adapter over WorkflowJob.

The reference integrates with Azkaban (its era's LinkedIn scheduler);
today's equivalent surface is an Airflow operator. No airflow import is
required — the class duck-types BaseOperator's ``execute(context)``
contract, and ``as_airflow_operator()`` grafts the real base class on when
airflow is installed.
"""

from __future__ import annotations

import os
import tempfile

from tony_tpu.workflow.job import FlowContext, WorkflowJob


class TonyTpuOperator:
    """Submit a tony-tpu job from a workflow DAG.

    Parameters mirror the Azkaban jobtype's prop names (TonyJobArg.java)
    so reference users' job definitions translate one-to-one::

        TonyTpuOperator(
            task_id="train",
            executes="train.py",
            src_dir="src/",
            props={"tony.worker.instances": "4", "worker_env.FOO": "1"},
        )
    """

    template_fields = ("props", "executes", "task_params")

    def __init__(self, task_id: str, executes: str = "", src_dir: str = "",
                 task_params: str = "", python_venv: str = "",
                 shell_env: str = "", conf_file: str = "",
                 props: dict[str, str] | None = None,
                 working_dir: str = "", **kwargs):
        self.task_id = task_id
        self.props = dict(props or {})
        # kept as attributes (not folded into props) so template_fields
        # rendering mutates them before execute() merges
        self.executes = executes
        self.task_params = task_params
        self.src_dir = src_dir
        self.python_venv = python_venv
        self.shell_env = shell_env
        self.conf_file = conf_file
        self.working_dir = working_dir
        self.kwargs = kwargs

    def _merged_props(self) -> dict[str, str]:
        props = dict(self.props)
        for key, value in [("executes", self.executes),
                           ("src_dir", self.src_dir),
                           ("task_params", self.task_params),
                           ("python_venv", self.python_venv),
                           ("shell_env", self.shell_env),
                           ("conf_file", self.conf_file)]:
            if value:
                props[key] = value
        return props

    def _flow_context(self, context: dict) -> FlowContext:
        """Map Airflow's template context to flow lineage tags."""
        dag = context.get("dag")
        run = context.get("dag_run")
        return FlowContext(
            execution_id=str(getattr(run, "run_id", "") or ""),
            flow_id=str(getattr(dag, "dag_id", "") or ""),
            project_name=str(context.get("project_name", "") or ""),
            scheduler_host=str(context.get("conf_host", "") or ""),
        )

    def execute(self, context: dict | None = None) -> bool:
        workdir = self.working_dir or tempfile.mkdtemp(prefix="tony_wf_")
        os.makedirs(workdir, exist_ok=True)
        job = WorkflowJob(
            job_id=self.task_id,
            props=self._merged_props(),
            working_dir=workdir,
            flow=self._flow_context(context or {}),
        )
        ok = job.run()
        if not ok:
            raise RuntimeError(f"tony-tpu workflow job {self.task_id} failed")
        return ok


def as_airflow_operator():
    """Return a real BaseOperator subclass when airflow is importable."""
    from airflow.models import BaseOperator  # raises if absent

    # TonyTpuOperator first so execute() and template_fields resolve to it
    # (BaseOperator.execute raises NotImplementedError)
    class _AirflowTonyTpuOperator(TonyTpuOperator, BaseOperator):
        template_fields = TonyTpuOperator.template_fields

        def __init__(self, *, task_id: str, **kwargs):
            operator_kwargs = {
                k: kwargs.pop(k) for k in list(kwargs)
                if k in ("executes", "src_dir", "task_params", "python_venv",
                         "shell_env", "conf_file", "props", "working_dir")
            }
            BaseOperator.__init__(self, task_id=task_id, **kwargs)
            TonyTpuOperator.__init__(self, task_id=task_id, **operator_kwargs)

    return _AirflowTonyTpuOperator
