"""Gateway invariants (tony_tpu.gateway) on the CPU tiny model.

The four ISSUE-2 acceptance properties:
- greedy outputs through the gateway are token-identical to a direct
  ``Server.run()`` (the front door adds routing, never math);
- a deadline-expired request is shed with 504 BEFORE it ever occupies
  a slot (prefill count is the witness);
- graceful drain under load loses zero accepted requests;
- two replicas both stay busy under skewed request lengths
  (least-outstanding-tokens routing).

Plus the serve-engine backpressure/drain hooks the gateway depends on
(``QueueFull``, ``Server.drain()``) and the HTTP face (unary +
streaming + health/stats) in-process. CPU-only, tiny model — the slow
marker end-to-end subprocess test lives at the bottom.
"""

import json
import threading
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.gateway import (BadRequest, DeadlineExceeded, Gateway,
                              GatewayClosed, GatewayEdge, GatewayHTTP,
                              GatewayQueueFull, GenRequest)
from tony_tpu.models import Transformer, TransformerConfig, generate
from tony_tpu.serve import QueueFull, Request, Server


@pytest.fixture(scope="module")
def tiny():
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=32,
                            dtype=jnp.float32,
                            attention_backend="reference")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _servers(tiny, n, **kw):
    model, params = tiny
    kw.setdefault("batch_size", 2)
    kw.setdefault("min_bucket", 8)
    return [Server(model, params, **kw) for _ in range(n)]


def _solo(tiny, prompt, n):
    model, params = tiny
    out = generate(model, params, jnp.asarray([prompt], jnp.int32),
                   max_new_tokens=n)
    return np.asarray(out)[0].tolist()


# ----------------------------------------------------- engine hooks


def test_server_submit_queue_full_typed(tiny):
    model, params = tiny
    server = Server(model, params, batch_size=1, min_bucket=8,
                    max_pending=2)
    server.submit(Request([1, 2], max_new_tokens=2))
    server.submit(Request([3, 4], max_new_tokens=2))
    with pytest.raises(QueueFull, match="max_pending=2"):
        server.submit(Request([5, 6], max_new_tokens=2))
    # QueueFull is a typed signal, not a ValueError (callers branch)
    assert not isinstance(QueueFull("x"), ValueError)
    assert sum(1 for _ in server.run()) == 2


def test_server_drain_finishes_in_flight_only(tiny):
    """drain() completes the slots without admitting pending — the
    graceful-shutdown primitive the gateway builds on."""
    model, params = tiny
    server = Server(model, params, batch_size=2, min_bucket=8)
    for i in range(4):
        server.submit(Request([1 + i, 2, 3], max_new_tokens=4, id=i))
    first = server.step()  # admits 2, decodes a chunk
    drained = server.drain()
    done_ids = {r.id for r in first} | {r.id for r in drained}
    assert done_ids == {0, 1}  # the two that held slots
    assert server.slots.n_active == 0
    assert server.n_pending == 2  # pending untouched, caller's call
    # results are exact, not truncated, for what drained
    by_id = {r.id: r for r in drained}
    for rid, res in by_id.items():
        assert res.tokens == _solo(tiny, res.prompt, 4)


def test_server_live_progress_tracks_generation(tiny):
    model, params = tiny
    server = Server(model, params, batch_size=1, min_bucket=8,
                    chunk_steps=1)
    server.submit(Request([1, 2, 3], max_new_tokens=4, id="x"))
    server.step()
    p1 = server.live_progress()
    assert list(p1) == ["x"] and len(p1["x"]) >= 1
    server.step()
    p2 = server.live_progress()
    assert len(p2["x"]) > len(p1["x"])
    assert p2["x"][:len(p1["x"])] == p1["x"]  # append-only


def test_server_reset_clears_live_and_pending(tiny):
    """reset() after a failed step must leave no engine ghosts: no
    pending, no _live entries decoding phantom results, all slots free
    — and the engine serves fresh requests exactly afterwards."""
    model, params = tiny
    server = Server(model, params, batch_size=2, min_bucket=8)
    for i in range(3):
        server.submit(Request([1 + i, 2, 3], max_new_tokens=6, id=i))
    server.step()  # two slots live, one pending
    server.reset()
    assert server.done and server.n_pending == 0
    assert server.live_progress() == {}
    assert server.slots.free_slots() == [0, 1]
    server.submit(Request([7, 2], max_new_tokens=4, id="fresh"))
    res = {r.id: r for r in server.run()}
    assert list(res) == ["fresh"]
    assert res["fresh"].tokens == _solo(tiny, [7, 2], 4)


# ------------------------------------------------------- gateway core


def test_gateway_vs_direct_greedy_parity(tiny):
    """The acceptance anchor: same tokens through the front door as
    through the engine directly, 1 and 2 replicas."""
    model, params = tiny
    prompts = [[1, 2, 3], [5, 9], [17, 46, 10, 20, 62, 26], [7, 2, 5, 11]]
    direct = {r.id: r.tokens for r in
              Server(model, params, batch_size=2, min_bucket=8).run(
                  Request(p, max_new_tokens=6, id=j)
                  for j, p in enumerate(prompts))}
    for n_replicas in (1, 2):
        gw = Gateway(_servers(tiny, n_replicas), max_queue=16).start()
        tickets = [gw.submit(GenRequest(p, max_new_tokens=6, id=i))
                   for i, p in enumerate(prompts)]
        for i, t in enumerate(tickets):
            assert t.result(timeout=120).tokens == direct[i], \
                (n_replicas, prompts[i])
        assert gw.drain(timeout=60)


def test_deadline_expired_requests_never_take_a_slot(tiny):
    """A request whose deadline passed while queued is shed with 504
    having cost ZERO device work: no prefill, no slot. Deterministic:
    tickets queue up before the replica thread starts."""
    servers = _servers(tiny, 1, batch_size=1)
    gw = Gateway(servers, max_queue=16)
    t_live = gw.submit(GenRequest([1, 2, 3], max_new_tokens=6, id="live"))
    t_dead = gw.submit(GenRequest([5, 9], max_new_tokens=6, id="dead",
                                  ttl_s=1e-6))  # expires instantly
    t_after = gw.submit(GenRequest([7, 2], max_new_tokens=4, id="after"))
    gw.start()
    with pytest.raises(DeadlineExceeded, match="deadline exceeded"):
        t_dead.result(timeout=120)
    assert t_live.result(timeout=120).tokens == _solo(tiny, [1, 2, 3], 6)
    assert t_after.result(timeout=120).tokens == _solo(tiny, [7, 2], 4)
    # an already-dead ttl is refused synchronously at submit
    with pytest.raises(DeadlineExceeded):
        gw.submit(GenRequest([1], max_new_tokens=1, ttl_s=0.0))
    assert gw.drain(timeout=60)
    # the witness: exactly the two admitted requests prefilled
    assert servers[0].prefills == 2
    snap = gw.snapshot()
    assert snap["shed"] == {504: 2}
    assert snap["completed"] == 2


def test_drain_under_load_loses_zero_accepted_requests(tiny):
    """SIGTERM semantics: everything accepted before the drain signal
    completes with a real result; nothing hangs, nothing is dropped."""
    gw = Gateway(_servers(tiny, 2), max_queue=64).start()
    prompts = [[1 + (i % 5), 2, 3] for i in range(12)]
    tickets = [gw.submit(GenRequest(p, max_new_tokens=3 + (i % 4), id=i))
               for i, p in enumerate(prompts)]
    assert gw.drain(timeout=180)  # most tickets still queued right now
    for i, t in enumerate(tickets):
        res = t.result(timeout=1)  # already terminal: must not block
        assert res.tokens == _solo(tiny, prompts[i],
                                   3 + (i % 4)), i
    snap = gw.snapshot()
    assert snap["completed"] == len(tickets)
    assert snap["queued"] == 0 and not snap["ready"]
    with pytest.raises(GatewayClosed):
        gw.submit(GenRequest([1, 2], max_new_tokens=2))


def test_two_replica_routing_spreads_skewed_load(tiny):
    """Least-outstanding-tokens routing: one 25-token request must not
    serialize the small requests behind it — both replicas do real
    work."""
    servers = _servers(tiny, 2, batch_size=2)
    gw = Gateway(servers, max_queue=64).start()
    tickets = [gw.submit(GenRequest([17, 46, 10], max_new_tokens=25,
                                    id="huge"))]
    tickets += [gw.submit(GenRequest([1 + i, 2], max_new_tokens=4,
                                     id=f"s{i}")) for i in range(8)]
    for t in tickets:
        t.result(timeout=120)
    assert gw.drain(timeout=60)
    stats = [r.stats() for r in gw.replicas]
    assert all(s["completed"] >= 1 for s in stats), stats
    assert all(s["prefills"] >= 1 and s["decode_steps"] > 0
               for s in stats), stats
    assert sum(s["completed"] for s in stats) == len(tickets)


def test_session_affinity_pins_replica(tiny):
    gw = Gateway(_servers(tiny, 2), max_queue=64).start()
    tickets = [gw.submit(GenRequest([1 + i, 2], max_new_tokens=2,
                                    session="conversation-42"))
               for i in range(4)]
    others = [gw.submit(GenRequest([9, 9 - i], max_new_tokens=2,
                                   session=f"other-{i}"))
              for i in range(4)]
    for t in tickets + others:
        t.result(timeout=120)
    assert len({t.replica for t in tickets}) == 1  # pinned
    assert len({t.replica for t in tickets + others}) == 2  # but not all
    assert gw.drain(timeout=60)


def test_admission_queue_bound_and_validation(tiny):
    """429 past max_queue; 400-class validation synchronously."""
    gw = Gateway(_servers(tiny, 1), max_queue=2)  # NOT started: queue
    gw.submit(GenRequest([1, 2], max_new_tokens=2))  # depth is exact
    gw.submit(GenRequest([3, 4], max_new_tokens=2))
    with pytest.raises(GatewayQueueFull, match="max_queue=2"):
        gw.submit(GenRequest([5, 6], max_new_tokens=2))
    with pytest.raises(BadRequest, match="empty"):
        gw.submit(GenRequest([], max_new_tokens=2))
    with pytest.raises(BadRequest, match="no room"):
        gw.submit(GenRequest(list(range(32)), max_new_tokens=2))
    with pytest.raises(BadRequest, match="max_new_tokens"):
        gw.submit(GenRequest([1], max_new_tokens=0))
    # every refusal is counted, by status — /stats must not undercount
    assert gw.snapshot()["shed"] == {429: 1, 400: 3}


def test_gateway_streaming_deltas_reassemble_exactly(tiny):
    """Concatenated token events == the final result tokens (chunk 1:
    per-token streaming)."""
    gw = Gateway(_servers(tiny, 1, chunk_steps=1), max_queue=8).start()
    got: list[int] = []
    done = threading.Event()

    def on_event(ticket, event):
        if event[0] == "tokens":
            got.extend(event[1])
        elif event[0] in ("done", "shed"):
            done.set()

    t = gw.submit(GenRequest([1, 2, 3], max_new_tokens=6), on_event)
    res = t.result(timeout=120)
    assert done.wait(timeout=10)
    assert got == res.tokens == _solo(tiny, [1, 2, 3], 6)
    assert gw.drain(timeout=60)


def test_per_request_metrics_recorded(tiny):
    from tony_tpu.metrics import MetricsStore

    store = MetricsStore()
    gw = Gateway(_servers(tiny, 1), max_queue=8,
                 metrics_store=store).start()
    t = gw.submit(GenRequest([1, 2, 3], max_new_tokens=5))
    t.result(timeout=120)
    assert gw.drain(timeout=60)
    snap = gw.snapshot()
    assert snap["tokens_in"] == 3 and snap["tokens_out"] == 5
    for key in ("queue_wait_ms", "ttft_ms", "tpot_ms"):
        assert snap[key]["p50"] >= 0.0
    rep = store.get_metrics("gateway:replica-0")
    assert rep["completed"] == 1 and rep["prefills"] == 1


def test_gateway_history_feeds_portal(tiny, tmp_path):
    """--history: the gateway shows up as a history job whose metrics
    page lists the per-request rows — zero portal changes."""
    from tony_tpu.events import history
    from tony_tpu.gateway import GatewayHistory

    hist = GatewayHistory(str(tmp_path), n_replicas=1)
    gw = Gateway(_servers(tiny, 1), max_queue=8, history=hist).start()
    gw.submit(GenRequest([1, 2, 3], max_new_tokens=4,
                         id="req-a")).result(timeout=120)
    assert gw.drain(timeout=60)
    jobs = history.list_jobs(str(tmp_path))
    assert [j["app_id"] for j in jobs] == [hist.app_id]
    assert jobs[0]["status"] == "SUCCEEDED"
    rows = [json.loads(ln) for ln in open(
        tmp_path / "intermediate" / hist.app_id / "metrics" /
        "requests.jsonl")]
    assert [r["id"] for r in rows] == ["req-a"]
    assert rows[0]["tokens_out"] == 4 and rows[0]["replica"] == 0


# -------------------------------------------------------------- http


@pytest.fixture(params=["event", "threaded"])
def http_gateway(tiny, request):
    # every front-door contract runs against BOTH edges: the event
    # loop (default) and the thread-per-connection A/B control
    gw = Gateway(_servers(tiny, 1, chunk_steps=1), max_queue=8).start()
    if request.param == "event":
        http = GatewayEdge(gw).start()
    else:
        http = GatewayHTTP(gw).start()
    yield gw, f"http://{http.host}:{http.port}"
    gw.drain(timeout=60)
    http.stop()


def _post(url, doc, timeout=120):
    req = urllib.request.Request(
        url + "/v1/generate", data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)


def test_http_unary_and_health(tiny, http_gateway):
    gw, url = http_gateway
    health = json.loads(urllib.request.urlopen(
        url + "/healthz", timeout=30).read())
    assert health["status"] == "ok" and health["healthy"] == 1
    assert health["replicas"][0]["state"] == "healthy"
    assert health["replicas"][0]["heartbeat_age_s"] < 30
    assert urllib.request.urlopen(url + "/readyz", timeout=30).status == 200
    doc = json.loads(_post(url, {"token_ids": [1, 2, 3],
                                 "max_new_tokens": 5, "id": "u"}).read())
    assert doc["id"] == "u"
    assert doc["token_ids"] == [1, 2, 3] + _solo(tiny, [1, 2, 3], 5)
    assert doc["finish_reason"] == "length"
    assert doc["metrics"]["tokens_out"] == 5
    stats = json.loads(urllib.request.urlopen(
        url + "/stats", timeout=30).read())
    assert stats["completed"] >= 1 and len(stats["replicas"]) == 1


def test_http_streaming_ndjson(tiny, http_gateway):
    gw, url = http_gateway
    resp = _post(url, {"token_ids": [1, 2, 3], "max_new_tokens": 5,
                       "stream": True, "id": "s"})
    assert resp.headers.get("Content-Type") == "application/x-ndjson"
    lines = [json.loads(ln) for ln in resp.read().decode().splitlines()]
    assert len(lines) >= 2  # at least one delta + the final doc
    toks = [t for ln in lines[:-1] for t in ln["token_ids"]]
    final = lines[-1]
    assert final["finish_reason"] == "length"
    assert final["token_ids"] == [1, 2, 3] + toks
    assert toks == _solo(tiny, [1, 2, 3], 5)


def test_http_error_mapping(tiny, http_gateway):
    gw, url = http_gateway
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(url, {"max_new_tokens": 5})
    assert e.value.code == 400  # no prompt/token_ids
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(url, {"token_ids": [1], "ttl_s": 0})
    assert e.value.code == 504  # dead on arrival
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(url + "/nope", timeout=30)
    assert e.value.code == 404
    gw.drain(timeout=60)  # front door closes
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(url, {"token_ids": [1, 2]})
    assert e.value.code == 503
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(url + "/readyz", timeout=30)
    assert e.value.code == 503


# --------------------------------------------------------------- e2e


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_gateway_cli_e2e_concurrent_and_sigterm(tmp_path):
    """The CLI front door end-to-end: boot --demo-model, fire
    concurrent clients (streaming + unary), then SIGTERM and assert a
    clean zero-loss drain (exit 0)."""
    import os
    import signal
    import subprocess
    import sys

    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": os.path.dirname(os.path.dirname(
               os.path.abspath(__file__)))}
    proc = subprocess.Popen(
        [sys.executable, "-m", "tony_tpu.cli.gateway", "--demo-model",
         "--replicas", "2", "--port", "0", "--compile-cache", ""],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env)
    try:
        boot = proc.stdout.readline().strip()
        url = boot.split()[3]
        results: dict[int, dict] = {}
        errors: list = []

        def client(i):
            try:
                stream = i % 2 == 0
                doc = {"token_ids": [1 + i, 2, 3],
                       "max_new_tokens": 4 + i % 3, "stream": stream,
                       "id": i}
                body = _post(url, doc, timeout=240).read().decode()
                results[i] = json.loads(body.splitlines()[-1])
            except Exception as e:  # noqa: BLE001 — collected, asserted
                errors.append((i, e))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=240)
        assert not errors, errors
        assert set(results) == set(range(8))
        for i, doc in results.items():
            assert doc["finish_reason"] in ("eos", "length"), doc
            assert doc["token_ids"][:3] == [1 + i, 2, 3]
        stats = json.loads(urllib.request.urlopen(
            url + "/stats", timeout=30).read())
        assert stats["completed"] == 8
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=120) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
