"""Runtime adapter tests.

Reference analogs: TestMLGenericRuntime (TB-port policy), TestHorovodRuntime
(cluster spec/env), TestUtils TF_CONFIG construction, runtime validations.
"""

import json

import pytest

from tony_tpu import constants as C
from tony_tpu.config import ConfError, TonyConf
from tony_tpu.runtime import TaskContext, get_am_adapter, get_task_adapter, get_runtime
from tony_tpu.runtime.jax_runtime import coordinator_address
from tony_tpu.runtime.tf_runtime import construct_tf_config
from tony_tpu.session import Session


def ctx_for(framework="jax", role="worker", index=0, spec=None, conf=None, **kw):
    conf = conf or TonyConf()
    spec = spec or {"worker": ["h0:1000", "h1:1001"]}
    return TaskContext(
        conf=conf,
        role=role,
        index=index,
        task_num=len(spec.get(role, [])),
        is_chief=(role in ("chief", "worker") and index == 0),
        cluster_spec=spec,
        command="true",
        **kw,
    )


# -- jax ---------------------------------------------------------------------


def test_jax_env_injection():
    env = get_task_adapter("jax").build_task_env(ctx_for(index=1))
    assert env[C.COORDINATOR_ADDRESS] == "h0:1000"
    assert env[C.PROCESS_ID] == "1"
    assert env[C.NUM_PROCESSES] == "2"
    assert json.loads(env[C.CLUSTER_SPEC]) == {"worker": ["h0:1000", "h1:1001"]}
    assert env[C.JOB_NAME] == "worker"
    assert env[C.IS_CHIEF] == "false"


def test_jax_flat_index_across_roles():
    spec = {"ps": ["p0:1"], "worker": ["w0:2", "w1:3"]}
    env = get_task_adapter("jax").build_task_env(ctx_for(role="worker", index=1, spec=spec))
    assert env[C.PROCESS_ID] == "2"  # ps:0 -> 0, worker:0 -> 1, worker:1 -> 2
    assert env[C.NUM_PROCESSES] == "3"


def test_jax_coordinator_prefers_chief():
    assert coordinator_address({"ps": ["p:1"], "chief": ["c:9"], "worker": ["w:2"]}) == "c:9"
    assert coordinator_address({"ps": ["p:1"], "worker": ["w:2"]}) == "w:2"
    assert coordinator_address({"head": ["h:3"]}) == "h:3"
    with pytest.raises(ValueError):
        coordinator_address({})


def test_jax_requires_gang():
    conf = TonyConf()
    conf.set("tony.application.distributed-mode", "FCFS")
    with pytest.raises(ConfError):
        get_am_adapter("jax").validate_and_update_config(conf)


# -- tensorflow --------------------------------------------------------------


def test_tf_config_strips_tensorboard_and_evaluator():
    spec = {
        "worker": ["w0:1", "w1:2"],
        "ps": ["p0:3"],
        "tensorboard": ["t:4"],
        "evaluator": ["e:5"],
    }
    cfg = json.loads(construct_tf_config(spec, "worker", 1))
    assert "tensorboard" not in cfg["cluster"]
    assert "evaluator" not in cfg["cluster"]
    assert cfg["task"] == {"type": "worker", "index": 1}
    # evaluator keeps itself in its own spec
    cfg_e = json.loads(construct_tf_config(spec, "evaluator", 0))
    assert "evaluator" in cfg_e["cluster"]


def test_tf_env_gang_only():
    conf = TonyConf()
    env = get_task_adapter("tensorflow").build_task_env(ctx_for("tensorflow", conf=conf))
    assert C.TF_CONFIG in env
    conf.set("tony.application.distributed-mode", "FCFS")
    env = get_task_adapter("tensorflow").build_task_env(ctx_for("tensorflow", conf=conf))
    assert C.TF_CONFIG not in env


# -- pytorch -----------------------------------------------------------------


def test_pytorch_env():
    env = get_task_adapter("pytorch").build_task_env(ctx_for("pytorch", index=1))
    assert env[C.PT_INIT_METHOD] == "tcp://h0:1000"
    assert env["MASTER_ADDR"] == "h0"
    assert env["MASTER_PORT"] == "1000"
    assert env[C.PT_RANK] == "1"
    assert env[C.PT_WORLD] == "2"
    assert env["WORLD_SIZE"] == "2"


# -- mxnet -------------------------------------------------------------------


def test_mxnet_env():
    spec = {
        "scheduler": ["127.0.0.1:5000"],
        "server": ["s0:1", "s1:2"],
        "worker": ["w0:3"],
    }
    env = get_task_adapter("mxnet").build_task_env(ctx_for("mxnet", role="server",
                                                           index=1, spec=spec))
    assert env[C.MX_DMLC_PS_ROOT_URI] == "127.0.0.1"
    assert env[C.MX_DMLC_PS_ROOT_PORT] == "5000"
    assert env[C.MX_DMLC_ROLE] == "server"
    assert env[C.MX_DMLC_NUM_SERVER] == "2"
    assert env[C.MX_DMLC_NUM_WORKER] == "1"
    assert env[C.MX_DMLC_LOCAL] == "0"


def test_mxnet_single_scheduler():
    conf = TonyConf()
    conf.set("tony.scheduler.instances", 2)
    with pytest.raises(ConfError):
        get_am_adapter("mxnet").validate_and_update_config(conf)


# -- standalone / ray --------------------------------------------------------


def test_standalone_single_instance_only():
    conf = TonyConf()
    conf.set("tony.worker.instances", 2)
    with pytest.raises(ConfError):
        get_am_adapter("standalone").validate_and_update_config(conf)
    conf.set("tony.worker.instances", 1)
    get_am_adapter("standalone").validate_and_update_config(conf)


def test_ray_env_and_validation():
    conf = TonyConf()
    conf.set("tony.worker.instances", 2)
    with pytest.raises(ConfError):
        get_am_adapter("ray").validate_and_update_config(conf)
    conf.set("tony.head.instances", 1)
    get_am_adapter("ray").validate_and_update_config(conf)
    spec = {"head": ["hd:6379"], "worker": ["w0:1", "w1:2"]}
    env = get_task_adapter("ray").build_task_env(ctx_for("ray", spec=spec))
    assert env["RAY_HEAD_ADDRESS"] == "hd:6379"
    assert env["RAY_HEAD_PORT"] == "6379"


# -- gating + TB port policy -------------------------------------------------


def test_gang_gating():
    conf = TonyConf()
    conf.set("tony.worker.instances", 2)
    session = Session(conf)
    session.add_expected(2)
    am = get_am_adapter("jax")
    am.set_session(session)
    session.init_task("worker")
    session.init_task("worker")
    session.register("worker:0", "h0:1")
    assert not am.can_start_task(C.GANG, "worker:0")
    assert am.can_start_task(C.FCFS, "worker:0")
    session.register("worker:1", "h1:2")
    assert am.can_start_task(C.GANG, "worker:0")
    spec = json.loads(am.construct_cluster_spec("worker:0"))
    assert spec == {"worker": ["h0:1", "h1:2"]}


def test_tb_port_policy():
    """Ref: MLGenericRuntime.needReserveTBPort :161-178 + E2E tests :359."""
    adapter = get_task_adapter("jax")
    conf = TonyConf()
    conf.set("tony.worker.instances", 1)
    # no tensorboard role: chief reserves
    assert adapter.need_reserve_tb_port("worker", True, conf)
    assert not adapter.need_reserve_tb_port("worker", False, conf)
    # sidecar tensorboard role present: chief does NOT reserve, tb executor does
    conf.set("tony.tensorboard.instances", 1)
    assert not adapter.need_reserve_tb_port("worker", True, conf)
    assert adapter.need_reserve_tb_port("tensorboard", False, conf)


def test_unknown_framework():
    with pytest.raises(ValueError, match="unknown framework"):
        get_runtime("caffe")


def test_jax_multislice_env_contract():
    """VERDICT r2 #4: with tony.tpu.num-slices>1 the jax runtime injects
    the real multi-slice Cloud TPU env — MEGASCALE_* (DCN rendezvous) and
    per-slice TPU_WORKER_HOSTNAMES/TPU_WORKER_ID (libtpu ICI bring-up)."""
    from tony_tpu.runtime.jax_runtime import JaxTaskAdapter

    conf = TonyConf()
    conf.set("tony.tpu.num-slices", 2)
    spec = {"worker": ["h0:1111", "h1:1111", "h2:1111", "h3:1111"]}

    def env_for(idx):
        return JaxTaskAdapter().build_task_env(
            ctx_for(role="worker", index=idx, spec=spec, conf=conf))

    e0, e2, e3 = env_for(0), env_for(2), env_for(3)
    for e in (e0, e2, e3):
        assert e["MEGASCALE_NUM_SLICES"] == "2"
        assert e["MEGASCALE_COORDINATOR_ADDRESS"] == "h0:8080"
    assert e0["MEGASCALE_SLICE_ID"] == "0"
    assert e0["TPU_WORKER_HOSTNAMES"] == "h0,h1"
    assert e0["TPU_WORKER_ID"] == "0"
    assert e2["MEGASCALE_SLICE_ID"] == "1"
    assert e2["TPU_WORKER_HOSTNAMES"] == "h2,h3"
    assert e2["TPU_WORKER_ID"] == "0"
    assert e3["TPU_WORKER_ID"] == "1"
    # jax.distributed coordination stays GLOBAL (all 4 processes)
    assert e2["TONY_NUM_PROCESSES"] == "4"


def test_jax_multislice_env_single_slice_is_clean():
    from tony_tpu.runtime.jax_runtime import JaxTaskAdapter

    conf = TonyConf()
    env = JaxTaskAdapter().build_task_env(
        ctx_for(role="worker", index=0, spec={"worker": ["h0:1", "h1:1"]}, conf=conf))
    assert not any(k.startswith("MEGASCALE") for k in env)
    assert "TPU_WORKER_HOSTNAMES" not in env


def test_jax_multislice_env_rejects_indivisible_gang():
    from tony_tpu.config import ConfError
    from tony_tpu.runtime.jax_runtime import JaxTaskAdapter

    conf = TonyConf()
    conf.set("tony.tpu.num-slices", 2)
    with pytest.raises(ConfError, match="does not divide"):
        JaxTaskAdapter().build_task_env(
            ctx_for(role="worker", index=0,
                    spec={"worker": ["h0:1", "h1:1", "h2:1"]}, conf=conf))
