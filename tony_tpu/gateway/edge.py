"""The event-driven serving edge: tens of thousands of concurrent
streams on a few threads.

TonY's AM serves its whole cluster — heartbeats, registrations, the
portal — from a handful of event-driven server threads (PAPER.md); the
thread-per-connection ``GatewayHTTP`` inverted that, so the fleet
behind the queue could scale while the front door could not. This
module is the re-inversion: ``GatewayEdge`` serves the exact same
routes (gateway/http.py's shared helpers) from

  - ONE asyncio loop thread doing all accept/read/parse/write I/O,
  - a small FIXED ThreadPoolExecutor (default 4) for the blocking
    gateway calls (submit, snapshot, result) — sized to the route
    work, never to the connection count.

Concurrency model
-----------------
Every connection is one coroutine parsing HTTP/1.1 requests
sequentially off its reader (keep-alive + pipelining-safe by
construction: a connection's responses go out in request order because
the coroutine handles one request at a time). Blocking work hops to
the executor via ``run_in_executor``; token events flow back from the
replica threads via ``loop.call_soon_threadsafe`` into a per-request
``asyncio.Queue`` — no thread ever blocks on a client's readiness.
An idle COMMITTED stream emits ``{"keepalive": true}`` lines on the
same cadence as the threaded edge (http.STREAM_KEEPALIVE_S).

Slow-client policy
------------------
A reader that stops draining its socket gets bounded buffering, then a
clean abort — never a pinned worker thread or an unbounded buffer:
the transport's write buffer is capped (``write_buffer_kb``), writes
await ``drain()`` under ``drain_timeout_s``, and a drain that times
out aborts the transport, counts ``slow_client_aborts``, and detaches
the event callback so the replica's remaining events for that request
are dropped on the floor (the request itself finishes server-side;
its tokens just have no reader). ``write_buffer_hwm`` records the
worst buffered-bytes watermark observed at write time.

Connection-limit breaker
------------------------
Past ``max_connections`` the edge sheds NEW connections with an
immediate 503 + ``Retry-After`` and closes — before the accept
backlog melts or fds run out — counted as ``conn_limit_sheds``. The
limit defaults under the typical fd budget (ulimit -n) rather than at
it, leaving room for the agent channels and history files.

A ``GatewayEdge`` is drop-in for ``GatewayHTTP``: same constructor
shape, ``.host``/``.port``/``.start()``/``.stop()``; the CLI's
``--edge event`` (default) / ``--edge threaded`` picks between them.
On start it registers its connection-plane stats with the gateway
(``Gateway.register_edge``), so /stats grows an ``edge`` block and
/metrics the ``tony_edge_*`` families.
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable
from urllib.parse import parse_qsl, unquote

from tony_tpu.gateway.core import Gateway, Shed
from tony_tpu.gateway.http import (STREAM_KEEPALIVE_S, finish_doc,
                                   get_route, parse_generate,
                                   profile_request, shed_headers)

log = logging.getLogger(__name__)

_MAX_HEADER = 16 << 10  # request line + headers cap (8K is the common
#                         server default; 16K leaves margin)
_MAX_BODY = 8 << 20  # same POST body cap as the threaded edge

_CLOSE = object()  # queue sentinel: response complete, close allowed


class _EdgeStats:
    """Connection-plane counters. Mutated ONLY on the loop thread;
    snapshot() is read cross-thread from /stats scrapes — plain int
    reads are atomic under the GIL, and a torn multi-field view is
    acceptable for monitoring, so no lock."""

    def __init__(self, workers: int, max_connections: int):
        self.workers = workers
        self.max_connections = max_connections
        self.open_connections = 0
        self.active_streams = 0
        self.accepts = 0
        self.requests = 0
        self.slow_client_aborts = 0
        self.conn_limit_sheds = 0
        self.client_disconnects = 0
        self.keepalives_sent = 0
        self.write_buffer_hwm = 0
        self.t_start = time.monotonic()
        # accepts/s over a short sliding window (deque of accept
        # timestamps would be O(rate); a two-sample rate is enough)
        self._rate_t = self.t_start
        self._rate_n = 0
        self.accept_rate = 0.0

    def on_accept(self) -> None:
        self.accepts += 1
        now = time.monotonic()
        if now - self._rate_t >= 1.0:
            self.accept_rate = ((self.accepts - self._rate_n)
                                / (now - self._rate_t))
            self._rate_t, self._rate_n = now, self.accepts

    def snapshot(self) -> dict:
        now = time.monotonic()
        # refresh the rate when accepts stopped (else it freezes at
        # the last burst's value forever)
        rate = self.accept_rate
        if now - self._rate_t >= 5.0:
            rate = (self.accepts - self._rate_n) / (now - self._rate_t)
        return {
            "kind": "event",
            "threads": 1 + self.workers,  # the loop + the pool: FIXED
            "workers": self.workers,
            "max_connections": self.max_connections,
            "open_connections": self.open_connections,
            "active_streams": self.active_streams,
            "accepts": self.accepts,
            "accepts_per_s": round(rate, 3),
            "requests": self.requests,
            "slow_client_aborts": self.slow_client_aborts,
            "conn_limit_sheds": self.conn_limit_sheds,
            "client_disconnects": self.client_disconnects,
            "keepalives_sent": self.keepalives_sent,
            "write_buffer_hwm_bytes": self.write_buffer_hwm,
            "uptime_s": round(now - self.t_start, 3),
        }


class _HTTPError(Exception):
    """Protocol-level refusal: (status, message)."""

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


class _SlowClientAbort(ConnectionResetError):
    """A drain() deadline fired: the client stopped reading. Distinct
    from an ordinary disconnect so the counters stay honest."""


async def _read_request(reader: asyncio.StreamReader,
                        io_timeout_s: float):
    """Parse one HTTP/1.1 request head + Content-Length body.
    Returns (method, path, headers, body) or None on clean EOF before
    a request line (keep-alive close).

    An IDLE keep-alive connection (zero bytes of the next request) is
    free to sit — that is the 10k-idle-connections case, and it costs
    one coroutine + buffers, no deadline. The moment the first byte
    arrives, the REST of the head and the whole body read under
    ``io_timeout_s``: a client trickling bytes one per second cannot
    hold the parser hostage — it costs at most the deadline and the
    bytes buffered so far, then a clean 408."""
    try:
        first = await reader.readexactly(1)  # idle: no deadline
    except asyncio.IncompleteReadError:
        return None  # clean EOF between requests
    try:
        head = first + await asyncio.wait_for(
            reader.readuntil(b"\r\n\r\n"), timeout=io_timeout_s)
    except asyncio.IncompleteReadError:
        raise _HTTPError(400, "truncated request head") from None
    except asyncio.LimitOverrunError:
        raise _HTTPError(431, "request head too large") from None
    except asyncio.TimeoutError:
        raise _HTTPError(408, "request head read timed out") from None
    lines = head.decode("latin-1").split("\r\n")
    try:
        method, target, _version = lines[0].split(" ", 2)
    except ValueError:
        raise _HTTPError(400, "malformed request line") from None
    headers = {}
    for line in lines[1:]:
        if not line:
            continue
        name, _, value = line.partition(":")
        headers[name.strip().lower()] = value.strip()
    body = b""
    length = headers.get("content-length")
    if length is not None:
        try:
            n = int(length)
        except ValueError:
            raise _HTTPError(400, "bad Content-Length") from None
        if n > _MAX_BODY:
            raise _HTTPError(413, "request body too large")
        if n > 0:
            try:
                body = await asyncio.wait_for(
                    reader.readexactly(n), timeout=io_timeout_s)
            except asyncio.IncompleteReadError:
                raise _HTTPError(400, "truncated request body") from None
            except asyncio.TimeoutError:
                # the trickled-POST case: bounded cost, clean refusal
                raise _HTTPError(408, "request body read timed out") \
                    from None
    elif headers.get("transfer-encoding", "").lower() == "chunked":
        raise _HTTPError(411, "chunked request bodies not supported; "
                              "send Content-Length")
    return method, target, headers, body


def _response(status: int, body: bytes, content_type: str,
              extra: dict | None = None, close: bool = False) -> bytes:
    reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
              408: "Request Timeout", 409: "Conflict",
              411: "Length Required", 413: "Payload Too Large",
              429: "Too Many Requests", 431: "Request Header Fields "
              "Too Large", 500: "Internal Server Error",
              503: "Service Unavailable",
              504: "Gateway Timeout"}.get(status, "")
    head = [f"HTTP/1.1 {status} {reason}",
            f"Content-Type: {content_type}",
            f"Content-Length: {len(body)}"]
    for k, v in (extra or {}).items():
        head.append(f"{k}: {v}")
    if close:
        head.append("Connection: close")
    return ("\r\n".join(head) + "\r\n\r\n").encode() + body


def _json_response(status: int, doc: dict,
                   extra: dict | None = None) -> bytes:
    # error replies may leave pipelined/keep-alive state ambiguous
    # (e.g. an unparsed body) — close on >=400, same as the threaded
    # edge's _send contract
    return _response(status, json.dumps(doc).encode(),
                     "application/json", extra=extra, close=status >= 400)


def _chunk(doc: dict) -> bytes:
    data = (json.dumps(doc) + "\n").encode()
    return f"{len(data):X}\r\n".encode() + data + b"\r\n"


_STREAM_HEAD = (b"HTTP/1.1 200 OK\r\n"
                b"Content-Type: application/x-ndjson\r\n"
                b"Transfer-Encoding: chunked\r\n"
                b"Cache-Control: no-store\r\n\r\n")


class GatewayEdge:
    """The event-driven network face. Drop-in for ``GatewayHTTP``."""

    def __init__(self, gateway: Gateway, host: str = "127.0.0.1",
                 port: int = 0, encode: Callable | None = None,
                 decode: Callable | None = None,
                 keepalive_s: float = STREAM_KEEPALIVE_S,
                 max_connections: int = 16384, workers: int = 4,
                 write_buffer_kb: int = 256,
                 drain_timeout_s: float = 10.0,
                 io_timeout_s: float = 30.0):
        self.gateway = gateway
        self.encode = encode
        self.decode = decode
        self.keepalive_s = max(0.05, keepalive_s)
        self.max_connections = max(1, max_connections)
        self.write_buffer = max(1, write_buffer_kb) << 10
        self.drain_timeout_s = max(0.05, drain_timeout_s)
        self.io_timeout_s = max(0.1, io_timeout_s)
        self.stats = _EdgeStats(max(1, workers), self.max_connections)
        self._bind_host, self._bind_port = host, port
        self.host: str = host
        self.port: int = port
        self._loop: asyncio.AbstractEventLoop | None = None
        self._server: asyncio.base_events.Server | None = None
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, workers), thread_name_prefix="edge-worker")
        self._thread: threading.Thread | None = None
        self._started = threading.Event()
        self._start_error: BaseException | None = None

    # ------------------------------------------------------- lifecycle

    def start(self) -> "GatewayEdge":
        self._thread = threading.Thread(target=self._run,
                                        name="gateway-edge", daemon=True)
        self._thread.start()
        self._started.wait(timeout=30.0)
        if self._start_error is not None:
            raise self._start_error
        if not self._started.is_set():
            raise RuntimeError("edge failed to start within 30s")
        self.gateway.register_edge(self.stats.snapshot)
        log.info("gateway edge (event) at http://%s:%d "
                 "(%d workers, max %d connections)", self.host,
                 self.port, self.stats.workers, self.max_connections)
        return self

    def stop(self) -> None:
        loop = self._loop
        if loop is None or not loop.is_running():
            return
        self.gateway.register_edge(None)
        asyncio.run_coroutine_threadsafe(self._shutdown(), loop)
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        self._pool.shutdown(wait=False)

    async def _shutdown(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        # cancel every live connection coroutine, then stop the loop
        for task in asyncio.all_tasks():
            if task is not asyncio.current_task():
                task.cancel()
        self._loop.stop()

    def _run(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        try:
            self._server = loop.run_until_complete(asyncio.start_server(
                self._on_connection, self._bind_host, self._bind_port,
                limit=_MAX_HEADER, backlog=1024))
            addr = self._server.sockets[0].getsockname()
            self.host, self.port = addr[0], addr[1]
        except BaseException as e:  # surfaced in start()
            self._start_error = e
            self._started.set()
            loop.close()
            return
        self._started.set()
        try:
            loop.run_forever()
        finally:
            try:
                loop.run_until_complete(loop.shutdown_asyncgens())
            except Exception:
                pass
            loop.close()

    # ----------------------------------------------------- connections

    async def _on_connection(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        st = self.stats
        st.on_accept()
        if st.open_connections >= self.max_connections:
            # the breaker: shed BEFORE this connection costs anything —
            # an immediate 503 + honest Retry-After, then close
            st.conn_limit_sheds += 1
            try:
                writer.write(_json_response(
                    503, {"error": "connection limit reached"},
                    extra={"Retry-After": "1"}))
                await asyncio.wait_for(writer.drain(), timeout=1.0)
            except (ConnectionError, asyncio.TimeoutError):
                pass
            finally:
                writer.close()
            return
        st.open_connections += 1
        # bound the kernel-side write buffering: past the high mark,
        # drain() actually waits, which is what arms the slow-client
        # abort below
        writer.transport.set_write_buffer_limits(high=self.write_buffer)
        try:
            await self._serve_connection(reader, writer)
        except _SlowClientAbort:
            pass  # already counted + aborted in _write
        except (ConnectionError, asyncio.TimeoutError):
            # disconnect-without-FIN lands here too: the next read or
            # write on the dead socket raises, the slot frees, the
            # counter ticks — no 500, no co-tenant impact
            st.client_disconnects += 1
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("edge connection crashed")
        finally:
            st.open_connections -= 1
            writer.close()

    async def _serve_connection(self, reader, writer) -> None:
        """One coroutine per connection: parse requests sequentially
        (pipelining-safe), dispatch, write responses in order."""
        while True:
            try:
                parsed = await _read_request(reader, self.io_timeout_s)
            except _HTTPError as e:
                await self._write(writer, _json_response(
                    e.status, {"error": str(e)}))
                return  # protocol errors close (framing is suspect)
            if parsed is None:
                return  # clean keep-alive close
            self.stats.requests += 1
            method, target, headers, body = parsed
            try:
                close = await self._dispatch(method, target, headers,
                                             body, writer)
            except _HTTPError as e:
                await self._write(writer, _json_response(
                    e.status, {"error": str(e)}))
                return  # >=400 closes (see _json_response)
            if close or headers.get("connection", "").lower() == "close":
                return

    async def _dispatch(self, method: str, target: str, headers: dict,
                        body: bytes, writer) -> bool:
        """Route one request; returns True when the connection must
        close after the response."""
        path, _, query = target.partition("?")
        loop = asyncio.get_running_loop()
        if method == "GET":
            if path == "/metrics":
                from tony_tpu.obs import prometheus_text

                text = await loop.run_in_executor(
                    self._pool, prometheus_text, self.gateway)
                await self._write(writer, _response(
                    200, text.encode(),
                    "text/plain; version=0.0.4; charset=utf-8"))
                return False
            if path.startswith("/v1/stream/"):
                return await self._resume(path, query, writer)
            route = await loop.run_in_executor(
                self._pool, get_route, self.gateway, path)
            if route is None:
                await self._write(writer,
                                  _json_response(404,
                                                 {"error": "not found"}))
                return True
            await self._write(writer, _json_response(*route))
            return route[0] >= 400
        if method == "POST":
            if path == "/debug/profile":
                code, doc = await loop.run_in_executor(
                    self._pool, profile_request, self.gateway, query)
                await self._write(writer, _json_response(code, doc))
                return code >= 400
            if path == "/v1/generate":
                return await self._generate(headers, body, writer)
            await self._write(writer,
                              _json_response(404, {"error": "not found"}))
            return True
        raise _HTTPError(400, f"unsupported method {method}")

    # -------------------------------------------------------- generate

    async def _generate(self, headers: dict, body: bytes,
                        writer) -> bool:
        t_receive = time.monotonic()
        loop = asyncio.get_running_loop()
        try:
            doc = json.loads(body) if body else None
            if doc is None:
                raise ValueError("missing request body")
            req, stream = parse_generate(doc, self.encode)
            req.t_receive = t_receive
        except (TypeError, ValueError) as e:
            await self._write(writer, _json_response(400,
                                                     {"error": str(e)}))
            return True
        # the per-request event queue: replica threads push via
        # call_soon_threadsafe, this coroutine pops. ``aborted`` is the
        # slow-client detach: once set, further events are dropped at
        # the callback (no unbounded queue behind a dead reader).
        q: asyncio.Queue = asyncio.Queue()
        aborted = threading.Event()

        def on_event(_ticket, event):
            if aborted.is_set():
                return
            try:
                loop.call_soon_threadsafe(q.put_nowait, event)
            except RuntimeError:
                aborted.set()  # loop closed mid-shutdown

        try:
            # submit can block on admission bookkeeping — executor, not
            # the loop thread
            ticket = await loop.run_in_executor(
                self._pool, lambda: self.gateway.submit(req, on_event))
        except Shed as e:
            await self._write(writer, _json_response(
                e.http_status, {"error": e.reason},
                extra=shed_headers(e)))
            return True
        try:
            if stream:
                return await self._respond_stream(ticket, q, writer)
            return await self._respond_unary(ticket, q, writer)
        finally:
            aborted.set()  # detach: late events have no reader

    # ---------------------------------------------------------- resume

    async def _resume(self, path: str, query: str, writer) -> bool:
        """GET /v1/stream/<request_id>?offset=N (ISSUE-20): re-attach
        to a request's absolute token sequence. The gateway's
        ``resume_events`` is a blocking poll generator; parking it on
        the tiny shared executor would starve routing, so each resume
        gets a dedicated daemon pump thread that forwards docs onto an
        asyncio queue (same call_soon_threadsafe handoff as the
        generate path) and stops at the terminal line or when the
        watcher disconnects."""
        rid = unquote(path[len("/v1/stream/"):])
        if not rid:
            await self._write(writer,
                              _json_response(404, {"error": "not found"}))
            return True
        offset = 0
        for key, val in parse_qsl(query):
            if key == "offset":
                try:
                    offset = int(val)
                except ValueError:
                    await self._write(writer, _json_response(
                        400, {"error": "offset must be an integer"}))
                    return True
        if offset < 0:
            await self._write(writer, _json_response(
                400, {"error": "offset must be >= 0"}))
            return True
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()
        aborted = threading.Event()

        def pump():
            gen = self.gateway.resume_events(
                rid, offset, keepalive_s=self.keepalive_s)
            try:
                for doc in gen:
                    if aborted.is_set():
                        return
                    try:
                        loop.call_soon_threadsafe(q.put_nowait, doc)
                    except RuntimeError:
                        return  # loop closed mid-shutdown
                    if doc.get("gone") or doc.get("done") \
                            or doc.get("shed"):
                        return
            finally:
                try:
                    loop.call_soon_threadsafe(q.put_nowait, None)
                except RuntimeError:
                    pass

        threading.Thread(target=pump, daemon=True,
                         name=f"resume-{rid[:12]}").start()
        try:
            first = await q.get()
            if first is None or first.get("gone"):
                await self._write(writer, _json_response(
                    404,
                    {"error": f"unknown or reaped request {rid!r}"}))
                return True
            st = self.stats
            st.active_streams += 1
            try:
                await self._write(writer, _STREAM_HEAD)
                doc = first
                while doc is not None:
                    if doc.get("shed"):
                        await self._write(writer, _chunk(
                            {"id": rid, "request_id": rid,
                             "error": doc.get("reason", "shed"),
                             "status": doc.get("status", 503)})
                            + b"0\r\n\r\n")
                        return True
                    if doc.get("done"):
                        await self._write(writer, _chunk(
                            {"id": rid, "request_id": rid, "done": True,
                             "metrics": doc.get("metrics") or {}})
                            + b"0\r\n\r\n")
                        return False
                    if doc.get("keepalive"):
                        st.keepalives_sent += 1
                    doc.setdefault("id", rid)
                    doc.setdefault("request_id", rid)
                    await self._write(writer, _chunk(doc))
                    doc = await q.get()
                # pump died without a terminal line (shutdown): close
                await self._write(writer, b"0\r\n\r\n")
                return True
            finally:
                st.active_streams -= 1
        finally:
            aborted.set()  # detach: the pump stops at its next doc

    async def _respond_unary(self, ticket, q, writer) -> bool:
        """Unary waits on the SAME event queue the stream path uses —
        no executor thread parked on ticket.result(), so ten thousand
        concurrent unary requests cost queue entries, not threads."""
        while True:
            kind, *rest = await q.get()
            if kind == "tokens":
                continue  # unary: deltas accumulate server-side
            if kind == "done":
                res, metrics = rest
                await self._write(writer, _json_response(
                    200, finish_doc(res, metrics or {}, self.decode)))
                return False
            if kind == "shed":
                status, reason = rest
                await self._write(writer, _json_response(
                    status, {"error": reason}))
                return True

    async def _respond_stream(self, ticket, q, writer) -> bool:
        """Chunked NDJSON with lazy status commit (sheds keep real
        codes), keepalives once committed, and the slow-client abort
        armed on every write."""
        st = self.stats
        st.active_streams += 1
        headers_sent = False
        try:
            while True:
                try:
                    timeout = self.keepalive_s if headers_sent else None
                    kind, *rest = await asyncio.wait_for(
                        q.get(), timeout=timeout)
                except asyncio.TimeoutError:
                    st.keepalives_sent += 1
                    await self._write(writer, _chunk({"keepalive": True}))
                    continue
                if kind == "tokens":
                    if not headers_sent:
                        await self._write(writer, _STREAM_HEAD)
                        headers_sent = True
                    await self._write(writer, _chunk(
                        {"id": ticket.request.id,
                         "request_id": ticket.request.id,
                         "token_ids": rest[0]}))
                elif kind == "done":
                    res, metrics = rest
                    if not headers_sent:
                        await self._write(writer, _STREAM_HEAD)
                        headers_sent = True
                    await self._write(writer, _chunk(
                        finish_doc(res, metrics, self.decode))
                        + b"0\r\n\r\n")
                    return False
                elif kind == "shed":
                    status, reason = rest
                    if headers_sent:
                        await self._write(writer, _chunk(
                            {"id": ticket.request.id, "error": reason,
                             "status": status}) + b"0\r\n\r\n")
                        return True
                    await self._write(writer, _json_response(
                        status, {"error": reason}))
                    return True
        finally:
            st.active_streams -= 1

    # ----------------------------------------------------------- write

    async def _write(self, writer: asyncio.StreamWriter,
                     data: bytes) -> None:
        """The slow-client policy lives here: write, note the buffer
        watermark, then drain under a deadline. A drain timeout means
        the client stopped reading — abort the transport (RST, frees
        the fd now) and count it; the ConnectionResetError surfaces to
        _on_connection which frees the slot."""
        writer.write(data)
        buffered = writer.transport.get_write_buffer_size()
        if buffered > self.stats.write_buffer_hwm:
            self.stats.write_buffer_hwm = buffered
        try:
            await asyncio.wait_for(writer.drain(),
                                   timeout=self.drain_timeout_s)
        except asyncio.TimeoutError:
            self.stats.slow_client_aborts += 1
            writer.transport.abort()
            raise _SlowClientAbort(
                "slow client: write buffer not drained in "
                f"{self.drain_timeout_s:.1f}s") from None
