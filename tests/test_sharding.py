"""parallel/sharding.py preset units (ISSUE-14 satellite): spec_for
rule matching, tree_shardings over a realistic transformer param tree,
shard_params_by_size's non-divisible fallback, and the serving preset
(row-parallel flip, validation, KV-cache shardings, per-chip bytes)."""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tony_tpu.models import Transformer, TransformerConfig
from tony_tpu.models.generate import init_cache
from tony_tpu.models.transformer import logical_axis_rules_tree
from tony_tpu.parallel.mesh import EXPERT, MeshSpec, TENSOR, make_mesh
from tony_tpu.parallel.sharding import (RULES, kv_cache_shardings,
                                        kv_shard_count,
                                        serve_spec_for,
                                        serving_shardings,
                                        shard_params_by_size, spec_for,
                                        tree_shard_bytes,
                                        tree_shard_count,
                                        tree_shardings, validated_spec)


@pytest.fixture(scope="module")
def mesh4():
    return make_mesh(MeshSpec(data=1, tensor=4),
                     devices=jax.devices()[:4])


@pytest.fixture(scope="module")
def tiny():
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=2, d_ff=64, max_seq_len=64,
                            dtype=jnp.float32,
                            attention_backend="reference")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _by_path(tree):
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        out["/".join(getattr(p, "key", str(p)) for p in path)] = leaf
    return out


# ------------------------------------------------------ spec_for rules


def test_spec_for_rule_matching():
    rules = RULES["tp"]
    # q kernel (embed, heads, kv): heads -> tensor under tp
    assert spec_for(("embed", "heads", "kv"), rules) \
        == P(None, TENSOR, None)
    # mlp wi (embed, mlp)
    assert spec_for(("embed", "mlp"), rules) == P(None, TENSOR)
    # unknown logical names and Nones replicate
    assert spec_for((None, "nonexistent"), rules) == P(None, None)
    # dp: batch spans (data, fsdp)
    assert spec_for(("batch", "embed"), RULES["dp"]) \
        == P(("data", "fsdp"), None)


def test_tree_shardings_transformer_tree(mesh4, tiny):
    """tree_shardings over a realistic param tree: every leaf gets a
    NamedSharding whose spec follows its path-derived logical axes."""
    _, params = tiny
    logical = logical_axis_rules_tree(params)
    sh = tree_shardings(mesh4, logical, "tp")
    by = _by_path(sh)
    assert by["block_0/attn/q/kernel"].spec == P(None, TENSOR, None)
    assert by["block_0/mlp/wi/kernel"].spec == P(None, TENSOR)
    # tp shards vocab on the embedding
    assert by["embedding"].spec == P(TENSOR, None)
    # norm scales replicate
    assert by["ln_f/scale"].spec == P(None)
    # every leaf is a NamedSharding on the same mesh
    for leaf in jax.tree_util.tree_leaves(
            sh, is_leaf=lambda x: isinstance(x, NamedSharding)):
        assert isinstance(leaf, NamedSharding)


def test_shard_params_by_size_non_divisible_falls_back_replicated():
    mesh = make_mesh(MeshSpec(data=2, fsdp=4),
                     devices=jax.devices()[:8])
    params = {
        "big_divisible": jnp.zeros((256, 128)),
        # both dims indivisible by fsdp=4 -> replicated, not an error
        "big_odd": jnp.zeros((255, 129)),
        "small": jnp.zeros((4, 4)),
    }
    sh = shard_params_by_size(mesh, params)
    assert sh["big_divisible"].spec == P("fsdp", None)
    assert sh["big_odd"].spec == P()
    assert sh["small"].spec == P()


# ------------------------------------------------------- serve preset


def test_serve_spec_flips_row_parallel_kernels():
    rules = RULES["serve"]
    # column-parallel kernels shard their output dim
    assert serve_spec_for(("embed", "heads", "kv"), rules) \
        == P(None, TENSOR, None)
    assert serve_spec_for(("embed", "mlp"), rules) == P(None, TENSOR)
    # row-parallel kernels (o, wo) FLIP: the heads/mlp contraction dim
    # replicates and the trailing embed (output) dim shards — no
    # cross-chip partial-sum reduction, ever
    assert serve_spec_for(("heads", "kv", "embed"), rules) \
        == P(None, None, TENSOR)
    assert serve_spec_for(("mlp", "embed"), rules) == P(None, TENSOR)
    # the embedding does NOT flip (vocab is an output dim in the
    # logits projection; the input gather is not a contraction)
    assert serve_spec_for(("vocab", "embed"), rules) == P(TENSOR, None)
    # MoE wo keeps its expert axis, flips mlp -> embed
    assert serve_spec_for(("expert", "mlp", "embed"), rules) \
        == P(EXPERT, None, TENSOR)
    # rank-1 leaves never flip
    assert serve_spec_for(("embed",), rules) == P(None)


def test_validated_spec_drops_non_divisible(mesh4):
    # 4 divides 8 -> kept; 4 does not divide 6 -> dropped
    assert validated_spec(mesh4, P(TENSOR, None), (8, 3)) \
        == P(TENSOR, None)
    assert validated_spec(mesh4, P(TENSOR, None), (6, 3)) == P(None, None)
    # tuple assignments validate against the product
    mesh8 = make_mesh(MeshSpec(data=2, tensor=4),
                      devices=jax.devices()[:8])
    assert validated_spec(mesh8, P(("data", "tensor")), (16,)) \
        == P(("data", "tensor"))
    assert validated_spec(mesh8, P(("data", "tensor")), (12,)) == P(None)


def test_serving_shardings_transformer(mesh4, tiny):
    _, params = tiny
    sh = serving_shardings(mesh4, params)
    by = _by_path(sh)
    # q/k/v column-parallel on heads (MHA: kv heads == heads == 4)
    assert by["block_0/attn/q/kernel"].spec == P(None, TENSOR, None)
    assert by["block_0/attn/k/kernel"].spec == P(None, TENSOR, None)
    # o and wo flipped to output-dim (embed) sharding
    assert by["block_0/attn/o/kernel"].spec == P(None, None, TENSOR)
    assert by["block_0/mlp/wo/kernel"].spec == P(None, TENSOR)
    assert by["block_0/mlp/wi/kernel"].spec == P(None, TENSOR)
    assert by["embedding"].spec == P(TENSOR, None)
    assert by["ln_f/scale"].spec == P(None)


def test_serving_shardings_gqa_small_heads_replicate(mesh4):
    """GQA with kv_heads=2 on a tensor=4 mesh: K/V kernels (and the
    pools, below) replicate via validation; q (4 heads... also
    indivisible) replicates too — nothing errors."""
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_kv_heads=2, n_layers=1, d_ff=64,
                            max_seq_len=64, dtype=jnp.float32,
                            attention_backend="reference")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    sh = serving_shardings(mesh4, params)
    by = _by_path(sh)
    # kv_heads=2 not divisible by 4 -> replicated
    assert by["block_0/attn/k/kernel"].spec == P(None, None, None)
    # n_heads=4 IS divisible -> q still shards
    assert by["block_0/attn/q/kernel"].spec == P(None, TENSOR, None)
    cache = init_cache(model, params, 2)
    assert kv_shard_count(mesh4, cache) == 1
    for leaf in jax.tree_util.tree_leaves(
            kv_cache_shardings(mesh4, cache),
            is_leaf=lambda x: isinstance(x, NamedSharding)):
        assert leaf.spec == P()


def test_serving_shardings_q8_leaves(mesh4):
    """int8 serving weights (models/quantize.py): kernel_q8/scale
    leaves shard alongside their bf16 twins — o/wo q8 kernels flip to
    embed like the float kernels."""
    from tony_tpu.models.quantize import quantize_for_serving

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=1, d_ff=64, max_seq_len=64,
                            dtype=jnp.float32,
                            attention_backend="reference")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    _, qparams = quantize_for_serving(model, params)
    sh = serving_shardings(mesh4, qparams)
    by = _by_path(sh)
    # q: column-parallel on the flattened heads output dim
    assert by["block_0/attn/q/kernel_q8"].spec == P(None, TENSOR)
    assert by["block_0/attn/q/scale"].spec == P(TENSOR)
    # o: row-parallel -> flipped to the embed output dim; its rank-1
    # scale ("embed",) has no flip trigger and replicates — tiny, and
    # GSPMD slices it against the sharded output where needed
    assert by["block_0/attn/o/kernel_q8"].spec == P(None, TENSOR)
    assert by["block_0/attn/o/scale"].spec == P(None)
    # wi: column-parallel — its scale shards with the mlp output dim
    assert by["block_0/mlp/wi/kernel_q8"].spec == P(None, TENSOR)
    assert by["block_0/mlp/wi/scale"].spec == P(TENSOR)


# --------------------------------------------------- KV cache shardings


def test_kv_cache_shardings_paged_and_unpaged(mesh4, tiny):
    from tony_tpu.serve.slots import paged_cache

    model, params = tiny
    # unpaged rows [b, max_len, kvh, dh]: kvh (dim 2) shards
    cache = init_cache(model, params, 2)
    by = _by_path(kv_cache_shardings(mesh4, cache))
    key = next(k for k in by if k.endswith("cached_key"))
    assert by[key].spec == P(None, None, TENSOR, None)
    assert kv_shard_count(mesh4, cache) == 4
    # paged pools [n_pages, page_size, kvh, dh]: same rule, page axis
    # whole (the host allocator's page ids mean the same everywhere)
    pool = paged_cache(model, params, 8, 16)
    byp = _by_path(kv_cache_shardings(mesh4, pool))
    keyp = next(k for k in byp if k.endswith("cached_key"))
    assert byp[keyp].spec == P(None, None, TENSOR, None)
    # shared counters replicate
    idx = next(k for k in byp if k.endswith("cache_index"))
    assert byp[idx].spec == P()


def test_tree_shard_bytes_counts_per_chip(mesh4):
    params = {"sharded": jnp.zeros((8, 16), jnp.float32),
              "replicated": jnp.zeros((6, 2), jnp.float32)}
    sh = {"sharded": NamedSharding(mesh4, P(TENSOR, None)),
          "replicated": NamedSharding(mesh4, P())}
    # sharded leaf contributes 1/4, replicated leaf its whole size
    assert tree_shard_bytes(params, sh) == (8 * 16 // 4 + 6 * 2) * 4
    assert tree_shard_count(params, sh) == 8 * 16 // 4 + 6 * 2


def test_int8_kv_flash_bytes_ratio_still_below_one(tiny):
    """The r13 regression sensor must keep pinning bytes < 1 after the
    BlockSpec relayout (the kernel-shape suspect is what changed; the
    read set did not grow)."""
    from bench import _int8_kv_flash_bytes

    model, params = tiny
    out = _int8_kv_flash_bytes(model.cfg, params, batch=8,
                               cache_tokens=512)
    assert out["int8_kv_flash_bytes_ratio"] < 1.0, out
    assert out["int8_kv_flash_verdict"] == "dispatch", out
