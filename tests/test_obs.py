"""Serving observability tests (ISSUE 6): traces, timeline, /metrics.

Four layers, pinned bottom-up:

- ``obs.trace`` units: span invariants (monotonic timestamps, strict
  nesting), the stale-span drop rule, Chrome trace-event export, the
  bounded trace ring;
- ``obs.prom`` units: exposition golden checks — ``# HELP``/``# TYPE``
  headers, label escaping, histogram bucket monotonicity and the
  ``+Inf`` tail;
- ``obs.timeline`` units: per-dispatch records, the compile/steady
  split, cross-replica merge — plus the ENGINE integration (a real
  ``serve.Server`` emits prefill/decode/verify records whose token
  counts reconcile with results);
- gateway integration: every completed request leaves a trace whose
  spans nest and whose export ``json.loads``; a forced mid-stream
  replica kill leaves ONE trace carrying BOTH attempts with distinct
  replica tags and the failover fence between them (the ISSUE-6
  acceptance pin); ``GET /metrics`` is format-valid and consistent
  with ``/stats``; ``/debug/trace/<id>`` and ``/debug/profile`` work
  over real HTTP; client-supplied request ids thread through every
  surface, absent ids come back as server UUIDs.

The always-on-cheap contract (TPOT with tracing+timeline enabled
within 1.1x of disabled) is pinned by the slow overhead gate at the
bottom; bench ``extras.obs`` records the same A/B as a datum.
"""

import json
import re
import time
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from tony_tpu.gateway import Gateway, GatewayHistory, GatewayHTTP, GenRequest
from tony_tpu.models import Transformer, TransformerConfig
from tony_tpu.obs import (DispatchRecord, DispatchTimeline, Histogram,
                          MetricFamily, RequestTrace, TraceBuffer,
                          check_invariants, escape_label_value,
                          prometheus_text, render)
from tony_tpu.serve import FaultPlan, Request, Server


@pytest.fixture(scope="module")
def tiny():
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=32,
                            dtype=jnp.float32,
                            attention_backend="reference")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


# ------------------------------------------------------- trace units


def test_trace_spans_nest_and_export():
    tr = RequestTrace("r1", t0=100.0)
    tr.begin_attempt(replica=0, epoch=0, t0=100.5)
    tr.add("queue_wait", 100.5, 101.0, attempt=True)
    tr.add("prefill", 101.0, 101.5, attempt=True, bucket=16)
    tr.add("decode", 101.5, 102.0, attempt=True, tokens=4)
    tr.end_attempt(102.0, outcome="done")
    tr.finish(102.0, outcome="done")
    assert check_invariants(tr) == []
    assert tr.n_attempts == 1 and tr.done
    doc = tr.to_chrome()
    json.loads(json.dumps(doc))  # valid JSON end to end
    events = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    names = [e["name"] for e in events]
    assert names == ["request", "attempt-1", "queue_wait", "prefill",
                     "decode"]
    # complete events with microsecond ts/dur; spans inside the root
    # (5 us tolerance: ts is epoch microseconds ~1e15, where float64
    # granularity alone is ~0.25 us)
    root = events[0]
    for e in events[1:]:
        assert e["ts"] >= root["ts"] - 5
        assert e["ts"] + e["dur"] <= root["ts"] + root["dur"] + 5
    # the attempt renders on its replica's pid, its own tid row
    att = events[1]
    assert att["pid"] == 0 and att["tid"] == 1
    assert doc["otherData"]["request_id"] == "r1"


def test_trace_stale_spans_dropped_after_steal_and_finish():
    """The failover fence, tracing flavor: spans from a stale owner
    (attempt already ended / trace already finished) are DROPPED, so a
    wedged replica returning late can never mutate an exported trace."""
    tr = RequestTrace("r2", t0=0.0)
    tr.begin_attempt(0, 0, t0=0.1)
    tr.end_attempt(0.5, outcome="failed")  # the supervisor's steal
    tr.add("decode", 0.4, 0.6, attempt=True)  # stale owner's late record
    assert tr.dropped == 1
    tr.begin_attempt(1, 0, t0=0.7)
    # the airtight fence: a stale owner that raced a steal AND the
    # survivor's re-placement must not land its span in the NEW
    # attempt — attempt_key is checked atomically under the trace lock
    tr.add("decode", 0.55, 0.65, attempt_key=(0, 0))  # old replica
    assert tr.dropped == 2
    tr.add("decode", 0.8, 0.9, attempt_key=(1, 0))  # current owner
    tr.finish(1.0)
    tr.add("decode", 1.0, 1.1)  # post-finish: dropped too
    assert tr.dropped == 3
    assert check_invariants(tr) == []
    assert tr.n_attempts == 2
    names = [c.name for a in tr.root.children for c in a.children]
    assert names == ["decode"]  # only the current owner's span landed


def test_trace_span_cap_bounds_memory():
    """A marathon generation (thousands of decode dispatches) must not
    grow its trace without bound: past max_spans further spans are
    counted as truncated, not stored, and the export stays valid."""
    tr = RequestTrace("big", t0=0.0, max_spans=4)
    tr.begin_attempt(0, 0, t0=0.1)
    for i in range(10):
        tr.add("decode", 0.2 + i * 0.1, 0.3 + i * 0.1, attempt=True)
    tr.finish(2.0)
    assert tr.truncated == 6
    assert check_invariants(tr) == []
    doc = tr.to_chrome()
    assert doc["otherData"]["truncated_spans"] == 6
    assert len([e for e in doc["traceEvents"] if e["ph"] == "X"]) == 6


def test_trace_open_spans_clamped_in_export():
    """An in-flight request inspected early must still export
    well-formed JSON: open spans clamp to the latest timestamp seen."""
    tr = RequestTrace("r3", t0=10.0)
    tr.begin_attempt(0, 0, t0=10.1)
    tr.add("decode", 10.2, 10.4, attempt=True)
    doc = tr.to_chrome()  # attempt + root still open
    for e in doc["traceEvents"]:
        if e["ph"] == "X":
            assert e["dur"] >= 0


def test_check_invariants_catches_violations():
    tr = RequestTrace("bad", t0=50.0)
    tr.add("inverted", 52.0, 51.0)  # t1 < t0
    tr.add("early", 49.0, 49.5)     # before the root AND before sibling
    tr.finish(53.0)
    problems = check_invariants(tr)
    assert any("t1" in p for p in problems)
    assert any("outside parent" in p or "before" in p for p in problems)


def test_trace_buffer_bounded_and_last_writer_wins():
    buf = TraceBuffer(capacity=2)
    for i in range(3):
        t = RequestTrace(f"t{i}", t0=float(i))
        t.finish(float(i) + 1)
        buf.put(t)
    assert len(buf) == 2
    assert buf.get("t0") is None  # evicted oldest-first
    assert buf.ids() == ["t1", "t2"]
    newer = RequestTrace("t1", t0=9.0)
    newer.finish(9.5)
    buf.put(newer)
    assert buf.get("t1") is newer  # re-used id: last writer wins


# ----------------------------------------------------- exposition units


def test_label_escaping():
    assert escape_label_value('a"b\\c\nd') == 'a\\"b\\\\c\\nd'


def test_metric_family_render_golden():
    fam = MetricFamily("tony_test_total", "counter", "A test counter")
    fam.add(3, {"replica": "0"})
    fam.add(4.5, {"replica": "1", "state": 'we"ird'})
    text = fam.render()
    lines = text.splitlines()
    assert lines[0] == "# HELP tony_test_total A test counter"
    assert lines[1] == "# TYPE tony_test_total counter"
    assert lines[2] == 'tony_test_total{replica="0"} 3'
    assert lines[3] == 'tony_test_total{replica="1",state="we\\"ird"} 4.5'


def test_histogram_buckets_cumulative_monotonic():
    h = Histogram(buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0, 0.05):
        h.observe(v)
    fam = h.family("tony_lat_seconds", "latency")
    text = fam.render()
    buckets = re.findall(r'le="([^"]+)"\} (\d+)', text)
    assert [b[0] for b in buckets] == ["0.01", "0.1", "1", "+Inf"]
    counts = [int(b[1]) for b in buckets]
    assert counts == sorted(counts)      # cumulative => monotonic
    assert counts == [1, 3, 4, 5]
    assert counts[-1] == h.count == 5    # +Inf == _count
    assert "tony_lat_seconds_count 5" in text
    assert h.snapshot()["count"] == 5
    # render() of the whole document ends with a newline (spec)
    assert render([fam]).endswith("\n")


# ------------------------------------------------------- timeline units


def test_timeline_summary_compile_split_and_merge():
    tl = DispatchTimeline(capacity=8)
    tl.record(DispatchRecord("decode", 0.0, 100.0, 2, 8, 16, True))
    tl.record(DispatchRecord("decode", 1.0, 2.0, 2, 8, 16, False))
    tl.record(DispatchRecord("decode", 2.0, 4.0, 2, 8, 16, False))
    tl.record(DispatchRecord("prefill", 3.0, 50.0, 1, 16, 1, True))
    s = tl.summary()
    d = s["decode"]
    assert d["count"] == 3 and d["compiles"] == 1
    assert d["compile_ms"] == 100.0
    # steady-state mean excludes the first-call spike
    assert d["steady_mean_ms"] == pytest.approx(3.0)
    assert d["tokens"] == 48 and d["tokens_per_dispatch"] == 16.0
    assert s["prefill"]["count"] == 1
    merged = DispatchTimeline.merge([s, s])
    assert merged["decode"]["count"] == 6
    assert merged["decode"]["steady_mean_ms"] == pytest.approx(3.0)
    assert merged["decode"]["max_ms"] == 100.0


def test_timeline_ring_and_cursor():
    tl = DispatchTimeline(capacity=4)
    for i in range(6):
        tl.record(DispatchRecord("decode", float(i), 1.0, 1, 1, 1, False))
    new, cursor = tl.take_new(0)
    assert cursor == 6
    assert [r.seq for r in new] == [3, 4, 5, 6]  # 2 evicted, gone
    assert tl.take_new(cursor) == ([], 6)
    assert len(tl.recent(2)) == 2
    # lifetime aggregates survive ring eviction
    assert tl.summary()["decode"]["count"] == 6


def test_engine_timeline_records_reconcile_with_results(tiny):
    """The engine integration: run real traffic, check record kinds,
    token accounting (landed tokens == emitted tokens, overshoot
    excluded), compile flags (first (kind, shape) call only), and the
    requests tag decode spans are attached by."""
    model, params = tiny
    server = Server(model, params, batch_size=2, min_bucket=8,
                    chunk_steps=2)
    results = list(server.run([
        Request([1, 2, 3], max_new_tokens=5, id="a"),
        Request([4, 5], max_new_tokens=3, id="b"),
        Request([6], max_new_tokens=4, id="c")]))
    recs = server.timeline.recent(100)
    kinds = {r.kind for r in recs}
    assert kinds == {"prefill", "decode"}
    prefills = [r for r in recs if r.kind == "prefill"]
    assert {r.request_id for r in prefills} == {"a", "b", "c"}
    assert all(r.tokens == 1 for r in prefills)  # first token rides admit
    decodes = [r for r in recs if r.kind == "decode"]
    # tokens landed across dispatches == tokens emitted minus the admit
    # ones; trimmed chunk overshoot is NOT counted as landed
    total_emitted = sum(len(r.tokens) for r in results)
    assert sum(r.tokens for r in decodes) == total_emitted - len(results)
    # compile flag: exactly one first-call per distinct program shape —
    # (kind, bucket) plus, on the paged engine, the bucketed view span
    # (tags.view_tokens), which is a second shape knob
    for kind in ("prefill", "decode"):
        by_bucket = {}
        for r in recs:
            if r.kind == kind:
                key = (r.bucket, r.tags.get("view_tokens", 0))
                by_bucket.setdefault(key, []).append(r.compile)
        for bucket, flags in by_bucket.items():
            assert flags[0] is True and not any(flags[1:]), (kind, bucket)
    # decode records carry the engine ids live at dispatch time
    assert all(set(r.tags["requests"]) <= {"a", "b", "c"}
               for r in decodes)
    assert all(r.occupancy >= 1 for r in decodes)
    summary = server.timeline.summary()
    assert summary["decode"]["count"] == len(decodes)


def test_engine_timeline_verify_records(tiny):
    """Speculation rounds record as kind=verify with drafted/accepted
    tags — the per-dispatch view of the spec counters."""
    model, params = tiny
    server = Server(model, params, batch_size=1, min_bucket=8,
                    chunk_steps=1, speculate_k=2)
    list(server.run([Request([1, 2, 3, 1, 2, 3, 1, 2], max_new_tokens=8,
                             id="rep")]))
    recs = server.timeline.recent(100)
    verifies = [r for r in recs if r.kind == "verify"]
    assert verifies, [r.kind for r in recs]
    assert server.spec_rounds == len(verifies)
    assert sum(r.tags["drafted"] for r in verifies) == server.spec_drafted
    assert sum(r.tags["accepted"] for r in verifies) == server.spec_accepted
    assert all(r.bucket >= 2 for r in verifies)  # window = pow2 + 1


def test_engine_timeline_off_is_none(tiny):
    model, params = tiny
    server = Server(model, params, batch_size=1, min_bucket=8,
                    timeline=False)
    list(server.run([Request([1, 2], max_new_tokens=3, id="x")]))
    assert server.timeline is None  # and nothing crashed


# -------------------------------------------------- gateway integration


def _mk_gateway(tiny, n=1, history=None, stall_timeout_s=10.0,
                **server_kw):
    model, params = tiny
    servers = [Server(model, params, batch_size=2, min_bucket=8,
                      **server_kw) for _ in range(n)]
    return Gateway(servers, max_queue=32, history=history,
                   max_attempts=3, stall_timeout_s=stall_timeout_s,
                   breaker_base_s=0.05, breaker_max_s=0.2)


def test_gateway_trace_lifecycle_and_history(tiny, tmp_path):
    hist = GatewayHistory(str(tmp_path), n_replicas=1)
    gw = _mk_gateway(tiny, history=hist, chunk_steps=2).start()
    try:
        tickets = [gw.submit(GenRequest([1 + i, 2, 3], max_new_tokens=4,
                                        id=f"r{i}")) for i in range(3)]
        for t in tickets:
            t.result(timeout=120)
        for i in range(3):
            tr = gw.traces.get(f"r{i}")
            assert tr is not None and tr.done
            assert check_invariants(tr) == [], i
            doc = json.loads(tr.to_json())
            names = [e["name"] for e in doc["traceEvents"]
                     if e["ph"] == "X"]
            assert names[0] == "request"
            assert "attempt-1" in names and "queue_wait" in names
            assert "prefill" in names and "decode" in names
            # terminal tags carry the request metrics
            root = [e for e in doc["traceEvents"]
                    if e["name"] == "request"][0]
            assert root["args"]["outcome"] == "done"
            assert root["args"]["tokens_out"] == 4
    finally:
        assert gw.drain(timeout=60)
    import os

    rows = [json.loads(ln) for ln in
            open(os.path.join(hist.job_dir, "metrics", "traces.jsonl"))]
    assert {r["otherData"]["request_id"] for r in rows} == \
        {"r0", "r1", "r2"}
    assert all(r["traceEvents"] for r in rows)


def test_failover_produces_one_trace_with_both_attempts(tiny):
    """THE ISSUE-6 acceptance pin: a request that survives a mid-stream
    replica kill (TONY_SERVE_FAULTS-style injection) produces ONE trace
    containing both attempts — queue/admit/prefill/decode spans on the
    failed replica, then the failover fence and re-run spans on the
    survivor — exported as Chrome trace-event JSON that json.loads and
    the span-invariant checks accept."""
    model, params = tiny
    servers = [Server(model, params, batch_size=2, min_bucket=8,
                      chunk_steps=1,
                      fault_plan=(FaultPlan.fail_at(4) if i == 0
                                  else None))
               for i in range(2)]
    gw = Gateway(servers, max_queue=32, max_attempts=3,
                 stall_timeout_s=10.0, breaker_base_s=0.05,
                 breaker_max_s=0.2)
    prompts = [[1 + i, 2, 3] for i in range(4)]
    # pre-start submits: equal costs alternate 0,1,0,1 so replica 0
    # deterministically holds admitted tickets when dispatch 4 dies
    tickets = [gw.submit(GenRequest(p, max_new_tokens=8, id=f"c{i}"))
               for i, p in enumerate(prompts)]
    gw.start()
    try:
        for t in tickets:
            t.result(timeout=120)
        victims = [t for t in tickets if t.metrics["attempts"] >= 1]
        assert victims, "no ticket was failed over"
        for t in victims:
            tr = gw.traces.get(t.request.id)
            assert tr is not None and tr.n_attempts == 2
            assert check_invariants(tr) == []
            doc = json.loads(tr.to_json())
            events = [e for e in doc["traceEvents"] if e["ph"] == "X"]
            atts = [e for e in events if e["name"].startswith("attempt-")]
            assert len(atts) == 2
            # distinct replica tags; the failed attempt says why
            assert atts[0]["args"]["replica"] == 0
            assert atts[1]["args"]["replica"] == 1
            assert atts[0]["args"]["outcome"] == "failed"
            assert atts[1]["args"]["outcome"] == "done"
            # epoch fence between them
            fo = [e for e in events if e["name"] == "failover"]
            assert len(fo) == 1
            assert fo[0]["args"]["from_replica"] == 0
            assert fo[0]["args"]["new_epoch"] == 1
            assert fo[0]["args"]["admitted"] is True
            # both attempts ran real engine work
            first = [e["name"] for e in events
                     if e.get("tid") == atts[0]["tid"]
                     and not e["name"].startswith("attempt-")]
            second = [e["name"] for e in events
                      if e.get("tid") == atts[1]["tid"]
                      and not e["name"].startswith("attempt-")]
            assert "prefill" in first
            assert "decode" in second
            # the attempts render on different pid (replica) rows
            assert atts[0]["pid"] != atts[1]["pid"]
    finally:
        assert gw.drain(timeout=60)


def test_shed_request_trace_is_exported(tiny):
    """A shed request's trace is exactly what an operator debugs — it
    lands in the buffer with outcome=shed and the status."""
    model, params = tiny
    gw = _mk_gateway(tiny).start()
    try:
        # ttl 100 ns: positive (a ttl <= 0 is refused AT SUBMIT, before
        # a trace exists), yet expired by the time the replica's pop
        # runs its deadline check — 0.0001 s flaked on fast boxes where
        # an idle replica's cv wakeup admitted inside the window
        t = gw.submit(GenRequest([1, 2], max_new_tokens=4, id="dead",
                                 ttl_s=1e-7))
        with pytest.raises(Exception):
            t.result(timeout=60)
        tr = gw.traces.get("dead")
        assert tr is not None and tr.done
        assert tr.root.tags["outcome"] == "shed"
        assert tr.root.tags["status"] == 504
        assert check_invariants(tr) == []
    finally:
        assert gw.drain(timeout=60)


def test_server_uuid_ids_and_stats_threading(tiny):
    """Absent ids come back as server-minted UUID strings, threaded
    into metrics rows and the trace buffer — the correlation satellite."""
    gw = _mk_gateway(tiny).start()
    try:
        t = gw.submit(GenRequest([1, 2, 3], max_new_tokens=3))
        rid = t.request.id
        assert isinstance(rid, str) and len(rid) == 32
        res = t.result(timeout=120)
        assert res.id == rid
        assert t.metrics["id"] == rid
        # the rolling /stats window rows carry the id (the handle the
        # history requests.jsonl rows and trace file share)
        assert rid in [r["id"] for r in gw.stats.window]
        assert gw.traces.get(rid) is not None
    finally:
        assert gw.drain(timeout=60)


def test_snapshot_dispatch_and_host_blocks(tiny):
    gw = _mk_gateway(tiny, n=2).start()
    try:
        for i in range(4):
            gw.submit(GenRequest([1 + i, 2], max_new_tokens=3,
                                 id=i)).result(timeout=120)
        snap = gw.snapshot()
        # per-replica host gauges: RSS is always there (this process)
        for row in snap["replicas"]:
            assert row["host"]["rss_bytes"] > 0
            assert "dispatch" in row
        # fleet dispatch block merges the replica summaries
        fleet = snap["engine"]["dispatch"]
        assert fleet["prefill"]["count"] == \
            sum(r["dispatch"].get("prefill", {}).get("count", 0)
                for r in snap["replicas"])
        assert fleet["prefill"]["count"] == snap["engine"]["prefills"]
        assert fleet["decode"]["tokens"] > 0
        assert fleet["decode"]["compiles"] >= 1
    finally:
        assert gw.drain(timeout=60)


def test_tracing_disabled_gateway_works(tiny):
    gw_off = Gateway([Server(*tiny, batch_size=2, min_bucket=8,
                             timeline=False)],
                     max_queue=8, tracing=False).start()
    try:
        res = gw_off.submit(GenRequest([1, 2, 3], max_new_tokens=3,
                                       id="q")).result(timeout=120)
        assert len(res.tokens) == 3
        assert gw_off.traces is None
        snap = gw_off.snapshot()
        assert snap["engine"]["dispatch"] == {}
    finally:
        assert gw_off.drain(timeout=60)


# -------------------------------------------------- /metrics exposition

# one line of the exposition: comment, blank, or sample with optional
# labels and a number (int/float/scientific/+Inf/NaN)
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\")*\})?"
    r" (-?\d+(\.\d+)?([eE][+-]?\d+)?|\+Inf|-Inf|NaN)$")


def _validate_exposition(text: str) -> dict:
    """Format-validate a whole exposition document; returns
    {metric_name: type}. Asserts HELP/TYPE precede samples and
    histogram bucket series are cumulative-monotonic ending in +Inf."""
    types: dict = {}
    cur = None
    buckets: dict = {}
    assert text.endswith("\n")
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            cur = line.split()[2]
            continue
        if line.startswith("# TYPE "):
            _, _, name, mtype = line.split(None, 3)
            assert name == cur, f"TYPE without preceding HELP: {line}"
            assert mtype in ("counter", "gauge", "histogram"), line
            types[name] = mtype
            continue
        assert not line.startswith("#"), line
        assert _SAMPLE_RE.match(line), f"malformed sample line: {line!r}"
        name = re.split(r"[{ ]", line, 1)[0]
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        owner = name if name in types else base
        assert owner in types, f"sample before TYPE: {line}"
        if types.get(base) == "histogram" and name.endswith("_bucket"):
            le = re.search(r'le="([^"]+)"', line).group(1)
            series = re.sub(r',?le="[^"]+"', "", line.split(" ")[0])
            val = float(line.rsplit(" ", 1)[1])
            buckets.setdefault(series, []).append((le, val))
    for series, pts in buckets.items():
        vals = [v for _, v in pts]
        assert vals == sorted(vals), f"non-monotonic buckets: {series}"
        assert pts[-1][0] == "+Inf", f"missing +Inf: {series}"
    return types


def test_metrics_exposition_format_and_stats_consistency(tiny):
    """The acceptance check at gateway level: /metrics renders
    format-valid text whose counters agree with /stats — TTFT/TPOT/
    queue-wait histograms, supervision, prefix, and spec counters."""
    gw = _mk_gateway(tiny, n=2, chunk_steps=2, prefix_cache_mb=1.0,
                     speculate_k=2).start()
    try:
        for i in range(6):
            gw.submit(GenRequest([1, 2, 3, 1, 2, 3, 1 + i],
                                 max_new_tokens=4,
                                 id=f"m{i}")).result(timeout=120)
        text = prometheus_text(gw)
        types = _validate_exposition(text)
        snap = gw.snapshot()
        # counters consistent with /stats
        assert f"tony_requests_completed_total {snap['completed']}" \
            in text
        assert f"tony_requests_accepted_total {snap['accepted']}" in text
        assert f"tony_tokens_out_total {snap['tokens_out']}" in text
        # histograms present, counts match completed requests
        for name in ("tony_request_ttft_seconds",
                     "tony_request_tpot_seconds",
                     "tony_request_queue_wait_seconds"):
            assert types[name] == "histogram"
            assert f"{name}_count {snap['completed']}" in text
        # supervision / prefix / spec families
        assert types["tony_replica_failures_total"] == "counter"
        assert types["tony_engine_prefix_hits_total"] == "counter"
        assert types["tony_engine_spec_accepted_total"] == "counter"
        assert types["tony_dispatch_seconds_total"] == "counter"
        assert types["tony_host_rss_bytes"] == "gauge"
        assert 'tony_replica_state{replica="0",state="healthy"} 1' in text
        # per-replica engine counters reconcile with the /stats rows
        for i, row in enumerate(snap["replicas"]):
            assert (f'tony_engine_prefills_total{{replica="{i}"}} '
                    f'{row["prefills"]}') in text
        # ISSUE-18: migration families render on every fleet (zero
        # here — nothing migrated) and agree with /stats on both the
        # per-replica rows and the carry-inclusive fleet rollup
        mig = snap["engine"]["migrations"]
        assert types["tony_migration_out_total"] == "counter"
        assert f'tony_migrations_total {snap["routing"]["migrations"]}' \
            in text
        for key, fam in (("out", "tony_migration_out_total"),
                         ("in", "tony_migration_in_total"),
                         ("local", "tony_migration_local_total"),
                         ("remote", "tony_migration_remote_total"),
                         ("pages_moved",
                          "tony_migration_pages_moved_total"),
                         ("bytes_avoided",
                          "tony_migration_bytes_avoided_total"),
                         # ISSUE-19: the wire-economy pair rides the
                         # same carry-inclusive rollup
                         ("bytes_wire",
                          "tony_migration_bytes_wire_total"),
                         ("delta_in",
                          "tony_migration_delta_in_total")):
            assert f"{fam} {mig[key]}" in text, fam
        for i, row in enumerate(snap["replicas"]):
            assert (f'tony_engine_migrations_out_total{{replica="{i}"}} '
                    f'{row["migrations_out"]}') in text
            assert (f'tony_engine_migrate_bytes_wire_total'
                    f'{{replica="{i}"}} '
                    f'{row["migrate_bytes_wire"]}') in text
        # ISSUE-19: rebalance families are absent until a Rebalancer
        # is attached, then agree with the /stats rebalance block
        assert "tony_rebalance_" not in text
        from tony_tpu.gateway import Rebalancer

        Rebalancer(gw, interval_s=999.0)  # registers, never started
        text2 = prometheus_text(gw)
        _validate_exposition(text2)
        rb = gw.snapshot()["rebalance"]
        assert rb["enabled"]
        for key, fam in (("moves", "tony_rebalance_moves_total"),
                         ("move_failures",
                          "tony_rebalance_move_failures_total"),
                         ("ticks", "tony_rebalance_ticks_total"),
                         ("streak", "tony_rebalance_streak")):
            assert f"{fam} {rb[key]}" in text2, fam
        # the paged-KV block: /metrics and /stats must agree on every
        # kv_pages figure (per-replica gauges sum to the engine rollup)
        kv = snap["engine"]["kv_pages"]
        assert kv["enabled"]
        assert "tony_kv_paged_enabled 1" in text
        for key, gauge in (("kv_pages_total", "tony_kv_pages_total_pages"),
                           ("kv_pages_used", "tony_kv_pages_used"),
                           ("kv_cow_shared", "tony_kv_cow_shared_pages"),
                           ("kv_bytes_resident", "tony_kv_bytes_resident"),
                           ("kv_tokens_resident",
                            "tony_kv_tokens_resident")):
            rollup_key = key.replace("kv_pages_", "").replace("kv_", "")
            total = 0
            for i, row in enumerate(snap["replicas"]):
                assert (f'{gauge}{{replica="{i}"}} '
                        f'{row[key]}') in text
                total += row[key]
            assert kv[rollup_key] == total, (key, kv)
        assert kv["used"] + kv["free"] == kv["total"]
        # ISSUE-10: the goodput gauges carry the same ledger /stats
        # engine.goodput does. The ledger is TIME-dependent (idle
        # grows between two snapshots), so the exported values are
        # parsed back and compared with a drift tolerance; the
        # sums-to-<=1 invariant must hold exactly on the exported
        # document itself (both surfaces render ONE snapshot each).
        gp = snap["engine"]["goodput"]
        assert gp["buckets"] and sum(gp["buckets"].values()) <= 1 + 1e-6
        exported = {
            m.group(1): float(m.group(2)) for m in re.finditer(
                r'tony_goodput_fraction\{bucket="([^"]+)"\} (\S+)',
                text)}
        assert set(exported) == set(gp["buckets"])
        assert sum(exported.values()) <= 1.0 + 1e-6
        for bucket, v in gp["buckets"].items():
            assert exported[bucket] == pytest.approx(v, abs=0.05), bucket
        # per-replica dispatch cost estimates ride the dispatch family
        # (pure counters: exact across snapshots)
        from tony_tpu.obs.prom import _fmt

        for i, row in enumerate(snap["replicas"]):
            for kind, agg in row["dispatch"].items():
                assert (f'tony_dispatch_est_bytes_total{{replica="{i}"'
                        f',kind="{kind}"}} '
                        f'{_fmt(agg["est_bytes"])}') in text
        # build info + alert families (ISSUE-10 satellites)
        assert types["tony_build_info"] == "gauge"
        assert 'tony_build_info{version="' in text
        assert "tony_alerts_enabled 1" in text
        al = snap["alerts"]
        assert al["enabled"] and "kv_pages_pressure" in al["rules"]
        for rule in al["rules"]:
            assert (f'tony_alerts_fired_total{{alert="{rule}"}} '
                    f'{al["fired"].get(rule, 0)}') in text
    finally:
        assert gw.drain(timeout=60)


def test_metrics_exposition_consistency_with_remote_stub(tiny):
    """ISSUE-15: the exposition-consistency contract extended to a
    fleet with one REMOTE replica — dispatch families, goodput
    fractions, and the new clock-offset/obs-channel series must agree
    between /metrics and /stats. The obs-puller is frozen once the
    pulled timeline accounts for every landed token, so the two
    surfaces render the IDENTICAL pulled state and the comparison is
    exact, not tolerance-based."""
    import time as _time

    from tony_tpu.gateway.remote import RemoteServer
    from tony_tpu.serve.agent import AgentHTTP, ReplicaAgent

    model, params = tiny
    agent = AgentHTTP(ReplicaAgent(Server(
        model, params, batch_size=2, min_bucket=8))).start()
    stub = RemoteServer(agent.address, heartbeat_interval_s=0.1,
                        lease_misses=3, boot_timeout_s=20.0)
    gw = Gateway([stub], max_queue=32, max_attempts=3,
                 stall_timeout_s=10.0, breaker_base_s=0.05,
                 breaker_max_s=0.2).start()
    try:
        n, budget = 4, 4
        for i in range(n):
            gw.submit(GenRequest([1 + i, 2, 3], max_new_tokens=budget,
                                 id=f"rm{i}")).result(timeout=120)
        deadline = _time.monotonic() + 30
        while _time.monotonic() < deadline:
            summ = stub.timeline.summary()
            if summ and sum(a["tokens"] for a in summ.values()) \
                    >= n * budget:
                break
            _time.sleep(0.02)
        stub._obs_pull = False  # freeze: exact two-surface comparison
        text = prometheus_text(gw)
        _validate_exposition(text)
        snap = gw.snapshot()
        row = snap["replicas"][0]
        # dispatch families agree with the (pulled) /stats block
        for kind, agg in row["dispatch"].items():
            assert (f'tony_dispatch_count_total{{replica="0"'
                    f',kind="{kind}"}} {agg["count"]}') in text
            assert (f'tony_dispatch_tokens_total{{replica="0"'
                    f',kind="{kind}"}} {agg["tokens"]}') in text
        assert row["dispatch"]["prefill"]["count"] == n
        # goodput fractions: both surfaces render the same frozen
        # pulled ledger — exact equality per bucket
        gp = snap["engine"]["goodput"]
        assert gp["buckets"] and sum(gp["buckets"].values()) <= 1 + 1e-6
        exported = {
            m.group(1): float(m.group(2)) for m in re.finditer(
                r'tony_goodput_fraction\{bucket="([^"]+)"\} (\S+)',
                text)}
        assert exported == {k: pytest.approx(v)
                            for k, v in gp["buckets"].items()}
        # the clock-offset series agrees with the transport block
        tr = row["transport"]
        m = re.search(r'tony_transport_clock_offset_ms\{[^}]*\} (\S+)',
                      text)
        assert m is not None
        assert float(m.group(1)) == pytest.approx(
            tr["clock_offset_ms"], abs=1.0)
        assert "tony_transport_clock_offset_unc_ms{" in text
        # the obs channel's health series agree with the row's block
        obs = row["obs"]
        assert (f'tony_transport_obs_pulls_total{{replica="0",'
                f'host="{agent.address}"}} {obs["pulls"]}') in text
        assert (f'tony_transport_obs_pull_errors_total{{replica="0",'
                f'host="{agent.address}"}} 0') in text
    finally:
        gw.drain(timeout=60)
        agent.stop()


def test_metrics_exposition_edge_block(tiny):
    """ISSUE-16: the exposition-consistency contract extended to the
    connection plane — with an event edge attached, snapshot() grows
    an `edge` block and /metrics grows the tony_edge_* families, and
    the two surfaces agree on every figure."""
    from tony_tpu.gateway import GatewayEdge

    gw = _mk_gateway(tiny).start()
    edge = GatewayEdge(gw).start()
    try:
        url = f"http://{edge.host}:{edge.port}"
        body = json.dumps({"token_ids": [1, 2, 3], "max_new_tokens": 3,
                           "id": "e0"}).encode()
        req = urllib.request.Request(
            url + "/v1/generate", data=body,
            headers={"Content-Type": "application/json"})
        assert json.loads(urllib.request.urlopen(
            req, timeout=120).read())["id"] == "e0"
        text = prometheus_text(gw)
        types = _validate_exposition(text)
        snap = gw.snapshot()
        e = snap["edge"]
        assert e["kind"] == "event"
        assert types["tony_edge_threads"] == "gauge"
        assert types["tony_edge_accepts_total"] == "counter"
        assert types["tony_edge_requests_total"] == "counter"
        assert types["tony_edge_slow_client_aborts_total"] == "counter"
        assert types["tony_edge_conn_limit_sheds_total"] == "counter"
        assert f'tony_edge_threads {e["threads"]}' in text
        assert f'tony_edge_max_connections {e["max_connections"]}' \
            in text
        # counters only move via edge traffic, so they are exact
        # across the two snapshots here
        assert f'tony_edge_requests_total {e["requests"]}' in text
        assert f'tony_edge_accepts_total {e["accepts"]}' in text
        assert e["requests"] >= 1 and e["accepts"] >= 1
        # and /stats through the edge itself carries the same block
        stats = json.loads(urllib.request.urlopen(
            url + "/stats", timeout=60).read())
        assert stats["edge"]["kind"] == "event"
        assert stats["edge"]["requests"] >= e["requests"]
    finally:
        edge.stop()
        assert "edge" not in gw.snapshot()  # stop() detaches
        gw.drain(timeout=60)


# ------------------------------------------------------ HTTP endpoints


def _get(url, timeout=60):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.headers, r.read()


def test_http_metrics_and_trace_endpoints(tiny):
    """The network face: /metrics scrapes and /debug/trace/<id> serves
    a completed request's Chrome JSON."""
    gw = _mk_gateway(tiny).start()
    http = GatewayHTTP(gw, port=0).start()
    url = f"http://{http.host}:{http.port}"
    try:
        body = json.dumps({"token_ids": [1, 2, 3], "max_new_tokens": 3,
                           "request_id": "web-1"}).encode()
        req = urllib.request.Request(url + "/v1/generate", data=body)
        doc = json.loads(urllib.request.urlopen(req, timeout=120).read())
        assert doc["request_id"] == "web-1" and doc["id"] == "web-1"
        assert doc["metrics"]["id"] == "web-1"

        status, headers, data = _get(url + "/metrics")
        assert status == 200
        assert headers["Content-Type"].startswith("text/plain")
        _validate_exposition(data.decode())
        assert b"tony_requests_completed_total 1" in data

        status, _, data = _get(url + "/debug/trace")
        assert status == 200
        assert "web-1" in json.loads(data)["request_ids"]
        status, _, data = _get(url + "/debug/trace/web-1")
        assert status == 200
        trace_doc = json.loads(data)
        assert trace_doc["otherData"]["request_id"] == "web-1"
        assert any(e["name"] == "prefill" for e in
                   trace_doc["traceEvents"])
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(url + "/debug/trace/nope")
        assert e.value.code == 404
    finally:
        http.stop()
        assert gw.drain(timeout=60)


@pytest.mark.slow  # the FIRST jax start_trace of a process blocks
# >10 s (plugin spin-up); the protocol itself is unit-tested fast in
# test_profiler, and serve-smoke drives this path on a live gateway
def test_http_profile_endpoint_real_capture(tiny, tmp_path):
    """POST /debug/profile arms a real jax.profiler capture that the
    fleet's next working iterations finish. Client logdir is a
    RELATIVE subdir of the server-configured profile dir; escapes 400."""
    model, params = tiny
    gw = Gateway([Server(model, params, batch_size=2, min_bucket=8)],
                 max_queue=32, max_attempts=3, stall_timeout_s=60.0,
                 breaker_base_s=0.05, breaker_max_s=0.2,
                 profile_dir=str(tmp_path)).start()
    http = GatewayHTTP(gw, port=0).start()
    url = f"http://{http.host}:{http.port}"
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(urllib.request.Request(
                url + "/debug/profile?steps=2&logdir=../escape",
                data=b"", method="POST"), timeout=60)
        assert e.value.code == 400  # no arbitrary-path write primitive
        logdir = str(tmp_path / "prof")
        req = urllib.request.Request(
            url + "/debug/profile?steps=2&logdir=prof", data=b"",
            method="POST")
        armed = json.loads(urllib.request.urlopen(req, timeout=60).read())
        # a fresh timestamped dir per capture under the validated sub:
        # re-using one name would double-count in the xplane parsers
        assert armed["armed"]
        assert armed["logdir"].startswith(logdir + "/profile-")
        logdir = armed["logdir"]
        # a second arm while pending is refused (409): jax has ONE
        # global profiler session
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(urllib.request.Request(
                url + "/debug/profile?steps=2", data=b"",
                method="POST"), timeout=60)
        assert e.value.code == 409
        body = json.dumps({"token_ids": [5, 6], "max_new_tokens": 6,
                           "request_id": "prof-drive"}).encode()
        urllib.request.urlopen(urllib.request.Request(
            url + "/v1/generate", data=body), timeout=120).read()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            status_doc = json.loads(_get(url + "/debug/profile")[2])
            if status_doc["captures"] >= 1:
                break
            # keep the fleet working so the armed steps burn down
            urllib.request.urlopen(urllib.request.Request(
                url + "/v1/generate", data=body), timeout=120).read()
        assert status_doc["captures"] == 1, status_doc
        assert status_doc["last_logdir"] == logdir
        assert not status_doc["active"]
        import glob
        assert glob.glob(logdir + "/**/*", recursive=True), \
            "capture wrote nothing"
    finally:
        http.stop()
        assert gw.drain(timeout=60)


# ---------------------------------------------------- overhead (slow)


@pytest.mark.slow
def test_obs_overhead_gate(tiny):
    """The always-on-cheap contract: TPOT with tracing + dispatch
    timeline enabled within 1.1x of fully disabled, on the serving
    workload shape bench extras.obs records. Min-of-rounds per arm so
    a CI scheduler hiccup cannot fail the gate spuriously. ISSUE-15
    extends the gate to the fleet channel: the same bound with the
    obs-puller + span fragments + alerts + bundle recorder armed
    against a REMOTE replica vs the channel fully off."""
    from bench import bench_obs

    out = bench_obs(on_tpu=False)
    assert out["tpot_ratio_on_off"] <= 1.1, out
    assert out["remote_tpot_ratio_obs_on_off"] <= 1.1, out
