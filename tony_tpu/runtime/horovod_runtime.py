"""Horovod-compat runtime: gang + gloo-style rendezvous orchestration.

Reference: runtime/HorovodRuntime.java (357 LoC) + horovod/HorovodDriver.java
(331 LoC). The reference's most complex runtime path (SURVEY.md §3.4):

- AM side injects a hidden, untracked ``driver`` role
  (``validateAndUpdateConfig`` :210-232), gates workers until the driver's
  rendezvous callback arrives (``canStartTask`` :181-207), and attaches the
  slot plan to the cluster spec handed to workers (:87-120).
- The driver task forks the rendezvous bootstrap
  (tony_tpu/runtime/horovod_driver.py), polls for its
  ``{port}____HOROVOD_RENDEZVOUS_SERVER____`` announcement file
  (HorovodDriver.java ``waitTillServerStarted`` :128), and reports
  ``{host, port, slots}`` back over ``register_callback_info``
  (:285-288).
- Worker tasks receive the plan and export ``HOROVOD_*`` rendezvous/rank
  env (``setHorovodRunEnv`` :312-350).

On TPU the flagship path is runtime/jax_runtime.py (no rendezvous server
at all); this runtime exists for capability parity with horovod/gloo-style
payloads and as the reference's hardest lifecycle test case (driver crash,
debug driver, fake-mode CI — TestTonyE2E :531-567).
"""

from __future__ import annotations

import glob
import json
import logging
import os
import shlex
import subprocess
import sys
import time

from tony_tpu import constants as C
from tony_tpu.config import ConfError, TonyConf
from tony_tpu.config.config import role_key
from tony_tpu.runtime.base import AMAdapter, Runtime, TaskAdapter, TaskContext
from tony_tpu.runtime.horovod_driver import PORT_FILE_SUFFIX

log = logging.getLogger(__name__)

AUX_KEY = "__aux__"


def build_worker_list(cluster_spec: dict[str, list[str]],
                      role: str = C.WORKER_JOB_NAME) -> str:
    """``{"worker": ["h1:p", "h1:p2", "h2:p"]}`` -> ``"h1:2,h2:1"``
    (ref: HorovodRuntime.buildWorkerList :133-157 groups worker hosts and
    counts procs per host, order-preserving)."""
    counts: dict[str, int] = {}
    for host_port in cluster_spec.get(role, []):
        host = host_port.rsplit(":", 1)[0]
        counts[host] = counts.get(host, 0) + 1
    if not counts:
        raise ValueError(f"no {role!r} tasks in cluster spec")
    return ",".join(f"{h}:{n}" for h, n in counts.items())


class HorovodDriver:
    """Forks + babysits the rendezvous bootstrap process (ref:
    horovod/HorovodDriver.java: ``create`` :97, ``startRendezvousServer``
    :189, ``waitTillServerStarted`` :128, ``getCallbackInfo`` :317)."""

    POLL_INTERVAL_S = 0.2
    START_TIMEOUT_S = 30.0

    def __init__(self, proc: subprocess.Popen, port: int, slots: list[dict],
                 workdir: str):
        self.proc = proc
        self.port = port
        self.slots = slots
        self.workdir = workdir

    @classmethod
    def create(cls, worker_list: str, workdir: str, fake: bool = False,
               fail: bool = False, debug_command: str = "",
               discovery_command: str = "") -> "HorovodDriver":
        """Fork the driver script (or a user debug command, ref: debug mode
        HorovodDriver.java:189-216) and wait for the port file.
        ``discovery_command`` switches the driver to elastic mode (the
        reference's elastic_driver_fn is a stub; see horovod_driver.py)."""
        os.makedirs(workdir, exist_ok=True)
        for stale in glob.glob(os.path.join(workdir, f"*{PORT_FILE_SUFFIX}")):
            os.remove(stale)
        if debug_command:
            cmd = shlex.split(debug_command)
        else:
            cmd = [sys.executable, "-m", "tony_tpu.runtime.horovod_driver",
                   "-w", worker_list, "-d", workdir]
            if fake:
                cmd.append("--fake")
            if fail:
                cmd.append("--fail")
            if discovery_command:
                cmd += ["--elastic", "--discover", discovery_command]
        # the driver runs from the job workdir; make sure the package stays
        # importable there (agents may run from an unpacked staging dir)
        env = dict(os.environ)
        import tony_tpu
        pkg_parent = os.path.dirname(os.path.dirname(tony_tpu.__file__))
        env["PYTHONPATH"] = pkg_parent + os.pathsep + env.get("PYTHONPATH", "")
        # NO start_new_session: the rendezvous server must stay in the
        # driver agent's process group so the launcher's group SIGKILL
        # (stop_all/_kill_tree) reaps it — as a session leader it survived
        # every job, since SIGKILL runs no finally/driver.kill() path
        # (observed: one orphaned rendezvous server per completed job)
        proc = subprocess.Popen(cmd, cwd=workdir, env=env)
        # preemption forwarding (agent SIGTERM handler) must reach the
        # rendezvous driver too, not only execute_shell children
        from tony_tpu.utils.shell import (
            register_external_process,
            unregister_external_process,
        )

        register_external_process(proc)
        deadline = time.time() + cls.START_TIMEOUT_S
        while time.time() < deadline:
            files = glob.glob(os.path.join(workdir, f"*{PORT_FILE_SUFFIX}"))
            if files:
                # the in-tree driver writes atomically (os.replace), but a
                # user debug command may not — treat a torn/partial file as
                # "not announced yet" and keep polling until the deadline
                try:
                    name = os.path.basename(files[0])
                    port = int(name[: -len(PORT_FILE_SUFFIX)])
                    with open(files[0]) as f:
                        slots = json.load(f)["slots"]
                    return cls(proc, port, slots, workdir)
                except (ValueError, KeyError, OSError):
                    pass
            if proc.poll() is not None:
                unregister_external_process(proc)  # never leak a dead entry
                raise RuntimeError(
                    f"rendezvous driver exited {proc.returncode} before "
                    "announcing its port")
            time.sleep(cls.POLL_INTERVAL_S)
        proc.kill()
        unregister_external_process(proc)
        raise TimeoutError("rendezvous driver did not announce a port in "
                           f"{cls.START_TIMEOUT_S}s")

    def callback_info(self, host: str) -> str:
        """JSON shipped to the AM (ref: DriverCallbackInfo {port, host,
        slotInfos})."""
        return json.dumps(
            {"host": host, "port": self.port, "slots": self.slots})

    def wait(self) -> int:
        try:
            return self.proc.wait()
        finally:
            from tony_tpu.utils.shell import unregister_external_process

            unregister_external_process(self.proc)

    def kill(self) -> None:
        from tony_tpu.utils.shell import unregister_external_process

        if self.proc.poll() is None:
            self.proc.kill()
        unregister_external_process(self.proc)


class HorovodAMAdapter(AMAdapter):
    def __init__(self) -> None:
        super().__init__()
        self.driver_ready = False
        self.rendezvous_host = ""
        self.rendezvous_port = 0
        self.slots: list[dict] = []

    def validate_and_update_config(self, conf: TonyConf) -> None:
        """Inject the hidden untracked driver role (ref:
        validateAndUpdateConfig :210-232). Runs in both the client and the
        coordinator (TonyClient.validateTonyConf + AM init), so it must be
        idempotent: a marker key distinguishes our own injected driver role
        from a user-declared one."""
        if conf.get_bool("tony.horovod.driver-injected", False):
            return
        if C.DRIVER_JOB_NAME in conf.roles():
            raise ConfError(
                "role name 'driver' is reserved by the horovod runtime")
        if C.WORKER_JOB_NAME not in conf.roles():
            raise ConfError(
                "horovod runtime requires a 'worker' role (the rendezvous "
                "plan is built from worker hosts)")
        conf.set("tony.horovod.driver-injected", True)
        conf.set(role_key(C.DRIVER_JOB_NAME, "instances"), 1)
        # ":" is a no-op shell command; the task adapter intercepts the
        # driver role before exec, but the launcher requires a command
        conf.set(role_key(C.DRIVER_JOB_NAME, "command"), ":")
        untracked = conf.get_list("tony.application.untracked.jobtypes")
        if C.DRIVER_JOB_NAME not in untracked:
            conf.append("tony.application.untracked.jobtypes",
                        C.DRIVER_JOB_NAME)

    def can_start_task(self, mode: str, task_id: str) -> bool:
        """Driver starts once every *other* task has registered (it needs
        their hosts for the worker list); workers start once the driver's
        rendezvous callback arrived (ref: canStartTask :181-207)."""
        assert self.session is not None
        role = task_id.split(":")[0]
        if role == C.DRIVER_JOB_NAME:
            # the driver only needs the *worker* hosts (build_worker_list
            # covers the worker role alone), so gate on the worker role's
            # expected instance count — not allocated Task objects (with
            # DAG staging, unallocated slots are None and an allocated-only
            # check is vacuously true) and not every conf role (a role
            # scheduled in a later stage would deadlock the gate forever)
            req = self.session.requests.get(C.WORKER_JOB_NAME)
            if req is None:
                return False
            registered = sum(
                1 for t in self.session.all_tasks()
                if t.role == C.WORKER_JOB_NAME and t.registered)
            return registered >= req.instances
        return self.driver_ready and self.session.all_registered()

    def construct_cluster_spec(self, task_id: str) -> str:
        assert self.session is not None
        spec: dict = dict(self.session.cluster_spec())
        role = task_id.split(":")[0]
        if role != C.DRIVER_JOB_NAME:
            spec[AUX_KEY] = {
                "rendezvous_host": self.rendezvous_host,
                "rendezvous_port": self.rendezvous_port,
                "slots": self.slots,
            }
        return json.dumps(spec)

    def receive_task_callback_info(self, task_id: str, info: str) -> None:
        """Ref: receiveTaskCallbackInfo :161-178."""
        data = json.loads(info)
        self.rendezvous_host = data["host"]
        self.rendezvous_port = int(data["port"])
        self.slots = list(data["slots"])
        self.driver_ready = True
        log.info("rendezvous ready at %s:%d with %d slots (from %s)",
                 self.rendezvous_host, self.rendezvous_port,
                 len(self.slots), task_id)


class HorovodTaskAdapter(TaskAdapter):
    def need_reserve_tb_port(self, ctx_role: str, is_chief: bool,
                             conf: TonyConf) -> bool:
        if ctx_role == C.DRIVER_JOB_NAME:
            return False
        return super().need_reserve_tb_port(ctx_role, is_chief, conf)

    # -- driver task ---------------------------------------------------------
    def _run_driver(self, ctx: TaskContext) -> int:
        """Ref: HorovodRuntime.Task.run driver branch :268-296."""
        worker_list = build_worker_list(ctx.cluster_spec)
        fake = ctx.conf.get_bool("tony.horovod.test-mode", False)
        fail = ctx.conf.get_bool("tony.horovod.test-fast-fail", False)
        debug_cmd = str(ctx.conf.get("tony.horovod.driver.debug-command", ""))
        discover = ""
        if ctx.conf.get_bool("tony.horovod.elastic", False):
            discover = str(ctx.conf.get("tony.horovod.discovery-command",
                                        ""))
            if not discover:
                # fail loudly, like the standalone driver's exit 2: a
                # silently-static "elastic" job is the worst outcome
                log.error("tony.horovod.elastic=true requires "
                          "tony.horovod.discovery-command")
                return C.EXIT_FAIL
        try:
            driver = HorovodDriver.create(
                worker_list, workdir=ctx.workdir or ".", fake=fake, fail=fail,
                debug_command=debug_cmd, discovery_command=discover)
        except Exception:
            log.exception("rendezvous driver failed to start")
            return C.EXIT_FAIL
        host = ctx.cluster_spec[C.DRIVER_JOB_NAME][0].rsplit(":", 1)[0] \
            if ctx.cluster_spec.get(C.DRIVER_JOB_NAME) else "localhost"
        # everything after the fork is under try/finally so a failed
        # callback RPC can't orphan the rendezvous server process
        try:
            if ctx.callback_to_am:
                ctx.callback_to_am(driver.callback_info(host))
            # stay up serving rendezvous until the coordinator tears us
            # down (driver is untracked; ref: driver.waitFor() :291)
            return driver.wait()
        finally:
            driver.kill()

    # -- worker task ---------------------------------------------------------
    def _my_slot(self, ctx: TaskContext) -> dict:
        """Pick this worker's slot: group plan slots by host, take the Nth
        slot of our host where N = our position among same-host workers in
        the cluster spec (ref: setHorovodRunEnv matches slots by host
        :312-350)."""
        me = ctx.cluster_spec[ctx.role][ctx.index]
        my_host = me.rsplit(":", 1)[0]
        same_host_position = sum(
            1 for hp in ctx.cluster_spec[ctx.role][: ctx.index]
            if hp.rsplit(":", 1)[0] == my_host)
        workers = ctx.cluster_spec[ctx.role]
        host_slots = [s for s in ctx.aux.get("slots", [])
                      if s["hostname"] == my_host]
        if host_slots and same_host_position < len(host_slots):
            return host_slots[same_host_position]
        # fake/test plans use "localhost" hostnames that won't match real
        # hosts: fall back to flat worker order (never per-host position,
        # which would hand distinct workers the same slot)
        flat = list(ctx.aux.get("slots", []))
        if ctx.index < len(flat):
            return flat[ctx.index]
        return {"hostname": my_host, "rank": ctx.index,
                "size": len(workers), "local_rank": same_host_position,
                "local_size": len(host_slots) or 1, "cross_rank": 0,
                "cross_size": 1}

    def build_task_env(self, ctx: TaskContext) -> dict[str, str]:
        env = super().build_task_env(ctx)
        # only workers hold slots in the plan (build_worker_list covers the
        # worker role alone) — a co-located chief/evaluator must not match
        # by hostname and steal a worker's rank
        if ctx.role != C.WORKER_JOB_NAME or not ctx.aux:
            return env
        slot = self._my_slot(ctx)
        env[C.HOROVOD_CONTROLLER] = "gloo"
        env[C.HOROVOD_CPU_OPERATIONS] = "gloo"
        env[C.HOROVOD_GLOO_RENDEZVOUS_ADDR] = str(ctx.aux["rendezvous_host"])
        env[C.HOROVOD_GLOO_RENDEZVOUS_PORT] = str(ctx.aux["rendezvous_port"])
        env[C.HOROVOD_HOSTNAME] = str(slot["hostname"])
        env[C.HOROVOD_RANK] = str(slot["rank"])
        env[C.HOROVOD_SIZE] = str(slot["size"])
        env[C.HOROVOD_LOCAL_RANK] = str(slot["local_rank"])
        env[C.HOROVOD_LOCAL_SIZE] = str(slot["local_size"])
        env[C.HOROVOD_CROSS_RANK] = str(slot["cross_rank"])
        env[C.HOROVOD_CROSS_SIZE] = str(slot["cross_size"])
        return env

    def run(self, ctx: TaskContext) -> int:
        if ctx.role == C.DRIVER_JOB_NAME:
            return self._run_driver(ctx)
        return super().run(ctx)


class HorovodRuntime(Runtime):
    name = "horovod"
    am_adapter_cls = HorovodAMAdapter
    task_adapter_cls = HorovodTaskAdapter
