"""Pallas kernel tests (interpreter mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import functools
import pytest

from tony_tpu.ops import add_rmsnorm, flash_attention, rmsnorm
from tony_tpu.parallel import reference_attention


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_matches_reference(causal):
    key = jax.random.PRNGKey(0)
    b, l, h, d = 2, 128, 2, 32
    q, k, v = (jax.random.normal(kk, (b, l, h, d), jnp.float32)
               for kk in jax.random.split(key, 3))
    out = flash_attention(q, k, v, causal, 64, 64)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)


def test_flash_attention_grad():
    key = jax.random.PRNGKey(1)
    b, l, h, d = 1, 64, 2, 16
    q, k, v = (jax.random.normal(kk, (b, l, h, d), jnp.float32)
               for kk in jax.random.split(key, 3))

    g_flash = jax.grad(lambda q, k, v: flash_attention(q, k, v, True, 32, 32)
                       .sum())(q, k, v)
    g_ref = jax.grad(lambda q, k, v: reference_attention(q, k, v, causal=True)
                     .sum())(q, k, v)
    np.testing.assert_allclose(np.asarray(g_flash), np.asarray(g_ref),
                               atol=5e-5, rtol=5e-5)


def test_flash_attention_block_fallback():
    """Non-divisible seq lens fall back to the largest multiple-of-8
    divisor block and still match the reference; lengths with no usable
    divisor are a clear error (not a silent degenerate kernel)."""
    rng = np.random.default_rng(7)
    q = jnp.asarray(rng.standard_normal((1, 160, 2, 16)), jnp.float32)
    out = flash_attention(q, q, q, True, 64, 64)  # falls back to block 40
    s = jnp.einsum("bqhd,bkhd->bhqk", q, q) / 4.0
    s = jnp.where(jnp.tril(jnp.ones((160, 160), bool))[None, None], s, -1e30)
    ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), q)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)
    # 100 has no mult-of-8 divisor <= 64: causal pads to 128 and slices
    rng2 = np.random.default_rng(8)
    q = jnp.asarray(rng2.standard_normal((1, 100, 2, 16)), jnp.float32)
    out = flash_attention(q, q, q, True, 64, 64)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, q) / 4.0
    s = jnp.where(jnp.tril(jnp.ones((100, 100), bool))[None, None], s, -1e30)
    ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), q)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)
    # ... and its gradient flows through the pad/slice
    grad = jax.grad(lambda q: jnp.sum(flash_attention(q, q, q, True, 64, 64)))(q)
    assert grad.shape == q.shape and bool(jnp.all(jnp.isfinite(grad)))
    # non-causal cannot pad safely: clear error
    with pytest.raises(ValueError, match="non-causal"):
        flash_attention(q, q, q, False, 64, 64)


def test_rmsnorm_matches():
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 32, 64))
    scale = jax.random.normal(jax.random.PRNGKey(3), (64,)) + 1.0
    out = rmsnorm(x, scale)
    x32 = x.astype(jnp.float32)
    ref = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, -1, keepdims=True) + 1e-6) * scale
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5,
                               rtol=1e-5)


def test_add_rmsnorm():
    x = jax.random.normal(jax.random.PRNGKey(4), (8, 32))
    r = jax.random.normal(jax.random.PRNGKey(5), (8, 32))
    scale = jnp.ones((32,))
    normed, summed = add_rmsnorm(x, r, scale)
    np.testing.assert_allclose(np.asarray(summed), np.asarray(x + r), atol=1e-6)
    s = (x + r).astype(jnp.float32)
    ref = s * jax.lax.rsqrt(jnp.mean(s * s, -1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(np.asarray(normed), np.asarray(ref), atol=1e-5,
                               rtol=1e-5)


def test_flash_attention_gqa_matches_repeated_reference():
    """Grouped K/V (2 kv heads, 4 q heads) must equal reference attention
    over explicitly repeated K/V — forward and grads."""
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(8), 3)
    q = jax.random.normal(k1, (2, 32, 4, 16))
    k = jax.random.normal(k2, (2, 32, 2, 16))
    v = jax.random.normal(k3, (2, 32, 2, 16))
    kf = jnp.repeat(k, 2, axis=2)
    vf = jnp.repeat(v, 2, axis=2)
    out = flash_attention(q, k, v, True, 16, 16)
    ref = reference_attention(q, kf, vf, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-3, rtol=2e-3)

    def loss_gqa(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, 16, 16) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(
            q, jnp.repeat(k, 2, axis=2), jnp.repeat(v, 2, axis=2),
            causal=True) ** 2)

    g_gqa = jax.grad(loss_gqa, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_gqa, g_ref):
        assert a.shape == b.shape  # dk/dv stay at kv-head width
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-3, rtol=2e-3)


def test_transformer_pallas_gqa_backend():
    """The pallas backend consumes grouped K/V directly (no repeat) and
    agrees with the reference backend."""
    from tony_tpu.models import Transformer, TransformerConfig

    mk = lambda backend: TransformerConfig(  # noqa: E731
        vocab_size=64, d_model=64, n_heads=4, n_kv_heads=2, n_layers=2,
        d_ff=64, max_seq_len=64, dtype=jnp.float32,
        attention_backend=backend, attention_block_size=16)
    tokens = jax.random.randint(jax.random.PRNGKey(9), (2, 32), 0, 64)
    model_ref = Transformer(mk("reference"))
    params = model_ref.init(jax.random.PRNGKey(0), tokens)
    out_ref = model_ref.apply(params, tokens)
    out_pl = Transformer(mk("pallas")).apply(params, tokens)
    np.testing.assert_allclose(np.asarray(out_pl), np.asarray(out_ref),
                               atol=1e-3, rtol=1e-3)


def test_chunked_xent_matches_full():
    from tony_tpu.ops import chunked_cross_entropy, full_cross_entropy

    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    hidden = jax.random.normal(k1, (2, 8, 16))
    emb = jax.random.normal(k2, (100, 16))  # vocab not a chunk multiple
    labels = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 100)
    ref = full_cross_entropy(hidden, emb, labels)
    for chunk in (16, 32, 100, 4096):
        got = chunked_cross_entropy(hidden, emb, labels, chunk_size=chunk)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


def test_chunked_xent_grads_match():
    from tony_tpu.ops import chunked_cross_entropy, full_cross_entropy

    k1, k2 = jax.random.split(jax.random.PRNGKey(2))
    hidden = jax.random.normal(k1, (12, 8))
    emb = jax.random.normal(k2, (40, 8))
    labels = jax.random.randint(jax.random.PRNGKey(3), (12,), 0, 40)
    g_ref = jax.grad(full_cross_entropy, argnums=(0, 1))(hidden, emb, labels)
    g_chk = jax.grad(
        lambda h, e: chunked_cross_entropy(h, e, labels, chunk_size=16),
        argnums=(0, 1))(hidden, emb)
    for a, b in zip(g_chk, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_chunked_xent_bf16_compute_dtype():
    """compute_dtype=bf16 (the TPU head path: bf16 dot, fp32 accumulate)
    stays within bf16 rounding of the fp32 loss, values AND grads."""
    from tony_tpu.ops import chunked_cross_entropy, full_cross_entropy

    k1, k2 = jax.random.split(jax.random.PRNGKey(7))
    hidden = jax.random.normal(k1, (4, 16, 32))
    emb = jax.random.normal(k2, (96, 32))
    labels = jax.random.randint(jax.random.PRNGKey(8), (4, 16), 0, 96)
    ref = full_cross_entropy(hidden, emb, labels)
    got = chunked_cross_entropy(hidden, emb, labels, chunk_size=32,
                                compute_dtype=jnp.bfloat16)
    assert got.dtype == jnp.float32  # loss math stays fp32
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)
    g_ref = jax.grad(full_cross_entropy, argnums=(0, 1))(
        hidden.reshape(-1, 32), emb, labels.reshape(-1))
    g_bf = jax.grad(
        lambda h, e: chunked_cross_entropy(
            h, e, labels.reshape(-1), chunk_size=32,
            compute_dtype=jnp.bfloat16),
        argnums=(0, 1))(hidden.reshape(-1, 32), emb)
    for a, b in zip(g_bf, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-2, atol=5e-3)


def test_chunked_xent_z_loss_and_jit():
    from tony_tpu.ops import chunked_cross_entropy

    hidden = jax.random.normal(jax.random.PRNGKey(4), (2, 4, 8))
    emb = jax.random.normal(jax.random.PRNGKey(5), (30, 8))
    labels = jnp.zeros((2, 4), jnp.int32)
    base = chunked_cross_entropy(hidden, emb, labels, chunk_size=8)
    with_z = jax.jit(functools.partial(
        chunked_cross_entropy, chunk_size=8, z_loss=1e-3))(hidden, emb, labels)
    assert float(with_z) > float(base)  # lse^2 regularizer is additive


def test_transformer_hidden_plus_chunked_xent():
    """Training path: return_hidden + chunked loss == logits + standard CE."""
    from tony_tpu.models import Transformer, TransformerConfig
    from tony_tpu.ops import chunked_cross_entropy
    from tony_tpu.train import cross_entropy_loss

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=2,
                            d_ff=64, max_seq_len=32, dtype=jnp.float32,
                            attention_backend="blockwise",
                            attention_block_size=16)
    model = Transformer(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(6), (2, 16), 0, 64)
    params = model.init(jax.random.PRNGKey(0), tokens)
    logits = model.apply(params, tokens)
    ref = cross_entropy_loss(logits[:, :-1], tokens[:, 1:])
    hidden = model.apply(params, tokens, return_hidden=True)
    got = chunked_cross_entropy(hidden[:, :-1],
                                params["params"]["embedding"],
                                tokens[:, 1:], chunk_size=16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_chunked_xent_with_untied_lm_head():
    """return_hidden + params["lm_head"] must reproduce the full-logits
    loss for an untied (Llama-style) model — the documented pairing."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from tony_tpu.models import Transformer, TransformerConfig
    from tony_tpu.ops import chunked_cross_entropy

    cfg = TransformerConfig(
        vocab_size=300, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=32, dtype=jnp.float32, attention_backend="reference",
        gated_mlp=True, tied_embeddings=False)
    model = Transformer(cfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 300, (2, 16)))
    params = model.init(jax.random.PRNGKey(0), tokens)
    labels = jnp.asarray(
        np.random.default_rng(1).integers(0, 300, (2, 16)))

    logits = model.apply(params, tokens)
    onehot = jax.nn.one_hot(labels, 300)
    full = -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), axis=-1))

    hidden = model.apply(params, tokens, return_hidden=True)
    chunked = chunked_cross_entropy(
        hidden, params["params"]["lm_head"], labels, chunk_size=128)
    np.testing.assert_allclose(np.asarray(chunked), np.asarray(full),
                               rtol=1e-5, atol=1e-5)


def test_flash_attention_sliding_window_matches_reference():
    rng = jax.random.PRNGKey(5)
    q, k, v = (jax.random.normal(key, (2, 64, 2, 8))
               for key in jax.random.split(rng, 3))
    ref = reference_attention(q, k, v, causal=True, window=10)
    out = flash_attention(q, k, v, True, 16, 16, window=10)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # window crossing block boundaries AND window smaller than one block
    for w in (3, 16, 33):
        ref = reference_attention(q, k, v, causal=True, window=w)
        out = flash_attention(q, k, v, True, 16, 16, window=w)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5, err_msg=f"w={w}")


def test_flash_attention_sliding_window_grad():
    rng = jax.random.PRNGKey(6)
    q, k, v = (jax.random.normal(key, (1, 32, 2, 8))
               for key in jax.random.split(rng, 3))

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    g_flash = jax.grad(
        loss(lambda q, k, v: flash_attention(q, k, v, True, 8, 8, window=5)),
        argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(
        loss(lambda q, k, v: reference_attention(q, k, v, causal=True,
                                                 window=5)),
        argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-5, err_msg=name)


def test_flash_attention_sliding_window_gqa_grad():
    """Window + GQA together: the dkv kernel's group accumulation must
    respect the window's block pruning."""
    rng = jax.random.PRNGKey(7)
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (1, 32, 4, 8))
    k = jax.random.normal(kk, (1, 32, 2, 8))
    v = jax.random.normal(kv, (1, 32, 2, 8))
    group = 2

    def ref_fn(q, k, v):
        kr = jnp.repeat(k, group, axis=2)
        vr = jnp.repeat(v, group, axis=2)
        return reference_attention(q, kr, vr, causal=True, window=9)

    g_flash = jax.grad(
        lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, True, 8, 8, window=9) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(ref_fn(q, k, v) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-5, err_msg=name)


def test_flash_attention_window_requires_causal():
    q = jnp.ones((1, 16, 2, 8))
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, q, q, False, 16, 16, window=4)


def test_flash_attention_window_with_padded_length():
    """Unblockable seq lens go through the zero-pad path; the window mask
    must stay correct on the padded program."""
    rng = jax.random.PRNGKey(9)
    q, k, v = (jax.random.normal(key, (1, 50, 2, 8))  # 50: no divisor of 16
               for key in jax.random.split(rng, 3))
    ref = reference_attention(q, k, v, causal=True, window=12)
    out = flash_attention(q, k, v, True, 16, 16, window=12)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    g = jax.grad(lambda q: jnp.sum(
        flash_attention(q, k, v, True, 16, 16, window=12) ** 2))(q)
    gr = jax.grad(lambda q: jnp.sum(
        reference_attention(q, k, v, causal=True, window=12) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                               atol=2e-5, rtol=2e-5)


def test_flash_attention_segment_ids_match_reference():
    rng = jax.random.PRNGKey(11)
    q, k, v = (jax.random.normal(key, (2, 32, 2, 8))
               for key in jax.random.split(rng, 3))
    segs = jnp.asarray([[0] * 10 + [1] * 12 + [2] * 10,
                        [0] * 32], jnp.int32)
    ref = reference_attention(q, k, v, causal=True, segment_ids=segs)
    out = flash_attention(q, k, v, True, 8, 8, segment_ids=segs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # gradients through all three operands
    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)
    gf = jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, True, 8, 8, segment_ids=segs)), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss(lambda q, k, v: reference_attention(
        q, k, v, causal=True, segment_ids=segs)), argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-5, err_msg=name)


def test_flash_attention_segments_with_window_and_gqa():
    rng = jax.random.PRNGKey(12)
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (1, 32, 4, 8))
    k = jax.random.normal(kk, (1, 32, 2, 8))
    v = jax.random.normal(kv, (1, 32, 2, 8))
    segs = jnp.asarray([[0] * 13 + [1] * 19], jnp.int32)
    kr = jnp.repeat(k, 2, axis=2)
    vr = jnp.repeat(v, 2, axis=2)
    ref = reference_attention(q, kr, vr, causal=True, window=9,
                              segment_ids=segs)
    out = flash_attention(q, k, v, True, 8, 8, window=9, segment_ids=segs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_attention_segments_padded_length():
    rng = jax.random.PRNGKey(13)
    q, k, v = (jax.random.normal(key, (1, 27, 2, 8))  # unblockable
               for key in jax.random.split(rng, 3))
    segs = jnp.asarray([[0] * 11 + [1] * 16], jnp.int32)
    ref = reference_attention(q, k, v, causal=True, segment_ids=segs)
    out = flash_attention(q, k, v, True, 8, 8, segment_ids=segs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_attention_segments_mixed_blocks_padded():
    """block_q != block_k on an unblockable length: both sides must pad to
    one common length (regression: q-side seg blocks ran past the array)."""
    rng = jax.random.PRNGKey(14)
    q, k, v = (jax.random.normal(key, (1, 33, 2, 8))
               for key in jax.random.split(rng, 3))
    segs = jnp.asarray([[0] * 13 + [1] * 20], jnp.int32)
    ref = reference_attention(q, k, v, causal=True, segment_ids=segs)
    out = flash_attention(q, k, v, True, 16, 8, segment_ids=segs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # and without segments the mixed-block padded path stays exact too
    ref2 = reference_attention(q, k, v, causal=True)
    out2 = flash_attention(q, k, v, True, 16, 8)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref2),
                               atol=2e-5, rtol=2e-5)


def test_chunked_xent_mask_matches_full():
    from tony_tpu.ops import chunked_cross_entropy, full_cross_entropy

    rng = jax.random.PRNGKey(15)
    hidden = jax.random.normal(rng, (2, 6, 16))
    emb = jax.random.normal(jax.random.fold_in(rng, 1), (40, 16))
    labels = jax.random.randint(jax.random.fold_in(rng, 2), (2, 6), 0, 40)
    mask = jnp.asarray([[1, 1, 0, 1, 1, 1], [1, 0, 0, 1, 1, 1]], jnp.float32)
    got = float(chunked_cross_entropy(hidden, emb, labels, chunk_size=16,
                                      mask=mask))
    logits = jnp.einsum("bld,vd->blv", hidden, emb)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    want = -float((ll * mask).sum() / mask.sum())
    np.testing.assert_allclose(got, want, rtol=1e-5)
    # mask=None unchanged vs full reference
    np.testing.assert_allclose(
        float(chunked_cross_entropy(hidden, emb, labels, chunk_size=16)),
        float(full_cross_entropy(hidden, emb, labels)), rtol=1e-5)


def test_flash_attention_rejects_unequal_unblockable_causal():
    q = jnp.ones((1, 41, 2, 8))
    kv = jnp.ones((1, 24, 2, 8))
    with pytest.raises(ValueError, match="UNEQUAL"):
        flash_attention(q, kv, kv, True, 8, 8)


def test_flash_attention_causal_cross_blockable_lengths():
    """Regression (ADVICE r1): blockable causal cross-attention with
    lq > lk must not let the banded diagonal index run past the kv grid —
    the clamp in _banded_ki restores a full scan + position mask."""
    key = jax.random.PRNGKey(11)
    kq, kk, kv_ = jax.random.split(key, 3)
    q = jax.random.normal(kq, (1, 32, 2, 8), jnp.float32)
    k = jax.random.normal(kk, (1, 8, 2, 8), jnp.float32)
    v = jax.random.normal(kv_, (1, 8, 2, 8), jnp.float32)
    out = flash_attention(q, k, v, True, 8, 8)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)
    # ... and the lq < lk direction (kv-cache-style prefill chunk)
    out2 = flash_attention(k, q, q, True, 8, 8)
    ref2 = reference_attention(k, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref2), atol=2e-5,
                               rtol=2e-5)
    # dQ path shares the banded index: gradient must be finite and match
    g = jax.grad(lambda q: flash_attention(q, k, v, True, 8, 8).sum())(q)
    g_ref = jax.grad(
        lambda q: reference_attention(q, k, v, causal=True).sum())(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=5e-5,
                               rtol=5e-5)


# -- int8 weight-only matmul (ops/quant.py) ----------------------------------


def test_quantize_q8_roundtrip_error_bound():
    from tony_tpu.ops import dequantize_q8, quantize_q8

    w = jnp.asarray(np.random.default_rng(0).standard_normal((64, 48)),
                    jnp.float32)
    w_q, scale = quantize_q8(w)
    assert w_q.dtype == jnp.int8 and scale.shape == (48,)
    err = np.abs(np.asarray(dequantize_q8(w_q, scale)) - np.asarray(w))
    # symmetric rounding: error <= scale/2 per element, per channel
    assert (err <= np.asarray(scale)[None, :] / 2 + 1e-7).all()


def test_q8_matmul_matches_dequant_reference():
    from tony_tpu.ops import dequantize_q8, q8_matmul, quantize_q8

    rng = np.random.default_rng(1)
    for m, k, n in ((1, 64, 48), (8, 128, 256), (5, 96, 33)):
        x = jnp.asarray(rng.standard_normal((m, k)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((k, n)), jnp.float32)
        w_q, scale = quantize_q8(w)
        got = np.asarray(q8_matmul(x, w_q, scale))
        want = np.asarray(x) @ np.asarray(dequantize_q8(w_q, scale))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_q8_matmul_close_to_full_precision():
    from tony_tpu.ops import q8_matmul, quantize_q8

    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((4, 256)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((256, 128)), jnp.float32)
    w_q, scale = quantize_q8(w)
    got = np.asarray(q8_matmul(x, w_q, scale))
    ref = np.asarray(x) @ np.asarray(w)
    # int8 weight error ~0.4% relative for gaussian weights at this k
    rel = np.abs(got - ref).mean() / np.abs(ref).mean()
    assert rel < 0.01, rel


def test_q8_matmul_rejects_mismatched_shapes():
    from tony_tpu.ops import q8_matmul

    with pytest.raises(ValueError, match="shape mismatch"):
        q8_matmul(jnp.ones((2, 8)), jnp.ones((4, 8), jnp.int8),
                  jnp.ones((8,)))


def test_q8_matmul_undivisible_n_uses_divisor_block():
    """A non-divisible output dim (LM-head vocab shapes) must tile with a
    smaller divisor block, never a whole-n VMEM tile."""
    from tony_tpu.ops import dequantize_q8, q8_matmul, quantize_q8

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((2, 64)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((64, 384)), jnp.float32)  # 384%256!=0
    w_q, scale = quantize_q8(w)
    got = np.asarray(q8_matmul(x, w_q, scale, block_n=256))
    want = np.asarray(x) @ np.asarray(dequantize_q8(w_q, scale))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_chunked_xent_with_output_bias():
    """bias= (Phi lm_head_bias) must match full logits + bias exactly,
    across chunk boundaries."""
    from tony_tpu.ops import chunked_cross_entropy

    rng = np.random.default_rng(5)
    t, d, v = 6, 16, 50  # v not a chunk multiple
    hidden = jnp.asarray(rng.standard_normal((t, d)), jnp.float32)
    emb = jnp.asarray(rng.standard_normal((v, d)), jnp.float32)
    bias = jnp.asarray(rng.standard_normal((v,)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, v, t), jnp.int32)
    got = float(chunked_cross_entropy(hidden, emb, labels, chunk_size=16,
                                      bias=bias))
    logits = hidden @ emb.T + bias[None, :]
    logp = jax.nn.log_softmax(logits, axis=-1)
    want = float(-jnp.take_along_axis(
        logp, labels[:, None], axis=-1).mean())
    np.testing.assert_allclose(got, want, rtol=1e-5)
