"""HF GPT-2 weight-import parity: a randomly initialized torch
GPT2LMHeadModel and the converted tony-tpu Transformer must produce the
same logits (proves the architecture-family knobs — LayerNorm, learned
positions, biases, tanh-gelu — and the weight mapping are both exact).
Offline: the HF model is built from a config, no download.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def gpt2_pair():
    from tony_tpu.models.hf import from_hf_gpt2

    config = transformers.GPT2Config(
        vocab_size=96, n_positions=64, n_embd=48, n_layer=2, n_head=4,
        activation_function="gelu_new", resid_pdrop=0.0, embd_pdrop=0.0,
        attn_pdrop=0.0)
    torch.manual_seed(0)
    hf = transformers.GPT2LMHeadModel(config).eval()
    model, params = from_hf_gpt2(hf)
    return hf, model, params


def test_gpt2_logits_parity(gpt2_pair):
    hf, model, params = gpt2_pair
    tokens = np.random.default_rng(1).integers(0, 96, (2, 17))
    with torch.no_grad():
        ref = hf(torch.tensor(tokens)).logits.numpy()
    got = np.asarray(model.apply(params, jnp.asarray(tokens)))
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_gpt2_decode_parity(gpt2_pair):
    """Incremental KV-cache decode (learned positions advance through the
    top-level cache counter) matches the full forward."""
    hf, model, params = gpt2_pair
    tokens = np.random.default_rng(2).integers(0, 96, (1, 9))
    full = np.asarray(model.apply(params, jnp.asarray(tokens)))
    cache = model.init(jax.random.PRNGKey(0), jnp.asarray(tokens),
                       decode=True)["cache"]
    steps = []
    for i in range(tokens.shape[1]):
        logits, mut = model.apply(
            {"params": params["params"], "cache": cache},
            jnp.asarray(tokens[:, i:i + 1]), decode=True, mutable=["cache"])
        cache = mut["cache"]
        steps.append(np.asarray(logits[:, 0]))
    np.testing.assert_allclose(np.stack(steps, axis=1), full,
                               atol=1e-3, rtol=1e-3)


def test_gpt2_params_place_under_fsdp_tp(gpt2_pair):
    """Imported (use_bias) params must place under the sharding presets:
    biases get output-dim axes, dense wo kernels get ('mlp','embed') — two
    regression cases from review."""
    from tony_tpu.models.transformer import logical_axis_rules_tree
    from tony_tpu.parallel import MeshSpec, make_mesh
    from tony_tpu.parallel.sharding import tree_shardings

    _, model, params = gpt2_pair
    axes = logical_axis_rules_tree(params["params"])
    blk = axes["block_0"]
    assert blk["mlp"]["wo"]["kernel"] == ("mlp", "embed")
    assert blk["mlp"]["wi"]["bias"] == ("mlp",)
    assert blk["attn"]["q"]["bias"] == ("heads", "kv")
    assert blk["attn"]["o"]["bias"] == ("embed",)
    assert blk["ln1"]["bias"] == (None,)
    mesh = make_mesh(MeshSpec(data=2, fsdp=2, tensor=2))
    sh = tree_shardings(mesh, axes, "fsdp_tp")
    assert sh["block_0"]["mlp"]["wo"]["kernel"].spec[0] == "tensor"
    jax.device_put(params["params"], sh)  # raised pre-fix


def test_gpt2_config_respects_n_inner_and_activation():
    from tony_tpu.models.hf import gpt2_config

    config = transformers.GPT2Config(
        vocab_size=32, n_positions=16, n_embd=8, n_layer=1, n_head=2,
        n_inner=24, activation_function="gelu")
    cfg = gpt2_config(config)
    assert cfg.d_ff == 24
    assert cfg.activation == "gelu"
    config.activation_function = "relu"
    with pytest.raises(ValueError, match="unsupported"):
        gpt2_config(config)


def test_gpt2_generate_under_framework(gpt2_pair):
    """The imported model runs through the framework's generate() loop."""
    from tony_tpu.models import generate

    hf, model, params = gpt2_pair
    prompt = jnp.asarray(
        np.random.default_rng(3).integers(0, 96, (2, 5)))
    out = generate(model, params["params"], prompt, max_new_tokens=6,
                   temperature=0.0, rng=jax.random.PRNGKey(0))
    assert out.shape == (2, 6)
    # greedy framework decode must match HF's greedy generate
    with torch.no_grad():
        ref = hf.generate(torch.tensor(np.asarray(prompt)), max_new_tokens=6,
                          do_sample=False, pad_token_id=0)
    np.testing.assert_array_equal(np.asarray(out), ref.numpy()[:, 5:])


@pytest.fixture(scope="module")
def llama_pair():
    from tony_tpu.models.hf import from_hf_llama

    config = transformers.LlamaConfig(
        vocab_size=96, hidden_size=48, intermediate_size=80,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, rope_theta=50_000.0,
        tie_word_embeddings=False, attention_dropout=0.0)
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(config).eval()
    model, params = from_hf_llama(hf)
    return hf, model, params


def test_llama_config_mapping(llama_pair):
    _, model, _ = llama_pair
    cfg = model.cfg
    assert cfg.norm == "rms" and cfg.positional == "rope"
    assert cfg.gated_mlp and not cfg.use_bias and not cfg.tied_embeddings
    assert cfg.n_kv_heads == 2 and cfg.rope_theta == 50_000.0


def test_llama_logits_parity(llama_pair):
    """GQA + RMSNorm + RoPE(theta) + SwiGLU + untied head, all exact vs
    torch LlamaForCausalLM."""
    hf, model, params = llama_pair
    tokens = np.random.default_rng(1).integers(0, 96, (2, 17))
    with torch.no_grad():
        ref = hf(torch.tensor(tokens)).logits.numpy()
    got = np.asarray(model.apply(params, jnp.asarray(tokens)))
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_llama_decode_parity(llama_pair):
    """Incremental GQA KV-cache decode (RoPE applied at cached positions)
    matches the full forward."""
    hf, model, params = llama_pair
    tokens = np.random.default_rng(2).integers(0, 96, (1, 9))
    full = np.asarray(model.apply(params, jnp.asarray(tokens)))
    cache = model.init(jax.random.PRNGKey(0), jnp.asarray(tokens),
                       decode=True)["cache"]
    steps = []
    for i in range(tokens.shape[1]):
        logits, mut = model.apply(
            {"params": params["params"], "cache": cache},
            jnp.asarray(tokens[:, i:i + 1]), decode=True, mutable=["cache"])
        cache = mut["cache"]
        steps.append(np.asarray(logits[:, 0]))
    np.testing.assert_allclose(np.stack(steps, axis=1), full,
                               atol=1e-3, rtol=1e-3)


def test_llama_tied_variant():
    """tie_word_embeddings=True maps onto tied_embeddings (no lm_head
    param) and still matches torch logits."""
    from tony_tpu.models.hf import from_hf_llama

    config = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=48,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=32, tie_word_embeddings=True)
    torch.manual_seed(1)
    hf = transformers.LlamaForCausalLM(config).eval()
    model, params = from_hf_llama(hf)
    assert "lm_head" not in params["params"]
    tokens = np.random.default_rng(3).integers(0, 64, (1, 7))
    with torch.no_grad():
        ref = hf(torch.tensor(tokens)).logits.numpy()
    got = np.asarray(model.apply(params, jnp.asarray(tokens)))
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_llama_params_place_under_fsdp_tp(llama_pair):
    """Untied lm_head and the SwiGLU gate kernel must get sharding axes
    under the presets."""
    from tony_tpu.models.transformer import logical_axis_rules_tree
    from tony_tpu.parallel import MeshSpec, make_mesh
    from tony_tpu.parallel.sharding import tree_shardings

    _, model, params = llama_pair
    axes = logical_axis_rules_tree(params["params"])
    assert axes["lm_head"] == ("vocab", "embed")
    blk = axes["block_0"]
    assert blk["mlp"]["wg"]["kernel"] == ("embed", "mlp")
    assert blk["mlp"]["wi"]["kernel"] == ("embed", "mlp")
    assert blk["attn"]["k"]["kernel"] == ("embed", "kv_heads", "kv")
    mesh = make_mesh(MeshSpec(data=2, fsdp=2, tensor=2))
    sh = tree_shardings(mesh, axes, "fsdp_tp")
    jax.device_put(params["params"], sh)


def test_llama_importer_rejects_unsupported():
    from tony_tpu.models.hf import llama_config

    config = transformers.LlamaConfig(
        vocab_size=32, hidden_size=16, intermediate_size=32,
        num_hidden_layers=1, num_attention_heads=2, attention_bias=True)
    with pytest.raises(ValueError, match="attention_bias"):
        llama_config(config)


def test_llama_importer_rejects_unmapped_tensors():
    """Qwen2-style checkpoints (hardcoded q/k/v biases the config can't
    flag) must be rejected, not silently mis-imported."""
    from tony_tpu.models.hf import convert_llama_state_dict, llama_config

    config = transformers.LlamaConfig(
        vocab_size=32, hidden_size=16, intermediate_size=32,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=32, tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(config).eval()
    sd = dict(hf.state_dict())
    sd["model.layers.0.self_attn.q_proj.bias"] = torch.zeros(16)
    with pytest.raises(ValueError, match="does not map"):
        convert_llama_state_dict(sd, llama_config(config))


@pytest.fixture(scope="module")
def mistral_pair():
    from tony_tpu.models.hf import from_hf_llama

    config = transformers.MistralConfig(
        vocab_size=96, hidden_size=48, intermediate_size=80,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, sliding_window=4,
        tie_word_embeddings=False, attention_dropout=0.0,
        attn_implementation="eager")
    torch.manual_seed(0)
    hf = transformers.MistralForCausalLM(config).eval()
    model, params = from_hf_llama(hf)
    return hf, model, params


def test_mistral_config_mapping(mistral_pair):
    _, model, _ = mistral_pair
    assert model.cfg.sliding_window == 4
    assert not model.cfg.qkv_bias


def test_mistral_logits_parity(mistral_pair):
    """Sliding-window attention (window=4 << seq=17) exact vs torch
    MistralForCausalLM — past-the-window masking must agree."""
    hf, model, params = mistral_pair
    tokens = np.random.default_rng(1).integers(0, 96, (2, 17))
    with torch.no_grad():
        ref = hf(torch.tensor(tokens)).logits.numpy()
    got = np.asarray(model.apply(params, jnp.asarray(tokens)))
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_mistral_decode_parity(mistral_pair):
    """KV-cache decode applies the sliding window at each cached position."""
    hf, model, params = mistral_pair
    tokens = np.random.default_rng(2).integers(0, 96, (1, 11))
    full = np.asarray(model.apply(params, jnp.asarray(tokens)))
    cache = model.init(jax.random.PRNGKey(0), jnp.asarray(tokens),
                       decode=True)["cache"]
    steps = []
    for i in range(tokens.shape[1]):
        logits, mut = model.apply(
            {"params": params["params"], "cache": cache},
            jnp.asarray(tokens[:, i:i + 1]), decode=True, mutable=["cache"])
        cache = mut["cache"]
        steps.append(np.asarray(logits[:, 0]))
    np.testing.assert_allclose(np.stack(steps, axis=1), full,
                               atol=1e-3, rtol=1e-3)


def test_sliding_window_blockwise_matches_reference():
    from tony_tpu.parallel.ring_attention import (
        blockwise_attention, reference_attention)

    rng = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(key, (2, 15, 2, 8))
               for key in jax.random.split(rng, 3))
    ref = reference_attention(q, k, v, causal=True, window=5)
    blk = blockwise_attention(q, k, v, block_size=4, causal=True, window=5)
    np.testing.assert_allclose(np.asarray(blk), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)
    # and the window actually bites: full-causal differs
    full = reference_attention(q, k, v, causal=True)
    assert not np.allclose(np.asarray(full), np.asarray(ref), atol=1e-3)


def test_sliding_window_on_ring_backend():
    """r4: ring composes with sliding windows (kernel parity is pinned in
    tests/test_parallel.py); the model-level contract is now only that
    the ring backend demands a mesh."""
    from tony_tpu.models import Transformer, TransformerConfig
    from tony_tpu.parallel import MeshSpec, make_mesh

    cfg = TransformerConfig(vocab_size=32, d_model=16, n_heads=2, n_layers=1,
                            d_ff=32, max_seq_len=16, dtype=jnp.float32,
                            attention_backend="ring", sliding_window=4)
    with pytest.raises(ValueError, match="mesh"):
        Transformer(cfg).init(jax.random.PRNGKey(0),
                              jnp.zeros((1, 8), jnp.int32))
    mesh = make_mesh(MeshSpec(data=-1, seq=2))
    cfg = dataclasses.replace(cfg, mesh=mesh)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    out = model.apply(params, jnp.zeros((2, 8), jnp.int32))
    assert out.shape == (2, 8, 32)


@pytest.fixture(scope="module")
def qwen2_pair():
    from tony_tpu.models.hf import from_hf_llama

    config = transformers.Qwen2Config(
        vocab_size=96, hidden_size=48, intermediate_size=80,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=False,
        attention_dropout=0.0)
    torch.manual_seed(0)
    hf = transformers.Qwen2ForCausalLM(config).eval()
    model, params = from_hf_llama(hf)
    return hf, model, params


def test_qwen2_config_mapping(qwen2_pair):
    _, model, params = qwen2_pair
    assert model.cfg.qkv_bias and not model.cfg.use_bias
    # released Qwen2 configs gate sliding_window off
    assert model.cfg.sliding_window == 0
    blk = params["params"]["block_0"]["attn"]
    assert "bias" in blk["q"] and "bias" not in blk["o"]


def test_qwen2_logits_parity(qwen2_pair):
    """Qwen2 = Llama + q/k/v projection biases; exact vs torch."""
    hf, model, params = qwen2_pair
    tokens = np.random.default_rng(1).integers(0, 96, (2, 17))
    with torch.no_grad():
        ref = hf(torch.tensor(tokens)).logits.numpy()
    got = np.asarray(model.apply(params, jnp.asarray(tokens)))
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_qwen2_params_place_under_fsdp_tp(qwen2_pair):
    from tony_tpu.models.transformer import logical_axis_rules_tree
    from tony_tpu.parallel import MeshSpec, make_mesh
    from tony_tpu.parallel.sharding import tree_shardings

    _, model, params = qwen2_pair
    axes = logical_axis_rules_tree(params["params"])
    blk = axes["block_0"]
    assert blk["attn"]["q"]["bias"] == ("heads", "kv")
    assert blk["attn"]["k"]["bias"] == ("kv_heads", "kv")
    mesh = make_mesh(MeshSpec(data=2, fsdp=2, tensor=2))
    sh = tree_shardings(mesh, axes, "fsdp_tp")
    jax.device_put(params["params"], sh)


def test_qwen2_layer_gated_window_rejected():
    from tony_tpu.models.hf import llama_config

    config = transformers.Qwen2Config(
        vocab_size=32, hidden_size=16, intermediate_size=32,
        num_hidden_layers=4, num_attention_heads=2, num_key_value_heads=2,
        sliding_window=8, use_sliding_window=True, max_window_layers=2)
    with pytest.raises(ValueError, match="max_window_layers"):
        llama_config(config)
    # gate past the stack = no layer windowed = plain import
    config.max_window_layers = 4
    assert llama_config(config).sliding_window == 0


def test_window_noncausal_enforces_lower_bound():
    from tony_tpu.parallel.ring_attention import (
        blockwise_attention, reference_attention)

    rng = jax.random.PRNGKey(1)
    q, k, v = (jax.random.normal(key, (1, 10, 2, 8))
               for key in jax.random.split(rng, 3))
    # window>0 with causal=False must still hide future keys (documented
    # 0 <= q_pos - k_pos < window), matching the causal+window result
    ref = reference_attention(q, k, v, causal=True, window=3)
    ref_nc = reference_attention(q, k, v, causal=False, window=3)
    blk_nc = blockwise_attention(q, k, v, block_size=4, causal=False, window=3)
    np.testing.assert_allclose(np.asarray(ref_nc), np.asarray(ref),
                               atol=1e-6, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(blk_nc), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_llama3_rope_scaling_logits_parity():
    """rope_scaling type=llama3 (the Llama-3.1 long-context recipe) must be
    applied to the rotary frequencies exactly as HF does."""
    from tony_tpu.models.hf import from_hf_llama

    config = transformers.LlamaConfig(
        vocab_size=96, hidden_size=48, intermediate_size=80,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, tie_word_embeddings=False,
        rope_theta=10_000.0,
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 16})
    torch.manual_seed(2)
    hf = transformers.LlamaForCausalLM(config).eval()
    model, params = from_hf_llama(hf)
    assert model.cfg.rope_scaling is not None
    assert model.cfg.rope_scaling.kind == "llama3"
    # long enough that positions land well past original_max/LF thresholds
    tokens = np.random.default_rng(4).integers(0, 96, (2, 100))
    with torch.no_grad():
        ref = hf(torch.tensor(tokens)).logits.numpy()
    got = np.asarray(model.apply(params, jnp.asarray(tokens)))
    np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)


def test_linear_rope_scaling_logits_parity():
    from tony_tpu.models.hf import from_hf_llama

    config = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=48,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=True,
        rope_scaling={"rope_type": "linear", "factor": 4.0})
    torch.manual_seed(3)
    hf = transformers.LlamaForCausalLM(config).eval()
    model, params = from_hf_llama(hf)
    assert model.cfg.rope_scaling.kind == "linear"
    tokens = np.random.default_rng(5).integers(0, 64, (1, 50))
    with torch.no_grad():
        ref = hf(torch.tensor(tokens)).logits.numpy()
    got = np.asarray(model.apply(params, jnp.asarray(tokens)))
    np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)


def test_rope_scaling_decode_parity():
    """Scaled-RoPE decode must apply the same scaled frequencies at cached
    positions."""
    from tony_tpu.models.hf import from_hf_llama

    config = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=48,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=True,
        rope_scaling={"rope_type": "llama3", "factor": 4.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 2.0,
                      "original_max_position_embeddings": 8})
    torch.manual_seed(4)
    hf = transformers.LlamaForCausalLM(config).eval()
    model, params = from_hf_llama(hf)
    tokens = np.random.default_rng(6).integers(0, 64, (1, 20))
    full = np.asarray(model.apply(params, jnp.asarray(tokens)))
    cache = model.init(jax.random.PRNGKey(0), jnp.asarray(tokens),
                       decode=True)["cache"]
    steps = []
    for i in range(tokens.shape[1]):
        logits, mut = model.apply(
            {"params": params["params"], "cache": cache},
            jnp.asarray(tokens[:, i:i + 1]), decode=True, mutable=["cache"])
        cache = mut["cache"]
        steps.append(np.asarray(logits[:, 0]))
    np.testing.assert_allclose(np.stack(steps, axis=1), full,
                               atol=1e-3, rtol=1e-3)


def test_exotic_rope_scaling_rejected():
    from tony_tpu.models.hf import llama_config

    config = transformers.LlamaConfig(
        vocab_size=32, hidden_size=16, intermediate_size=32,
        num_hidden_layers=1, num_attention_heads=2,
        rope_scaling={"rope_type": "yarn", "factor": 2.0})
    with pytest.raises(ValueError, match="rope_scaling"):
        llama_config(config)


# -- Gemma (explicit head_dim, scaled embeddings, unit-offset RMSNorm) -------


@pytest.fixture(scope="module")
def gemma_pair():
    from tony_tpu.models.hf import from_hf_gemma

    config = transformers.GemmaConfig(
        vocab_size=96, hidden_size=48, intermediate_size=80,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16,  # 4 x 16 = 64 != hidden 48: the explicit-width path
        max_position_embeddings=64, attention_dropout=0.0,
        attn_implementation="eager")
    torch.manual_seed(0)
    hf = transformers.GemmaForCausalLM(config).eval()
    model, params = from_hf_gemma(hf)
    return hf, model, params


def test_gemma_config_mapping(gemma_pair):
    _, model, _ = gemma_pair
    cfg = model.cfg
    assert cfg.head_dim == 16 and cfg.explicit_head_dim == 16
    assert cfg.embed_scale and cfg.norm_unit_offset
    assert cfg.tied_embeddings and cfg.gated_mlp
    assert cfg.activation == "gelu_tanh"


def test_gemma_logits_parity(gemma_pair):
    """Exact vs torch GemmaForCausalLM: the sqrt(hidden) embedding
    normalizer, (1 + weight) RMSNorm, and head_dim > hidden/n_heads all
    have to agree."""
    hf, model, params = gemma_pair
    tokens = np.random.default_rng(3).integers(0, 96, (2, 13))
    with torch.no_grad():
        ref = hf(torch.tensor(tokens)).logits.numpy()
    got = np.asarray(model.apply(params, jnp.asarray(tokens)))
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_gemma_greedy_decode_parity(gemma_pair):
    from tony_tpu.models import generate

    hf, model, params = gemma_pair
    prompt = np.random.default_rng(4).integers(0, 96, (1, 7))
    with torch.no_grad():
        ref = hf.generate(torch.tensor(prompt), max_new_tokens=6,
                          do_sample=False).numpy()[0, 7:]
    got = np.asarray(generate(model, params["params"],
                              jnp.asarray(prompt), max_new_tokens=6))[0]
    np.testing.assert_array_equal(got, ref)


def test_gemma_hub_config_activation_and_untied():
    """Real hub Gemma configs carry BOTH hidden_act and hidden_activation;
    transformers' GemmaMLP runs hidden_act — the import must match the
    installed torch runtime, not the nominal field. Also: untied output
    heads must be honored, not silently dropped."""
    from tony_tpu.models.hf import from_hf_gemma

    config = transformers.GemmaConfig(
        vocab_size=96, hidden_size=48, intermediate_size=80,
        num_hidden_layers=1, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64, attention_dropout=0.0,
        hidden_act="gelu", hidden_activation="gelu_pytorch_tanh",
        tie_word_embeddings=False, attn_implementation="eager")
    torch.manual_seed(1)
    hf = transformers.GemmaForCausalLM(config).eval()
    model, params = from_hf_gemma(hf)
    assert model.cfg.activation == "gelu"  # hidden_act wins (ACT2FN path)
    assert not model.cfg.tied_embeddings
    assert "lm_head" in params["params"]
    tokens = np.random.default_rng(5).integers(0, 96, (1, 9))
    with torch.no_grad():
        ref = hf(torch.tensor(tokens)).logits.numpy()
    got = np.asarray(model.apply(params, jnp.asarray(tokens)))
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_gemma_importer_rejects_gemma2():
    from tony_tpu.models.hf import from_hf_gemma

    class FakeModel:
        class config:
            model_type = "gemma2"

    with pytest.raises(ValueError, match="gemma2"):
        from_hf_gemma(FakeModel())


# -- Mixtral (sparse MoE family) ---------------------------------------------


@pytest.fixture(scope="module")
def mixtral_pair():
    from tony_tpu.models.hf import from_hf_mixtral

    config = transformers.MixtralConfig(
        vocab_size=96, hidden_size=48, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, num_local_experts=4,
        num_experts_per_tok=2, tie_word_embeddings=False,
        sliding_window=None, attention_dropout=0.0,
        attn_implementation="eager")
    torch.manual_seed(0)
    hf = transformers.MixtralForCausalLM(config).eval()
    model, params = from_hf_mixtral(hf)
    return hf, model, params


def test_mixtral_config_mapping(mixtral_pair):
    _, model, _ = mixtral_pair
    cfg = model.cfg
    assert cfg.moe_every == 1 and cfg.moe_num_experts == 4
    assert cfg.moe_top_k == 2 and cfg.moe_gated
    assert cfg.moe_renormalize and cfg.moe_dropless
    assert cfg.moe_activation == "silu" and not cfg.gated_mlp
    assert cfg.n_kv_heads == 2


def test_mixtral_logits_parity(mixtral_pair):
    """Sparse-MoE decoder exact vs torch MixtralForCausalLM: top-2
    renormalized routing + SwiGLU experts + GQA attention. The dropless
    dense evaluation makes the comparison exact (no capacity drops)."""
    hf, model, params = mixtral_pair
    tokens = np.random.default_rng(5).integers(0, 96, (2, 13))
    with torch.no_grad():
        ref = hf(torch.tensor(tokens)).logits.numpy()
    got = np.asarray(model.apply(params, jnp.asarray(tokens)))
    np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)


def test_mixtral_decode_parity(mixtral_pair):
    """KV-cache decode through MoE blocks matches the full forward."""
    hf, model, params = mixtral_pair
    tokens = np.random.default_rng(6).integers(0, 96, (1, 8))
    full = np.asarray(model.apply(params, jnp.asarray(tokens)))
    cache = model.init(jax.random.PRNGKey(0), jnp.asarray(tokens),
                       decode=True)["cache"]
    steps = []
    for i in range(tokens.shape[1]):
        logits, mut = model.apply(
            {"params": params["params"], "cache": cache},
            jnp.asarray(tokens[:, i:i + 1]), decode=True, mutable=["cache"])
        cache = mut["cache"]
        steps.append(np.asarray(logits[:, 0]))
    np.testing.assert_allclose(np.stack(steps, axis=1), full,
                               atol=1e-3, rtol=1e-3)


def test_mixtral_importer_rejects_unmapped(mixtral_pair):
    from tony_tpu.models.hf import convert_mixtral_state_dict, mixtral_config

    hf, _, _ = mixtral_pair
    sd = dict(hf.state_dict())
    sd["model.layers.0.block_sparse_moe.experts.0.w9.weight"] = \
        torch.zeros(2, 2)
    with pytest.raises(ValueError, match="does not map"):
        convert_mixtral_state_dict(sd, mixtral_config(hf.config))


# -- GPT-NeoX / Pythia family ------------------------------------------------


@pytest.fixture(scope="module")
def neox_pair():
    from tony_tpu.models.hf import from_hf_neox

    config = transformers.GPTNeoXConfig(
        vocab_size=96, hidden_size=48, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64, rotary_pct=0.5,
        use_parallel_residual=True, tie_word_embeddings=False,
        attention_dropout=0.0, hidden_dropout=0.0,
        attn_implementation="eager")
    torch.manual_seed(0)
    hf = transformers.GPTNeoXForCausalLM(config).eval()
    model, params = from_hf_neox(hf)
    return hf, model, params


def test_neox_config_mapping(neox_pair):
    _, model, _ = neox_pair
    cfg = model.cfg
    assert cfg.norm == "layer" and cfg.positional == "rope"
    assert cfg.use_bias and cfg.parallel_residual
    assert cfg.rotary_dims == 6  # 0.5 * head_dim 12
    assert not cfg.gated_mlp and not cfg.tied_embeddings


def test_neox_logits_parity(neox_pair):
    """Partial rotary (rotary_pct) + parallel residual + biased dense,
    exact vs torch GPTNeoXForCausalLM."""
    hf, model, params = neox_pair
    tokens = np.random.default_rng(7).integers(0, 96, (2, 15))
    with torch.no_grad():
        ref = hf(torch.tensor(tokens)).logits.numpy()
    got = np.asarray(model.apply(params, jnp.asarray(tokens)))
    np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)


def test_neox_decode_parity(neox_pair):
    """KV-cache decode with partial rotary matches the full forward."""
    hf, model, params = neox_pair
    tokens = np.random.default_rng(8).integers(0, 96, (1, 9))
    full = np.asarray(model.apply(params, jnp.asarray(tokens)))
    cache = model.init(jax.random.PRNGKey(0), jnp.asarray(tokens),
                       decode=True)["cache"]
    steps = []
    for i in range(tokens.shape[1]):
        logits, mut = model.apply(
            {"params": params["params"], "cache": cache},
            jnp.asarray(tokens[:, i:i + 1]), decode=True, mutable=["cache"])
        cache = mut["cache"]
        steps.append(np.asarray(logits[:, 0]))
    np.testing.assert_allclose(np.stack(steps, axis=1), full,
                               atol=1e-3, rtol=1e-3)


def test_neox_sequential_residual_variant():
    """use_parallel_residual=False (GPT-NeoX small configs) maps onto the
    sequential block and still matches torch."""
    from tony_tpu.models.hf import from_hf_neox

    config = transformers.GPTNeoXConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=32, rotary_pct=1.0,
        use_parallel_residual=False, tie_word_embeddings=False,
        attention_dropout=0.0, hidden_dropout=0.0,
        attn_implementation="eager")
    torch.manual_seed(1)
    hf = transformers.GPTNeoXForCausalLM(config).eval()
    model, params = from_hf_neox(hf)
    assert not model.cfg.parallel_residual and model.cfg.rotary_dims == 0
    tokens = np.random.default_rng(9).integers(0, 64, (2, 11))
    with torch.no_grad():
        ref = hf(torch.tensor(tokens)).logits.numpy()
    got = np.asarray(model.apply(params, jnp.asarray(tokens)))
    np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)


def test_neox_importer_rejects_unmapped(neox_pair):
    from tony_tpu.models.hf import convert_neox_state_dict, neox_config

    hf, _, _ = neox_pair
    sd = dict(hf.state_dict())
    sd["gpt_neox.layers.0.attention.stray.weight"] = torch.zeros(2, 2)
    with pytest.raises(ValueError, match="does not map"):
        convert_neox_state_dict(sd, neox_config(hf.config))


def test_neox_rejects_biasless_and_exotic_rope():
    from tony_tpu.models.hf import neox_config

    base = dict(vocab_size=64, hidden_size=32, intermediate_size=64,
                num_hidden_layers=1, num_attention_heads=4,
                max_position_embeddings=32, rotary_pct=1.0)
    with pytest.raises(ValueError, match="attention_bias"):
        neox_config(transformers.GPTNeoXConfig(**base,
                                               attention_bias=False))
    with pytest.raises(ValueError, match="rope_scaling"):
        neox_config(transformers.GPTNeoXConfig(
            **base, rope_scaling={"rope_type": "yarn", "factor": 2.0}))


# -- Phi family --------------------------------------------------------------


@pytest.fixture(scope="module")
def phi_pair():
    from tony_tpu.models.hf import from_hf_phi

    config = transformers.PhiConfig(
        vocab_size=96, hidden_size=48, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=4, max_position_embeddings=64,
        partial_rotary_factor=0.5, tie_word_embeddings=False,
        attention_dropout=0.0, embd_pdrop=0.0, resid_pdrop=0.0,
        attn_implementation="eager")
    torch.manual_seed(0)
    hf = transformers.PhiForCausalLM(config).eval()
    model, params = from_hf_phi(hf)
    return hf, model, params


def test_phi_config_mapping(phi_pair):
    _, model, _ = phi_pair
    cfg = model.cfg
    assert cfg.norm == "layer" and cfg.parallel_residual
    assert cfg.rotary_dims == 6  # 0.5 * head_dim 12
    assert cfg.use_bias and cfg.lm_head_bias and not cfg.tied_embeddings


def test_phi_logits_parity(phi_pair):
    """Shared-norm parallel residual (ln1 duplicated into ln2) + partial
    rotary + biased lm_head, exact vs torch PhiForCausalLM."""
    hf, model, params = phi_pair
    tokens = np.random.default_rng(11).integers(0, 96, (2, 13))
    with torch.no_grad():
        ref = hf(torch.tensor(tokens)).logits.numpy()
    got = np.asarray(model.apply(params, jnp.asarray(tokens)))
    np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)


def test_phi_decode_parity(phi_pair):
    hf, model, params = phi_pair
    tokens = np.random.default_rng(12).integers(0, 96, (1, 8))
    full = np.asarray(model.apply(params, jnp.asarray(tokens)))
    cache = model.init(jax.random.PRNGKey(0), jnp.asarray(tokens),
                       decode=True)["cache"]
    steps = []
    for i in range(tokens.shape[1]):
        logits, mut = model.apply(
            {"params": params["params"], "cache": cache},
            jnp.asarray(tokens[:, i:i + 1]), decode=True, mutable=["cache"])
        cache = mut["cache"]
        steps.append(np.asarray(logits[:, 0]))
    np.testing.assert_allclose(np.stack(steps, axis=1), full,
                               atol=1e-3, rtol=1e-3)


def test_phi_importer_rejects_unmapped(phi_pair):
    from tony_tpu.models.hf import convert_phi_state_dict, phi_config

    hf, _, _ = phi_pair
    sd = dict(hf.state_dict())
    sd["model.layers.0.mlp.fc9.weight"] = torch.zeros(2, 2)
    with pytest.raises(ValueError, match="does not map"):
        convert_phi_state_dict(sd, phi_config(hf.config))


def test_lm_head_bias_param_exists_in_hidden_mode():
    """init(return_hidden=True) must yield the FULL param set for a
    lm_head_bias config — a tree missing the bias would fail normal
    logits-mode apply later (the chunked-CE training -> eval handoff)."""
    from tony_tpu.models import Transformer, TransformerConfig

    cfg = TransformerConfig(vocab_size=32, d_model=16, n_heads=2,
                            n_layers=1, d_ff=32, max_seq_len=8,
                            dtype=jnp.float32,
                            attention_backend="reference",
                            tied_embeddings=False, lm_head_bias=True)
    m = Transformer(cfg)
    t = jnp.zeros((1, 4), jnp.int32)
    p = m.init(jax.random.PRNGKey(0), t, return_hidden=True)
    assert "lm_head_bias" in p["params"]
    assert m.apply(p, t).shape == (1, 4, 32)


def test_phi_rejects_tied_embeddings():
    """ADVICE r3: a tied Phi would silently drop the converted biased
    lm_head — refuse at config mapping (no released Phi ties)."""
    from tony_tpu.models.hf import phi_config

    config = transformers.PhiConfig(
        vocab_size=96, hidden_size=48, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4,
        partial_rotary_factor=0.5, tie_word_embeddings=True)
    with pytest.raises(ValueError, match="tie_word_embeddings"):
        phi_config(config)
