"""Framework-wide constants: env var names, canonical roles, file names.

Reference: tony-core Constants.java:13-196. Names are re-derived for TPU
(coordinator env is jax.distributed's, not TF_CONFIG/MASTER_ADDR), but the
*set* of contracts is the same: task identity env, coordinator address env,
distributed-mode env, test fault-injection env, staging file names.
"""

from __future__ import annotations

# ---------------------------------------------------------------------------
# Task identity env, injected by the coordinator into every agent-launched
# task (reference: ApplicationMaster.java:1168-1188 container env).
# ---------------------------------------------------------------------------
JOB_NAME = "TONY_JOB_NAME"  # role name, e.g. "worker" (ref: JOB_NAME)
TASK_INDEX = "TONY_TASK_INDEX"  # index within the role (ref: TASK_INDEX)
TASK_NUM = "TONY_TASK_NUM"  # instance count of this role (ref: TASK_NUM)
IS_CHIEF = "TONY_IS_CHIEF"  # "true"/"false" (ref: IS_CHIEF)
JOB_ID = "TONY_JOB_ID"  # application id (ref: JOB_ID)
SESSION_ID = "TONY_SESSION_ID"  # session epoch, bumped on retry (ref: SESSION_ID)
DISTRIBUTED_MODE = "TONY_DISTRIBUTED_MODE"  # GANG | FCFS
ATTEMPT_NUMBER = "TONY_ATTEMPT_NUMBER"  # coordinator retry attempt (ref: ATTEMPT_NUMBER)
CHECKPOINT_DIR = "TONY_CHECKPOINT_DIR"  # resume: checkpoint root (no ref analog, SURVEY 5.4)
RESUME_STEP = "TONY_RESUME_STEP"  # resume: newest step found at (re)launch
JOB_DIR = "TONY_JOB_DIR"  # per-job working dir (staging, logs, events)
COMPILE_CACHE_DIR = "TONY_COMPILE_CACHE_DIR"  # persistent XLA compile cache
# (job-dir scoped: retry attempts reuse each other's compiles)
AGENT_PID = "TONY_AGENT_PID"  # pid of the task agent (preemption-notice target)
PREPROCESSING_JOB = "PREPROCESSING_JOB"  # "true" inside the preprocess task
# (ref: Constants.PREPROCESSING_JOB :75)
MODEL_PARAMS = "MODEL_PARAMS"  # preprocess stdout "Model parameters: ..."
# remainder, exported to every training task (ref: TASK_PARAM_KEY :90)
NUM_AM_RETRIES = "TONY_NUM_COORD_RETRIES"  # retries left (ref: NUM_AM_RETRIES)
TASK_MEMORY = "TONY_TASK_MEMORY"  # role memory (launchers enforce: rlimit/--memory)
TASK_CHIPS = "TONY_TASK_CHIPS"  # chips requested (ssh launcher packs per host)
TASK_VCORES = "TONY_TASK_VCORES"  # role vcores (docker --cpus; advisory locally)
TPU_VISIBLE_DEVICES = "TPU_VISIBLE_DEVICES"  # libtpu device-subset contract

# Coordinator (AM) control-plane address, for agents to register back
# (reference: AM_HOST/AM_PORT consumed in TaskExecutor.initConfigs :240-281).
COORDINATOR_HOST = "TONY_COORDINATOR_HOST"
COORDINATOR_PORT = "TONY_COORDINATOR_PORT"
METRICS_PORT = "TONY_METRICS_PORT"
JOB_TOKEN = "TONY_JOB_TOKEN"  # HMAC control-plane auth (ref: ClientToAM tokens)
TLS_FINGERPRINT = "TONY_TLS_FINGERPRINT"  # pin of the per-job cert (rpc/tls.py)

# ---------------------------------------------------------------------------
# Rendezvous env injected by runtimes (the TPU-native replacement for
# TF_CONFIG / RANK / DMLC_* / HOROVOD_* — see SURVEY.md section 2.5).
# ---------------------------------------------------------------------------
COORDINATOR_ADDRESS = "TONY_JAX_COORDINATOR"  # host:port for jax.distributed
PROCESS_ID = "TONY_PROCESS_ID"  # global process index
NUM_PROCESSES = "TONY_NUM_PROCESSES"
CLUSTER_SPEC = "CLUSTER_SPEC"  # JSON {role: ["host:port", ...]} (ref name kept:
# ray-on-tony discovery.py reads CLUSTER_SPEC verbatim)
TB_PORT = "TB_PORT"  # TensorBoard port reserved on chief / sidecar
TB_LOG_DIR = "TB_LOG_DIR"

# Framework-compat rendezvous env (emitted by the respective runtime adapters)
TF_CONFIG = "TF_CONFIG"
PT_RANK = "RANK"
PT_WORLD = "WORLD"
PT_INIT_METHOD = "INIT_METHOD"
MX_DMLC_ROLE = "DMLC_ROLE"
MX_DMLC_PS_ROOT_URI = "DMLC_PS_ROOT_URI"
MX_DMLC_PS_ROOT_PORT = "DMLC_PS_ROOT_PORT"
MX_DMLC_NUM_SERVER = "DMLC_NUM_SERVER"
MX_DMLC_NUM_WORKER = "DMLC_NUM_WORKER"
MX_DMLC_LOCAL = "DMLC_LOCAL"

# Horovod-compat env (emitted by the horovod runtime's worker adapter;
# reference: runtime/HorovodRuntime.java setHorovodRunEnv :312-350)
HOROVOD_CONTROLLER = "HOROVOD_CONTROLLER"
HOROVOD_CPU_OPERATIONS = "HOROVOD_CPU_OPERATIONS"
HOROVOD_GLOO_RENDEZVOUS_ADDR = "HOROVOD_GLOO_RENDEZVOUS_ADDR"
HOROVOD_GLOO_RENDEZVOUS_PORT = "HOROVOD_GLOO_RENDEZVOUS_PORT"
HOROVOD_RANK = "HOROVOD_RANK"
HOROVOD_SIZE = "HOROVOD_SIZE"
HOROVOD_LOCAL_RANK = "HOROVOD_LOCAL_RANK"
HOROVOD_LOCAL_SIZE = "HOROVOD_LOCAL_SIZE"
HOROVOD_CROSS_RANK = "HOROVOD_CROSS_RANK"
HOROVOD_CROSS_SIZE = "HOROVOD_CROSS_SIZE"
HOROVOD_HOSTNAME = "HOROVOD_HOSTNAME"

# ---------------------------------------------------------------------------
# Canonical role names (reference: Constants.java:111-118). Arbitrary role
# names are allowed via the config regex; these get special semantics.
# ---------------------------------------------------------------------------
CHIEF_JOB_NAME = "chief"
WORKER_JOB_NAME = "worker"
PS_JOB_NAME = "ps"
EVALUATOR_JOB_NAME = "evaluator"
TENSORBOARD_JOB_NAME = "tensorboard"
DRIVER_JOB_NAME = "driver"
NOTEBOOK_JOB_NAME = "notebook"

# ---------------------------------------------------------------------------
# Staging / history file names (reference: Constants.java TONY_FINAL_XML etc.)
# ---------------------------------------------------------------------------
TONY_FINAL_CONF = "tony-final.json"  # merged conf shipped to coord + agents
TONY_SRC_ZIP = "tony_src.zip"
TONY_VENV_ZIP = "venv.zip"
TONY_STAGING_PREFIX = ".tony"  # per-user staging dir (ref: ~/.tony/<uuid>)
HISTORY_INTERMEDIATE = "intermediate"
HISTORY_FINISHED = "finished"
JHIST_SUFFIX = ".jhist.jsonl"  # event-log container (jsonl in place of Avro)
INPROGRESS_SUFFIX = ".inprogress"
METADATA_FILE = "metadata.json"
LOG_SUFFIX = ".log"

# ---------------------------------------------------------------------------
# Exit codes (reference: TaskExecutor / ApplicationMaster conventions)
# ---------------------------------------------------------------------------
EXIT_SUCCESS = 0
EXIT_FAIL = 1
EXIT_INVALID_CONF = 2

# ---------------------------------------------------------------------------
# Fault-injection env for tests, honored by *production* code paths
# (reference: Constants.java:124-129, SURVEY.md section 4.2).
# ---------------------------------------------------------------------------
TEST_COORD_CRASH = "TEST_TONY_COORD_CRASH"  # ref: TEST_AM_CRASH
# which client-side (re)spawn of the coordinator this process is —
# the YARN attempt-number analog, used by crash injection to die once
COORD_CLIENT_ATTEMPT = "TONY_COORD_CLIENT_ATTEMPT"
TEST_COORD_THROW = "TEST_TONY_COORD_THROW"  # ref: TEST_AM_THROW_EXCEPTION_CRASH
TEST_TASK_NUM_HB_MISS = "TEST_TONY_NUM_HB_MISS"  # ref: TEST_TASK_EXECUTOR_NUM_HB_MISS
TEST_TASK_SKEW = "TEST_TONY_TASK_SKEW"  # "role#idx#ms" (ref: TEST_TASK_EXECUTOR_SKEW)
TEST_WORKER_TERMINATION = "TEST_TONY_WORKER_TERMINATION"  # kill chief mid-run
TEST_COMPLETION_DELAY = "TEST_TONY_COMPLETION_NOTIFICATION_DELAYED"

# Distributed modes (reference: TonyConfigurationKeys.DistributedMode)
GANG = "GANG"
FCFS = "FCFS"
