"""``tony-tpu gateway`` — the HTTP serving front door.

Boots N data-parallel ``serve.Server`` replicas (one scheduler thread
each, weights shared, KV caches private) behind ``tony_tpu.gateway``:
bounded admission with per-request deadlines, least-outstanding-tokens
routing, graceful drain on SIGTERM, per-request metrics on ``/stats``
(and in the portal via ``--history``).

    python -m tony_tpu.cli.gateway --model ./my-llama \
        --replicas 2 --serve-batch 4 --port 8000

    curl -s localhost:8000/v1/generate -d \
        '{"prompt": "Once upon a time", "max_new_tokens": 32}'

``--demo-model`` serves a tiny randomly initialized decoder instead of
a checkpoint — token_ids-only, but boots in seconds on CPU: the smoke
target (``make serve-smoke``) and quick integration checks use it.

Shutdown: SIGTERM/SIGINT stops admission (``/readyz`` -> 503 so a load
balancer pulls the replica), finishes every queued + in-flight request,
then exits 0. A second signal force-exits.

Fault tolerance (the TonY supervision story, serving flavor): replica
threads heartbeat; a watchdog fails a replica whose beats stall past
``--stall-timeout``, its requests fail over token-exactly to healthy
replicas (up to ``--max-attempts`` engine runs each), and the failed
replica re-earns admission through a circuit breaker
(``--breaker-base``/``--breaker-max`` backoff, ``--quarantine-after``
strikes). ``TONY_SERVE_FAULTS`` arms deterministic fault injection for
chaos testing (``make chaos-smoke``; see ``serve/faults.py``).

Goodput + alerts (ISSUE-10; docs/OBSERVABILITY.md): every dispatch is
priced by an analytic cost model (bytes/FLOPs, HBM-BW%/MFU with
``--hbm-gbps`` or a known chip), the wall clock decomposes into a
goodput ledger (``/stats engine.goodput``, ``GET /debug/goodput``
names the largest waste bucket), and a rule engine fires deduplicated
alerts (queue aging, KV-page pressure, TTFT-SLO burn, breaker flap,
goodput collapse) into ``/stats alerts``, ``tony_alerts_*`` and
history ``metrics/alerts.jsonl`` (``--alert-*`` knobs, ``--no-alerts``
off switch).

Elastic autoscaling + admission tiers (ISSUE-9; docs/SERVING.md):
``--autoscale-max N`` arms the control loop — the fleet grows from
``--replicas`` up to N under queue/SLO pressure (new replicas join
via circuit-breaker probe admission) and drains back to
``--autoscale-min`` when idle (zero-loss). Requests may carry
``priority`` (weighted-fair-queued tiers, ``--tier-weights``) and
``tenant`` (token-rate quotas, ``--tenant-quota`` -> 429 +
Retry-After on breach).
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys
import threading


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tony-tpu gateway",
        description="HTTP serving front door over N continuous-batching "
                    "replicas")
    src = p.add_mutually_exclusive_group()
    src.add_argument("--model", help="local checkpoint directory (HF format)")
    src.add_argument("--demo-model", action="store_true",
                     help="serve a tiny random decoder (no checkpoint, "
                          "token_ids requests only) — for smoke tests")
    p.add_argument("--remote-replica", action="store_true",
                   help="serve ON replica agents instead of in-process "
                        "threads: launch one `python -m "
                        "tony_tpu.cli.replica` subprocess per replica "
                        "(localhost; provisioned hosts run the same CLI "
                        "there) and drive each through a RemoteServer "
                        "stub — lease heartbeats, epoch fencing, "
                        "resumable token streams, token-exact failover "
                        "on host death (docs/SERVING.md)")
    p.add_argument("--agents", default="",
                   help="comma-separated host:port of ALREADY RUNNING "
                        "replica agents to attach to (implies remote "
                        "mode; the fleet is this list and the gateway "
                        "process loads no model weights at all)")
    p.add_argument("--agent-heartbeat", type=float, default=1.0,
                   help="gateway->agent heartbeat interval in seconds; "
                        "the lease horizon is interval x max(3, "
                        "--agent-lease-misses) — no successful "
                        "heartbeat for that long fails the replica "
                        "over (token-exact)")
    p.add_argument("--agent-lease-misses", type=int, default=5,
                   help="missed heartbeats before an agent's lease "
                        "expires (see --agent-heartbeat)")
    p.add_argument("--agent-channel", choices=("mux", "per-ticket"),
                   default="mux",
                   help="gateway->agent streaming transport: 'mux' is "
                        "ONE long-lived connection per replica "
                        "carrying every ticket stream as tagged "
                        "frames (reconnect re-establishes all of them "
                        "at their offsets in one round trip); "
                        "'per-ticket' keeps the one-connection-per-"
                        "request readers as the A/B control")
    p.add_argument("--replicas", type=int, default=1,
                   help="data-parallel serve.Server replicas (each with "
                        "its own KV cache and scheduler thread)")
    p.add_argument("--serve-batch", type=int, default=4,
                   help="cache slots per replica")
    p.add_argument("--chunk-steps", type=int, default=1,
                   help="decode micro-steps fused per dispatch; 1 = "
                        "lowest per-token streaming latency, larger = "
                        "higher throughput")
    p.add_argument("--prefill-chunk-tokens", type=int, default=0,
                   help="chunked prefill: max prompt tokens one "
                        "admission dispatch may consume (quantized to "
                        "the prefill bucket grid); long prompts "
                        "prefill in chunks interleaved between decode "
                        "rounds, capping co-tenant TPOT/TTFT "
                        "starvation. 0 = monolithic (the default)")
    p.add_argument("--roles", default="",
                   help="disaggregated prefill/decode: "
                        "'prefill=N,decode=M' splits the fleet into a "
                        "prefill pool (admission + chunked prefill "
                        "only; finished prompts hand off as page "
                        "lists) and a decode pool (receives handoffs, "
                        "decodes). Overrides --replicas to N+M; needs "
                        "the paged KV cache; token-exact vs a "
                        "generalist fleet (docs/SERVING.md)")
    p.add_argument("--no-prefix-affinity", action="store_true",
                   help="disable prefix-affinity routing (requests "
                        "route to the replica whose radix tree holds "
                        "their longest cached prefix; this flag is "
                        "the A/B control — routing degrades to "
                        "least-outstanding-tokens)")
    p.add_argument("--kv-host-mb", type=float, default=0.0,
                   help="host-RAM KV page tier byte budget per "
                        "replica: evicted prefix-store pages spill "
                        "device->host and page back in on a prefix "
                        "hit (bitwise round trip), so prefix reuse "
                        "stops being bounded by HBM. 0 disables; "
                        "needs paged KV + a prefix store; traffic "
                        "shows on /stats under engine.kv_host")
    p.add_argument("--prefix-cache-mb", type=float, default=64.0,
                   help="per-replica byte budget for the prefix "
                        "KV-cache store (radix reuse of shared prompt "
                        "prefixes: exact repeats skip prefill, shared "
                        "system prompts prefill only their suffix). "
                        "0 disables; hit rates show on /stats under "
                        "engine.prefix")
    p.add_argument("--speculate-k", type=int, default=0,
                   help="speculative decoding: max draft tokens per "
                        "slot per verify dispatch (prompt-lookup "
                        "n-gram drafting, batched multi-token "
                        "verification; greedy outputs unchanged, "
                        "sampled requests unaffected). 0 disables; "
                        "acceptance shows on /stats under engine.spec")
    p.add_argument("--kv-page-size", type=int, default=0,
                   help="tokens per KV-cache page (the block-paged "
                        "cache: residency bounded by actual tokens, "
                        "prefix reuse by copy-on-write page sharing). "
                        "0 auto-sizes from max_seq_len; utilization "
                        "shows on /stats under engine.kv_pages")
    p.add_argument("--kv-pages", type=int, default=0,
                   help="KV page-pool size per replica; 0 auto-sizes "
                        "(the unpaged-equivalent footprint, grown "
                        "into free TpuDiscoverer HBM on TPU — same "
                        "resolution style as --prefix-cache-mb)")
    p.add_argument("--no-paged-kv", action="store_true",
                   help="serve fixed-shape per-slot cache rows instead "
                        "of the paged pool (A/B escape hatch; "
                        "sliding-window models downgrade automatically)")
    p.add_argument("--no-shared-pool", action="store_true",
                   help="give each in-process replica its own private "
                        "KV page pool instead of one gateway-owned "
                        "shared pool (the shared pool makes "
                        "prefill->decode handoffs and live session "
                        "migration zero-copy owner swaps, and pools "
                        "the fleet's free-page headroom)")
    p.add_argument("--mesh", default="",
                   help="sharded replicas (ISSUE-14): devices per "
                        "replica as a bare count (tensor-parallel, "
                        "'--mesh 4') or an axis spec "
                        "('tensor=4,expert=2'). Params shard on "
                        "output dims, KV page pools on the kv-head "
                        "axis; streams are byte-identical to a "
                        "single-chip replica. '' = single-chip (the "
                        "default); topology shows on /stats under "
                        "engine.mesh")
    p.add_argument("--shard-rules", default="serve",
                   help="parallel.sharding rule preset for --mesh "
                        "(default 'serve' — the only preset with the "
                        "token-exactness contract)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000,
                   help="0 picks an ephemeral port")
    p.add_argument("--edge", choices=("event", "threaded"),
                   default="event",
                   help="HTTP front end: 'event' (default) is the "
                        "selector edge — one loop thread plus a small "
                        "fixed worker pool holds tens of thousands of "
                        "concurrent NDJSON streams; 'threaded' is the "
                        "thread-per-connection stdlib server, kept as "
                        "the A/B control")
    p.add_argument("--edge-max-connections", type=int, default=16384,
                   help="event edge connection breaker: past this "
                        "many open sockets new connections shed 503 "
                        "with Retry-After instead of degrading "
                        "everyone (threaded edge ignores this)")
    p.add_argument("--edge-workers", type=int, default=4,
                   help="event edge worker threads for blocking "
                        "gateway calls (submit, snapshot); the edge "
                        "itself stays on one loop thread")
    p.add_argument("--edge-write-buffer-kb", type=int, default=256,
                   help="event edge per-connection write buffer bound "
                        "in KiB; a client that cannot keep up beyond "
                        "it gets --edge-drain-timeout to catch up")
    p.add_argument("--edge-drain-timeout", type=float, default=10.0,
                   help="event edge slow-client policy: seconds a "
                        "full write buffer may take to drain before "
                        "the stream is aborted (counted, never pins "
                        "a worker thread)")
    p.add_argument("--edge-io-timeout", type=float, default=30.0,
                   help="event edge bound on reading one request "
                        "(head+body) once its first byte arrives — "
                        "trickled uploads get 408; IDLE keep-alive "
                        "connections are exempt and cost nothing")
    p.add_argument("--max-queue", type=int, default=128,
                   help="admission queue bound; past it requests shed "
                        "with 429")
    p.add_argument("--max-pending", type=int, default=1024,
                   help="per-replica engine queue bound (serve.QueueFull)")
    p.add_argument("--default-ttl", type=float, default=None,
                   help="default per-request deadline in seconds "
                        "(requests may override with ttl_s); expired "
                        "requests shed with 504 before taking a slot")
    p.add_argument("--eos-id", type=int, default=-1,
                   help="stop token (default: model config's "
                        "eos_token_id)")
    p.add_argument("--dtype", choices=("fp32", "bf16"), default="fp32",
                   help="parameter storage dtype (bf16 halves decode "
                        "HBM traffic — the serving default on TPU)")
    p.add_argument("--history", default="",
                   help="job-history root: record the gateway as a "
                        "portal-browsable job with per-request metrics "
                        "and Chrome-trace rows (metrics/traces.jsonl)")
    p.add_argument("--profile-dir", default="",
                   help="where POST /debug/profile drops its xplane "
                        "captures (default: <history job dir>/profiles "
                        "with --history, else ./profiles)")
    p.add_argument("--journal", action="store_true",
                   help="arm the durable ticket journal (ISSUE-20): a "
                        "write-ahead NDJSON log of every admit/route/"
                        "emit-offset/terminal under the history job "
                        "dir, compacted away on clean drain — the "
                        "record --recover replays after a crash. "
                        "Needs --history for a place to land")
    p.add_argument("--journal-fsync", default="batch",
                   choices=("always", "batch", "off"),
                   help="journal durability: 'always' fsyncs every "
                        "append, 'batch' (default) fsyncs admits and "
                        "terminals while emit offsets ride the page "
                        "cache, 'off' never fsyncs")
    p.add_argument("--recover", action="store_true",
                   help="crash recovery boot: replay the newest "
                        "journal under the --history root and "
                        "re-admit every still-live request — parked "
                        "agent sessions are adopted mid-stream "
                        "(token-exact, zero re-prefill), local ones "
                        "re-run from the prompt; clients resume via "
                        "GET /v1/stream/<id>?offset=. A no-op when "
                        "the previous boot drained clean")
    p.add_argument("--park-ttl", type=float, default=60.0,
                   help="seconds a terminal request stays resumable "
                        "at the gateway (GET /v1/stream/<id>) and a "
                        "launched agent keeps orphaned sessions "
                        "adoptable")
    p.add_argument("--agent-grace", type=float, default=0.0,
                   help="launched agents: seconds of gateway silence "
                        "before their in-flight slots freeze into "
                        "parked snapshots (forwarded as the replica "
                        "CLI's --gateway-grace; 0 = park only "
                        "finished results)")
    p.add_argument("--trace-capacity", type=int, default=256,
                   help="recent request traces kept for "
                        "GET /debug/trace/<request_id>; 0 disables "
                        "request tracing")
    p.add_argument("--drain-timeout", type=float, default=120.0,
                   help="max seconds to wait for in-flight requests on "
                        "shutdown")
    p.add_argument("--max-attempts", type=int, default=3,
                   help="engine runs a request may burn across replica "
                        "failures before it sheds 503 (the TonY task-"
                        "retry budget, per request)")
    p.add_argument("--stall-timeout", type=float, default=30.0,
                   help="seconds without a replica-thread heartbeat "
                        "before the watchdog declares it failed and "
                        "fails its requests over; must comfortably "
                        "exceed one step's worst dispatch time "
                        "(first-compile included)")
    p.add_argument("--breaker-base", type=float, default=0.25,
                   help="circuit breaker: first backoff before a failed "
                        "replica is probed (doubles per consecutive "
                        "failure up to --breaker-max)")
    p.add_argument("--breaker-max", type=float, default=8.0,
                   help="circuit breaker: backoff ceiling in seconds")
    p.add_argument("--quarantine-after", type=int, default=5,
                   help="consecutive failures (probe failures included) "
                        "before a replica is quarantined out of the "
                        "rotation for good")
    p.add_argument("--tier-weights", default="",
                   help="admission tier spec 'name=weight,...' "
                        "(default interactive=8,standard=4,batch=1); "
                        "requests pick a tier via their 'priority' "
                        "field, weights shape WFQ interleaving under "
                        "contention (idle fleets give any tier full "
                        "throughput)")
    p.add_argument("--tenant-quota", type=float, default=0.0,
                   help="per-tenant token-rate quota in tokens/s over "
                        "estimated request cost (prompt + budget); a "
                        "tenant over its rate gets 429 + Retry-After. "
                        "0 disables (the default)")
    p.add_argument("--tenant-burst", type=float, default=0.0,
                   help="per-tenant burst bucket in tokens "
                        "(default 4x --tenant-quota)")
    p.add_argument("--autoscale-max", type=int, default=0,
                   help="arm the elastic autoscaler: grow the fleet "
                        "up to this many replicas under queue/SLO "
                        "pressure (probe-admitted), drain back to "
                        "--autoscale-min when idle. 0 = fixed fleet "
                        "(the default)")
    p.add_argument("--autoscale-min", type=int, default=0,
                   help="fleet floor for scale-down "
                        "(default: --replicas)")
    p.add_argument("--autoscale-interval", type=float, default=1.0,
                   help="autoscaler control-loop tick in seconds")
    p.add_argument("--autoscale-up-queue", type=float, default=4.0,
                   help="queued requests per routable replica that "
                        "count as scale-up pressure")
    p.add_argument("--autoscale-up-wait", type=float, default=1.0,
                   help="oldest queued wait (s) that counts as "
                        "scale-up pressure")
    p.add_argument("--autoscale-ttft-slo", type=float, default=0.0,
                   help="TTFT SLO in seconds: scale-up pressure when "
                        ">10%% of a tick's completions exceed it "
                        "(0 disables the SLO-burn signal)")
    p.add_argument("--autoscale-cooldown-up", type=float, default=5.0,
                   help="lockout after a scale-up (s)")
    p.add_argument("--autoscale-cooldown-down", type=float, default=30.0,
                   help="lockout after a scale-down (s)")
    p.add_argument("--rebalance", action="store_true",
                   help="arm the pressure-driven rebalancer "
                        "(gateway/rebalance.py): watches per-replica "
                        "slot-occupancy skew and live-migrates "
                        "in-flight sessions off the hottest replica, "
                        "token-exact, preferring victims whose prefix "
                        "the cold side already caches")
    p.add_argument("--no-rebalance", action="store_true",
                   help="explicitly disable the rebalancer (the A/B "
                        "control; wins over --rebalance)")
    p.add_argument("--rebalance-interval", type=float, default=1.0,
                   help="rebalancer control-loop tick in seconds")
    p.add_argument("--rebalance-skew", type=float, default=0.5,
                   help="hot-minus-cold occupancy-fraction gap that "
                        "counts as skew (0.5 = 50 points fuller)")
    p.add_argument("--rebalance-stable", type=int, default=2,
                   help="consecutive skewed ticks before a move "
                        "(hysteresis)")
    p.add_argument("--rebalance-cooldown", type=float, default=5.0,
                   help="lockout after a successful move (s); a move "
                        "that found no victim waits twice as long")
    p.add_argument("--no-in-dispatch-eos", action="store_true",
                   help="disable the in-dispatch EOS/refill freeze "
                   "(ISSUE-13) and fused speculation rounds — the "
                   "pre-freeze engine behavior, kept as an A/B "
                   "control; costs chunk overshoot at depth")
    p.add_argument("--autotune", action="store_true",
                   help="arm the ledger-driven adaptive shape "
                   "controller (serve/autotune.py): steers "
                   "chunk-steps / speculate-k / prefill-chunk per "
                   "replica from the goodput ledger, within the "
                   "--autotune-* bounds; decisions go to /stats "
                   "engine.autotune, tony_autotune_* metrics, and "
                   "history metrics/autotune.jsonl")
    p.add_argument("--autotune-interval", type=float, default=1.0,
                   help="seconds between controller ticks")
    p.add_argument("--autotune-chunk-min", type=int, default=1,
                   help="chunk-steps floor the controller may steer to")
    p.add_argument("--autotune-chunk-max", type=int, default=32,
                   help="chunk-steps ceiling (0 pins chunk-steps)")
    p.add_argument("--autotune-spec-max", type=int, default=16,
                   help="speculate-k ceiling (0 pins speculate-k; the "
                   "controller never re-arms speculation from 0)")
    p.add_argument("--autotune-prefill-max", type=int, default=0,
                   help="prefill-chunk-tokens ceiling (0 = leave the "
                   "prefill chunk budget alone)")
    p.add_argument("--autotune-hold", type=int, default=2,
                   help="consecutive same-direction ticks before an "
                   "actuation (hysteresis)")
    p.add_argument("--autotune-cooldown", type=int, default=3,
                   help="ticks after an actuation during which the "
                   "knob is not re-judged")
    p.add_argument("--hbm-gbps", type=float, default=0.0,
                   help="peak HBM bandwidth reference in GB/s for the "
                        "goodput ledger's per-dispatch HBM-BW%% / MFU "
                        "estimates (0 auto-detects from the chip "
                        "table / TONY_HBM_GBPS; unknown chips and CPU "
                        "report bytes with utilization null)")
    p.add_argument("--no-alerts", action="store_true",
                   help="disable the serving alert bus (rule engine "
                        "over queue/KV/SLO/breaker/goodput signals "
                        "feeding /stats alerts, tony_alerts_* and "
                        "history alerts.jsonl) — the A/B escape hatch")
    p.add_argument("--alert-interval", type=float, default=1.0,
                   help="alert rule evaluation cadence in seconds")
    p.add_argument("--alert-queue-wait", type=float, default=5.0,
                   help="queue_aging alert: oldest queued wait (s) "
                        "that counts as an aging queue")
    p.add_argument("--alert-kv-free-frac", type=float, default=0.15,
                   help="kv_pages_pressure alert: free-after-"
                        "reservation fraction of the page pool under "
                        "which live load counts as pressure")
    p.add_argument("--alert-host-thrash-bytes", type=float,
                   default=float(1 << 20),
                   help="kv_host_thrash alert: host-tier page-in "
                        "bytes per evaluation tick that, together "
                        "with kv_pages_pressure, count as "
                        "spill/restore churn")
    p.add_argument("--alert-ttft-slo", type=float, default=0.0,
                   help="ttft_slo_burn alert: TTFT SLO in seconds "
                        "(>10%% of a tick's completions over it "
                        "fires; 0 disables the rule)")
    p.add_argument("--alert-shed-storm", type=int, default=50,
                   help="shed_storm alert: capacity sheds "
                        "(429/503/504, quota excluded) within the "
                        "storm window that count as a storm")
    p.add_argument("--alert-shed-window", type=float, default=10.0,
                   help="shed_storm alert: rate window in seconds")
    p.add_argument("--no-alert-bundles", action="store_true",
                   help="disable the flight recorder: by default a "
                        "FIRING alert dumps one self-contained debug "
                        "bundle (active alerts, recent traces incl. "
                        "remote spans, per-replica dispatch/goodput/"
                        "transport blocks, scale signals) into "
                        "<history job dir>/bundles/ — needs --history "
                        "for a place to land; GET /debug/bundle "
                        "serves the same document on demand either "
                        "way")
    p.add_argument("--compile-cache",
                   default=os.path.join(os.path.expanduser("~"), ".cache",
                                        "tony_tpu", "compile-cache"),
                   help="persistent XLA compile-cache dir ('' disables)")
    return p


def demo_model():
    """A tiny random decoder: boots in seconds on CPU, exercises the
    whole serving stack (prefill buckets, per-slot decode, EOS evict)."""
    import jax
    import jax.numpy as jnp

    from tony_tpu.models import Transformer, TransformerConfig

    # 4 heads so a --mesh 4 tensor axis divides the kv-head dim (the
    # shard-smoke round serves this model 4-way sharded); outputs are
    # only ever compared control-vs-treatment within one boot
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=2, d_ff=64, max_seq_len=64,
                            dtype=jnp.float32,
                            attention_backend="reference")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def parse_mesh(spec: str):
    """``--mesh`` -> a ``jax.sharding.Mesh`` over the FIRST N local
    devices, or None for single-chip. A bare count means pure tensor
    parallelism (``--mesh 4`` == ``tensor=4``); an axis spec names
    sizes per ``parallel.mesh`` axis (``tensor=4,expert=2`` -> 8
    devices/replica). Built once per process — every replica shares
    the mesh (its own params/pools, the same chips), exactly like
    the single-chip fleet shares the host."""
    s = spec.strip()
    if not s:
        return None
    import jax

    from tony_tpu.parallel.mesh import ALL_AXES, MeshSpec, make_mesh

    sizes = {}
    if s.isdigit():
        sizes["tensor"] = int(s)
    else:
        for part in s.split(","):
            name, sep, val = part.strip().partition("=")
            if not sep or name not in ALL_AXES:
                raise SystemExit(
                    f"--mesh expects a device count or 'axis=N,...' "
                    f"over {ALL_AXES}, got {spec!r}")
            try:
                sizes[name] = int(val)
            except ValueError:
                raise SystemExit(
                    f"--mesh size {val!r} is not an integer") from None
    n = 1
    for v in sizes.values():
        if v < 1:
            raise SystemExit(f"--mesh sizes must be >= 1, got {spec!r}")
        n *= v
    devices = jax.devices()
    if n > len(devices):
        raise SystemExit(
            f"--mesh {spec!r} needs {n} devices, "
            f"{len(devices)} visible")
    kwargs = {a: 1 for a in ALL_AXES}
    kwargs.update(sizes)
    return make_mesh(MeshSpec(**kwargs), devices=devices[:n])


def server_factory(args, model, params, eos):
    """One replica engine from parsed args — shared by boot-time
    construction AND the autoscaler's ThreadBackend, so a dynamically
    added replica is configured identically to a boot one (weights
    shared; its own KV cache/prefix store; TONY_SERVE_FAULTS applies
    by its fleet index, so chaos rounds can arm dynamic replicas
    too)."""
    from tony_tpu.cli.generate import (resolve_paged_kv,
                                       resolve_prefix_cache_mb)
    from tony_tpu.serve import FaultPlan, Server

    prefix_mb = resolve_prefix_cache_mb(args, model)
    # size the per-replica KV pool for the fleet CEILING: a pool sized
    # for --replicas would oversubscribe HBM the moment the scaler
    # grows past it
    ceiling = max(1, args.replicas,
                  getattr(args, "autoscale_max", 0) or 0)
    paged_kw = resolve_paged_kv(args, model, args.serve_batch,
                                n_replicas=ceiling)
    # one mesh per process, shared by every replica this factory mints
    # (including autoscaler-grown ones): each gets its own sharded
    # params/pools over the same chips
    mesh = parse_mesh(getattr(args, "mesh", ""))

    # the host tier spills EVICTED prefix-store entries: with the
    # store resolved off there is nothing to spill — downgrade loudly
    # instead of letting Server() refuse the whole boot
    kv_host_mb = getattr(args, "kv_host_mb", 0.0)
    if kv_host_mb > 0 and prefix_mb <= 0:
        logging.getLogger(__name__).warning(
            "--kv-host-mb ignored: the host page tier needs a prefix "
            "store (--prefix-cache-mb > 0)")
        kv_host_mb = 0.0

    # ONE gateway-owned shared PagePool lent to every co-located
    # replica (ISSUE-18): prefill->decode handoffs and live session
    # migration between in-process replicas become zero-copy refcount
    # owner swaps, and the fleet's free-page headroom is pooled (a
    # retiring replica's pages are instantly usable by the survivors).
    # Sized for the fleet CEILING — the same HBM the per-replica pools
    # would have held between them, in one allocation.
    pool = None
    if paged_kw.get("paged") \
            and not getattr(args, "no_shared_pool", False):
        from tony_tpu.serve.slots import PagePool, default_page_size

        cfg = model.cfg
        ps = paged_kw.get("kv_page_size", 0) \
            or default_page_size(cfg)
        ps = max(1, min(int(ps), cfg.max_seq_len))
        per_replica = paged_kw.get("kv_pages", 0) \
            or args.serve_batch * (-(-cfg.max_seq_len // ps))
        pool = PagePool(model, params, int(per_replica) * ceiling, ps,
                        mesh=mesh, shared=True)

    def make(index: int):
        return Server(model, params, batch_size=args.serve_batch,
                      eos_id=eos, chunk_steps=args.chunk_steps,
                      max_pending=args.max_pending,
                      prefix_cache_mb=prefix_mb,
                      speculate_k=args.speculate_k,
                      fault_plan=FaultPlan.from_env(replica=index),
                      hbm_gbps=getattr(args, "hbm_gbps", 0.0),
                      prefill_chunk_tokens=getattr(
                          args, "prefill_chunk_tokens", 0),
                      kv_host_mb=kv_host_mb,
                      in_dispatch_eos=not getattr(
                          args, "no_in_dispatch_eos", False),
                      mesh=mesh,
                      shard_rules=getattr(args, "shard_rules", "serve"),
                      page_pool=pool,
                      **paged_kw)

    return make


def parse_roles(spec: str) -> list | None:
    """``--roles prefill=N,decode=M`` -> the per-replica role list
    (prefill replicas first — their fleet indices are stable, so
    TONY_SERVE_FAULTS addressing and log lines stay readable)."""
    if not spec.strip():
        return None
    counts = {"prefill": 0, "decode": 0}
    for part in spec.split(","):
        name, sep, n = part.strip().partition("=")
        if not sep or name not in counts:
            raise SystemExit(
                f"--roles expects 'prefill=N,decode=M', got {spec!r}")
        try:
            counts[name] = int(n)
        except ValueError:
            raise SystemExit(f"--roles count {n!r} is not an integer") \
                from None
    if counts["prefill"] < 1 or counts["decode"] < 1:
        raise SystemExit("--roles needs at least one prefill AND one "
                         "decode replica")
    return ["prefill"] * counts["prefill"] \
        + ["decode"] * counts["decode"]


def agent_argv(args, index: int) -> list:
    """The ``python -m tony_tpu.cli.replica`` argv mirroring this
    gateway's engine knobs — a launched agent must be configured
    exactly like an in-process replica would have been."""
    argv = ["--serve-batch", str(args.serve_batch),
            "--chunk-steps", str(args.chunk_steps),
            "--prefill-chunk-tokens",
            str(getattr(args, "prefill_chunk_tokens", 0)),
            "--prefix-cache-mb", str(args.prefix_cache_mb),
            "--kv-host-mb", str(getattr(args, "kv_host_mb", 0.0)),
            "--speculate-k", str(args.speculate_k),
            "--kv-page-size", str(args.kv_page_size),
            "--kv-pages", str(args.kv_pages),
            "--max-pending", str(args.max_pending),
            "--eos-id", str(args.eos_id),
            "--dtype", args.dtype,
            "--replica-index", str(index),
            # launched agents share THIS host: auto-sized KV pools
            # must divide its HBM by the fleet CEILING, exactly like
            # in-process replicas do (the PR-8 oversubscription rule)
            "--host-share", str(max(1, args.replicas,
                                    getattr(args, "autoscale_max", 0)
                                    or 0)),
            # crash-safety knobs (ISSUE-20): launched agents keep
            # orphans adoptable exactly as long as the gateway keeps
            # terminals resumable, and freeze in-flight slots after
            # --agent-grace of gateway silence
            "--park-ttl", str(getattr(args, "park_ttl", 60.0)),
            "--gateway-grace", str(getattr(args, "agent_grace", 0.0)),
            "--port", "0"]
    if getattr(args, "mesh", "").strip():
        argv += ["--mesh", args.mesh,
                 "--shard-rules", getattr(args, "shard_rules", "serve")]
    if getattr(args, "profile_dir", ""):
        # launched agents share THIS host: their /v1/profile captures
        # land under the gateway's profile dir, one subdir per agent
        argv += ["--profile-dir",
                 os.path.join(args.profile_dir, f"agent-{index}")]
    if args.no_paged_kv:
        argv.append("--no-paged-kv")
    if getattr(args, "no_in_dispatch_eos", False):
        argv.append("--no-in-dispatch-eos")
    if args.demo_model:
        argv.append("--demo-model")
    else:
        argv += ["--model", args.model]
    if getattr(args, "compile_cache", ""):
        argv += ["--compile-cache", args.compile_cache]
    return argv


def remote_server_factory(args):
    """``make(index, hosts=None) -> RemoteServer`` — the remote twin
    of ``server_factory``. ``hosts`` is a provisioned slice's host
    list (``ProvisionerBackend.server_factory(hosts)`` — the grown
    remote mode): a ``host:port`` entry attaches to an agent already
    listening there (the slice's boot ran ``cli.replica``); a bare
    localhost entry (or no hosts — the dev/smoke shape) launches the
    agent as a local subprocess via ``launch_local_agent``.
    ``TONY_SERVE_FAULTS`` transport faults arm at the stub by fleet
    index while engine faults ride the launched agent's environment —
    one env var, both failure planes."""
    import tempfile

    from tony_tpu.gateway.remote import RemoteServer, launch_local_agent
    from tony_tpu.serve import FaultPlan

    def stub(address: str, index: int, proc=None) -> RemoteServer:
        return RemoteServer(
            address,
            heartbeat_interval_s=getattr(args, "agent_heartbeat", 1.0),
            lease_misses=getattr(args, "agent_lease_misses", 5),
            stall_timeout_s=args.stall_timeout,
            agent_channel=getattr(args, "agent_channel", "mux"),
            transport_faults=FaultPlan.transport_from_env(replica=index),
            agent_proc=proc)

    def make(index: int, hosts=None) -> RemoteServer:
        if hosts:
            h = str(hosts[0])
            if ":" in h:
                return stub(h, index)
            if h not in ("localhost", "127.0.0.1"):
                raise ValueError(
                    f"remote host {h!r} must either run `python -m "
                    f"tony_tpu.cli.replica` itself and be given as "
                    f"host:port, or be localhost (subprocess launch)")
        port_dir = tempfile.mkdtemp(prefix=f"tony-agent-{index}-")
        proc, address = launch_local_agent(
            agent_argv(args, index),
            port_file=os.path.join(port_dir, "agent.port"))
        try:
            return stub(address, index, proc=proc)
        except Exception:
            # the stub never existed, so nothing will ever close() it:
            # reap the agent here or a failed boot (bad engine, armed
            # boot fault) leaks a full engine's memory per attempt
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except Exception:  # noqa: BLE001 — best-effort teardown
                proc.kill()
            raise

    return make


def build_gateway(args, model, params, eos, *, metrics_store=None):
    """Servers + Gateway from parsed args (shared with tests/bench).
    Remote mode (``--agents`` attach / ``--remote-replica`` launch)
    ignores ``model``/``params`` — the agents own the weights and the
    gateway process is a pure router."""
    from tony_tpu.gateway import Gateway, GatewayHistory

    agents = [a.strip() for a in getattr(args, "agents", "").split(",")
              if a.strip()]
    # role split sizes the fleet itself: prefill=N,decode=M means
    # exactly N+M replicas, whatever --replicas said
    roles = parse_roles(getattr(args, "roles", ""))
    if roles:
        if agents and len(agents) != len(roles):
            raise SystemExit(
                f"--roles names {len(roles)} replicas but --agents "
                f"lists {len(agents)}")
        args.replicas = len(roles)
    # TONY_SERVE_FAULTS arms deterministic fault injection per replica
    # (serve/faults.py) — the chaos-smoke hook; unset = None = zero cost
    if agents:
        rmake = remote_server_factory(args)
        servers = [rmake(i, hosts=[addr])
                   for i, addr in enumerate(agents)]
    elif getattr(args, "remote_replica", False):
        rmake = remote_server_factory(args)
        servers = [rmake(i) for i in range(max(1, args.replicas))]
    else:
        make = server_factory(args, model, params, eos)
        servers = [make(i) for i in range(max(1, args.replicas))]
    armed = [i for i, s in enumerate(servers)
             if s.fault_plan is not None
             or getattr(s, "transport_faults", None) is not None]
    if armed:
        logging.getLogger(__name__).warning(
            "fault injection ARMED on replica(s) %s via TONY_SERVE_FAULTS",
            armed)
    history = None
    if args.history:
        history = GatewayHistory(args.history,
                                 n_replicas=len(servers))
    journal = None
    if getattr(args, "journal", False) or getattr(args, "recover",
                                                  False):
        # the WAL lands in THIS boot's history job dir (next to
        # requests.jsonl); --recover implies journaling — a recovered
        # gateway that did not journal would be unrecoverable itself
        if history is None:
            raise SystemExit("--journal/--recover need --history for "
                             "a place to put the journal")
        from tony_tpu.gateway.journal import TicketJournal

        journal = TicketJournal(
            os.path.join(history.job_dir, "journal.ndjson"),
            fsync=getattr(args, "journal_fsync", "batch"))
    trace_capacity = getattr(args, "trace_capacity", 256)
    return Gateway(servers, max_queue=args.max_queue,
                   default_ttl_s=args.default_ttl,
                   journal=journal,
                   park_ttl_s=getattr(args, "park_ttl", 60.0),
                   metrics_store=metrics_store, history=history,
                   max_attempts=args.max_attempts,
                   stall_timeout_s=args.stall_timeout,
                   breaker_base_s=args.breaker_base,
                   breaker_max_s=args.breaker_max,
                   quarantine_after=args.quarantine_after,
                   tracing=trace_capacity > 0,
                   trace_capacity=max(1, trace_capacity),
                   profile_dir=getattr(args, "profile_dir", "") or None,
                   tier_weights=getattr(args, "tier_weights", "") or None,
                   tenant_quota_rate=getattr(args, "tenant_quota", 0.0),
                   tenant_quota_burst=getattr(args, "tenant_burst", 0.0),
                   alerts=not getattr(args, "no_alerts", False),
                   alert_interval_s=getattr(args, "alert_interval", 1.0),
                   alert_thresholds={
                       "queue_wait_s": getattr(args, "alert_queue_wait",
                                               5.0),
                       "kv_free_frac": getattr(args,
                                               "alert_kv_free_frac",
                                               0.15),
                       "ttft_slo_s": getattr(args, "alert_ttft_slo",
                                             0.0),
                       "host_thrash_bytes": getattr(
                           args, "alert_host_thrash_bytes",
                           float(1 << 20)),
                       "shed_storm_count": getattr(
                           args, "alert_shed_storm", 50),
                       "shed_storm_window_s": getattr(
                           args, "alert_shed_window", 10.0),
                   },
                   bundle_on_alert=not getattr(args, "no_alert_bundles",
                                               False),
                   roles=roles,
                   prefix_affinity=not getattr(args,
                                               "no_prefix_affinity",
                                               False),
                   autotune=getattr(args, "autotune", False),
                   autotune_interval_s=getattr(args,
                                               "autotune_interval",
                                               1.0),
                   autotune_config={
                       "chunk_bounds": (
                           max(1, getattr(args, "autotune_chunk_min",
                                          1)),
                           getattr(args, "autotune_chunk_max", 32)),
                       "spec_bounds": (
                           0, getattr(args, "autotune_spec_max", 16)),
                       "prefill_bounds": (
                           0, getattr(args, "autotune_prefill_max",
                                      0)),
                       "hold_ticks": getattr(args, "autotune_hold", 2),
                       "cooldown_ticks": getattr(
                           args, "autotune_cooldown", 3),
                   } if getattr(args, "autotune", False) else None)


def build_scaler(args, gateway, model, params, eos):
    """Arm the elastic autoscaler when --autoscale-max asks for one:
    a ThreadBackend over the same server factory boot replicas used
    (weights shared — scale-up costs one KV cache + the probe's
    compile, not a checkpoint load). Returns None when not armed."""
    max_replicas = getattr(args, "autoscale_max", 0)
    if not max_replicas:
        return None
    if getattr(args, "roles", "").strip():
        # a scaler-minted replica would need a role assignment policy
        # (grow which pool?) this PR does not take a position on —
        # refuse loudly instead of growing a roleless generalist into
        # a fleet whose routing would never send it work
        raise SystemExit("--autoscale-max cannot be combined with "
                         "--roles (fixed role-split fleets only)")
    from tony_tpu.gateway import AutoScaler, ThreadBackend

    boot = max(1, args.replicas)
    if max_replicas < boot:
        raise SystemExit(f"--autoscale-max {max_replicas} is below "
                         f"--replicas {boot}")
    floor = max(1, getattr(args, "autoscale_min", 0) or boot)
    if floor > max_replicas:
        raise SystemExit(f"--autoscale-min {floor} is above "
                         f"--autoscale-max {max_replicas}")
    # a dynamic replica's fleet index is wherever the (append-only)
    # replica list currently ends — read at create time, so a failed
    # create/join cannot desync TONY_SERVE_FAULTS addressing for the
    # replicas that come after it (only the scaler thread creates, so
    # the read cannot race another add)
    if getattr(args, "agents", "").strip():
        raise SystemExit(
            "--autoscale-max cannot mint new agents in --agents attach "
            "mode (the fleet is the given list); use --remote-replica "
            "launch mode or a provisioner backend")
    if getattr(args, "remote_replica", False):
        rmake = remote_server_factory(args)
        backend = ThreadBackend(
            lambda: rmake(len(gateway.replicas)), label="remote-agent")
    else:
        make = server_factory(args, model, params, eos)
        backend = ThreadBackend(lambda: make(len(gateway.replicas)))
    return AutoScaler(
        gateway, backend,
        min_replicas=floor,
        max_replicas=max_replicas,
        interval_s=getattr(args, "autoscale_interval", 1.0),
        up_queue_depth=getattr(args, "autoscale_up_queue", 4.0),
        up_wait_s=getattr(args, "autoscale_up_wait", 1.0),
        ttft_slo_s=getattr(args, "autoscale_ttft_slo", 0.0),
        cooldown_up_s=getattr(args, "autoscale_cooldown_up", 5.0),
        cooldown_down_s=getattr(args, "autoscale_cooldown_down", 30.0),
        drain_timeout_s=getattr(args, "drain_timeout", 120.0))


def build_rebalancer(args, gateway):
    """Arm the pressure-driven rebalancer when --rebalance asks for
    one (--no-rebalance wins: it is the A/B control in smoke runs
    that pass both). Returns None when not armed."""
    if getattr(args, "no_rebalance", False) \
            or not getattr(args, "rebalance", False):
        return None
    from tony_tpu.gateway import Rebalancer

    cooldown = getattr(args, "rebalance_cooldown", 5.0)
    return Rebalancer(
        gateway,
        interval_s=getattr(args, "rebalance_interval", 1.0),
        skew_frac=getattr(args, "rebalance_skew", 0.5),
        stable=getattr(args, "rebalance_stable", 2),
        cooldown_s=cooldown,
        fail_cooldown_s=2 * cooldown)


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    remote = bool(args.agents.strip()) or args.remote_replica
    if not args.model and not args.demo_model and not args.agents:
        parser.error("one of --model / --demo-model / --agents is "
                     "required")
    if args.remote_replica and not (args.model or args.demo_model):
        parser.error("--remote-replica needs --model or --demo-model "
                     "to hand to the launched agents")
    logging.basicConfig(level=logging.INFO)
    if args.compile_cache:
        from tony_tpu.utils import compilecache

        compilecache.enable(args.compile_cache)

    encode = decode = None
    model = params = None
    eos: list = []
    if remote:
        # the gateway process is a pure router: the agents own the
        # weights (and pay the compiles). With a checkpoint named, load
        # ONLY the tokenizer so text prompts still work at the door.
        if args.model:
            try:
                import transformers

                tok = transformers.AutoTokenizer.from_pretrained(
                    args.model)
                encode, decode = tok.encode, tok.decode
            except Exception:  # noqa: BLE001 — token_ids still serve
                print("note: no tokenizer in model dir; token_ids "
                      "requests only", file=sys.stderr)
    elif args.demo_model:
        model, params, eos = *demo_model(), \
            ([args.eos_id] if args.eos_id >= 0 else [])
    else:
        from tony_tpu.cli.generate import load_model
        from tony_tpu.models.generate import normalize_eos_ids

        model, wrapped, config = load_model(args.model)
        params = wrapped["params"]
        if args.dtype == "bf16":
            import jax
            import jax.numpy as jnp

            params = jax.tree.map(
                lambda x: x.astype(jnp.bfloat16)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
        eos = normalize_eos_ids(args.eos_id) or \
            normalize_eos_ids(getattr(config, "eos_token_id", None))
        try:
            import transformers

            tok = transformers.AutoTokenizer.from_pretrained(args.model)
            encode, decode = tok.encode, tok.decode
        except Exception:  # noqa: BLE001 — a checkpoint without a
            # tokenizer still serves token_ids requests
            print("note: no tokenizer in model dir; token_ids "
                  "requests only", file=sys.stderr)

    from tony_tpu.gateway import GatewayEdge, GatewayHTTP
    from tony_tpu.metrics import MetricsStore

    # --recover: find the DEAD boot's journal BEFORE build_gateway
    # creates this boot's (fresh, newest-mtime) one — the replay must
    # see the previous incarnation's record, not our empty file
    recover_entries = None
    if getattr(args, "recover", False):
        from tony_tpu.gateway import journal as journal_mod

        prev = journal_mod.find_latest(args.history) \
            if args.history else None
        recover_entries = journal_mod.replay(prev) if prev else {}
        n_live = sum(1 for e in recover_entries.values() if e.live)
        print(f"recovery: replayed "
              f"{prev or '(no previous journal)'} — "
              f"{n_live} live request(s)", file=sys.stderr, flush=True)

    gateway = build_gateway(args, model, params, eos,
                            metrics_store=MetricsStore()).start()
    if recover_entries is not None:
        report = gateway.recover_from_journal(recover_entries)
        print(f"recovery: {report['adopted']} adopted mid-stream, "
              f"{report['rerun']} re-run from prompt, "
              f"{report['finished']} finished results, "
              f"{report['shed']} shed "
              f"({report.get('wall_ms', 0):.0f}ms)",
              file=sys.stderr, flush=True)
    scaler = build_scaler(args, gateway, model, params, eos)
    if scaler is not None:
        scaler.start()
    rebalancer = build_rebalancer(args, gateway)
    if rebalancer is not None:
        rebalancer.start()
    if getattr(args, "edge", "event") == "event":
        http = GatewayEdge(
            gateway, host=args.host, port=args.port,
            encode=encode, decode=decode,
            max_connections=args.edge_max_connections,
            workers=args.edge_workers,
            write_buffer_kb=args.edge_write_buffer_kb,
            drain_timeout_s=args.edge_drain_timeout,
            io_timeout_s=args.edge_io_timeout).start()
    else:
        http = GatewayHTTP(gateway, host=args.host, port=args.port,
                           encode=encode, decode=decode).start()
    elastic = "" if scaler is None else \
        (f", autoscale {scaler.min_replicas}-{scaler.max_replicas}")
    if rebalancer is not None:
        elastic += ", rebalance on"
    n_rep = len(gateway.replicas)
    mode = ""
    if remote:
        mode = " remote agents: " + ", ".join(
            r.host for r in gateway.replicas)
    print(f"tony-tpu gateway at http://{http.host}:{http.port} "
          f"({n_rep} replica(s) x {args.serve_batch} "
          f"slots{elastic}{mode})", flush=True)

    stop = threading.Event()

    def _on_signal(signum, frame):
        if stop.is_set():  # second signal: force exit
            os._exit(1)
        print(f"signal {signum}: draining (readyz -> 503, finishing "
              f"in-flight)...", file=sys.stderr, flush=True)
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    stop.wait()
    ok = gateway.drain(timeout=args.drain_timeout)
    http.stop()
    if not ok:
        print("drain timed out with requests still in flight",
              file=sys.stderr)
        return 1
    print("drained clean", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
