"""``tony-tpu score`` — perplexity/log-likelihood of a local HF checkpoint.

The eval face of the serving stack (sibling of ``tony-tpu generate``):
import a GPT-2/Llama/Mistral/Qwen2 directory, run the full forward, and
report per-token negative log-likelihood + perplexity over the given
text or token ids. Offline; one jitted forward per input length.

    python -m tony_tpu.cli.score --model ./my-llama --text-file eval.txt
    python -m tony_tpu.cli.score --model ./ckpt --token-ids 1,2,3,4
"""

from __future__ import annotations

import argparse
import math
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tony-tpu score",
        description="Perplexity of a local HF checkpoint over given text",
    )
    p.add_argument("--model", required=True,
                   help="local checkpoint directory (HF format)")
    p.add_argument("--text", action="append", default=[],
                   help="text to score (repeatable; needs a tokenizer in "
                        "the model dir)")
    p.add_argument("--text-file", action="append", default=[],
                   help="file whose contents to score (repeatable)")
    p.add_argument("--token-ids", action="append", default=[],
                   help="raw ids, comma-separated (repeatable)")
    p.add_argument("--max-len", type=int, default=0,
                   help="truncate inputs to this many tokens "
                        "(default: the model's max_seq_len)")
    return p


def score_ids(model, params, ids) -> tuple[float, int]:
    """(total nll, token count) of ids under the model (teacher-forced)."""
    import jax.nn
    import jax.numpy as jnp

    tokens = jnp.asarray([ids], jnp.int32)
    logits = model.apply(params, tokens)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(
        logp[:, :-1], tokens[:, 1:, None], axis=-1)[0, :, 0]
    return float(-picked.sum()), len(ids) - 1


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from tony_tpu.cli.generate import load_model

    inputs: list[list[int]] = []
    texts = list(args.text)
    for path in args.text_file:
        with open(path, encoding="utf-8") as f:
            texts.append(f.read())
    model, params, config = load_model(args.model)
    if texts:
        import transformers

        tokenizer = transformers.AutoTokenizer.from_pretrained(args.model)
        inputs += [tokenizer.encode(t) for t in texts]
    inputs += [[int(i) for i in ids.split(",")] for ids in args.token_ids]
    if not inputs:
        print("need --text, --text-file, or --token-ids", file=sys.stderr)
        return 2

    limit = args.max_len or model.cfg.max_seq_len
    total_nll = 0.0
    total_tokens = 0
    for ids in inputs:
        ids = ids[:limit]
        if len(ids) < 2:
            print("skipping input with < 2 tokens", file=sys.stderr)
            continue
        nll, n = score_ids(model, params, ids)
        total_nll += nll
        total_tokens += n
        print(f"tokens={n} nll/token={nll / n:.4f} "
              f"ppl={math.exp(nll / n):.2f}")
    if total_tokens:
        avg = total_nll / total_tokens
        print(f"TOTAL tokens={total_tokens} nll/token={avg:.4f} "
              f"ppl={math.exp(avg):.2f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
