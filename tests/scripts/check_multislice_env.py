"""Multislice gang e2e payload (VERDICT r4 stretch #10): each worker
asserts the MEGASCALE_*/per-slice libtpu env the jax runtime injected
for ITS (role, index) — slice id, intra-slice worker id, per-slice
hostname partition, shared DCN coordinator — and then the whole gang
proves it actually runs together: global jax.distributed rendezvous +
allgather across all slices (coordination is global even when libtpu
bring-up is per-slice). Exit codes mark which leg failed."""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# one local device per process (see check_jax_psum.py: pinning the
# multi-process contract, not virtual-device fan-out, on a 1-core box)
os.environ["XLA_FLAGS"] = " ".join(
    [f for f in os.environ.get("XLA_FLAGS", "").split()
     if "xla_force_host_platform_device_count" not in f]
    + ["--xla_force_host_platform_device_count=1"])

spec = json.loads(os.environ["CLUSTER_SPEC"])
workers = spec["worker"]
idx = int(os.environ["TONY_TASK_INDEX"])
n_slices = int(os.environ.get("MEGASCALE_NUM_SLICES", "0"))
if n_slices != 2:
    print("expected MEGASCALE_NUM_SLICES=2, got", n_slices)
    sys.exit(3)
per_slice = len(workers) // n_slices
if os.environ.get("MEGASCALE_SLICE_ID") != str(idx // per_slice):
    print("bad MEGASCALE_SLICE_ID", os.environ.get("MEGASCALE_SLICE_ID"))
    sys.exit(4)
if os.environ.get("TPU_WORKER_ID") != str(idx % per_slice):
    print("bad TPU_WORKER_ID", os.environ.get("TPU_WORKER_ID"))
    sys.exit(5)
slice_hosts = [w.rsplit(":", 1)[0]
               for w in workers[(idx // per_slice) * per_slice:
                                (idx // per_slice + 1) * per_slice]]
if os.environ.get("TPU_WORKER_HOSTNAMES") != ",".join(slice_hosts):
    print("bad TPU_WORKER_HOSTNAMES",
          os.environ.get("TPU_WORKER_HOSTNAMES"), slice_hosts)
    sys.exit(6)
coord = os.environ.get("MEGASCALE_COORDINATOR_ADDRESS", "")
if coord.rsplit(":", 1)[0] != workers[0].rsplit(":", 1)[0] \
        or ":" not in coord:
    print("bad MEGASCALE_COORDINATOR_ADDRESS", coord)
    sys.exit(7)

# the gang leg: global rendezvous + collective across BOTH slices
from tony_tpu import distributed  # noqa: E402

dspec = distributed.initialize(timeout_s=180)
if dspec is None:
    print("not in a gang")
    sys.exit(8)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.experimental import multihost_utils  # noqa: E402

if jax.process_count() != len(workers):
    print("coordination must stay GLOBAL across slices:",
          jax.process_count(), "!=", len(workers))
    sys.exit(9)
val = jnp.asarray([float(idx + 1)])
total = float(multihost_utils.process_allgather(val).sum())
n = len(workers)
if abs(total - n * (n + 1) / 2) > 1e-6:
    print("bad global sum", total)
    sys.exit(10)
print("multislice gang ok: slice", os.environ["MEGASCALE_SLICE_ID"],
      "worker", os.environ["TPU_WORKER_ID"], "sum", total)
