"""Build/version info.

Reference: tony-core util/VersionInfo.java (149 LoC) injects
version/revision/branch/user/date into the job conf; we expose the same
fields and inject them in ``tony_tpu.config.TonyConf.finalize``.
"""

from __future__ import annotations

import getpass
import os
import subprocess
import time

__version__ = "0.1.0"


def _git(*args: str) -> str:
    try:
        out = subprocess.run(
            ["git", *args],
            capture_output=True,
            text=True,
            timeout=5,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        return out.stdout.strip() if out.returncode == 0 else "unknown"
    except Exception:
        return "unknown"


def version_info() -> dict:
    """Version metadata injected into the final job conf.

    Mirrors the keys of TonyConfigurationKeys.java:34-41 (tony.version,
    tony.revision, tony.branch, tony.user, tony.date).
    """
    return {
        "tony.version": __version__,
        "tony.revision": _git("rev-parse", "HEAD"),
        "tony.branch": _git("rev-parse", "--abbrev-ref", "HEAD"),
        "tony.user": getpass.getuser(),
        "tony.date": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
    }
