"""Chunked-over-vocab softmax cross-entropy.

No reference analog (TonY has no numerics). Motivation: with logits
[B, L, V] in fp32, a 256k-vocab model at L=8k burns gigabytes of HBM on a
tensor that exists only to be reduced — on TPU the loss becomes the memory
peak of the whole step. This op never materializes more than one
[T, chunk] tile: it streams vocab chunks of the embedding through an
online logsumexp (the flash-attention trick applied to the classifier),
with the scan body rematerialized (jax.checkpoint) so the backward pass
recomputes tiles instead of storing them.

The matmuls are [T, D] x [D, chunk] — large, static-shaped, MXU-friendly;
chunk defaults to a multiple of 128 lanes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def chunked_cross_entropy(hidden, embedding, labels, *,
                          chunk_size: int = 8192, z_loss: float = 0.0,
                          mask=None, bias=None, compute_dtype=None):
    """Mean token cross-entropy of ``logits = hidden @ embedding.T`` without
    materializing the logits.

    Args:
      hidden: [B, L, D] (or [T, D]) final-layer activations.
      embedding: [V, D] tied output embedding.
      labels: [B, L] (or [T]) int targets.
      chunk_size: vocab tile width (rounded use: keep a multiple of 128).
      z_loss: optional logsumexp^2 regularizer weight (PaLM-style), keeps
        logits from drifting — free here since lse is already computed.
      mask: optional per-position 0/1 (or bool) weights shaped like
        labels — e.g. packed-document training dropping the
        cross-boundary target after each EOS.
      bias: optional [V] output bias (Phi-family ``lm_head_bias``),
        added per vocab tile — the chunked twin of
        ``logits = h @ W.T + b``.
      compute_dtype: dtype for the logit MATMUL inputs (accumulation is
        always fp32 via preferred_element_type, and all softmax math
        stays fp32). Default None keeps the historical fp32 dot; pass
        ``jnp.bfloat16`` on TPU — fp32 matmuls run several times below
        the bf16 MXU rate, and the head is ~9 percent of a small
        model's FLOPs, so an fp32 head dominates the step.

    Returns mean loss (fp32 scalar) over the unmasked positions.
    """
    if hidden.ndim == 3:
        t = hidden.shape[0] * hidden.shape[1]
        hidden = hidden.reshape(t, hidden.shape[2])
        labels = labels.reshape(t)
        if mask is not None:
            mask = mask.reshape(t)
    v, d = embedding.shape
    chunk = min(chunk_size, v)
    n_chunks = (v + chunk - 1) // chunk
    pad = n_chunks * chunk - v
    emb = jnp.pad(embedding, ((0, pad), (0, 0))) if pad else embedding
    if bias is not None:
        bias = jnp.pad(bias, (0, pad)) if pad else bias
        bias = bias.astype(jnp.float32)
    h_mm = hidden.astype(compute_dtype or jnp.float32)
    labels = labels.astype(jnp.int32)

    def body(carry, i):
        m, s, lab = carry
        e_chunk = lax.dynamic_slice(emb, (i * chunk, 0), (chunk, d))
        # [T, chunk]; fp32 accumulation regardless of input dtype
        logits = jnp.matmul(h_mm, e_chunk.astype(h_mm.dtype).T,
                            preferred_element_type=jnp.float32)
        if bias is not None:
            logits = logits + lax.dynamic_slice(bias, (i * chunk,),
                                                (chunk,))[None, :]
        pos = i * chunk + jnp.arange(chunk)
        logits = jnp.where(pos[None, :] < v, logits, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1)
        idx = labels - i * chunk
        in_chunk = (idx >= 0) & (idx < chunk)
        picked = jnp.take_along_axis(
            logits, jnp.clip(idx, 0, chunk - 1)[:, None], axis=-1)[:, 0]
        lab = jnp.where(in_chunk, picked, lab)
        return (m_new, s, lab), None

    t = h_mm.shape[0]
    init = (jnp.full((t,), NEG_INF, jnp.float32),
            jnp.zeros((t,), jnp.float32),
            jnp.full((t,), NEG_INF, jnp.float32))
    # remat: the backward pass recomputes each [T, chunk] tile instead of
    # keeping n_chunks of them alive — peak memory stays O(T * chunk)
    (m, s, lab), _ = lax.scan(jax.checkpoint(body), init,
                              jnp.arange(n_chunks))
    lse = m + jnp.log(s)
    per_tok = lse - lab
    if mask is None:
        loss = jnp.mean(per_tok)
        if z_loss:
            loss = loss + z_loss * jnp.mean(lse * lse)
        return loss
    w = mask.astype(jnp.float32)
    denom = jnp.maximum(jnp.sum(w), 1.0)
    loss = jnp.sum(per_tok * w) / denom
    if z_loss:
        loss = loss + z_loss * jnp.sum(lse * lse * w) / denom
    return loss


def full_cross_entropy(hidden, embedding, labels):
    """Reference O(T*V)-memory computation (tests / small vocab)."""
    if hidden.ndim == 3:
        t = hidden.shape[0] * hidden.shape[1]
        hidden = hidden.reshape(t, hidden.shape[2])
        labels = labels.reshape(t)
    logits = hidden.astype(jnp.float32) @ embedding.astype(jnp.float32).T
    lse = jax.nn.logsumexp(logits, axis=-1)
    lab = jnp.take_along_axis(logits, labels[:, None].astype(jnp.int32),
                              axis=-1)[:, 0]
    return jnp.mean(lse - lab)
