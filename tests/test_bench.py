"""bench.py resilience machinery (VERDICT r2 #1a): probe retry/backoff,
last-known-good persistence, and the TPU re-exec guards. The driver's
end-of-round artifact depends on these paths running unattended."""

import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_platform_explicit_cpu_request_skips_probe(bench, monkeypatch):
    # _platform() reads the env var directly (module-global
    # _env_platforms only gates import-time config + the reexec path)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    calls = []
    monkeypatch.setattr(bench, "_probe_platform",
                        lambda t: calls.append(t) or "tpu")
    assert bench._platform() == "cpu"
    assert calls == []  # no probe, no tunnel dial


def test_platform_retries_with_backoff_then_pins_cpu(bench, monkeypatch):
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setenv("TONY_BENCH_PROBE_RETRIES", "3")
    sleeps, probes = [], []
    monkeypatch.setattr(bench.time, "sleep", lambda s: sleeps.append(s))
    monkeypatch.setattr(bench, "_probe_platform",
                        lambda t: probes.append(t) or "")
    assert bench._platform() == "cpu"
    assert len(probes) == 3
    assert sleeps == [20.0, 60.0]  # backoff BETWEEN attempts
    assert os.environ["JAX_PLATFORMS"] == "cpu"  # pinned for the run


def test_platform_recovers_on_second_probe(bench, monkeypatch):
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    results = iter(["", "axon"])
    monkeypatch.setattr(bench, "_probe_platform",
                        lambda t: next(results))
    assert bench._platform() == "axon"


def test_lkg_roundtrip(bench, monkeypatch, tmp_path):
    monkeypatch.setattr(bench, "LKG_PATH", str(tmp_path / "lkg.json"))
    line = {"metric": "m", "value": 1.0, "extras": {"platform": "axon"}}
    bench.save_lkg(line)
    doc = bench.load_lkg()
    assert doc["line"] == line
    assert doc["source"] == "bench.py on-chip run"
    assert doc["timestamp"] and "commit" in doc
    # corrupt file -> None, never an exception into the bench
    (tmp_path / "lkg.json").write_text("{broken")
    assert bench.load_lkg() is None


def test_reexec_skips_when_probe_says_cpu(bench, monkeypatch):
    """A 'cpu' probe result is NOT a tunnel recovery: no child re-run."""
    monkeypatch.setattr(bench, "_env_platforms", "")
    monkeypatch.delenv("TONY_BENCH_NO_REEXEC", raising=False)
    monkeypatch.setattr(bench, "_probe_platform", lambda t: "cpu")
    ran = []
    monkeypatch.setattr(bench.subprocess, "run",
                        lambda *a, **k: ran.append(a))
    line = {"metric": "x"}
    assert bench._maybe_reexec_on_tpu(line) is line
    assert ran == []


def test_reexec_rejects_child_that_fell_back_to_cpu(bench, monkeypatch):
    """Tunnel flaps mid-child: a cpu-platform child line must not ship
    with TPU provenance — the parent keeps its own line."""
    monkeypatch.setattr(bench, "_env_platforms", "")
    monkeypatch.delenv("TONY_BENCH_NO_REEXEC", raising=False)
    monkeypatch.setattr(bench, "_probe_platform", lambda t: "axon")

    class Child:
        returncode = 0
        stdout = json.dumps({"metric": "resnet_cpu_proxy",
                             "extras": {"platform": "cpu"}})

    monkeypatch.setattr(bench.subprocess, "run", lambda *a, **k: Child())
    line = {"metric": "x"}
    assert bench._maybe_reexec_on_tpu(line) is line


def test_reexec_adopts_tpu_child_line(bench, monkeypatch):
    monkeypatch.setattr(bench, "_env_platforms", "")
    monkeypatch.delenv("TONY_BENCH_NO_REEXEC", raising=False)
    monkeypatch.setattr(bench, "_probe_platform", lambda t: "axon")
    child_line = {"metric": "resnet", "extras": {"platform": "axon"}}

    class Child:
        returncode = 0
        stdout = "noise\n" + json.dumps(child_line)

    captured = {}

    def fake_run(argv, **kw):
        captured["env"] = kw["env"]
        return Child()

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    got = bench._maybe_reexec_on_tpu({"metric": "cpu_line"})
    assert got["metric"] == "resnet"
    assert "reexec" in got["extras"]
    # the child must not inherit the parent's CPU pin, and must not
    # recurse into a third process
    assert "JAX_PLATFORMS" not in captured["env"]
    assert captured["env"]["TONY_BENCH_NO_REEXEC"] == "1"


def test_reexec_guard_blocks_recursion(bench, monkeypatch):
    monkeypatch.setattr(bench, "_env_platforms", "")
    monkeypatch.setenv("TONY_BENCH_NO_REEXEC", "1")
    probes = []
    monkeypatch.setattr(bench, "_probe_platform",
                        lambda t: probes.append(t) or "axon")
    line = {"metric": "x"}
    assert bench._maybe_reexec_on_tpu(line) is line
    assert probes == []


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_bench_gateway_concurrent_beats_serial(bench):
    """The extras.gateway acceptance bound: concurrent clients through
    the front door must reach at least the single-client serial
    throughput (continuous batching fills the slots serial leaves
    idle; measured ~2.8x on the CI box)."""
    out = bench.bench_gateway(False)
    assert out["concurrent_beats_serial"], out
    assert out["concurrent_tok_s_1r"] >= out["serial_tok_s"], out
    assert out["ttft_ms_1r"]["p99"] >= out["ttft_ms_1r"]["p50"] >= 0


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_bench_prefix_store_saves_prefill(bench):
    """The extras.prefix acceptance bound: on the shared-system-prompt
    workload the prefix store must run strictly fewer prefill
    dispatches than store-off serving, save prefill tokens, and not
    regress TTFT (measured ~1.9x p50 on the CI box; outputs are
    asserted identical inside the bench itself)."""
    out = bench.bench_prefix(False)
    assert out["prefill_dispatches_on"] < out["prefill_dispatches_off"], out
    assert out["prefill_tokens_saved"] > 0, out
    assert 0 < out["prefix_hit_rate"] <= 1, out
    assert out["ttft_p50_speedup"] >= 1.0, out


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_bench_paged_bounds(bench):
    """The extras.paged acceptance bounds: (a) equal-batch decode
    holds >= 0.95x unpaged tok/s (gather overhead bounded), (b) at an
    equal KV byte budget the paged side fits a strictly larger batch
    and clears >= 1.3x aggregate tok/s on the mixed-length workload,
    (c) a prefix-hit admission moves >= 10x fewer bytes than the
    row-copy path, with the aliasing admits visible as cow_admit
    dispatches (outputs are asserted identical inside the bench)."""
    out = bench.bench_paged(False)
    assert out["equal_batch_ratio"] >= 0.95, out
    assert out["paged_batch"] > out["unpaged_batch"]
    assert out["equal_hbm_speedup"] >= 1.3, out
    assert out["cow_admit_dispatches_paged"] == \
        out["hit_admit_dispatches_unpaged"], out
    assert out["hit_bytes_ratio"] >= 10, out
    assert out["outputs_identical"]


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_bench_disagg_ttft_and_affinity_bounds(bench):
    """The extras.disagg acceptance bounds (ISSUE-12): (a) short-chat
    TTFT p50/p99 with chunked+role-split serving at least matches the
    interleaved single-pool control under long-prompt co-traffic
    (measured ~2-4x p99 on the CI box; outputs asserted identical and
    zero shed inside the bench); (b) prefix-affinity routing runs
    strictly fewer fleet prefill dispatches than least-outstanding
    spreading on the shared-system-prompt workload (deterministic
    counter)."""
    out = bench.bench_disagg(False)
    assert out["short_ttft_p50_improvement"] >= 1.0, out
    assert out["short_ttft_p99_improvement"] >= 1.0, out
    assert out["handoffs"] == out["n_long"] + out["n_short"], out
    assert out["chunk_dispatches"] > 0, out
    assert out["fleet_prefills_affinity_on"] \
        < out["fleet_prefills_affinity_off"], out
    assert out["prefix_routed"] > 0, out
    assert out["outputs_identical"]


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_bench_eos_refill_closes_the_overshoot_bucket(bench):
    """The extras.decode ISSUE-13 acceptance bounds: in-dispatch
    EOS/refill at chunk 16 vs the pre-freeze engine at chunk 4 on the
    mixed-budget workload must (a) leave outputs token-identical,
    (b) run >= 1.3x fewer decode dispatches per 1k tokens (the
    CPU-box criterion — host dispatch overhead is the binding cost
    where no HBM roofline exists; the TPU artifact additionally
    carries the >= 1.15x tok/s gate), (c) land the treatment's
    overshoot fraction < 1% with zero wasted_steps (the frozen tail
    is padding, priced honestly in the ledger block), and (d) report
    the int8-KV-flash analytic bytes ratio < 1 (the 0.54x regression
    cannot be a bytes problem — docs/PERF.md carries the verdict)."""
    out = bench.bench_decode(False)
    ab = out["eos_refill"]
    assert ab["outputs_identical"], ab
    assert ab["dispatch_ratio"] >= 1.3, ab
    assert ab["treatment"]["ledger"]["overshoot"] < 0.01, ab
    assert ab["treatment"]["wasted_steps"] == 0, ab
    assert ab["control"]["wasted_steps"] > 0, ab
    assert ab["treatment"]["frozen_steps"] > 0, ab
    assert out["int8_kv_flash_bytes_ratio"] < 1.0, out
    assert out["int8_kv_flash_verdict"] == "dispatch", out


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_bench_migrate_drain_and_bytes_bounds(bench):
    """The extras.migrate acceptance bounds (ISSUE-18): (a) both arms
    of the drain A/B stay token-identical to the no-migration control
    with zero shed; (b) the migrating drain beats decode-to-completion
    by >= 3x (measured ~40x: freeze cost vs ~45 wedged dispatches);
    (c) the owner swap moved ZERO pages while the bytes a gather copy
    would have shipped registered in bytes_avoided. The ISSUE-19
    arms: (d) a migration into a warm target ships >= 5x fewer wire
    bytes via the prefix-delta trim, token-exact; (e) two co-located
    engines on one shared pool decode >= 1.2x faster with overlapping
    dispatch windows than under the serialize_dispatch control,
    token-exact."""
    out = bench.bench_migrate(False)
    assert out["outputs_identical"], out
    assert out["shed_migrate"] == {} and out["shed_decode"] == {}, out
    assert out["drain_speedup"] >= 3.0, out
    assert out["migrations_out"] >= 1 and out["migrations_in"] >= 1, out
    assert out["owner_swap_pages_moved"] == 0, out
    assert out["owner_swap_bytes_avoided"] > 0, out
    assert out["gather_copy_pages"] > 0, out
    assert out["delta_outputs_identical"], out
    assert out["wire_bytes_ratio"] >= 5.0, out
    assert out["wire_bytes_delta"] < out["wire_bytes_full"], out
    assert out["delta_in"] == 1, out
    assert out["concurrent_outputs_identical"], out
    assert out["pool_concurrency_speedup"] >= 1.2, out


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_bench_goodput_ledger_and_overhead_gate(bench):
    """The extras.goodput acceptance bounds (ISSUE-10): (a) the ledger
    produced by the product sensor is well-formed — bucket fractions
    sum to <= 1.0, a largest waste bucket is named, useful work is
    nonzero, and the CPU arm reports bytes with utilization null (no
    roofline reference, no made-up percentage); (b) the PR-6 overhead
    discipline re-run with goodput+alerts armed: TPOT with the whole
    observability stack (timeline + tracing + cost model + alert bus)
    enabled within 1.1x of fully disabled, min-over-adjacent-pairs
    statistic."""
    out = bench.bench_goodput(False)
    assert out["ledger_sum"] <= 1.0 + 1e-6, out
    assert out["largest_waste"] in (
        "compile", "padding", "overshoot", "spec_rejected", "idle"), out
    assert out["useful_fraction"] > 0, out
    assert out["decode_est_bytes"] > 0, out
    assert out["decode_hbm_bw_pct"] is None, out  # CPU: null, honest
    assert out["tpot_ratio_armed_off"] <= 1.1, out


def test_stdout_guard_artifact_is_final_line():
    """VERDICT item 7: everything printed inside the guard (python- or
    fd-level, as sub-benches and their children do) lands on stderr;
    the artifact JSON printed after it is the one and only stdout
    line, so the round driver's `parsed` field is non-null."""
    import subprocess
    import sys

    code = (
        "import importlib.util, json, os, sys\n"
        f"spec = importlib.util.spec_from_file_location('b', {os.path.join(REPO, 'bench.py')!r})\n"
        "m = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(m)\n"
        "with m._StdoutToStderr():\n"
        "    print('python-level noise')\n"
        "    os.write(1, b'fd-level noise\\n')\n"
        "print(json.dumps({'metric': 'x', 'value': 1}))\n")
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO})
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = proc.stdout.strip().splitlines()
    assert lines == ['{"metric": "x", "value": 1}']
    assert json.loads(lines[-1])["metric"] == "x"
    assert "python-level noise" in proc.stderr
    assert "fd-level noise" in proc.stderr
