"""Pipeline parallelism: GPipe-style microbatch schedule over the ``pipe``
mesh axis.

Absent from the reference (its TaskScheduler DAG sequences *jobs*, not
micro-batches — SURVEY.md section 2.4). Here each pipe-axis device holds
one stage's parameters (stacked along a leading "layers" dim sharded on
``pipe``); activations flow stage-to-stage via ``lax.ppermute`` inside a
``lax.scan`` bubble schedule. Differentiable; jit-compatible (static
schedule length n_micro + n_stages - 1).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from tony_tpu.parallel.mesh import PIPE


def _pipeline_local(stage_params, x_micro, *, stage_fn, axis_name):
    """Body under shard_map.

    stage_params: this stage's param tree (leading stacked dim stripped
      to size 1 by sharding; squeezed before use).
    x_micro: [n_micro, mb, ...] full microbatched input (replicated).
    Returns [n_micro, mb, ...] outputs (valid on every device after psum).
    """
    n_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    params = jax.tree.map(lambda p: p[0], stage_params)  # strip stacked dim
    n_micro = x_micro.shape[0]
    total = n_micro + n_stages - 1
    out_buf = jnp.zeros_like(x_micro)
    carry_act = jnp.zeros_like(x_micro[0])

    def step(state, t):
        carry_act, out_buf = state
        # stage 0 ingests microbatch t (clamped; masked later)
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        inp = jnp.where(stage == 0, x_micro[mb_idx], carry_act)
        y = stage_fn(params, inp)
        # last stage writes finished microbatch t-(n_stages-1)
        out_idx = t - (n_stages - 1)
        valid_out = (stage == n_stages - 1) & (out_idx >= 0) & (out_idx < n_micro)
        out_buf = lax.cond(
            valid_out,
            lambda b: lax.dynamic_update_index_in_dim(b, y, jnp.maximum(out_idx, 0), 0),
            lambda b: b,
            out_buf,
        )
        # shift activations to the next stage
        perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]
        carry_act = lax.ppermute(y, axis_name, perm)
        return (carry_act, out_buf), None

    (carry_act, out_buf), _ = lax.scan(step, (carry_act, out_buf),
                                       jnp.arange(total))
    # outputs only live on the last stage; broadcast over the ring
    mask = (stage == n_stages - 1).astype(out_buf.dtype)
    return lax.psum(out_buf * mask, axis_name)


def pipeline_apply(stage_fn: Callable, stacked_params, x, *, mesh: Mesh,
                   n_microbatches: int, axis_name: str = PIPE,
                   remat: bool = False):
    """Run ``x`` through ``n_stages`` pipeline stages.

    stage_fn(params, x_mb) -> y_mb with y_mb.shape == x_mb.shape (uniform
      inter-stage activation shape, standard for decoder stacks).
    stacked_params: pytree whose leaves have leading dim n_stages (sharded
      along ``axis_name``).
    x: [batch, ...]; batch must divide by n_microbatches.
    remat: rematerialize each stage call in the backward pass — activation
      memory per device drops from O(schedule_len x stage_activations) to
      O(schedule_len x microbatch) at the cost of one extra forward, the
      standard trade for deep pipelines on HBM-bound TPUs.
    """
    n_stages = mesh.shape[axis_name]
    batch = x.shape[0]
    if batch % n_microbatches:
        raise ValueError(f"batch {batch} % n_microbatches {n_microbatches} != 0")
    x_micro = x.reshape(n_microbatches, batch // n_microbatches, *x.shape[1:])

    params_specs = jax.tree.map(lambda _: P(axis_name), stacked_params)
    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    fn = shard_map(
        functools.partial(_pipeline_local, stage_fn=stage_fn,
                          axis_name=axis_name),
        mesh=mesh,
        in_specs=(params_specs, P()),
        out_specs=P(),
        check_vma=False,
    )
    out = fn(stacked_params, x_micro)
    return out.reshape(batch, *x.shape[1:])


def stack_stage_params(per_stage_params: list) -> dict:
    """Stack per-stage param trees along a new leading dim for pipe sharding."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)
