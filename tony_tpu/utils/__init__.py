from tony_tpu.utils.fs import (
    LocalizableResource,
    app_staging_dir,
    new_app_id,
    parse_resources,
    staging_root,
    unzip,
    zip_dir,
)
from tony_tpu.utils.net import ServerPort, local_host_name, reserve_port
from tony_tpu.utils.shell import execute_shell, python_interpreter

__all__ = [
    "LocalizableResource",
    "ServerPort",
    "app_staging_dir",
    "execute_shell",
    "local_host_name",
    "new_app_id",
    "parse_resources",
    "python_interpreter",
    "reserve_port",
    "staging_root",
    "unzip",
    "zip_dir",
]
