"""User-facing distributed init: the TPU-native replacement for reading
TF_CONFIG / RANK / DMLC_* by hand.

A training script launched by tony-tpu calls::

    import tony_tpu.distributed as dist
    dist.initialize()          # jax.distributed from injected env
    mesh = dist.default_mesh() # all devices, named ("data",)

which wires jax.distributed.initialize(coordinator_address, num_processes,
process_id) from the env the JaxRuntime injected (SURVEY.md section 2.5:
the launcher's whole job is computing this spec and exporting the env).
Safe on a single process with no env: becomes a no-op.
"""

from __future__ import annotations

import json
import logging
import os

from tony_tpu import constants as C

log = logging.getLogger(__name__)


def env_spec() -> dict | None:
    """The injected rendezvous env, or None outside a tony-tpu task."""
    addr = os.environ.get(C.COORDINATOR_ADDRESS)
    if not addr:
        return None
    return {
        "coordinator_address": addr,
        "process_id": int(os.environ.get(C.PROCESS_ID, "0")),
        "num_processes": int(os.environ.get(C.NUM_PROCESSES, "1")),
        "cluster_spec": json.loads(os.environ.get(C.CLUSTER_SPEC, "{}")),
    }


def initialize(timeout_s: int | None = None) -> dict | None:
    """Call jax.distributed.initialize from injected env. No-op (returns
    None) when running outside a gang or with a single process."""
    from tony_tpu.profiler import maybe_start_server
    from tony_tpu.utils import compilecache

    # before any compile: point XLA's persistent cache at the job-scoped
    # dir so retries/resumes (and other gang members on this host) reuse
    # compiled executables. No-op outside a job.
    compilecache.enable()

    spec = env_spec()
    if spec is None or spec["num_processes"] <= 1:
        log.info("single-process run; skipping jax.distributed.initialize")
        maybe_start_server()  # the profiler port applies at any gang size
        return spec
    import jax

    # CPU gangs (CI, the mini cluster, local smoke runs): the CPU
    # backend's cross-process collectives need the gloo implementation
    # selected BEFORE backend init, or every psum/allgather dies with
    # "Multiprocess computations aren't implemented on the CPU
    # backend". Newer jax defaults to gloo and may drop the knob — the
    # update is best-effort. Read the platform from config/env, not
    # jax.default_backend(), which would initialize the backend early.
    platforms = str(jax.config.jax_platforms
                    or os.environ.get("JAX_PLATFORMS", ""))
    if platforms.split(",")[0] == "cpu":
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except Exception:
            pass
    kwargs = {}
    if timeout_s is not None:
        kwargs["initialization_timeout"] = timeout_s
    jax.distributed.initialize(
        coordinator_address=spec["coordinator_address"],
        num_processes=spec["num_processes"],
        process_id=spec["process_id"],
        **kwargs,
    )
    log.info(
        "jax.distributed initialized: process %d/%d via %s",
        spec["process_id"], spec["num_processes"], spec["coordinator_address"],
    )
    maybe_start_server()  # TONY_PROFILER_PORT-gated; no-op otherwise
    return spec


def default_mesh(axis_name: str = "data"):
    """All addressable devices as a 1-D data-parallel mesh."""
    import jax
    from jax.sharding import Mesh

    return Mesh(jax.devices(), (axis_name,))


def task_identity() -> tuple[str, int]:
    """(role, index) of this task, or ("", 0) outside a job."""
    return os.environ.get(C.JOB_NAME, ""), int(os.environ.get(C.TASK_INDEX, "0"))


def is_chief() -> bool:
    return os.environ.get(C.IS_CHIEF, "false") == "true"
