"""Hugging Face checkpoint import for the flagship transformer.

No reference analog (TonY has no models). GPT-2-family weights map onto
``TransformerConfig(norm="layer", positional="learned", use_bias=True,
activation="gelu_tanh")``; the converter is pure tensor reshuffling
(torch state_dict -> jax pytree), so it works on any GPT-2-sized
checkpoint already on disk — no network needed.

HF GPT-2 layout notes: ``Conv1D`` stores weights as [in, out] (already
the jax kernel orientation); ``c_attn`` packs Q,K,V as one [d, 3d]
matrix split here into per-head kernels; ``wte`` is tied to the LM head
(our model ties through the same ``embedding`` param).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from tony_tpu.models.transformer import Transformer, TransformerConfig


_HF_ACTIVATIONS = {"gelu_new": "gelu_tanh", "gelu_pytorch_tanh": "gelu_tanh",
                   "gelu": "gelu", "silu": "silu", "swish": "silu"}


def gpt2_config(hf_config, **overrides) -> TransformerConfig:
    """TransformerConfig matching a transformers GPT2Config."""
    act = getattr(hf_config, "activation_function", "gelu_new")
    if act not in _HF_ACTIVATIONS:
        raise ValueError(f"unsupported GPT-2 activation_function {act!r}; "
                         f"supported: {sorted(_HF_ACTIVATIONS)}")
    n_inner = getattr(hf_config, "n_inner", None)
    kw = dict(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.n_embd,
        n_heads=hf_config.n_head,
        n_layers=hf_config.n_layer,
        d_ff=n_inner if n_inner else 4 * hf_config.n_embd,
        max_seq_len=hf_config.n_positions,
        dtype=jnp.float32,
        attention_backend="reference",
        norm="layer",
        positional="learned",
        use_bias=True,
        activation=_HF_ACTIVATIONS[act],
        norm_eps=hf_config.layer_norm_epsilon,
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def _np(t) -> np.ndarray:
    return t.detach().cpu().numpy()


def convert_gpt2_state_dict(state_dict: dict, cfg: TransformerConfig) -> Any:
    """torch GPT-2 state_dict -> tony-tpu Transformer params pytree."""
    d, h, dh = cfg.d_model, cfg.n_heads, cfg.head_dim
    sd = {k.removeprefix("transformer."): v for k, v in state_dict.items()}
    params: dict[str, Any] = {
        "embedding": _np(sd["wte.weight"]),
        "pos_embedding": _np(sd["wpe.weight"]),
        "ln_f": {"scale": _np(sd["ln_f.weight"]),
                 "bias": _np(sd["ln_f.bias"])},
    }
    for i in range(cfg.n_layers):
        pre = f"h.{i}."
        qkv_w = _np(sd[pre + "attn.c_attn.weight"])  # [d, 3d] (Conv1D)
        qkv_b = _np(sd[pre + "attn.c_attn.bias"])  # [3d]
        qw, kw, vw = np.split(qkv_w, 3, axis=1)
        qb, kb, vb = np.split(qkv_b, 3, axis=0)
        block = {
            "ln1": {"scale": _np(sd[pre + "ln_1.weight"]),
                    "bias": _np(sd[pre + "ln_1.bias"])},
            "ln2": {"scale": _np(sd[pre + "ln_2.weight"]),
                    "bias": _np(sd[pre + "ln_2.bias"])},
            "attn": {
                "q": {"kernel": qw.reshape(d, h, dh),
                      "bias": qb.reshape(h, dh)},
                "k": {"kernel": kw.reshape(d, h, dh),
                      "bias": kb.reshape(h, dh)},
                "v": {"kernel": vw.reshape(d, h, dh),
                      "bias": vb.reshape(h, dh)},
                "o": {"kernel": _np(
                          sd[pre + "attn.c_proj.weight"]).reshape(h, dh, d),
                      "bias": _np(sd[pre + "attn.c_proj.bias"])},
            },
            "mlp": {
                "wi": {"kernel": _np(sd[pre + "mlp.c_fc.weight"]),
                       "bias": _np(sd[pre + "mlp.c_fc.bias"])},
                "wo": {"kernel": _np(sd[pre + "mlp.c_proj.weight"]),
                       "bias": _np(sd[pre + "mlp.c_proj.bias"])},
            },
        }
        params[f"block_{i}"] = block
    return {"params": jax.tree.map(jnp.asarray, params)}


def from_hf_gpt2(model) -> tuple[Transformer, Any]:
    """(Transformer, params) from a transformers GPT2LMHeadModel (or its
    GPT2Model trunk) instance — local weights, no network."""
    cfg = gpt2_config(model.config)
    params = convert_gpt2_state_dict(model.state_dict(), cfg)
    return Transformer(cfg), params
