from tony_tpu.train.checkpoint import CheckpointManager, restore_or_init
from tony_tpu.train.trainer import (
    Trainer,
    TrainState,
    build_train_step,
    cross_entropy_loss,
)

__all__ = [
    "CheckpointManager",
    "Trainer",
    "TrainState",
    "build_train_step",
    "cross_entropy_loss",
    "restore_or_init",
]
