"""History mover + purger.

Reference: tony-portal/app/history/HistoryFileMover.java:35-120 (moves
completed jobs intermediate/ -> finished/yyyy/mm/dd/<app>/, finalizes
killed apps' inprogress files) and HistoryFilePurger.java:26-101 (deletes
finished history older than tony.history.retention-sec).
"""

from __future__ import annotations

import logging
import os
import shutil
import time

from tony_tpu.events import history

log = logging.getLogger(__name__)


def move_finished_jobs(history_root: str, stale_after_s: float = 3600) -> list[str]:
    """Move every intermediate job with a finalized jhist into finished/.
    Inprogress jobs whose files have not been touched for ``stale_after_s``
    are treated as killed and finalized as KILLED first (ref: mover's
    YARN-state poll for killed apps — no RM here, so staleness stands in)."""
    moved = []
    inter = os.path.join(history_root, "intermediate")
    if not os.path.isdir(inter):
        return moved
    for app_id in os.listdir(inter):
        job_dir = os.path.join(inter, app_id)
        entries = history._scan_job_dir(job_dir)
        if not entries:
            continue
        entry = entries[0]
        if entry["inprogress"]:
            age = time.time() - os.path.getmtime(entry["jhist"])
            if age < stale_after_s:
                continue
            completed_ms = int(os.path.getmtime(entry["jhist"]) * 1000)
            final = os.path.join(
                job_dir,
                history.finished_name(app_id, entry["started"], completed_ms,
                                      "unknown", "KILLED"),
            )
            os.rename(entry["jhist"], final)
            entry = {**entry, "completed": completed_ms, "jhist": final}
        dest = history.finished_dir(history_root, entry["completed"], app_id)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        shutil.move(job_dir, dest)
        moved.append(dest)
        log.info("moved history %s -> %s", app_id, dest)
    return moved


def purge_old_history(history_root: str, retention_sec: int) -> list[str]:
    """Delete finished job dirs older than retention (ref: HistoryFilePurger)."""
    purged = []
    cutoff_ms = (time.time() - retention_sec) * 1000
    for entry in history.list_jobs(history_root):
        if entry["inprogress"] or entry["completed"] < 0:
            continue
        if entry["completed"] < cutoff_ms:
            shutil.rmtree(entry["dir"], ignore_errors=True)
            purged.append(entry["dir"])
            log.info("purged history %s", entry["dir"])
    # clean now-empty yyyy/mm/dd parents
    fin = os.path.join(history_root, "finished")
    for root, dirs, files in os.walk(fin, topdown=False) if os.path.isdir(fin) else []:
        if not dirs and not files and root != fin:
            os.rmdir(root)
    return purged
