"""Continuous-batching serving over the slot-tolerant decode path.

The TPU-serving analog of TonY's job multiplexing (``TonySession`` /
``TaskScheduler`` packing many jobs onto one container pool): many
REQUESTS multiplex onto one resident KV cache. One jitted decode step
of fixed shape [batch_size, max_seq_len] runs forever; requests stream
through its slots — admitted into free slots at their own positions,
evicted the moment they hit EOS or their token budget, replaced the
same iteration (Orca/vLLM-style iteration-level scheduling). Static
shapes mean the step compiles ONCE; mixed-length traffic never waits
on the longest sequence in a batch. Shared-prefix traffic (system
prompts, few-shot preambles, multi-turn) additionally skips prefill
work through the radix ``PrefixStore`` (serve/prefix.py), and
predictable continuations (extractive/repetitive/templated output)
skip sequential decode steps through speculative decoding —
prompt-lookup drafting + one batched multi-token verify dispatch
(``Server(speculate_k=...)``), greedy outputs unchanged.
"""

from tony_tpu.serve.engine import (QueueFull, Request, Result, Server,
                                   bucket_len)
from tony_tpu.serve.faults import Fault, FaultPlan, InjectedFault
from tony_tpu.serve.prefix import PrefixStore, tree_nbytes
from tony_tpu.serve.slots import (SlotCache, cache_batch_axis,
                                  read_slot_row, write_slot_row)

__all__ = [
    "Fault",
    "FaultPlan",
    "InjectedFault",
    "PrefixStore",
    "QueueFull",
    "Request",
    "Result",
    "Server",
    "SlotCache",
    "bucket_len",
    "cache_batch_axis",
    "read_slot_row",
    "tree_nbytes",
    "write_slot_row",
]
