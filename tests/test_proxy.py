"""TCP proxy byte-pump: python fallback + native C++ binary.

Reference: tony-proxy ProxyServer.java:21-91 (threaded gateway->cluster
byte pump used by NotebookSubmitter). Both implementations must tunnel
bidirectional traffic transparently.
"""

from __future__ import annotations

import os
import socket
import socketserver
import subprocess
import threading

import pytest

from tony_tpu.proxy import ProxyServer
from tony_tpu.proxy.proxy import _NATIVE_BIN


class _Echo(socketserver.BaseRequestHandler):
    def handle(self):
        while True:
            data = self.request.recv(65536)
            if not data:
                return
            self.request.sendall(data.upper())


@pytest.fixture()
def echo_server():
    srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), _Echo)
    srv.daemon_threads = True
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv.server_address[1]
    srv.shutdown()
    srv.server_close()


def _roundtrip(port: int, payload: bytes) -> bytes:
    with socket.create_connection(("127.0.0.1", port), timeout=10) as s:
        s.sendall(payload)
        out = b""
        while len(out) < len(payload):
            chunk = s.recv(65536)
            if not chunk:
                break
            out += chunk
    return out


def test_python_proxy_roundtrip(echo_server):
    proxy = ProxyServer("127.0.0.1", echo_server, prefer_native=False).start()
    try:
        assert proxy.local_port > 0
        payload = b"hello through the tunnel " * 1000
        assert _roundtrip(proxy.local_port, payload) == payload.upper()
        # a second concurrent-ish connection must also be served
        assert _roundtrip(proxy.local_port, b"again") == b"AGAIN"
    finally:
        proxy.stop()


def _build_native() -> bool:
    if os.path.exists(_NATIVE_BIN):
        return True
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(["make", "-C", os.path.join(root, "native")],
                       capture_output=True, text=True)
    return r.returncode == 0 and os.path.exists(_NATIVE_BIN)


def _native_libc_error() -> str:
    """The dynamic-link error of the checked-in binary, or '' when it
    loads. The binary ships built against glibc 2.34; on older images
    (this container: 2.31) the loader rejects it before main, so probe
    the binary itself rather than doing version arithmetic."""
    try:
        probe = subprocess.run([_NATIVE_BIN], capture_output=True,
                               text=True, timeout=10)
    except OSError as e:
        return str(e)
    err = probe.stderr.strip()
    return err if "GLIBC" in err else ""


def test_native_proxy_roundtrip(echo_server):
    if not _build_native():
        pytest.skip("native proxy not built and no toolchain")
    libc_err = _native_libc_error()
    if libc_err:
        pytest.skip("prebuilt native proxy needs a newer glibc than this "
                    f"image ships (typically GLIBC >= 2.34): {libc_err}")
    proxy = ProxyServer("127.0.0.1", echo_server, prefer_native=True).start()
    try:
        assert proxy.prefer_native, "native binary exists but was not chosen"
        assert proxy._native_proc is not None, "fell back to python"
        payload = b"native byte pump " * 4096
        assert _roundtrip(proxy.local_port, payload) == payload.upper()
    finally:
        proxy.stop()


def test_python_proxy_upstream_unreachable():
    """Client connects, upstream is dead: the connection is closed, the
    proxy survives for the next client."""
    proxy = ProxyServer("127.0.0.1", 1, prefer_native=False).start()  # port 1: nothing listens
    try:
        with socket.create_connection(("127.0.0.1", proxy.local_port),
                                      timeout=10) as s:
            assert s.recv(1) == b""  # closed without data
        # proxy still accepts after the failure
        with socket.create_connection(("127.0.0.1", proxy.local_port),
                                      timeout=10):
            pass
    finally:
        proxy.stop()
