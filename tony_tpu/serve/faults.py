"""Deterministic fault injection for the serving stack.

TonY's defining robustness story — heartbeat the workers, fail the
silent ones, retry their tasks elsewhere — is only real if the failure
paths actually run. This module is the switch that runs them: a
``FaultPlan`` is a list of pre-declared faults hooked into the two
places a replica does device work (``Server.step()`` and request
admission), so a test or a smoke script can say "the 3rd dispatch on
replica 0 dies" or "this request wedges for two seconds" and get the
SAME failure on every run — the gateway's supervision, failover, and
circuit-breaker paths are pinned by tests instead of being dead code
waiting for real hardware to misbehave.

Two delivery routes:

- **constructor**: ``Server(..., fault_plan=FaultPlan.fail_at(3))`` —
  what the unit tests use.
- **environment**: ``TONY_SERVE_FAULTS`` holds a JSON fault list; the
  gateway CLI arms each replica's engine with the faults addressed to
  it (``FaultPlan.from_env(replica=i)``), so a shell script can chaos-
  test a real subprocess gateway (``make chaos-smoke``) without any
  code hook.

Fault spec fields (JSON object or ``Fault`` kwargs):

  op        "fail" (raise ``InjectedFault``) or "wedge" (sleep —
            simulates a stalled, not crashed, dispatch; the watchdog's
            case)
  dispatch  fire on ``step()`` calls numbered >= this (1-based count
            per engine, probes included)
  request   fire when this ENGINE request id is admitted (through the
            gateway, engine ids are the replica's own deterministic
            0,1,2... sequence; the breaker probe admits id
            ``"__probe__"``, so a plan can keep probes failing)
  seconds   wedge duration
  times     firings before the fault is spent (default 1; -1 = every
            match — a permanently broken replica)
  replica   restrict an env fault to one replica index (None = all)

A fired fault is logged loudly; ``InjectedFault`` subclasses
``RuntimeError`` so nothing upstream special-cases it — it takes the
exact path a real dispatch failure would.
"""

from __future__ import annotations

import json
import logging
import os
import time
from dataclasses import dataclass
from typing import Any

log = logging.getLogger(__name__)

ENV_VAR = "TONY_SERVE_FAULTS"


class InjectedFault(RuntimeError):
    """The deterministic stand-in for a dead dispatch. Deliberately a
    plain ``RuntimeError`` subclass: supervision must treat it exactly
    like a real failure, or the tests prove nothing."""


@dataclass
class Fault:
    """One pre-declared failure. See the module docstring for field
    semantics; a fault needs at least one trigger (``dispatch`` or
    ``request``)."""

    op: str = "fail"
    dispatch: int | None = None
    request: Any = None
    seconds: float = 0.0
    times: int = 1
    replica: int | None = None

    def __post_init__(self):
        if self.op not in ("fail", "wedge"):
            raise ValueError(
                f"fault op must be 'fail' or 'wedge', got {self.op!r}")
        if self.dispatch is None and self.request is None:
            raise ValueError("fault needs a trigger: dispatch or request")
        if self.op == "wedge" and self.seconds <= 0:
            raise ValueError("wedge fault needs seconds > 0")


class FaultPlan:
    """The engine-side hook object: owns its faults plus a dispatch
    counter (one per engine — probes advance it too, so a spent fault
    lets the breaker probe succeed while ``times=-1`` keeps a replica
    down through every probe)."""

    def __init__(self, faults):
        self.faults = list(faults)
        self.n_dispatches = 0
        self.fired = 0

    # --------------------------------------------------- construction

    @classmethod
    def from_env(cls, replica: int | None = None,
                 env=None) -> "FaultPlan | None":
        """Parse ``TONY_SERVE_FAULTS`` (a JSON fault object or list)
        into the plan addressed to ``replica`` — None when the variable
        is unset/empty or no fault targets this replica. Invalid specs
        raise loudly: a chaos run with a silently ignored typo'd fault
        would assert against a fault-free gateway."""
        spec = (os.environ if env is None else env).get(ENV_VAR, "").strip()
        if not spec:
            return None
        try:
            docs = json.loads(spec)
        except json.JSONDecodeError as e:
            raise ValueError(f"{ENV_VAR} is not valid JSON: {e}") from None
        if isinstance(docs, dict):
            docs = [docs]
        faults = []
        for d in docs:
            if not isinstance(d, dict):
                raise ValueError(f"{ENV_VAR} entries must be objects: {d!r}")
            f = Fault(**d)
            if f.replica is None or replica is None or f.replica == replica:
                faults.append(f)
        return cls(faults) if faults else None

    @classmethod
    def fail_at(cls, dispatch: int, times: int = 1) -> "FaultPlan":
        return cls([Fault("fail", dispatch=dispatch, times=times)])

    @classmethod
    def wedge_at(cls, dispatch: int, seconds: float,
                 times: int = 1) -> "FaultPlan":
        return cls([Fault("wedge", dispatch=dispatch, seconds=seconds,
                          times=times)])

    @classmethod
    def fail_request(cls, request, times: int = 1) -> "FaultPlan":
        return cls([Fault("fail", request=request, times=times)])

    # --------------------------------------------------------- firing

    def _fire(self, fault: Fault, what: str) -> None:
        if fault.times > 0:
            fault.times -= 1
        self.fired += 1
        if fault.op == "wedge":
            log.warning("fault injection: wedging %.2fs at %s",
                        fault.seconds, what)
            time.sleep(fault.seconds)
            return
        log.warning("fault injection: failing %s", what)
        raise InjectedFault(f"injected failure at {what}")

    def on_dispatch(self) -> None:
        """Hook at the top of ``Server.step()``; counts scheduler
        dispatches and fires any armed dispatch-triggered fault."""
        self.n_dispatches += 1
        for f in self.faults:
            if f.times == 0 or f.dispatch is None:
                continue
            if self.n_dispatches >= f.dispatch:
                self._fire(f, f"dispatch {self.n_dispatches}")

    def on_admit(self, request_id) -> None:
        """Hook before a request's prefill admission dispatch."""
        for f in self.faults:
            if f.times == 0 or f.request is None:
                continue
            if f.request == request_id:
                self._fire(f, f"admit of request {request_id!r}")
