"""TPU device discovery tests — the TestGpuDiscoverer /
TestGpuDeviceInformationParser analogs (canned outputs, error cap)."""

import json
import os
import stat

import pytest

from tony_tpu.utils import tpu_info as T

CANNED = {
    "accelerator_type": "v5p-32",
    "chips": [
        {"device_id": 0, "hbm_used_bytes": 1024, "hbm_total_bytes": 95 * 2**30,
         "duty_cycle_pct": 93.5},
        {"device_id": 1, "hbm_used_bytes": 2048, "hbm_total_bytes": 95 * 2**30,
         "duty_cycle_pct": 86.5},
    ],
}


def fake_info_binary(tmp_path, payload: str, exit_code: int = 0) -> str:
    path = tmp_path / "tpu-info"
    path.write_text("#!/bin/sh\n"
                    f"cat <<'EOF'\n{payload}\nEOF\n"
                    f"exit {exit_code}\n")
    path.chmod(path.stat().st_mode | stat.S_IEXEC)
    return str(path)


def test_parse_canned_json():
    info = T.parse_tpu_info_json(json.dumps(CANNED))
    assert info.accelerator_type == "v5p-32"
    assert info.chip_count == 2
    assert info.chips[1].hbm_used_bytes == 2048
    assert info.source == "info-command"


@pytest.mark.parametrize("bad", ["not json", "[]", '{"chips": 3}',
                                 '{"chips": ["x"]}'])
def test_parse_rejects_malformed(bad):
    with pytest.raises(T.TpuInfoException):
        T.parse_tpu_info_json(bad)


def test_discoverer_runs_info_command(tmp_path):
    binary = fake_info_binary(tmp_path, json.dumps(CANNED))
    d = T.TpuDiscoverer(info_exec_path=binary)
    info = d.get_device_information()
    assert info.source == "info-command"
    assert info.chip_count == 2
    metrics = d.device_metrics()
    assert metrics["util"] == pytest.approx(90.0)
    assert metrics["hbm"] == 3072.0


def test_discoverer_error_cap(tmp_path, monkeypatch):
    """Ref: GpuDiscoverer gives up after 10 consecutive failures."""
    monkeypatch.setattr(T, "ACCEL_DEVICE_GLOBS", ())
    monkeypatch.delenv("TPU_CHIPS_PER_HOST_BOUNDS", raising=False)
    binary = fake_info_binary(tmp_path, "garbage", exit_code=0)
    d = T.TpuDiscoverer(info_exec_path=binary)
    for _ in range(T.MAX_REPEATED_ERRORS + 2):
        d.get_device_information()
    assert d.error_count == T.MAX_REPEATED_ERRORS
    # capped: no more subprocess attempts
    assert d._run_info_command() is None


def test_fallback_to_device_files(tmp_path, monkeypatch):
    for i in range(4):
        (tmp_path / f"accel{i}").touch()
    monkeypatch.setattr(T, "ACCEL_DEVICE_GLOBS",
                        (str(tmp_path / "accel*"),))
    d = T.TpuDiscoverer(info_exec_path=str(tmp_path / "missing"))
    info = d.get_device_information()
    assert info.source == "device-files"
    assert info.chip_count == 4
    assert d.device_metrics() == {}  # presence only, no counters


def test_fallback_to_env(monkeypatch):
    monkeypatch.setattr(T, "ACCEL_DEVICE_GLOBS", ())
    monkeypatch.setenv("TPU_CHIPS_PER_HOST_BOUNDS", "2,2,1")
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5p-8")
    d = T.TpuDiscoverer(info_exec_path="/nonexistent")
    info = d.get_device_information()
    assert info.source == "env"
    assert info.chip_count == 4
    assert info.accelerator_type == "v5p-8"


def test_sampler_folds_tpu_metrics(tmp_path):
    """TaskMetricsMonitor integrates discoverer output into max/avg."""
    from tony_tpu.metrics import sampler as S

    binary = fake_info_binary(tmp_path, json.dumps(CANNED))
    mon = S.TaskMetricsMonitor(lambda: os.getpid(), lambda m: None,
                               tpu_info_exec_path=binary)
    mon.sample_once()
    assert mon.metrics[S.MAX_TPU_UTIL] == pytest.approx(90.0)
    assert mon.metrics[S.AVG_TPU_HBM] == pytest.approx(3072.0)
    assert mon.metrics[S.MAX_MEMORY_RSS] > 0
