"""Config key schema: every global key, its default, type, and doc line.

Reference: TonyConfigurationKeys.java:13-337 + tony-default.xml (60 keys),
drift-locked by TestTonyConfigurationFields (SURVEY.md section 4.3). Here the
schema *is* the default source (no separate XML to drift), and
tests/test_config.py locks KEYS <-> DEFAULTS bijection plus doc coverage.

Per-role keys are regex-driven (reference: TonyConfigurationKeys.java:189-257):
``tony.<role>.instances|chips|memory|command|resources|depends-on|...`` —
see ROLE_KEY_RE / role_key() in config.py. Any role name is legal.
"""

from __future__ import annotations

from typing import Any, NamedTuple


class Key(NamedTuple):
    default: Any
    type: type
    doc: str


# ---------------------------------------------------------------------------
# Global (non-role) keys. Names keep the reference's tony.* namespace with
# TPU-flavored semantics (chips instead of gpus, coordinator instead of am).
# ---------------------------------------------------------------------------
KEYS: dict[str, Key] = {
    # application
    "tony.application.name": Key("tony-tpu", str, "Display name of the job"),
    "tony.application.framework": Key(
        "jax", str, "Runtime adapter: jax|tensorflow|pytorch|mxnet|horovod|standalone|ray"
    ),
    "tony.application.distributed-mode": Key(
        "GANG", str, "GANG (all tasks rendezvous before start) or FCFS"
    ),
    "tony.application.security.tls": Key(
        False, bool, "TLS on the control-plane RPC: the client mints a "
        "per-job self-signed cert into the job dir; agents/client pin its "
        "SHA-256 fingerprint (ref: ClientToAM SASL transport, "
        "ApplicationMaster.java:484-504)"
    ),
    "tony.application.security.enabled": Key(
        True, bool, "HMAC-authenticate control-plane RPC with a per-job token"
    ),
    "tony.application.timeout-ms": Key(
        0, int, "Whole-job timeout in ms; 0 = unlimited (ref: tony.application.timeout)"
    ),
    "tony.application.node-label": Key(
        "", str, "Placement label for all roles unless overridden per-role"
    ),
    "tony.application.prepare-stage": Key(
        "", str, "Comma list of roles scheduled in the prepare stage (ref: Utils.java:377-403)"
    ),
    "tony.application.training-stage": Key(
        "", str, "Comma list of roles gated on prepare-stage completion"
    ),
    "tony.application.untracked.jobtypes": Key(
        "ps", str, "Comma list of roles whose exit does not gate job completion"
    ),
    "tony.application.sidecar.jobtypes": Key(
        "tensorboard", str, "Untracked helper roles whose failure is tolerated"
    ),
    "tony.application.tensorboard-log-dir": Key(
        "", str,
        "Log dir served by the built-in sidecar TensorBoard launcher "
        "(ref: setSidecarTBResources TonyClient.java:571-600)"
    ),
    "tony.application.checkpoint-dir": Key(
        "", str,
        "Checkpoint directory for restart-with-resume (no reference analog: "
        "TonY has no in-tree checkpointing, SURVEY.md 5.4). When set, tasks "
        "get TONY_CHECKPOINT_DIR (relative paths resolve under the job dir) "
        "and, on coordinator retry, TONY_RESUME_STEP with the newest step "
        "found there so training resumes instead of restarting from scratch"
    ),
    "tony.application.stop-on-failure.jobtypes": Key(
        "", str, "Roles whose single-task failure fails the whole job immediately"
    ),
    "tony.application.fail-on-worker-failure-enabled": Key(
        False, bool, "If true any tracked task failure fails the job"
    ),
    "tony.application.enable-preprocess": Key(
        False, bool, "Run the chief command inside the coordinator first (ref: doPreprocessingJob)"
    ),
    "tony.application.single-node-mode": Key(
        False, bool, "0-instance mode: the coordinator itself hosts the user process"
    ),
    "tony.application.launch-mode": Key(
        "local", str, "Agent placement: local (subprocesses), ssh (remote "
        "TPU-VM hosts), or docker (containers on this host)"
    ),
    # docker containers (ref: tony.docker.enabled + DOCKER_* env,
    # HadoopCompatibleAdapter.getContainerEnvForDocker)
    "tony.docker.enabled": Key(
        False, bool, "Run each agent inside a docker container (ref: tony.docker.enabled)"
    ),
    "tony.docker.image": Key(
        "", str, "Container image for docker launch mode (ref: tony.docker.containers.image)"
    ),
    "tony.docker.mounts": Key(
        "", str, "Comma list of host:container[:ro] bind mounts for docker tasks"
    ),
    "tony.docker.run-args": Key(
        "", str, "Extra args spliced into docker run (e.g. --shm-size=4g)"
    ),
    "tony.docker.bin": Key(
        "docker", str, "Container CLI binary (docker/podman; test shims)"
    ),
    "tony.application.hosts": Key(
        "", str, "Comma list of TPU-VM hosts for launch-mode=ssh, round-robin per task"
    ),
    "tony.application.remote-pythonpath": Key(
        "", str, "PYTHONPATH exported on ssh-launched hosts (repo/install location)"
    ),
    "tony.application.ssh-bin": Key(
        "ssh", str, "ssh binary for launch-mode=ssh (tests point this at a "
        "local fake that runs the command in-place)"
    ),
    "tony.ssh.ship-job-dir": Key(
        True, bool, "launch-mode=ssh: tar-pipe the staged job dir (src, "
        "venv, conf, resources) to each host before its first task; hosts "
        "that already see the dir (shared mount) are probed and skipped "
        "(ref: HDFS upload + per-container extract, TonyClient.java:229-310)"
    ),
    "tony.ssh.remote-job-root": Key(
        "", str, "launch-mode=ssh: directory on the remote hosts to place "
        "the shipped job dir under (job-dir paths in the task env are "
        "rewritten); empty = mirror the coordinator's absolute job-dir path"
    ),
    # coordinator (reference: tony.am.*)
    "tony.coordinator.memory": Key("2g", str, "Coordinator process memory hint"),
    "tony.coordinator.command": Key(
        "", str, "Preprocess command run on the coordinator before training "
        "roles launch (with tony.application.enable-preprocess); its stdout "
        "'Model parameters: ...' line is exported to tasks as MODEL_PARAMS "
        "(ref: tony.am.command + doPreprocessingJob stdout scrape)"
    ),
    "tony.coordinator.retry-count": Key(
        0, int, "Times the coordinator rebuilds the session after "
                "failure (ref: tony.am.retry-count)"
    ),
    "tony.coordinator.monitor-interval-ms": Key(
        1000, int, "Coordinator monitor loop cadence (ref AM 5s; faster since no YARN)"
    ),
    "tony.coordinator.registration-timeout-ms": Key(
        900_000, int, "Task allocated but never registered => fail (ref: 15 min)"
    ),
    "tony.coordinator.host": Key("127.0.0.1", str, "Bind host for control-plane RPC"),
    # task / agent
    "tony.task.heartbeat-interval-ms": Key(
        1000, int, "Agent->coordinator heartbeat cadence (ref: same default)"
    ),
    "tony.task.max-missed-heartbeats": Key(
        25, int, "Liveness expiry = interval * max(3, this) (ref: same)"
    ),
    "tony.task.metrics-interval-ms": Key(
        5000, int, "Resource-metrics sampling cadence (ref: same)"
    ),
    "tony.task.executor.execution-timeout-ms": Key(
        0, int, "Per-task user-process timeout; 0 = unlimited (ref: same)"
    ),
    "tony.task.reuse-port": Key(
        False, bool, "Reserve rendezvous ports with SO_REUSEPORT "
                     "across exec (ref: TF_GRPC_REUSE_PORT)"
    ),
    "tony.elastic.grace-ms": Key(
        15_000, int, "Grace period for tasks to checkpoint-and-exit on an "
        "elastic resize before the gang restart proceeds"
    ),
    "tony.task.preemption-grace-ms": Key(
        15_000, int, "On SIGTERM (TPU spot preemption / maintenance event — "
        "the heartbeat-expiry analog, SURVEY.md 7.9b), the agent forwards "
        "SIGTERM to the user process and waits this long for a "
        "checkpoint-and-exit before SIGKILL; the coordinator records the "
        "task as preempted so a retry (with checkpoint-dir set) resumes"
    ),
    "tony.task.profiler-port": Key(
        0, int, "Base port for per-task jax profiler servers (0 = off); "
        "task flat-index is added so shared hosts don't collide"
    ),
    # task command construction (ref: TonyClient.buildTaskCommand :618-635)
    "tony.application.executes": Key(
        "", str, "User training entrypoint (script or shell command) run by every task"
    ),
    "tony.application.task-params": Key(
        "", str, "Extra CLI args appended to the task entrypoint"
    ),
    # python environment shipped with the job
    "tony.application.python-venv": Key("", str, "Path to a venv zip shipped to tasks"),
    "tony.application.shell-env": Key(
        "", str, "Comma list of K=V pairs exported into every task's env (ref: --shell_env)"
    ),
    "tony.application.tags": Key(
        "", str, "Workflow tags (exec id, flow, project) attached by scheduler integrations"
    ),
    "tony.application.python-command": Key(
        "", str, "Python interpreter override used to build task commands"
    ),
    "tony.application.src-dir": Key(
        "", str, "User source dir zipped + shipped to every task (ref: src_dir)"
    ),
    # staging / history
    "tony.staging-dir": Key(
        "", str, "Shared staging root; default ~/.tony (ref: HDFS ~/.tony/<uuid>)"
    ),
    "tony.history.location": Key(
        "", str, "History root holding intermediate/ and finished/ (ref: tony.history.location)"
    ),
    "tony.history.retention-sec": Key(
        2_592_000, int, "Purge finished history older than this (ref: 30 days)"
    ),
    "tony.history.mover-interval-ms": Key(
        300_000, int, "History mover/purger cadence (ref: portal 5 min)"
    ),
    "tony.keytab.user": Key("", str, "Principal for secure deployments (slot only)"),
    # portal
    "tony.portal.port": Key(19885, int, "History portal HTTP port"),
    # client
    "tony.client.poll-interval-ms": Key(
        1000, int, "Client job-status poll cadence (ref: TonyClient 1s)"
    ),
    "tony.client.coordinator-max-attempts": Key(
        1, int, "Times the client will (re)spawn the coordinator process; "
        ">1 restarts a crashed coordinator, the YARN AM-attempt analog "
        "(checkpoint-dir jobs resume from the last checkpoint)"
    ),
    # limits (reference: tony.application.max-total-instances etc.)
    "tony.application.max-total-instances": Key(
        -1, int, "Cap on total task instances; -1 = unlimited"
    ),
    "tony.application.max-total-chips": Key(
        -1, int, "Cap on total TPU chips requested; -1 = unlimited"
    ),
    # provisioner — the RM capacity-acquisition analog (ref:
    # TonyClient.submitApplication :314-349 + setupContainerRequestForRM,
    # util/Utils.java:420-430; allocation timeout TonyConfigurationKeys
    # .java:261-262)
    "tony.provisioner.mode": Key(
        "none", str, "none (hosts pre-exist / local devices), tpu-vm "
        "(gcloud compute tpus tpu-vm create), or queued (queued-resources "
        "capacity queue — the tony.yarn.queue analog)"
    ),
    "tony.provisioner.name": Key(
        "", str, "TPU resource name; default tony-<app_id>"
    ),
    "tony.provisioner.zone": Key("", str, "GCE zone for the slice"),
    "tony.provisioner.project": Key("", str, "GCP project (empty = gcloud default)"),
    "tony.provisioner.accelerator-type": Key(
        "", str, "Slice accelerator type (v5p-32, v6e-16, ...); falls back "
        "to tony.tpu.topology"
    ),
    "tony.provisioner.runtime-version": Key(
        "tpu-ubuntu2204-base", str, "TPU-VM runtime/software version"
    ),
    "tony.provisioner.gcloud-bin": Key(
        "gcloud", str, "gcloud binary path (tests point this at a fake)"
    ),
    "tony.provisioner.timeout-ms": Key(
        900_000, int, "Slice-allocation timeout (ref: 15-min container-"
        "allocation timeout, TonyConfigurationKeys.java:261-262)"
    ),
    "tony.provisioner.poll-interval-ms": Key(
        10_000, int, "Describe-poll cadence while waiting for READY"
    ),
    "tony.provisioner.keep": Key(
        False, bool, "Leave the slice up at job end (reuse across jobs)"
    ),
    "tony.provisioner.reuse": Key(
        True, bool, "Adopt an existing same-name slice instead of failing"
    ),
    "tony.provisioner.spot": Key(
        False, bool, "Request spot/preemptible capacity"
    ),
    "tony.provisioner.network": Key("", str, "VPC network for the slice"),
    "tony.provisioner.labels": Key(
        "", str, "Comma k=v labels attached to the slice"
    ),
    # TPU topology (new territory: replaces YARN gpus/vcores resource model)
    "tony.tpu.topology": Key(
        "", str, "Requested TPU slice topology, e.g. v5p-32; empty = local devices"
    ),
    "tony.tpu.chips-per-host": Key(
        0, int, "TPU chips per agent host; > 0 turns on capacity-aware "
        "packing + per-task TPU_VISIBLE_DEVICES subsets in the ssh "
        "launcher (0 = unknown: plain round-robin placement)"
    ),
    "tony.tpu.info-exec-path": Key(
        "", str, "Path to a tpu-info-style command emitting chip metrics JSON "
        "(ref: tony.gpu-exec-path for nvidia-smi)"
    ),
    "tony.tpu.num-slices": Key(
        1, int, "Multislice job shape: >1 groups the gang into N equal "
        "DCN-connected slices — the jax runtime injects MEGASCALE_* + "
        "per-slice TPU_WORKER_HOSTNAMES env, and the queued provisioner "
        "creates an N-node queued resource (--node-count)"
    ),
    "tony.tpu.megascale-port": Key(
        8080, int, "Port of the megascale DCN coordinator (slice 0, host 0) "
        "baked into MEGASCALE_COORDINATOR_ADDRESS"
    ),
    # test fault injection via conf (reference: tony.horovod.mode.test etc.)
    "tony.test.crash-coordinator": Key(
        False, bool, "Crash the coordinator once after start (ref: TEST_AM_CRASH conf twin)"
    ),
    # horovod-compat runtime (reference: TonyConfigurationKeys.java:313-316)
    "tony.horovod.test-mode": Key(
        False, bool,
        "Rendezvous driver emits a fake 2-slot plan on a fake port "
        "(ref: tony.horovod.mode.test)"
    ),
    "tony.horovod.test-fast-fail": Key(
        False, bool,
        "Rendezvous driver exits 1 immediately (ref: tony.horovod.mode.test.fast.fail)"
    ),
    "tony.horovod.driver-injected": Key(
        False, bool,
        "Internal marker: the hidden driver role was already injected "
        "(keeps validateAndUpdateConfig idempotent across client+coordinator)"
    ),
    "tony.horovod.driver.debug-command": Key(
        "", str,
        "User-supplied command replacing the built-in rendezvous driver "
        "(ref: HorovodDriver debug mode :189-216)"
    ),
    "tony.horovod.elastic": Key(
        False, bool,
        "Elastic rendezvous: the driver polls the discovery command and "
        "republishes the slot plan (new generation) on membership change "
        "(ref: horovod_driver.py elastic_driver_fn stub :28-29 — real here)"
    ),
    "tony.horovod.discovery-command": Key(
        "", str,
        "Elastic host-discovery command printing host[:slots] lines "
        "(horovod's discovery-script contract); required with "
        "tony.horovod.elastic"
    ),
}

# Per-role key suffixes (reference: TonyConfigurationKeys.java:189-257)
ROLE_SUFFIXES: dict[str, Key] = {
    "instances": Key(0, int, "Number of task instances for the role"),
    "max-instances": Key(-1, int, "Upper bound on instances; -1 = unlimited"),
    "chips": Key(0, int, "TPU chips per instance (ref: tony.<role>.gpus)"),
    "memory": Key("2g", str, "Memory per instance (ref: tony.<role>.memory)"),
    "vcores": Key(1, int, "CPU cores per instance"),
    "command": Key("", str, "Role-specific command overriding the global task command"),
    "resources": Key("", str, "Comma list of path[::localName][#archive] to localize"),
    "node-label": Key("", str, "Placement label for this role"),
    "depends-on": Key("", str, "Comma list of roles that must complete first (DAG)"),
}

MULTI_VALUE_KEYS = frozenset({"tony.application.untracked.jobtypes"})
"""Keys where repeated --conf occurrences append rather than replace
(reference: TonyConfigurationKeys.MULTI_VALUE_CONF / TonyClient.java:672-684)."""


def defaults() -> dict[str, Any]:
    """Flat {key: default} map for all global keys."""
    return {k: v.default for k, v in KEYS.items()}
