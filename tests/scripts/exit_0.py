"""Trivial success payload (ref: tony-core test/resources/scripts/exit_0.py)."""
import sys

sys.exit(0)
