"""Ledger-driven adaptive shape controller (ISSUE-13, ROADMAP 4c).

The serving engine's hot-loop shape knobs — ``chunk_steps`` (decode
micro-steps fused per dispatch), ``speculate_k`` (draft window), and
``prefill_chunk`` (prompt tokens per admission dispatch) — were static
CLI settings an operator had to tune per workload. PR 10's goodput
ledger can now *price* each setting live: the padding / overshoot /
spec_rejected fractions and the per-kind dispatch aggregates say
exactly which knob is wasting time. This module closes the loop: a
host-side controller samples each replica's timeline deltas every tick
and steers the knobs within operator-configured bounds.

Design constraints, in order:

- **Output-invariant.** Every knob it touches is output-invariant by
  the engine's own exactness pins (chunk-invariance, spec on/off
  parity, chunked-prefill parity), so an actuation can NEVER change a
  request's tokens — only the dispatch schedule.
- **No compile storms.** Actuations move on the power-of-two grid the
  engine's programs are already bucketed on, one step per actuation,
  with hysteresis (``hold_ticks`` consecutive same-direction proposals
  before acting) and a per-knob cooldown afterwards — so each
  actuation lands on an already-compiled bucket or deliberately pays
  ONE new compile, and the decision row says which
  (``new_compile``).
- **Idle replicas are never actuated.** A tick that saw fewer than
  ``min_dispatches`` decode/verify dispatches carries no signal;
  acting on it would be noise-chasing (and the convergence contract —
  actuations stop on steady traffic — would be unfalsifiable).
- **Bounded convergence.** Every rule moves a knob monotonically
  toward a bound or a dead zone; once traffic is steady the streaks
  stop refreshing and the controller goes quiet. ``converged`` in the
  snapshot is that condition made visible.

The controller is deliberately engine-local (it reads
``server.timeline.summary()`` + ``server.counters()`` and writes
``server.chunk_steps`` etc. — plain host attributes the scheduler
re-reads each round, so cross-thread actuation is safe: the new value
simply applies from the next round). Remote replicas have no local
timeline and are skipped. The gateway wires it into a sampling thread
and threads decisions to ``/stats engine.autotune``,
``tony_autotune_*`` metrics, and history ``metrics/autotune.jsonl``
(gateway/core.py).
"""

from __future__ import annotations

import logging
import time
from collections import deque
from dataclasses import dataclass, field

log = logging.getLogger(__name__)


def _pow2_down(n: int) -> int:
    """Largest power of two <= n (n >= 1) — actuations live on the
    same pow2 grid the engine's program buckets do."""
    b = 1
    while b * 2 <= n:
        b *= 2
    return b


def _pow2_up(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


@dataclass
class KnobBounds:
    """Operator bounds for one knob. ``lo == hi`` pins the knob (the
    controller will never propose a move); ``hi == 0`` disables
    tuning of that knob entirely."""

    lo: int
    hi: int

    def clamp(self, v: int) -> int:
        return max(self.lo, min(self.hi, v))


@dataclass
class _KnobState:
    """Per-(replica, knob) hysteresis: a proposal must repeat
    ``hold_ticks`` times in the SAME direction before it actuates, and
    a fresh actuation starts a cooldown during which proposals are
    ignored (the new shape needs a few ticks of data before being
    judged)."""

    direction: int = 0   # -1 shrink / +1 grow of the pending streak
    streak: int = 0
    cooldown: int = 0


@dataclass
class _ReplState:
    """Per-replica sampling state: the previous cumulative sample the
    next tick diffs against (first tick only establishes the
    baseline)."""

    prev: dict | None = None
    knobs: dict = field(default_factory=dict)  # knob name -> _KnobState


# the step-shaped dispatch kinds whose deltas carry the decode-loop
# signal (prefill-shaped kinds feed the prefill_chunk rule instead)
_STEP_KINDS = ("decode", "verify")


class AutotuneController:
    """See the module docstring. ``tick(replicas)`` takes
    ``[(index, server), ...]``, samples each local engine, and applies
    at most one actuation per knob per replica; it returns the
    decision rows it actuated (for logging / history). Thread-safety:
    tick() is called from ONE loop thread; snapshot() may be read from
    any (it only copies plain fields)."""

    def __init__(self, *,
                 chunk_bounds: tuple = (1, 32),
                 spec_bounds: tuple = (0, 16),
                 prefill_bounds: tuple = (0, 0),
                 hold_ticks: int = 2, cooldown_ticks: int = 3,
                 min_dispatches: int = 4,
                 overshoot_hi: float = 0.05,
                 overshoot_lo: float = 0.01,
                 frozen_hi: float = 0.50,
                 reject_hi: float = 0.35,
                 accept_hi: float = 0.60,
                 history: int = 64):
        self.chunk_bounds = KnobBounds(*chunk_bounds)
        self.spec_bounds = KnobBounds(*spec_bounds)
        self.prefill_bounds = KnobBounds(*prefill_bounds)
        self.hold_ticks = max(1, int(hold_ticks))
        self.cooldown_ticks = max(0, int(cooldown_ticks))
        self.min_dispatches = max(1, int(min_dispatches))
        self.overshoot_hi = float(overshoot_hi)
        self.overshoot_lo = float(overshoot_lo)
        self.frozen_hi = float(frozen_hi)
        self.reject_hi = float(reject_hi)
        self.accept_hi = float(accept_hi)
        self._st: dict = {}
        self.ticks = 0
        self.idle_ticks = 0
        self.actuation_counts: dict[str, int] = {}
        self.new_compiles = 0
        self.last_actuation_tick = 0
        self.recent: deque = deque(maxlen=max(1, history))

    # ------------------------------------------------------- sampling

    def _sample(self, server) -> dict | None:
        """One cumulative reading of the engine's shape-relevant
        sensors; None for engines with no timeline (timeline=False)
        and for REMOTE stubs — such replicas are never actuated. The
        remote check is explicit (a transport marks a stub) rather
        than timeline-is-None: since ISSUE-15 stubs carry a pulled
        RemoteTimeline, but their shape knobs live on the AGENT's
        engine — actuating the stub's dead local attributes would log
        phantom decisions that never reach the device."""
        timeline = getattr(server, "timeline", None)
        if timeline is None or getattr(server, "transport",
                                       None) is not None:
            return None
        summ = timeline.summary()
        out = {"dispatches": 0, "tokens": 0, "work": 0,
               "steady_ms": 0.0, "useful_ms": 0.0, "padding_ms": 0.0,
               "overshoot_ms": 0.0, "rejected_ms": 0.0,
               "prefill_steady_ms": 0.0, "prefill_padding_ms": 0.0,
               "prefill_count": 0}
        for kind in _STEP_KINDS:
            a = summ.get(kind)
            if not a:
                continue
            out["dispatches"] += a["count"]
            out["tokens"] += a["tokens"]
            out["work"] += a.get("work", 0)
            out["steady_ms"] += a["ms"] - a["compile_ms"]
            out["useful_ms"] += a["useful_ms"]
            out["padding_ms"] += a["padding_ms"]
            out["overshoot_ms"] += a["overshoot_ms"]
            out["rejected_ms"] += a["rejected_ms"]
        out["frozen_steps"] = getattr(server, "frozen_steps", 0)
        for kind in ("prefill", "prefill_chunk"):
            a = summ.get(kind)
            if not a:
                continue
            out["prefill_count"] += a["count"]
            out["prefill_steady_ms"] += a["ms"] - a["compile_ms"]
            out["prefill_padding_ms"] += a["padding_ms"]
        out["spec_drafted"] = getattr(server, "spec_drafted", 0)
        out["spec_accepted"] = getattr(server, "spec_accepted", 0)
        return out

    @staticmethod
    def _delta(prev: dict, cur: dict) -> dict:
        return {k: cur[k] - prev.get(k, 0) for k in cur}

    # ------------------------------------------------------ proposals

    def _proposals(self, server, d: dict) -> list:
        """Rule evaluation over one tick's deltas -> [(knob, target,
        direction, reason, signals)]. Each rule is a monotone move
        toward a bound or a dead zone, so steady traffic converges."""
        out = []
        steady = max(d["steady_ms"], 1e-9)
        overshoot = d["overshoot_ms"] / steady
        padding = d["padding_ms"] / steady
        # frozen re-emits as a fraction of the step dispatches'
        # POSITION capacity: the chunk-depth-induced share of padding.
        # Empty-slot padding (low occupancy) is orthogonal to
        # chunk_steps and must not veto growth.
        frozen = d["frozen_steps"] / max(1, d["work"])
        signals = {"overshoot_frac": round(overshoot, 4),
                   "padding_frac": round(padding, 4),
                   "frozen_frac": round(frozen, 4),
                   "dispatches": d["dispatches"],
                   "tokens": d["tokens"]}

        # chunk_steps: overshoot says the chunk runs past finishes the
        # engine pays for (only possible with in-dispatch EOS off, or
        # on the verify path) -> shrink; a clean ledger whose frozen
        # share leaves headroom -> grow toward the bound, amortizing
        # the per-dispatch host cost over more tokens. Judged only on
        # ticks that actually ran decode/verify dispatches — a
        # prefill-only tick carries no decode-shape signal.
        cur = int(getattr(server, "chunk_steps", 0))
        bounds = self.chunk_bounds
        if bounds.hi > 0 and cur > 0 \
                and d["dispatches"] >= self.min_dispatches:
            if overshoot > self.overshoot_hi \
                    and bounds.clamp(_pow2_down(cur) // 2 or 1) < cur:
                out.append(("chunk_steps",
                            bounds.clamp(_pow2_down(cur) // 2 or 1),
                            -1, "overshoot", signals))
            elif frozen > (1.0 + self.frozen_hi) / 2 \
                    and bounds.clamp(_pow2_down(cur) // 2 or 1) < cur:
                # most positions re-emit frozen finals: the chunk is
                # far deeper than the workload's typical remaining
                # budget — walk it back
                out.append(("chunk_steps",
                            bounds.clamp(_pow2_down(cur) // 2 or 1),
                            -1, "frozen", signals))
            elif overshoot <= self.overshoot_lo \
                    and frozen < self.frozen_hi \
                    and bounds.clamp(_pow2_up(cur) * 2) > cur:
                out.append(("chunk_steps",
                            bounds.clamp(_pow2_up(cur) * 2),
                            +1, "amortize_dispatches", signals))

        # speculate_k: judged on this tick's draft economics alone.
        # Never re-arms from 0 — a disabled path produces no data to
        # justify enabling it.
        cur = int(getattr(server, "speculate_k", 0))
        bounds = self.spec_bounds
        drafted = d.get("spec_drafted", 0)
        if bounds.hi > 0 and cur > 0 and drafted > 0:
            rej = 1.0 - d.get("spec_accepted", 0) / drafted
            sig = dict(signals, drafted=drafted,
                       reject_frac=round(rej, 4))
            if rej > self.reject_hi \
                    and bounds.clamp(_pow2_down(cur) // 2) < cur:
                out.append(("speculate_k",
                            bounds.clamp(_pow2_down(cur) // 2),
                            -1, "spec_rejected", sig))
            elif rej < 1.0 - self.accept_hi \
                    and bounds.clamp(_pow2_up(cur) * 2) > cur:
                out.append(("speculate_k",
                            bounds.clamp(_pow2_up(cur) * 2),
                            +1, "spec_accepted", sig))

        # prefill chunk budget: a padding-heavy prefill mix means the
        # chunk windows are wider than the prompts feeding them ->
        # shrink; pad-free chunked prefills -> grow toward the bound
        # (fewer interleave rounds per long prompt). The engine floor
        # is its bucket minimum.
        cur = int(getattr(server, "prefill_chunk", 0))
        bounds = self.prefill_bounds
        if bounds.hi > 0 and cur > 0 \
                and d["prefill_count"] >= self.min_dispatches:
            pf_steady = max(d["prefill_steady_ms"], 1e-9)
            pf_pad = d["prefill_padding_ms"] / pf_steady
            floor = max(bounds.lo, int(getattr(server, "min_bucket",
                                               16)))
            sig = dict(signals, prefill_padding_frac=round(pf_pad, 4),
                       prefill_count=d["prefill_count"])
            if pf_pad > 0.5 and max(floor, _pow2_down(cur) // 2) < cur:
                out.append(("prefill_chunk",
                            min(bounds.hi,
                                max(floor, _pow2_down(cur) // 2)),
                            -1, "prefill_padding", sig))
            elif pf_pad < 0.1 \
                    and bounds.clamp(_pow2_up(cur) * 2) > cur:
                out.append(("prefill_chunk",
                            bounds.clamp(_pow2_up(cur) * 2),
                            +1, "prefill_interleave", sig))
        return out

    # ------------------------------------------------------ actuation

    def _lands_on_compiled(self, server, knob: str, target: int) -> bool:
        """Whether the target value's program shape has already been
        compiled on this engine — the 'no compile storm' receipt each
        decision row carries. Conservative: unknown kinds report
        False (a deliberate, logged new compile)."""
        compiled = getattr(server, "_compiled", None)
        if not compiled:
            return False
        if knob == "chunk_steps":
            return any(k[0] == "decode" and len(k) > 1 and k[1] == target
                       for k in compiled)
        if knob == "speculate_k":
            # verify windows are pow2(draft)+1 bucketed; a smaller k
            # reuses the windows a bigger k already compiled
            return any(k[0] == "verify" and len(k) > 1
                       and k[1] <= _pow2_up(max(1, target)) + 1
                       for k in compiled)
        if knob == "prefill_chunk":
            return any(k[0] == "prefill_chunk" and len(k) > 1
                       and k[1] == target for k in compiled)
        return False

    def tick(self, replicas: list) -> list[dict]:
        """One controller evaluation over ``[(index, server), ...]``.
        Returns the actuation rows applied this tick."""
        self.ticks += 1
        decisions = []
        for index, server in replicas:
            if server is None:
                continue
            sample = self._sample(server)
            if sample is None:
                continue
            st = self._st.setdefault(index, _ReplState())
            prev, st.prev = st.prev, sample
            if prev is None:
                continue  # baseline tick: nothing to diff yet
            d = self._delta(prev, sample)
            for ks in st.knobs.values():
                if ks.cooldown > 0:
                    ks.cooldown -= 1
            if d["dispatches"] < self.min_dispatches \
                    and d["prefill_count"] < self.min_dispatches:
                # idle replica: no signal, no actuation, and stale
                # streaks must not fire the moment traffic returns
                self.idle_ticks += 1
                for ks in st.knobs.values():
                    ks.streak, ks.direction = 0, 0
                continue
            proposals = self._proposals(server, d)
            proposed = {p[0] for p in proposals}
            for knob, target, direction, reason, sig in proposals:
                ks = st.knobs.setdefault(knob, _KnobState())
                if ks.cooldown > 0:
                    continue
                if ks.direction == direction:
                    ks.streak += 1
                else:
                    ks.direction, ks.streak = direction, 1
                if ks.streak < self.hold_ticks:
                    continue
                cur = int(getattr(server, knob))
                if target == cur:
                    ks.streak, ks.direction = 0, 0
                    continue
                new_compile = not self._lands_on_compiled(
                    server, knob, target)
                setattr(server, knob, target)
                ks.streak, ks.direction = 0, 0
                # +1: the per-tick decrement runs before the judgment,
                # so this blocks exactly cooldown_ticks judgments
                ks.cooldown = self.cooldown_ticks + 1
                row = {"t": time.time(), "replica": index,
                       "knob": knob, "from": cur, "to": target,
                       "reason": reason, "signals": sig,
                       "new_compile": new_compile, "tick": self.ticks}
                self.actuation_counts[knob] = \
                    self.actuation_counts.get(knob, 0) + 1
                self.new_compiles += int(new_compile)
                self.last_actuation_tick = self.ticks
                self.recent.append(row)
                decisions.append(row)
                log.info(
                    "autotune replica %d: %s %d -> %d (%s%s)", index,
                    knob, cur, target, reason,
                    ", pays one new compile" if new_compile else
                    ", already-compiled bucket")
            # a knob no rule proposed this tick loses its streak —
            # hysteresis means N CONSECUTIVE proposals
            for knob, ks in st.knobs.items():
                if knob not in proposed:
                    ks.streak, ks.direction = 0, 0
        return decisions

    # ------------------------------------------------------- surfaces

    def knob_values(self, replicas: list) -> dict:
        """Current knob values per replica (for /stats + /metrics
        gauges) — read live from the engines, so the numbers can never
        drift from what the scheduler actually uses."""
        out = {}
        for index, server in replicas:
            if server is None or getattr(server, "timeline", None) \
                    is None:
                continue
            out[index] = {
                "chunk_steps": int(getattr(server, "chunk_steps", 0)),
                "speculate_k": int(getattr(server, "speculate_k", 0)),
                "prefill_chunk": int(getattr(server, "prefill_chunk",
                                             0)),
            }
        return out

    def snapshot(self) -> dict:
        return {
            "enabled": True,
            "ticks": self.ticks,
            "idle_ticks": self.idle_ticks,
            "actuations": dict(self.actuation_counts),
            "actuations_total": sum(self.actuation_counts.values()),
            "new_compiles": self.new_compiles,
            "last_actuation_tick": self.last_actuation_tick,
            # quiet for a full hysteresis+cooldown horizon = converged
            "converged": self.ticks - self.last_actuation_tick
            > self.hold_ticks + self.cooldown_ticks,
            "bounds": {
                "chunk_steps": [self.chunk_bounds.lo,
                                self.chunk_bounds.hi],
                "speculate_k": [self.spec_bounds.lo,
                                self.spec_bounds.hi],
                "prefill_chunk": [self.prefill_bounds.lo,
                                  self.prefill_bounds.hi],
            },
            "recent": list(self.recent)[-8:],
        }
