"""Distributed PyTorch training from tony-tpu's injected DDP env.

Reference analog: tony-examples/mnist-pytorch/mnist_distributed.py, which
reads RANK / WORLD / INIT_METHOD (set by the reference PyTorchRuntime,
runtime/PyTorchRuntime.java:45-57) and calls
``torch.distributed.init_process_group``. tony-tpu's pytorch runtime
injects the same contract, so this script is what a migrating user keeps
running unchanged. Gloo backend on CPU hosts; on TPU VMs swap the backend
for torch-xla's ``xla://`` init.
"""

from __future__ import annotations

import argparse
import os

import torch
import torch.distributed as td
import torch.nn as nn


def make_dataset(n: int, seed: int):
    g = torch.Generator().manual_seed(seed)
    labels = torch.randint(0, 10, (n,), generator=g)
    images = 0.1 + torch.randn(n, 28, 28, generator=g)
    for k in range(10):
        images[labels == k, k * 2:k * 2 + 2, :] += 2.0
    return images.reshape(n, 784), labels


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch", type=int, default=128)
    args = p.parse_args()

    rank = int(os.environ.get("RANK", "0"))
    world = int(os.environ.get("WORLD", os.environ.get("WORLD_SIZE", "1")))
    init_method = os.environ.get("INIT_METHOD", "")
    distributed = world > 1 and init_method
    if distributed:
        td.init_process_group("gloo", init_method=init_method,
                              rank=rank, world_size=world)
        print(f"rank {rank}/{world} joined via {init_method}")

    model = nn.Sequential(nn.Linear(784, 128), nn.ReLU(), nn.Linear(128, 10))
    if distributed:
        model = nn.parallel.DistributedDataParallel(model)
    opt = torch.optim.AdamW(model.parameters(), lr=1e-3)
    loss_fn = nn.CrossEntropyLoss()

    images, labels = make_dataset(args.batch * 4, seed=rank)
    loss = None
    for step in range(args.steps):
        lo = (step * args.batch) % (images.shape[0] - args.batch)
        opt.zero_grad()
        loss = loss_fn(model(images[lo:lo + args.batch]),
                       labels[lo:lo + args.batch])
        loss.backward()  # DDP averages grads across the gang here
        opt.step()
        if rank == 0:
            print(f"step {step}: loss={loss.item():.4f}")

    if distributed:
        td.destroy_process_group()
    return 0 if loss is not None and loss.item() < 2.3 else 1


if __name__ == "__main__":
    raise SystemExit(main())
