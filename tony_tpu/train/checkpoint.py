"""Checkpoint/resume via orbax.

The reference has NO in-tree checkpointing (SURVEY.md section 5.4 — its
"resume" is the AM retry loop restarting user scripts that must checkpoint
themselves). tony-tpu makes it first-class: the coordinator's retry loop
plus these helpers give restart-with-checkpoint resume, which the
launch->first-step-latency metric rewards.
"""

from __future__ import annotations

import logging
import os
from typing import Any

log = logging.getLogger(__name__)


class CheckpointManager:
    """Thin orbax wrapper: numbered step checkpoints + latest-restore."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 save_interval_steps: int = 1):
        import orbax.checkpoint as ocp

        from tony_tpu.utils.remotefs import is_remote

        self._ocp = ocp
        if is_remote(directory):
            # gs:// roots go to orbax verbatim (tensorstore speaks GCS);
            # abspath/makedirs are local-path concepts
            self.directory = directory
        else:
            self.directory = os.path.abspath(directory)
            os.makedirs(self.directory, exist_ok=True)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
            ),
        )

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        saved = self.manager.save(
            step, args=self._ocp.args.StandardSave(state), force=force)
        if saved:
            log.info("checkpoint saved at step %d", step)
        return bool(saved)

    def latest_step(self) -> int | None:
        return self.manager.latest_step()

    def restore(self, state_template: Any, step: int | None = None) -> Any:
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        restored = self.manager.restore(
            step, args=self._ocp.args.StandardRestore(state_template))
        log.info("restored checkpoint step %d", step)
        return restored

    def wait(self) -> None:
        self.manager.wait_until_finished()

    def close(self) -> None:
        self.manager.close()


def restore_or_init(directory: str, init_fn, state_template=None):
    """Resume-if-possible entry: returns (state, manager, resumed: bool)."""
    manager = CheckpointManager(directory)
    template = state_template if state_template is not None else init_fn()
    if manager.latest_step() is not None:
        restored = manager.restore(template)
        if restored is not None:
            return restored, manager, True
    return template, manager, False


def scan_latest_step(directory: str) -> int | None:
    """Newest checkpoint step under `directory` without importing orbax —
    numbered subdirs are orbax's on-disk layout. Used by the coordinator
    (which must stay lightweight) to advertise TONY_RESUME_STEP."""
    def complete(name: str) -> bool:
        # per-entry guard: a step dir GC'd mid-scan (orbax max_to_keep)
        # must not abort the scan of the surviving steps
        try:
            path = os.path.join(directory, name)
            # an in-flight orbax save holds a *.orbax-checkpoint-tmp*
            # marker inside; only complete steps count
            return os.path.isdir(path) and \
                not any("tmp" in f for f in os.listdir(path))
        except OSError:
            return False

    try:
        names = os.listdir(directory)
    except OSError:
        return None
    steps = [int(n) for n in names if n.isdigit() and complete(n)]
    return max(steps) if steps else None


def job_checkpoint_dir() -> str | None:
    """The coordinator-injected checkpoint dir for this task, if any."""
    return os.environ.get("TONY_CHECKPOINT_DIR") or None


def auto_resume(init_fn, state_template=None):
    """User-script one-liner: resume from the job's TONY_CHECKPOINT_DIR if
    the coordinator injected one (set tony.application.checkpoint-dir),
    else init fresh with no manager. Returns (state, manager|None, resumed).
    """
    directory = job_checkpoint_dir()
    if directory is None:
        template = state_template if state_template is not None else init_fn()
        return template, None, False
    return restore_or_init(directory, init_fn, state_template)
