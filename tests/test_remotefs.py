"""gs:// remote-scheme inputs (VERDICT r2 #8).

Reference parity: remote-scheme --conf_file and resource paths
(TonyClient.java:657-691; LocalizableResource.java:30-114 remote branch).
The copier is mocked with tests/scripts/fake_gsutil.sh serving a local
"bucket" directory via $FAKE_GCS_ROOT — no network anywhere.
"""

from __future__ import annotations

import json
import os
import zipfile

import pytest

from tony_tpu.utils import remotefs

SCRIPTS = os.path.join(os.path.dirname(__file__), "scripts")
FAKE_GSUTIL = os.path.join(SCRIPTS, "fake_gsutil.sh")


@pytest.fixture
def bucket(tmp_path, monkeypatch):
    """A local 'GCS bucket': gs://testbkt/... resolves under it."""
    root = tmp_path / "gcs"
    (root / "testbkt").mkdir(parents=True)
    monkeypatch.setenv("TONY_GSUTIL", FAKE_GSUTIL)
    monkeypatch.setenv("FAKE_GCS_ROOT", str(root))
    return root / "testbkt"


def test_is_remote():
    assert remotefs.is_remote("gs://b/k")
    assert not remotefs.is_remote("/local/path")
    assert not remotefs.is_remote("relative/path")


def test_fetch_file(bucket, tmp_path):
    (bucket / "data.txt").write_text("payload")
    dest = remotefs.fetch_to_dir("gs://testbkt/data.txt", str(tmp_path / "d"))
    assert open(dest).read() == "payload"
    assert os.path.basename(dest) == "data.txt"


def test_fetch_failure_raises(bucket, tmp_path):
    with pytest.raises(RuntimeError, match="fetch gs://testbkt/missing"):
        remotefs.fetch("gs://testbkt/missing", str(tmp_path / "x"))


def test_copier_requires_tool(monkeypatch):
    monkeypatch.delenv("TONY_GSUTIL", raising=False)
    monkeypatch.setenv("PATH", "/nonexistent")
    with pytest.raises(RuntimeError, match="TONY_GSUTIL"):
        remotefs.fetch("gs://b/k", "/tmp/never")


def test_conf_file_from_gcs(bucket):
    """build_conf accepts a gs:// --conf_file."""
    from tony_tpu.config import build_conf

    (bucket / "job.json").write_text(json.dumps({
        "tony": {"worker": {"instances": 3},
                 "application": {"name": "gcs-job"}}}))
    conf = build_conf(conf_file="gs://testbkt/job.json")
    assert conf.get_int("tony.worker.instances", 0) == 3
    assert str(conf.get("tony.application.name")) == "gcs-job"


def test_resource_localization_from_gcs(bucket, tmp_path):
    """tony.<role>.resources accepts gs:// paths, plain and #archive."""
    from tony_tpu.utils.fs import parse_resources

    (bucket / "vocab.txt").write_text("a b c")
    with zipfile.ZipFile(bucket / "assets.zip", "w") as zf:
        zf.writestr("inner/weights.bin", "W")

    dest = tmp_path / "job"
    specs = parse_resources(
        "gs://testbkt/vocab.txt::v.txt,gs://testbkt/assets.zip#archive")
    out = [r.localize(str(dest)) for r in specs]
    assert open(out[0]).read() == "a b c"
    assert os.path.basename(out[0]) == "v.txt"
    assert open(os.path.join(out[1], "inner", "weights.bin")).read() == "W"
    # the fetched archive itself is not left behind in the job dir
    assert not [f for f in os.listdir(dest) if f.endswith(".fetch.zip")]


def test_client_stage_with_gcs_srcdir_and_venv(bucket, tmp_path):
    """TonyClient.stage pulls a gs:// src tree and venv zip into the job
    dir (ref: processTonyConfResources HDFS download, :701-780)."""
    from tony_tpu.client import TonyClient
    from tony_tpu.config import build_conf

    (bucket / "src").mkdir()
    (bucket / "src" / "train.py").write_text("print('hi')")
    with zipfile.ZipFile(bucket / "venv.zip", "w") as zf:
        zf.writestr("bin/activate", "# venv")

    conf = build_conf(overrides=[
        "tony.application.src-dir=gs://testbkt/src",
        "tony.application.python-venv=gs://testbkt/venv.zip",
        f"tony.staging-dir={tmp_path / 'staging'}",
        "tony.worker.instances=1",
        "tony.application.executes=train.py",
    ])
    client = TonyClient(conf)
    job_dir = client.stage()
    assert open(os.path.join(job_dir, "train.py")).read() == "print('hi')"
    assert os.path.exists(os.path.join(job_dir, "venv", "bin", "activate"))


def test_checkpoint_manager_passes_gs_path_through(monkeypatch):
    """A gs:// checkpoint root must reach orbax verbatim — no local
    makedirs/abspath mangling. Orbax itself is stubbed: the assertion is
    about the path contract, not GCS IO."""
    import sys
    import types

    from tony_tpu.train.checkpoint import CheckpointManager

    seen = {}

    fake = types.ModuleType("orbax.checkpoint")

    class FakeManager:
        def __init__(self, directory, options=None):
            seen["dir"] = directory

    fake.CheckpointManager = FakeManager
    fake.CheckpointManagerOptions = lambda **kw: None
    fake.args = types.SimpleNamespace()
    orbax_pkg = types.ModuleType("orbax")
    orbax_pkg.checkpoint = fake
    monkeypatch.setitem(sys.modules, "orbax", orbax_pkg)
    monkeypatch.setitem(sys.modules, "orbax.checkpoint", fake)

    CheckpointManager("gs://bkt/ckpts")
    assert seen["dir"] == "gs://bkt/ckpts"


def test_client_stage_with_gcs_venv_directory(bucket, tmp_path):
    """A gs:// venv DIRECTORY (no .zip) stages like the local copytree
    branch."""
    from tony_tpu.client import TonyClient
    from tony_tpu.config import build_conf

    (bucket / "venv" / "bin").mkdir(parents=True)
    (bucket / "venv" / "bin" / "activate").write_text("# venv dir")

    conf = build_conf(overrides=[
        "tony.application.python-venv=gs://testbkt/venv",
        f"tony.staging-dir={tmp_path / 'staging'}",
        "tony.worker.instances=1",
        "tony.application.executes=train.py",
    ])
    job_dir = TonyClient(conf).stage()
    assert open(os.path.join(job_dir, "venv", "bin",
                             "activate")).read() == "# venv dir"


def test_dir_resource_localization_from_gcs(bucket, tmp_path):
    """Directory-prefix gs:// resources localize recursively (ADVICE r3:
    the remote analog of the local isdir/copytree branch; ref HDFS dir
    localization) — both with an explicit trailing slash and via the
    fallback when the flat copy fails."""
    from tony_tpu.utils.fs import LocalizableResource

    (bucket / "srcdir").mkdir()
    (bucket / "srcdir" / "a.txt").write_text("A")
    (bucket / "srcdir" / "sub").mkdir()
    (bucket / "srcdir" / "sub" / "b.txt").write_text("B")

    dest = tmp_path / "job1"
    out = LocalizableResource.parse(
        "gs://testbkt/srcdir/::code").localize(str(dest))
    assert open(os.path.join(out, "a.txt")).read() == "A"
    assert open(os.path.join(out, "sub", "b.txt")).read() == "B"

    dest2 = tmp_path / "job2"
    out2 = LocalizableResource.parse(
        "gs://testbkt/srcdir::code").localize(str(dest2))
    assert open(os.path.join(out2, "a.txt")).read() == "A"
