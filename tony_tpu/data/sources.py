"""Data sources for the input pipeline.

No reference analog: TonY leaves data loading entirely to the user script
(its examples read MNIST from local disk/HDFS themselves). A TPU framework
cannot — keeping the MXU fed is half the throughput battle — so tony-tpu
ships a small source/loader layer: a ``Source`` is random-access over
*examples* (host-side numpy), and the ``DataLoader`` (loader.py) turns it
into sharded, prefetched, device-resident global batches.

Sources are deliberately host-side and framework-free (pure numpy): the
device boundary is crossed exactly once, in the loader.
"""

from __future__ import annotations

import json
import os
from typing import Any, Mapping, Sequence

import numpy as np


class Source:
    """Random-access examples: len() + [i] -> dict of numpy arrays."""

    def __len__(self) -> int:  # pragma: no cover - interface
        raise NotImplementedError

    def __getitem__(self, idx: int) -> Mapping[str, np.ndarray]:
        raise NotImplementedError  # pragma: no cover - interface


class ArraySource(Source):
    """Wraps a dict of equal-leading-dim numpy arrays (in-memory dataset)."""

    def __init__(self, arrays: Mapping[str, np.ndarray]):
        if not arrays:
            raise ValueError("ArraySource needs at least one array")
        sizes = {k: len(v) for k, v in arrays.items()}
        if len(set(sizes.values())) != 1:
            raise ValueError(f"leading dims differ: {sizes}")
        self.arrays = {k: np.asarray(v) for k, v in arrays.items()}
        self._n = next(iter(sizes.values()))

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, idx: int) -> Mapping[str, np.ndarray]:
        return {k: v[idx] for k, v in self.arrays.items()}


class SyntheticTokenSource(Source):
    """Deterministic random token sequences (LM training/benchmarks).

    Example i is reproducible from (seed, i) alone, so every process
    materializes identical data without coordination — the multi-host-safe
    way to synthesize.
    """

    def __init__(self, num_examples: int, seq_len: int, vocab_size: int,
                 seed: int = 0):
        self.num_examples = num_examples
        self.seq_len = seq_len
        self.vocab_size = vocab_size
        self.seed = seed

    def __len__(self) -> int:
        return self.num_examples

    def __getitem__(self, idx: int) -> Mapping[str, np.ndarray]:
        rng = np.random.default_rng((self.seed, idx))
        return {"tokens": rng.integers(
            0, self.vocab_size, (self.seq_len,), dtype=np.int32)}


class SyntheticImageSource(Source):
    """Deterministic random image/label pairs (vision benchmarks)."""

    def __init__(self, num_examples: int, height: int, width: int,
                 channels: int = 3, num_classes: int = 1000, seed: int = 0):
        self.num_examples = num_examples
        self.shape = (height, width, channels)
        self.num_classes = num_classes
        self.seed = seed

    def __len__(self) -> int:
        return self.num_examples

    def __getitem__(self, idx: int) -> Mapping[str, np.ndarray]:
        rng = np.random.default_rng((self.seed, idx))
        return {
            "image": rng.standard_normal(self.shape, dtype=np.float32),
            "label": np.int32(rng.integers(0, self.num_classes)),
        }


class JsonlSource(Source):
    """Pre-tokenized examples from .jsonl file(s): one JSON object per line,
    values are lists/scalars converted to numpy. Line offsets are indexed
    once at open, so access is random without loading the file into memory.
    """

    def __init__(self, paths: str | Sequence[str],
                 dtypes: Mapping[str, Any] | None = None):
        if isinstance(paths, (str, os.PathLike)):
            paths = [paths]
        self.paths = [str(p) for p in paths]
        self.dtypes = dict(dtypes or {})
        self._index: list[tuple[int, int]] = []  # (file idx, byte offset)
        for fi, path in enumerate(self.paths):
            offset = 0
            with open(path, "rb") as f:
                for line in f:
                    if line.strip():
                        self._index.append((fi, offset))
                    offset += len(line)

    def __len__(self) -> int:
        return len(self._index)

    def __getitem__(self, idx: int) -> Mapping[str, np.ndarray]:
        fi, offset = self._index[idx]
        with open(self.paths[fi], "rb") as f:
            f.seek(offset)
            obj = json.loads(f.readline())
        out = {}
        for k, v in obj.items():
            dtype = self.dtypes.get(k)
            out[k] = np.asarray(v, dtype=dtype) if dtype else np.asarray(v)
        return out
