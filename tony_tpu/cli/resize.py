"""``tony-tpu resize`` — elastic resize of a running job.

No reference analog (elasticity is stubbed there); see tony_tpu/elastic.py
for the checkpoint-aware gang-restart protocol this triggers.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys

from tony_tpu import constants as C
from tony_tpu.rpc import RpcClient


def main(argv: list[str] | None = None) -> int:
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser(prog="tony-tpu resize")
    p.add_argument("--job_dir", required=True,
                   help="the running job's staging dir (holds coordinator.json)")
    p.add_argument("role", help="role to resize, e.g. worker")
    p.add_argument("instances", type=int, help="new instance count")
    p.add_argument("--secret", default=os.environ.get(C.JOB_TOKEN),
                   help="job token when security is enabled")
    args = p.parse_args(argv)

    info_path = os.path.join(args.job_dir, "coordinator.json")
    if not os.path.exists(info_path):
        print(f"no coordinator.json in {args.job_dir}", file=sys.stderr)
        return C.EXIT_FAIL
    with open(info_path) as f:
        info = json.load(f)
    # TLS jobs: pin the job's cert straight from its job-dir copy
    tls_fp = None
    cert = os.path.join(args.job_dir, "tls-cert.pem")
    if os.path.exists(cert):
        from tony_tpu.rpc.tls import cert_fingerprint

        tls_fp = cert_fingerprint(cert)
    client = RpcClient(info["host"], info["port"], secret=args.secret,
                       tls_fingerprint=tls_fp)
    try:
        ok = client.call("resize_role", role=args.role,
                         instances=args.instances)
    finally:
        client.close()
    print(f"resize {args.role} -> {args.instances}: "
          f"{'accepted' if ok else 'rejected'}")
    return C.EXIT_SUCCESS if ok else C.EXIT_FAIL


if __name__ == "__main__":
    raise SystemExit(main())
