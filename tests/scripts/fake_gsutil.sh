#!/bin/bash
# Fake gsutil for remotefs tests: serves gs://<bucket>/<path> from the
# local directory $FAKE_GCS_ROOT/<bucket>/<path>. Supports `cp [-r]`,
# including a trailing /* source glob (the src-dir fetch shape).
[ "$1" = cp ] || exit 64
shift
rec=""
if [ "$1" = -r ]; then rec="-r"; shift; fi
src="$1"; dest="$2"
local="$FAKE_GCS_ROOT/${src#gs://}"
case "$local" in
  */\*) exec cp $rec "${local%/\*}"/* "$dest";;
  *) exec cp $rec "$local" "$dest";;
esac
