"""Small fused pallas kernels: RMSNorm and residual-add-norm.

HBM-bandwidth ops the XLA fuser usually handles; kept as pallas kernels
both as the pattern reference for this repo and for the cases XLA splits
(norm feeding multiple consumers). Interpreter fallback off-TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from tony_tpu.ops.platform import interpret_mode as _interp


def _rmsnorm_kernel(x_ref, scale_ref, o_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    o_ref[:] = (x * jax.lax.rsqrt(var + eps) * scale_ref[:].astype(jnp.float32)
                ).astype(o_ref.dtype)


def _add_rmsnorm_kernel(x_ref, res_ref, scale_ref, o_ref, sum_ref, *, eps: float):
    s = x_ref[:].astype(jnp.float32) + res_ref[:].astype(jnp.float32)
    sum_ref[:] = s.astype(sum_ref.dtype)
    var = jnp.mean(s * s, axis=-1, keepdims=True)
    o_ref[:] = (s * jax.lax.rsqrt(var + eps) * scale_ref[:].astype(jnp.float32)
                ).astype(o_ref.dtype)


def rmsnorm(x, scale, *, eps: float = 1e-6, block_rows: int = 256):
    """x: [..., D], scale: [D]."""
    shape = x.shape
    d = shape[-1]
    rows = int(jnp.prod(jnp.array(shape[:-1]))) if len(shape) > 1 else 1
    x2 = x.reshape(rows, d)
    br = min(block_rows, rows)
    if rows % br:
        br = rows  # fall back to one block for awkward sizes
    out = pl.pallas_call(
        functools.partial(_rmsnorm_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
        grid=(rows // br,),
        in_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((br, d), lambda i: (i, 0)),
        interpret=_interp(),
    )(x2, scale)
    return out.reshape(shape)


def add_rmsnorm(x, residual, scale, *, eps: float = 1e-6, block_rows: int = 256):
    """Fused (x + residual) -> (normed, sum). Returns the residual stream sum
    too, as transformer blocks need it."""
    shape = x.shape
    d = shape[-1]
    rows = int(jnp.prod(jnp.array(shape[:-1]))) if len(shape) > 1 else 1
    x2 = x.reshape(rows, d)
    r2 = residual.reshape(rows, d)
    br = min(block_rows, rows)
    if rows % br:
        br = rows
    normed, summed = pl.pallas_call(
        functools.partial(_add_rmsnorm_kernel, eps=eps),
        out_shape=(
            jax.ShapeDtypeStruct((rows, d), x.dtype),
            jax.ShapeDtypeStruct((rows, d), x.dtype),
        ),
        grid=(rows // br,),
        in_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=(
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            pl.BlockSpec((br, d), lambda i: (i, 0)),
        ),
        interpret=_interp(),
    )(x2, r2, scale)
    return normed.reshape(shape), summed.reshape(shape)
