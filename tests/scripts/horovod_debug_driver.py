"""Debug-mode rendezvous driver: user-supplied replacement for the built-in
bootstrap (ref: TestTonyE2E horovod debug-mode case :567 +
test resources horovod_debug_driver.py). Writes the port file in cwd with a
fake plan, then stays alive."""

import json
import time

from tony_tpu.runtime.horovod_driver import (
    PORT_FILE_SUFFIX,
    build_fake_slot_plan,
)


def main() -> int:
    port = 9876
    with open(f"{port}{PORT_FILE_SUFFIX}", "w") as f:
        json.dump({"port": port, "slots": build_fake_slot_plan()}, f)
    while True:
        time.sleep(3600)


if __name__ == "__main__":
    raise SystemExit(main())
