"""HostPageTier: a host-RAM second tier under the device prefix store.

The paged prefix store (serve/prefix.py over serve/slots.PagePool)
made shared-prompt reuse cheap — but its working set is bounded by
HBM: a fleet serving millions of sessions evicts a conversation's
pages minutes before its next turn arrives, and the next turn pays a
full re-prefill. This module adds the tier below: when the device
store evicts an entry (LRU churn, or the engine's pool-pressure
squeeze), the entry's page CONTENT is copied device->host into this
tier (the ``PrefixStore.on_evict`` hook fires before the pages are
unpinned); when a later prompt's longest cached prefix lives here
rather than on the device, the engine pages it back in — allocate
pool pages, scatter the host bytes, re-insert into the device store —
and the admission that follows hits it exactly as if it had never
left. Million-session prefix reuse stops being bounded by HBM; it is
bounded by host RAM (``--kv-host-mb``).

Spills are ASYNC (ISSUE-18): the engine dispatches the page gather
(cheap — device work it never waits on) and hands the still-on-device
payload to ``spill_async``; ONE background thread does the
device->host sync and the store insert, FIFO, so decode rounds
proceed during the copy. Lookups that feed a page-in (``acquire``)
flush the queue first — the evict-then-resubmit race stays
deterministic — while the routing probe (``match_len``) never blocks.

Exactness: the spill and the page-in are the ``gather_pages`` /
``scatter_pages`` pair from serve/slots.py — pure copies, no
arithmetic — so a device->host->device round trip is BITWISE
identical (tests/test_tier.py pins it across dtype x scan_layers x
int8-KV scale leaves), and a prefix hit served through the tier
produces byte-identical tokens to a no-tier engine that never evicted
(the greedy-parity anchor).

The tier's index IS a ``PrefixStore`` (no pool): entries keep the
host payload as their ``row``, so the radix lookup, LRU, byte budget,
refcount pinning and eviction discipline are all the ones already
pinned by tests/test_prefix.py. Payloads are stored UNPADDED (the
pow2 gather bucket's junk tail is sliced off host-side) so the budget
charges real pages only.

This module also owns the WIRE codec for page payloads (base64 over
the leaves of the gathered pytree, dtype/shape carried per leaf) —
the ``/v1/handoff`` agent op and the host tier move the same object,
so one encoder serves both.
"""

from __future__ import annotations

import base64
import logging
import threading
import time
from collections import deque
from typing import Any

import jax
import numpy as np

from tony_tpu.serve.prefix import PrefixStore, tree_nbytes
from tony_tpu.serve.slots import cache_batch_axis

log = logging.getLogger(__name__)


# ------------------------------------------------------ payload shaping


def payload_pages(tree: Any) -> int:
    """Page-axis length of a gathered payload (the pow2 bucket the
    gather was padded to) — what a scatter's destination index list
    must match."""
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        ax = cache_batch_axis(path, leaf)
        if ax is not None:
            return int(leaf.shape[ax])
    raise ValueError("payload holds no paged leaves")


def pages_to_host(tree: Any, n: int) -> Any:
    """Device payload -> host numpy, sliced to its ``n`` REAL pages
    (the gather's pow2 padding is junk — storing it would double the
    tier's byte charge for nothing). ``np.asarray`` is the device
    sync; values are untouched, so the hop is bitwise."""
    def s(path, leaf):
        a = np.asarray(leaf)
        ax = cache_batch_axis(path, leaf)
        if ax is None:
            return a.copy()
        sl = [slice(None)] * a.ndim
        sl[ax] = slice(0, n)
        return a[tuple(sl)].copy()

    return jax.tree_util.tree_map_with_path(s, tree)


def pad_host_pages(tree: Any, n_pad: int) -> Any:
    """Host payload zero-padded back up to the ``n_pad`` pow2 bucket a
    scatter program expects — the padding rows land on the sentinel
    index and DROP, so their values never matter."""
    def p(path, leaf):
        ax = cache_batch_axis(path, leaf)
        if ax is None or leaf.shape[ax] >= n_pad:
            return leaf
        width = [(0, 0)] * leaf.ndim
        width[ax] = (0, n_pad - leaf.shape[ax])
        return np.pad(leaf, width)

    return jax.tree_util.tree_map_with_path(p, tree)


# ----------------------------------------------------------- wire codec


def _np_dtype(name: str) -> np.dtype:
    """dtype from its string name, including the ml_dtypes extras
    (bfloat16 and friends) a bare ``np.dtype`` does not know."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def encode_array(arr) -> dict:
    """One array as its wire form: dtype name + shape + base64 raw
    bytes (bitwise; no float round trip through text)."""
    a = np.ascontiguousarray(np.asarray(arr))
    return {"dtype": str(a.dtype), "shape": list(a.shape),
            "b64": base64.b64encode(a.tobytes()).decode("ascii")}


def decode_array(doc: dict) -> np.ndarray:
    return np.frombuffer(
        base64.b64decode(doc["b64"]),
        dtype=_np_dtype(doc["dtype"])).reshape(doc["shape"])


def encode_payload(tree: Any) -> dict:
    """A gathered page payload as JSON-safe wire form. Leaves ride in
    ``tree_flatten`` order; the receiver unflattens against its OWN
    cache treedef — both sides run the same model config, so the
    structures agree (``decode_payload`` checks the leaf count).

    Each PAGED leaf also records its ``page_axis`` so a relay can
    slice the payload page-wise WITHOUT knowing the cache treedef —
    the delta-migration trim (``trim_payload``) rides on it."""
    if isinstance(tree, dict) and "leaves" in tree:
        return tree  # already wire form (a pure-router gateway relays)
    leaves = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        doc = encode_array(leaf)
        ax = cache_batch_axis(path, leaf)
        if ax is not None:
            doc["page_axis"] = int(ax)
        leaves.append(doc)
    return {"leaves": leaves}


def decode_payload(doc: dict, treedef) -> Any:
    leaves = [decode_array(d) for d in doc["leaves"]]
    if treedef.num_leaves != len(leaves):
        raise ValueError(
            f"handoff payload carries {len(leaves)} leaves, this "
            f"engine's cache has {treedef.num_leaves} — mismatched "
            "model configs between the prefill and decode pools")
    return jax.tree_util.tree_unflatten(treedef, leaves)


def trim_payload(doc: dict, start: int, stop: int) -> dict:
    """Slice a WIRE payload to pages ``[start, stop)`` along each
    leaf's recorded ``page_axis`` — the delta-migration trim: ship
    only the suffix pages the adopter does not already hold, and drop
    the gather's pow2 padding while at it (the adopter re-pads to its
    own scatter bucket). Leaves without a page axis (none today) pass
    through untouched. Pure reshaping of already-encoded bytes; the
    values are bitwise."""
    out = []
    for d in doc["leaves"]:
        ax = d.get("page_axis")
        if ax is None:
            out.append(d)
            continue
        a = decode_array(d)
        sl = [slice(None)] * a.ndim
        sl[int(ax)] = slice(int(start), int(stop))
        trimmed = encode_array(a[tuple(sl)])
        trimmed["page_axis"] = int(ax)
        out.append(trimmed)
    return {"leaves": out}


def payload_nbytes(doc: dict) -> int:
    """Decoded byte size of a wire payload's leaves — what a migration
    actually ships (modulo base64's fixed 4/3), the number
    ``migrate_bytes_wire`` and the bench's delta-vs-full ratio count."""
    return sum(int(np.prod(d["shape"])) * _np_dtype(d["dtype"]).itemsize
               for d in doc["leaves"])


# ------------------------------------------------------------- the tier


class HostPageTier:
    """Host-RAM KV pages under an explicit byte budget.

    The engine drives it single-threaded (its own scheduler thread);
    the inner ``PrefixStore``'s lock keeps cross-thread STAT reads
    (gateway /stats) consistent, same contract as the device store.
    """

    def __init__(self, budget_bytes: int):
        self.store = PrefixStore(max(0, int(budget_bytes)))
        self.spills = 0          # entries copied device -> host
        self.page_ins = 0        # entries restored host -> device
        self.bytes_spilled = 0   # payload bytes copied out, lifetime
        self.bytes_paged_in = 0  # payload bytes restored, lifetime
        # async spill machinery (ISSUE-18): a FIFO of dispatched-but-
        # not-yet-copied payloads drained by ONE background thread, so
        # the device->host sync never blocks the scheduler's decode
        # rounds. FIFO + single worker = inserts land in eviction
        # order, the ordering the tests pin.
        self._q: deque = deque()
        self._pending: set[bytes] = set()  # keys queued or mid-copy
        self._cond = threading.Condition()
        self._worker: threading.Thread | None = None

    # ------------------------------------------------------------ index

    def has(self, tokens) -> bool:
        key = np.asarray(tokens, np.int32).tobytes()
        with self._cond:
            if key in self._pending:
                return True  # queued content counts: don't re-spill
        return self.store.has(tokens)

    def touch(self, tokens) -> None:
        """Refresh an EXISTING sequence's LRU position (the caller
        checked ``has()``): a re-evicted device entry whose content
        already lives here skips the device->host copy entirely."""
        key = np.asarray(tokens, np.int32).tobytes()
        with self._cond:
            if key in self._pending:
                return  # the queued copy will land with a fresh tick
        self.store.insert(tokens, row=None)

    def match_len(self, tokens) -> int:
        # pending spills are invisible here ON PURPOSE: this is the
        # routing probe, and blocking it on a flush would trade a
        # transient undercount for scheduler stalls
        return self.store.match_len(tokens)

    def acquire(self, tokens):
        # the lookup that feeds a PAGE-IN must see every spill already
        # initiated, or an evict-then-resubmit race would re-prefill
        # nondeterministically; flush is a no-op when the queue is dry
        self.flush()
        return self.store.acquire(tokens)

    def release(self, entry) -> None:
        self.store.release(entry)

    # ------------------------------------------------------------ moves

    def insert(self, tokens, payload: Any, logits) -> bool:
        """One SYNCHRONOUS spill: store the host ``payload`` (numpy
        pytree of the sequence's real pages) + optional last-position
        logits. Returns False when the budget refuses it (payload
        alone over budget, or everything resident is pinned)."""
        ok = self.store.insert(tokens, row=payload, logits=logits)
        if ok:
            self.spills += 1
            self.bytes_spilled += tree_nbytes(payload) + (
                tree_nbytes(logits) if logits is not None else 0)
        return ok

    def spill_async(self, tokens, payload: Any, n: int,
                    logits) -> None:
        """Queue one spill: ``payload`` is the still-on-device gather
        the engine just dispatched (its ``n`` real pages + pow2
        padding); the background thread does the device->host sync +
        tier insert, FIFO, while decode rounds keep running. Counters
        move NOW — they mean "spills initiated", stay single-writer
        deterministic for the engine thread, and equal the completed
        count after ``flush()``."""
        tokens = np.asarray(tokens, np.int32)
        per_page = tree_nbytes(payload) // max(1, payload_pages(payload))
        self.spills += 1
        self.bytes_spilled += per_page * int(n) + (
            tree_nbytes(logits) if logits is not None else 0)
        with self._cond:
            self._pending.add(tokens.tobytes())
            self._q.append((tokens, payload, int(n), logits))
            if self._worker is None:
                self._worker = threading.Thread(
                    target=self._copy_loop, name="kv-host-spill",
                    daemon=True)
                self._worker.start()
            self._cond.notify_all()

    def flush(self, timeout: float | None = None) -> bool:
        """Block until every queued spill has landed in the store.
        True on drained; False on timeout. A dry queue returns
        immediately (one lock round trip)."""
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        with self._cond:
            while self._pending:
                left = None if deadline is None \
                    else deadline - time.monotonic()
                if left is not None and left <= 0:
                    return False
                self._cond.wait(timeout=0.5 if left is None else left)
        return True

    def _copy_loop(self) -> None:
        while True:
            with self._cond:
                while not self._q:
                    self._cond.wait()
                tokens, payload, n, logits = self._q[0]
            try:
                host = pages_to_host(payload, n)  # the sync, off-thread
                logits_h = np.asarray(logits) \
                    if logits is not None else None
                self.store.insert(tokens, row=host, logits=logits_h)
            except Exception:
                log.exception("async KV spill failed")
            with self._cond:
                self._q.popleft()
                self._pending.discard(tokens.tobytes())
                self._cond.notify_all()

    def note_page_in(self, n_bytes: int) -> None:
        self.page_ins += 1
        self.bytes_paged_in += int(n_bytes)

    def summary(self, max_items: int = 512) -> list:
        """The tier's share of the heartbeat prefix summary
        (ISSUE-18): same ``[[n_tokens, crc32], ...]`` convention as
        the device store — a page-in is still far cheaper than a
        re-prefill, so remote affinity should count host-resident
        prefixes too."""
        return self.store.summary(max_items)

    # ------------------------------------------------------------ stats

    def stats(self) -> dict:
        st = self.store.stats()
        return {
            "entries": st["entries"],
            "bytes": st["bytes"],
            "budget_bytes": st["budget_bytes"],
            "tokens": st["tokens"],
            "nodes": st["nodes"],
            "max_depth": st["max_depth"],
            "evictions": st["evictions"],
            "rejected": st["rejected"],
            "spills": self.spills,
            "page_ins": self.page_ins,
            "bytes_spilled": self.bytes_spilled,
            "bytes_paged_in": self.bytes_paged_in,
        }
