"""User-process execution — the single exec point for task commands.

Reference: Utils.executeShell (util/Utils.java:299-329): ``bash -c <cmd>``
with injected env, optional timeout, output streamed to the task log.
"""

from __future__ import annotations

import logging
import os
import signal
import subprocess
import sys
import threading
import time

log = logging.getLogger(__name__)

# live children of this process (pgid leaders), for preemption forwarding
_ACTIVE: set = set()
_ACTIVE_LOCK = threading.Lock()


def register_external_process(proc) -> None:
    """Track a Popen started outside execute_shell (e.g. the horovod
    rendezvous driver) so preemption forwarding reaches it too."""
    with _ACTIVE_LOCK:
        _ACTIVE.add(proc)


def unregister_external_process(proc) -> None:
    with _ACTIVE_LOCK:
        _ACTIVE.discard(proc)


def request_graceful_shutdown(grace_ms: int = 15_000) -> int:
    """TPU preemption/maintenance path: forward SIGTERM to every active
    user-process group so training can checkpoint-and-exit, then SIGKILL
    whatever is still alive after the grace period. Returns the number of
    process groups signalled, immediately (the killer runs on a daemon
    thread); callers keep waiting on the child, which exits with 143
    (SIGTERM) or 137 (SIGKILL). NOT async-signal-safe (takes locks): call
    from a worker thread, never directly inside a signal handler."""
    with _ACTIVE_LOCK:
        procs = list(_ACTIVE)

    def signal_proc(proc, sig) -> None:
        # registered processes are USUALLY their own group leaders
        # (execute_shell children run under start_new_session), but not
        # always — the horovod rendezvous server deliberately stays in
        # the agent's group so the launcher's group kill reaps it. killpg
        # on a non-leader pid raises ProcessLookupError; fall back to
        # signalling the process itself rather than silently skipping it
        try:
            os.killpg(proc.pid, sig)
        except ProcessLookupError:
            try:
                proc.send_signal(sig)
            except (ProcessLookupError, OSError):
                pass

    for proc in procs:
        signal_proc(proc, signal.SIGTERM)

    def kill_after_grace():
        # one shared deadline: per-proc fresh timeouts would compound to
        # N x grace and outlive the platform's actual reclaim window
        deadline = time.monotonic() + grace_ms / 1000
        for proc in procs:
            try:
                proc.wait(timeout=max(0.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                log.warning("grace period (%d ms) expired; SIGKILL pgid %d",
                            grace_ms, proc.pid)
                signal_proc(proc, signal.SIGKILL)

    threading.Thread(target=kill_after_grace, daemon=True).start()
    return len(procs)


def execute_shell(
    command: str,
    timeout_ms: int = 0,
    env: dict[str, str] | None = None,
    log_path: str | None = None,
    cwd: str | None = None,
) -> int:
    """Run ``bash -c command``; returns the exit code (124 on timeout, like
    coreutils timeout). The child gets its own process group so a timeout
    kills the whole user-process tree."""
    full_env = dict(os.environ)
    if env:
        full_env.update({k: str(v) for k, v in env.items()})
    if log_path:
        # a shipped job dir arrives without logs/ (excluded from the tar
        # stream); the exec point owns creating its own log home
        os.makedirs(os.path.dirname(os.path.abspath(log_path)),
                    exist_ok=True)
    out = open(log_path, "ab", buffering=0) if log_path else None
    try:
        proc = subprocess.Popen(
            ["bash", "-c", command],
            env=full_env,
            cwd=cwd,
            stdout=out if out else None,
            stderr=subprocess.STDOUT if out else None,
            start_new_session=True,
        )
        with _ACTIVE_LOCK:
            _ACTIVE.add(proc)
        try:
            return proc.wait(timeout=timeout_ms / 1000 if timeout_ms > 0 else None)
        except subprocess.TimeoutExpired:
            log.error("command timed out after %d ms: %s", timeout_ms, command)
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            proc.wait()
            return 124
        finally:
            with _ACTIVE_LOCK:
                _ACTIVE.discard(proc)
    finally:
        if out:
            out.close()


def tee_output(proc: subprocess.Popen, log_path: str, scan=None) -> threading.Thread:
    """Stream a child's stdout to a log file (and optionally a scanner
    callback per line — used by the preprocessing path that scrapes
    output, ref: ApplicationMaster.doPreprocessingJob :780-832)."""

    def pump():
        with open(log_path, "ab", buffering=0) as f:
            assert proc.stdout is not None
            for line in proc.stdout:
                f.write(line)
                if scan is not None:
                    try:
                        scan(line.decode(errors="replace"))
                    except Exception:
                        log.exception("output scanner failed")

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    return t


def python_interpreter(venv_dir: str | None = None) -> str:
    """Pick the task python: shipped venv's bin/python if present, else the
    current interpreter (ref: TonyClient.buildTaskCommand :618-635)."""
    if venv_dir:
        cand = os.path.join(venv_dir, "bin", "python")
        if os.path.exists(cand):
            return cand
    return sys.executable
