#!/bin/bash
# Fake ssh for launcher tests: drop options, ignore the host, run the
# remote command locally — so the full ssh launch/kill path (setsid pgid
# capture, remote kill -- -PGID) is exercised without sshd.
while [ $# -gt 0 ]; do
  case "$1" in
    -o) shift 2;;
    -*) shift;;
    *) break;;
  esac
done
host="$1"; shift
exec sh -c "$*"
