"""End-to-end serving observability: traces, timeline, exposition.

The TonY lesson (PAPER.md L4/L6) applied to serving: orchestration is
worth little if you cannot see where a request's time went. Three
layers, each consumable on its own:

- ``trace``: per-request span trees (queue-wait -> admit -> decode
  rounds, one attempt span per engine run across failovers), exported
  as Chrome trace-event JSON for Perfetto (``/debug/trace/<id>``);
- ``timeline``: per-dispatch engine records (kind / occupancy / shape
  bucket / host-wall duration, compile split from steady state) — the
  ``/stats`` ``dispatches`` block and the sensor for dispatch-overhead
  work;
- ``prom`` + ``export``: dependency-free Prometheus text exposition of
  the gateway's counters, gauges, and latency histograms
  (``GET /metrics``).

- ``goodput`` + ``alerts``: the attribution layer — an analytic
  bytes/FLOPs cost model stamped onto every timeline record, a
  wall-clock goodput ledger whose named buckets sum to <= 1
  (``/stats`` ``engine.goodput``, ``GET /debug/goodput``), and a
  rule-engine alert bus emitting deduplicated fire/resolve events
  (``/stats`` ``alerts``, history ``metrics/alerts.jsonl``).

The whole layer is always-on-cheap (appends under small locks, export
cost only when asked); bench ``extras.obs`` and ``extras.goodput``
pin the overhead.
"""

from tony_tpu.obs.alerts import AlertBus, AlertEvent, Rule, default_rules
from tony_tpu.obs.export import prometheus_text
from tony_tpu.obs.goodput import (CostModel, detect_hbm_gbps,
                                  detect_peak_flops, ledger,
                                  merge_ledgers)
from tony_tpu.obs.prom import (DEFAULT_TIME_BUCKETS_S, Histogram,
                               MetricFamily, escape_label_value, render)
from tony_tpu.obs.timeline import DispatchRecord, DispatchTimeline
from tony_tpu.obs.trace import (RequestTrace, Span, TraceBuffer,
                                check_invariants)

__all__ = [
    "DEFAULT_TIME_BUCKETS_S",
    "AlertBus",
    "AlertEvent",
    "CostModel",
    "DispatchRecord",
    "DispatchTimeline",
    "Histogram",
    "MetricFamily",
    "RequestTrace",
    "Rule",
    "Span",
    "TraceBuffer",
    "check_invariants",
    "default_rules",
    "detect_hbm_gbps",
    "detect_peak_flops",
    "escape_label_value",
    "ledger",
    "merge_ledgers",
    "prometheus_text",
    "render",
]
