"""Sharded, prefetched data loading: host numpy -> device-resident global
batches.

No reference analog (TonY delegates input to the user script; SURVEY.md
section 2.2 tony-examples read MNIST themselves). TPU-first design:

- **per-process sharding**: every process sees the same seeded per-epoch
  permutation and takes a disjoint stride of it, so a multi-host job reads
  each example exactly once per epoch with zero coordination traffic —
  the data analog of the env-var rendezvous the launcher already does.
- **global batch assembly**: with a ``NamedSharding``, local host batches
  are stitched into one global ``jax.Array`` via
  ``jax.make_array_from_process_local_data`` — the multi-host pjit input
  idiom (each host contributes only the shard its devices own).
- **background prefetch**: a daemon thread stages the next batches while
  the current step runs, hiding host->HBM transfer behind MXU time.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterator, Mapping

import jax
import numpy as np

from tony_tpu.data.sources import Source

_STOP = object()


class DataLoader:
    """Iterates device-ready batches from a ``Source``.

    Args:
      source: random-access examples.
      global_batch_size: batch size summed over all processes.
      shuffle: reshuffle each epoch with a (seed, epoch)-derived permutation.
      seed: base shuffle seed (must match across processes).
      drop_remainder: drop the trailing partial batch (required for jit's
        static shapes; keep True for training).
      num_epochs: None = loop forever.
      process_index/process_count: which stride of the permutation this
        process owns; default = jax.process_index()/process_count().
      sharding: optional ``NamedSharding`` for the batch. When set, the
        iterator yields global ``jax.Array``s (multi-host safe); when None
        it yields host numpy dicts.
      prefetch: how many batches to stage ahead (0 = synchronous).
    """

    def __init__(self, source: Source, global_batch_size: int, *,
                 shuffle: bool = True, seed: int = 0,
                 drop_remainder: bool = True, num_epochs: int | None = None,
                 process_index: int | None = None,
                 process_count: int | None = None,
                 sharding: Any | None = None, prefetch: int = 2):
        self.source = source
        self.global_batch_size = global_batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder
        self.num_epochs = num_epochs
        self.process_index = (jax.process_index() if process_index is None
                              else process_index)
        self.process_count = (jax.process_count() if process_count is None
                              else process_count)
        if global_batch_size % self.process_count:
            raise ValueError(
                f"global_batch_size={global_batch_size} not divisible by "
                f"process_count={self.process_count}")
        self.local_batch_size = global_batch_size // self.process_count
        if drop_remainder and \
                len(source) // self.process_count < self.local_batch_size:
            # would loop forever yielding nothing (steps_per_epoch == 0) —
            # fail loudly instead of hanging the gang's first collective
            raise ValueError(
                f"dataset too small: {len(source)} examples over "
                f"{self.process_count} processes yields less than one "
                f"local batch of {self.local_batch_size}")
        self.sharding = sharding
        self.prefetch = prefetch

    # -- host-side iteration -------------------------------------------------

    def _epoch_indices(self, epoch: int) -> np.ndarray:
        n = len(self.source)
        if self.shuffle:
            order = np.random.default_rng((self.seed, epoch)).permutation(n)
        else:
            order = np.arange(n)
        return order[self.process_index::self.process_count]

    def _host_batches(self, start_batch: int = 0) \
            -> Iterator[Mapping[str, np.ndarray]]:
        lb = self.local_batch_size
        spe = self.steps_per_epoch()
        epoch, skip = (divmod(start_batch, spe) if spe else (0, 0))
        while self.num_epochs is None or epoch < self.num_epochs:
            mine = self._epoch_indices(epoch)
            if self.drop_remainder:
                # every process must yield the SAME batch count: the global
                # batch is assembled collectively (and the following pjit
                # step is a cross-host collective), so one process ending an
                # epoch a step early would hang the others. Cap by the
                # minimum per-process example count, not this stride's.
                stop = (len(self.source) // self.process_count) // lb * lb
            else:
                stop = len(mine)
            for start in range(skip * lb, stop, lb):
                rows = [self.source[int(i)] for i in mine[start:start + lb]]
                yield {k: np.stack([r[k] for r in rows]) for k in rows[0]}
            skip = 0
            epoch += 1

    # -- public iterator -----------------------------------------------------

    def __iter__(self):
        return self.from_step(0)

    def from_step(self, step: int):
        """Iterator starting at global batch index `step` — the data-order
        half of checkpoint resume: skipping is index arithmetic (the seeded
        per-epoch permutation is recomputed), no examples are read. Every
        process must pass the same step. Requires drop_remainder."""
        if step and not self.drop_remainder:
            raise ValueError("from_step needs drop_remainder=True "
                             "(stable steps_per_epoch)")
        it = self._host_batches(start_batch=step)
        if self.sharding is not None:
            it = (self._to_global(b) for b in it)
        if self.prefetch > 0:
            it = _prefetch_iter(it, self.prefetch)
        return it

    def _to_global(self, batch: Mapping[str, np.ndarray]):
        return {
            k: jax.make_array_from_process_local_data(self.sharding, v)
            for k, v in batch.items()
        }

    def steps_per_epoch(self) -> int:
        if self.drop_remainder:
            # same formula as _host_batches: identical on every process
            return (len(self.source) // self.process_count) \
                // self.local_batch_size
        per_proc = (len(self.source) + self.process_count - 1
                    - self.process_index) // self.process_count
        return (per_proc + self.local_batch_size - 1) // self.local_batch_size


def _prefetch_iter(it: Iterator, size: int) -> Iterator:
    """Stage up to `size` items from a daemon thread.

    Closeable: generator .close() (or abandonment + GC) signals the worker
    to stop, so a consumer that exits early (e.g. fit() hitting its step
    target on an infinite loader) does not leak a blocked thread pinning
    `size` staged device batches for the life of the process.
    """
    q: queue.Queue = queue.Queue(maxsize=size)
    stop = threading.Event()
    err: list[BaseException] = []

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for item in it:
                if not put(item):
                    return
        except BaseException as e:  # surfaced on the consumer side
            err.append(e)
        finally:
            put(_STOP)

    threading.Thread(target=worker, daemon=True).start()
    try:
        while True:
            item = q.get()
            if item is _STOP:
                if err:
                    raise err[0]
                return
            yield item
    finally:
        stop.set()
        while True:  # unblock a producer stuck on a full queue
            try:
                q.get_nowait()
            except queue.Empty:
                break


def device_prefetch(iterator: Iterator, sharding: Any, size: int = 2):
    """Wrap any host-batch iterator: device_put with `size` lookahead so the
    next batch's host->HBM DMA overlaps the current step's compute."""

    def put(batch):
        return jax.tree.map(
            lambda x: jax.device_put(x, sharding), batch)

    return _prefetch_iter((put(b) for b in iterator), size)
