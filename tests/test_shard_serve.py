"""Sharded serving (ISSUE-14): tensor-sharded replicas on the virtual
CPU mesh must be BYTE-IDENTICAL to single-chip replicas.

The exactness argument is structural (parallel.sharding "serve"
preset): weights shard only on OUTPUT dims (the row-parallel o/wo
kernels flip to embed), the model pins activations replicated at those
boundaries (``TransformerConfig.shard_activations``), so every float
reduction runs whole on one chip in the single-chip order and all
cross-chip traffic is all-gather — pure data movement. These tests pin
the consequence: token streams, dispatch counts, prefill counts, and
speculation/prefix counters all equal mesh=1 vs mesh=4, across paged x
unpaged x greedy x seeded-sampling x speculation x prefix hits x
chunked prefill x handoff x host tier. Plus the capacity-unlock math
(a footprint that exceeds one chip fits per-chip under the mesh) and
the per-chip goodput pricing."""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from tony_tpu.models import Transformer, TransformerConfig
from tony_tpu.parallel.mesh import MeshSpec, make_mesh
from tony_tpu.serve import Request, Server

pytestmark = pytest.mark.skipif(jax.device_count() < 4,
                                reason="needs 4 virtual devices")


@pytest.fixture(scope="module")
def tiny():
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=2, d_ff=64, max_seq_len=64,
                            dtype=jnp.float32,
                            attention_backend="reference")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


@pytest.fixture(scope="module")
def mesh4():
    return make_mesh(MeshSpec(data=1, tensor=4),
                     devices=jax.devices()[:4])


def _workload():
    """Greedy + seeded sampling + an exact prefix repeat + a
    repetitive prompt the prompt-lookup drafter hits on."""
    rep = [7, 8, 9, 7, 8, 9, 7, 8, 9, 7, 8]
    return [
        Request(prompt=[1, 2, 3, 4, 5], max_new_tokens=12, id="greedy"),
        Request(prompt=rep, max_new_tokens=10, id="spec"),
        Request(prompt=[3, 1, 4, 1, 5, 9, 2, 6], max_new_tokens=8,
                temperature=0.8, top_k=8, seed=123, id="sampled"),
        Request(prompt=[1, 2, 3, 4, 5], max_new_tokens=12, id="hit"),
    ]


def _run(tiny, mesh, paged, **kw):
    model, params = tiny
    kw.setdefault("batch_size", 3)
    kw.setdefault("chunk_steps", 4)
    kw.setdefault("prefix_cache_mb", 8)
    kw.setdefault("speculate_k", 4)
    s = Server(model, params, paged=paged, mesh=mesh, **kw)
    out = {}
    for r in s.run(_workload()):
        out[r.id] = list(r.tokens)
    return out, s


@pytest.mark.parametrize("paged", [True, False])
def test_token_exact_mesh4_vs_single_chip(tiny, mesh4, paged):
    """THE gate: byte-identical streams AND identical dispatch/prefill
    counts (no new host syncs, no extra dispatches) on the full mixed
    workload — greedy, seeded sampling, speculation, prefix hits."""
    a, sa = _run(tiny, None, paged)
    b, sb = _run(tiny, mesh4, paged)
    assert a == b
    assert sa.dispatches == sb.dispatches
    assert sa.prefills == sb.prefills
    assert sa.steps == sb.steps
    # speculation + prefix behavior identical, not just outputs
    assert sa.spec_drafted == sb.spec_drafted
    assert sa.spec_accepted == sb.spec_accepted
    assert sa.prefix_hits == sb.prefix_hits
    assert sb.kv_shards == 4


def test_mesh1_is_the_trivial_shard(tiny):
    """A 1-device mesh is the degenerate sharded path — same streams,
    same counters (the smoke control's A/B anchor)."""
    mesh1 = make_mesh(MeshSpec(data=1, tensor=1),
                      devices=jax.devices()[:1])
    a, _ = _run(tiny, None, True)
    b, sb = _run(tiny, mesh1, True)
    assert a == b
    assert sb.mesh_info()["devices"] == 1


def test_pools_stay_sharded_across_serving(tiny, mesh4):
    """The KV pools must KEEP their kv-head sharding through admits,
    decode chunks, verify rounds and evictions — a silent gather would
    quietly forfeit the capacity unlock."""
    _, s = _run(tiny, mesh4, True)
    found = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(
            s.slots.cache)[0]:
        name = str(path[-1].key if hasattr(path[-1], "key")
                   else path[-1])
        if name in ("cached_key", "cached_value"):
            found += 1
            spec = tuple(leaf.sharding.spec)
            assert "tensor" in spec, (name, spec)
    assert found >= 4  # k + v per layer


def test_scan_layers_int8_kv_sharded_parity(tiny, mesh4):
    """The stacked-layers + int8-KV cell: scan params carry a leading
    layers axis (the serve preset must place it whole) and the int8
    scale leaves shard alongside their pools."""
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=2, d_ff=64, max_seq_len=64,
                            dtype=jnp.float32, scan_layers=True,
                            kv_cache_quant=True, positional="learned",
                            norm="layer", use_bias=True,
                            attention_backend="reference")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    reqs = lambda: [Request(prompt=[1, 2, 3, 4], max_new_tokens=8,
                            id="a"),
                    Request(prompt=[9, 8, 7], max_new_tokens=6,
                            temperature=0.7, top_k=8, seed=7, id="b")]
    outs = []
    for mesh in (None, mesh4):
        s = Server(model, params, batch_size=2, chunk_steps=2,
                   paged=True, mesh=mesh)
        outs.append({r.id: list(r.tokens) for r in s.run(reqs())})
    assert outs[0] == outs[1]


def test_chunked_prefill_sharded_parity(tiny, mesh4):
    """Chunked prefill under the mesh: same chunk count, same slot
    state, same stream."""
    model, params = tiny
    long_prompt = list(range(1, 41))
    outs, chunks = [], []
    for mesh in (None, mesh4):
        s = Server(model, params, batch_size=2, prefill_chunk_tokens=16,
                   paged=True, mesh=mesh)
        res = list(s.run([Request(prompt=long_prompt,
                                  max_new_tokens=6, id="long")]))
        outs.append([list(r.tokens) for r in res])
        chunks.append((res[0].prefill_chunks,
                       s.prefill_chunk_dispatches))
    assert outs[0] == outs[1]
    assert chunks[0] == chunks[1]
    assert chunks[0][0] >= 2  # actually chunked


def test_handoff_between_sharded_engines(tiny, mesh4):
    """The disaggregation handoff under the mesh: the page-list
    payload is a SHARDED pytree gathered on the prefill engine and
    scattered into the decode engine's sharded pools — streams equal a
    generalist single-chip engine."""
    model, params = tiny
    prompt = [5, 4, 3, 2, 1, 6, 7]
    control = Server(model, params, batch_size=2, paged=True)
    want = [list(r.tokens) for r in control.run(
        [Request(prompt=prompt, max_new_tokens=8, seed=3,
                 temperature=0.6, top_k=8, id="x")])]

    pre = Server(model, params, batch_size=2, paged=True, mesh=mesh4)
    dec = Server(model, params, batch_size=2, paged=True, mesh=mesh4)
    (h,) = list(pre.run([Request(prompt=prompt, max_new_tokens=8,
                                 prefill_only=True, id="x")]))
    assert h.finish_reason == "handoff"
    got = [list(r.tokens) for r in dec.run(
        [Request(prompt=prompt, max_new_tokens=8, seed=3,
                 temperature=0.6, top_k=8, handoff=h.handoff,
                 id="x")])]
    assert got == want
    assert pre.handoffs_out == 1 and dec.handoffs_in == 1


def test_host_tier_spill_page_in_sharded(tiny, mesh4):
    """Host-tier round trip under the mesh: spilled pages gather from
    sharded pools to host RAM and scatter back bitwise — streams equal
    the unsharded tier engine's."""
    model, params = tiny
    p1 = list(range(1, 17))
    p2 = list(range(20, 36))
    reqs = lambda: [Request(prompt=p, max_new_tokens=4, id=f"r{i}")
                    for i, p in enumerate([p1, p2, p1])]
    outs, tiers = [], []
    for mesh in (None, mesh4):
        s = Server(model, params, batch_size=2, paged=True,
                   prefix_cache_mb=0.02, kv_host_mb=4, mesh=mesh)
        outs.append({r.id: list(r.tokens) for r in s.run(reqs())})
        tiers.append(s.host_tier.stats()["spills"])
    assert outs[0] == outs[1]
    assert tiers[0] == tiers[1]
    assert tiers[0] > 0  # the tiny store actually churned


def test_capacity_unlock_math(tiny, mesh4):
    """The reason this PR exists: a param+KV footprint that does NOT
    fit one chip fits per-chip under the mesh — demonstrated via the
    same worst-case byte accounting admission uses, on an engine that
    then actually serves end-to-end."""
    _, s = _run(tiny, mesh4, True)
    info = s.mesh_info()
    total = info["param_bytes_total"] + info["kv_bytes_total"]
    per_chip = info["param_bytes_per_chip"] + info["kv_bytes_per_chip"]
    # pick the notional per-chip HBM budget between the two: one chip
    # could NOT hold the model, the 4-chip mesh holds it with room
    budget = (total + per_chip) // 2
    assert total > budget > per_chip
    assert info["kv_shards"] == 4
    # and the engine genuinely served the workload sharded
    assert s.dispatches > 0 and s.prefills > 0


def test_per_chip_goodput_pricing(tiny, mesh4):
    """The goodput satellite: the cost model prices dispatches with
    PER-CHIP bytes/FLOPs (vs the single-chip roofline), the ledger
    still reconciles, and counters carry the topology."""
    _, single = _run(tiny, None, True)
    _, s = _run(tiny, mesh4, True)
    # per-chip param bytes are the sharded residency, not the total
    assert s.cost.param_bytes == s.mesh_info()["param_bytes_per_chip"]
    assert s.cost.param_bytes < single.cost.param_bytes
    # KV bytes/token divide by the pool shard count
    assert s.cost.kv_token_bytes == pytest.approx(
        single.cost.kv_token_bytes / 4)
    # attention work splits with the pools
    assert s.cost.n_heads == single.cost.n_heads // 4
    # a decode dispatch estimate is ~1/4 the single-chip estimate
    nb1, fl1 = single.cost.decode(4, 3, 64)
    nb4, fl4 = s.cost.decode(4, 3, 64)
    assert nb4 < nb1 and fl4 < fl1
    # the ledger still holds its structural invariant sharded
    g = s.goodput()
    assert sum(g["buckets"].values()) <= 1.0 + 1e-9
    # flat counters carry the topology (MetricsStore + agent wire)
    c = s.counters()
    assert c["mesh_devices"] == 4
    assert c["mesh_kv_shards"] == 4
    assert c["mesh_param_bytes_per_chip"] == s.cost.param_bytes


def test_flash_decode_refused_under_mesh(tiny, mesh4):
    model, params = tiny
    cfg = dataclasses.replace(model.cfg, decode_attention="flash")
    with pytest.raises(NotImplementedError, match="flash"):
        Server(Transformer(cfg), params, batch_size=2, mesh=mesh4)


def test_gateway_sharded_stats_and_metrics(tiny, mesh4):
    """The fleet surfaces: /stats engine.mesh topology + per-replica
    mesh block + tony_mesh_* on the prom render."""
    from tony_tpu.gateway import Gateway, GenRequest
    from tony_tpu.obs.export import prometheus_text

    model, params = tiny
    servers = [Server(model, params, batch_size=2, mesh=mesh4)]
    gw = Gateway(servers, max_queue=16).start()
    try:
        tickets = [gw.submit(GenRequest([1 + i, 2, 3],
                                        max_new_tokens=4, id=i))
                   for i in range(3)]
        for t in tickets:
            t.result(timeout=120)
        snap = gw.snapshot()
        mesh = snap["engine"]["mesh"]
        assert mesh["enabled"] and mesh["devices"] == 4
        assert mesh["kv_shards"] == 4
        assert mesh["topology"] == {"tensor": 4}
        row = snap["replicas"][0]
        assert row["mesh"]["devices"] == 4
        assert row["mesh_devices"] == 4  # flat twin for MetricsStore
        text = prometheus_text(gw)
        assert "tony_mesh_enabled 1" in text
        assert "tony_mesh_devices 4" in text
        assert "tony_mesh_kv_shards 4" in text
    finally:
        gw.drain(timeout=60)
